"""Headline benchmark: map_blocks rows/sec/chip (BASELINE.md config 3).

Workload: the Scala-DSL-equivalent ``mapBlocks`` add-constant over a
1M-row double column (reference ``README.md:154-172``), on the framework's
device-resident path: the frame is ``distribute``d to the chip mesh once
(the analogue of data living in Spark executors' memory), then each
``dmap_blocks`` iteration is one compiled XLA dispatch per step with NO
host↔device transfer — the TPU-native design BASELINE.json's north star
asks for ("streams ... directly into TPU HBM device buffers").

``vs_baseline``: the reference publishes no numbers (``BASELINE.md``), so the
denominator is a faithful host re-implementation of the reference's own data
path on this machine: materialize Row objects from the columns, map the
computation, rebuild columns from Rows — the row-at-a-time
convert/convertBack structure of ``DataOps.scala:158-283`` (its acknowledged
weakness, ``DataOps.scala:30-33``), with the arithmetic vectorized in its
favor. Ratio > 1 means the columnar TPU-resident path beats the
row-marshalling design at equal scale.

Prints exactly ONE JSON line on stdout.
"""

import json
import sys
import time

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu import dtypes as _dt
from tensorframes_tpu.computation import Computation, TensorSpec
from tensorframes_tpu.marshal import columns_to_rows, rows_to_columns
from tensorframes_tpu.parallel.distributed import distribute, dmap_blocks
from tensorframes_tpu.parallel.mesh import local_mesh
from tensorframes_tpu.shape import Shape, Unknown

N_ROWS = 1_000_000
WARMUP = 3
ITERS = 20


def build_frame():
    x = np.arange(N_ROWS, dtype=np.float64)
    df = tft.frame({"x": x}, num_partitions=1)
    df.cache()
    return df


def bench_dmap_blocks(df) -> float:
    import jax

    mesh = local_mesh()
    dist = distribute(df, mesh)
    # one Computation object -> one jit trace across all iterations
    comp = Computation.trace(
        lambda x: {"z": x + 3.0},
        [TensorSpec("x", _dt.double, Shape(Unknown))])
    for _ in range(WARMUP):
        out = dmap_blocks(comp, dist, trim=True)
        jax.block_until_ready(out.columns["z"])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = dmap_blocks(comp, dist, trim=True)
        jax.block_until_ready(out.columns["z"])
    dt = (time.perf_counter() - t0) / ITERS
    return N_ROWS / dt


def bench_reference_rowpath(df) -> float:
    """The reference's structure: Rows materialized in and out per block."""
    schema = df.schema
    t0 = time.perf_counter()
    for b in df.blocks():
        rows = columns_to_rows(b.columns, schema)          # convert
        mapped = [(r[0] + 3.0,) for r in rows]             # the computation
        rows_to_columns(mapped, schema)                    # convertBack
    dt = time.perf_counter() - t0
    return N_ROWS / dt


def main():
    df = build_frame()
    ours = bench_dmap_blocks(df)
    ref = bench_reference_rowpath(df)
    n_chips = max(1, local_chips())
    print(json.dumps({
        "metric": "map_blocks_add_const_1M_rows",
        "value": round(ours / n_chips, 1),
        "unit": "rows/sec/chip",
        "vs_baseline": round(ours / ref, 2),
    }))


def local_chips() -> int:
    import jax

    return len(jax.devices())


if __name__ == "__main__":
    sys.exit(main())
