"""Headline benchmark: map_blocks rows/sec/chip (BASELINE.md config 3).

Workload: the Scala-DSL-equivalent ``mapBlocks`` add-constant over a
1M-row double column (reference ``README.md:154-172``), on the framework's
device-resident path: the frame is ``distribute``d to the chip mesh once
(the analogue of data living in Spark executors' memory), then each
``dmap_blocks`` iteration is one compiled XLA dispatch per step with NO
host↔device transfer — the TPU-native design BASELINE.json's north star
asks for ("streams ... directly into TPU HBM device buffers").

``vs_baseline``: the reference publishes no numbers (``BASELINE.md``), so the
denominator is a faithful host re-implementation of the reference's own data
path on this machine: materialize Row objects from the columns, map the
computation, rebuild columns from Rows — the row-at-a-time
convert/convertBack structure of ``DataOps.scala:158-283`` (its acknowledged
weakness, ``DataOps.scala:30-33``), with the arithmetic vectorized in its
favor. Ratio > 1 means the columnar TPU-resident path beats the
row-marshalling design at equal scale.

Robustness contract (the driver runs this unattended): the parent process
NEVER runs jax itself. It launches the measurement in a subprocess with a
hard timeout — first on the default (TPU) backend, then forced-CPU if the
TPU attempt fails or hangs (a wedged TPU grant blocks indefinitely rather
than erroring). Exactly ONE JSON line is printed on stdout in every case,
with ``platform`` and (on failure) ``error`` fields.
"""

import json
import os
import shutil
import subprocess
import sys
import time

N_ROWS = 1_000_000
WARMUP = 3
ITERS = 20
PROBE_TIMEOUT_S = 25  # tiny dispatch: client init + one add; wedge hangs it
TPU_TIMEOUT_S = 420   # first TPU compile is 20-40s; a wedged grant hangs
CPU_TIMEOUT_S = 300
CHIP_RESULTS = os.path.join(
    os.path.dirname(os.path.abspath(__file__)),
    "benchmarks", "chip_results.jsonl")


# --------------------------------------------------------------------------
# child: the actual measurement (runs in a subprocess with a timeout)
# --------------------------------------------------------------------------

def _child(platform: str) -> None:
    if platform == "cpu":
        import jax

        jax.config.update("jax_platforms", "cpu")

    import numpy as np

    import tensorframes_tpu as tft
    from tensorframes_tpu import dtypes as _dt
    from tensorframes_tpu.computation import Computation, TensorSpec
    from tensorframes_tpu.marshal import columns_to_rows, rows_to_columns
    from tensorframes_tpu.parallel.distributed import distribute, dmap_blocks
    from tensorframes_tpu.parallel.mesh import local_mesh
    from tensorframes_tpu.shape import Shape, Unknown

    import jax

    x = np.arange(N_ROWS, dtype=np.float64)
    df = tft.frame({"x": x}, num_partitions=1)
    df.cache()

    # ours: device-resident columnar path, one dispatch per iteration
    mesh = local_mesh()
    dist = distribute(df, mesh)
    comp = Computation.trace(
        lambda x: {"z": x + 3.0},
        [TensorSpec("x", _dt.double, Shape(Unknown))])
    for _ in range(WARMUP):
        out = dmap_blocks(comp, dist, trim=True)
        jax.block_until_ready(out.columns["z"])
    t0 = time.perf_counter()
    for _ in range(ITERS):
        out = dmap_blocks(comp, dist, trim=True)
        jax.block_until_ready(out.columns["z"])
    ours = N_ROWS / ((time.perf_counter() - t0) / ITERS)

    # end-to-end including host<->device marshalling each iteration (the
    # reference's acknowledged weak spot, DataOps.scala:30-33): columnar
    # host frame -> device -> compute -> back to host

    def e2e_iter():
        d2 = distribute(df, mesh)
        o2 = dmap_blocks(comp, d2, trim=True)
        np.asarray(o2.columns["z"])

    e2e_iter()  # warm: allocator + any per-shape retrace out of the loop
    t0 = time.perf_counter()
    for _ in range(5):
        e2e_iter()
    e2e = N_ROWS / ((time.perf_counter() - t0) / 5)

    # which executor backs the engine path (native C++ core vs in-process
    # jax) — evidence for BASELINE.md, not part of the measured loop above
    from tensorframes_tpu.engine.executor import default_executor
    executor = type(default_executor()).__name__

    # secondary metric (never costs the headline): the host engine's
    # pipelined block stream vs the serial path on the SAME 1M-row
    # map_blocks workload, multi-partition so blocks actually stream.
    # TFT_PIPELINE_DEPTH=1 is the serial engine by construction. A
    # wall-clock budget (checked between full-frame forcings) keeps a
    # slow host from eating the parent's subprocess timeout — the
    # headline must survive slowness, not just errors.
    pipeline_secondary = None
    pipe_budget_s = 60.0
    pipe_t0 = time.perf_counter()
    try:
        pdf = tft.frame({"x": x}, num_partitions=8)
        pdf.cache()
        pcomp = Computation.trace(
            lambda x: {"z": x + 3.0},
            [TensorSpec("x", _dt.double, Shape(Unknown))])

        def _engine_rows_per_s(depth: int, reps: int = 3) -> float:
            os.environ["TFT_PIPELINE_DEPTH"] = str(depth)
            if time.perf_counter() - pipe_t0 > pipe_budget_s:
                raise RuntimeError(
                    f"pipeline secondary exceeded its {pipe_budget_s:.0f}s "
                    f"budget before the depth-{depth} warmup")
            pdf.map_blocks(pcomp, trim=True).blocks()  # warm the compile
            best = float("inf")
            for _ in range(reps):
                if time.perf_counter() - pipe_t0 > pipe_budget_s \
                        and best < float("inf"):
                    break
                t0 = time.perf_counter()
                pdf.map_blocks(pcomp, trim=True).blocks()
                best = min(best, time.perf_counter() - t0)
            return N_ROWS / best

        serial_rps = _engine_rows_per_s(1)
        pipelined_rps = _engine_rows_per_s(3)
        pipeline_secondary = {
            "serial_rows_per_s": round(serial_rps, 1),
            "pipelined_rows_per_s": round(pipelined_rps, 1),
            "speedup": round(pipelined_rps / serial_rps, 3),
            "depth": 3,
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        pipeline_secondary = {"error": str(e)[:300]}
    finally:
        os.environ.pop("TFT_PIPELINE_DEPTH", None)

    # secondary metric (never costs the headline): the observability
    # layer's cost on the SAME 1M-row host-engine map_blocks workload.
    # Three modes: "bypass" (query_trace/add_event short-circuited at
    # their first flag check — the closest runtime stand-in for the
    # pre-observability engine), "off" (the hooks run their normal
    # disabled checks — the default production path), "on" (TFT_TRACE=1
    # with query traces, block events, and stage attribution). The
    # acceptance bar is the off-vs-bypass delta: the disabled layer must
    # cost <2%. Wall-clock budgeted like the pipeline secondary.
    tracing_secondary = None
    trace_budget_s = 45.0
    trace_t0 = time.perf_counter()
    try:
        from tensorframes_tpu.observability import events as _obs_events
        from tensorframes_tpu.utils import tracing as _tracing

        tdf = tft.frame({"x": x}, num_partitions=8)
        tdf.cache()
        tcomp = Computation.trace(
            lambda x: {"z": x + 3.0},
            [TensorSpec("x", _dt.double, Shape(Unknown))])

        def _force_once() -> float:
            t0 = time.perf_counter()
            tdf.map_blocks(tcomp, trim=True).blocks()
            return time.perf_counter() - t0

        def _measure_bypass() -> float:
            with _obs_events.bypass():
                return _force_once()

        def _measure_off() -> float:
            return _force_once()

        def _measure_on() -> float:
            _tracing.enable()
            try:
                return _force_once()
            finally:
                _tracing.disable()

        from statistics import median as _median

        # The acceptance bar (off regresses <2% vs the layer stripped
        # out) is measured FIRST and alone, as alternating pairs with
        # the in-pair order flipped each round: sequential clumps
        # confound with machine drift, fixed ordering adds position
        # bias, min-of is unstable between near-identical distributions,
        # and tracing-ON iterations in the same loop leave allocation/GC
        # debt that lands asymmetrically — each effect alone dwarfs the
        # disabled layer's real (nanoseconds/block) cost on a ~10ms
        # workload. Medians over ~80 interleaved pairs are stable.
        _tracing.disable()
        _force_once()  # warm the compile cache once for every mode
        samples = {"bypass": [], "off": [], "on": []}
        rounds = 0
        pair_budget_s = trace_budget_s * 0.75
        while rounds < 250 and (time.perf_counter() - trace_t0
                                < pair_budget_s or rounds < 2):
            if rounds % 2:
                samples["off"].append(_measure_off())
                samples["bypass"].append(_measure_bypass())
            else:
                samples["bypass"].append(_measure_bypass())
                samples["off"].append(_measure_off())
            rounds += 1
        # tracing-ON cost is informational (the documented price of
        # TFT_TRACE=1), measured after the off/bypass pairs
        while len(samples["on"]) < 20 and (
                time.perf_counter() - trace_t0 < trace_budget_s
                or not samples["on"]):
            samples["on"].append(_measure_on())

        bypass_rps = N_ROWS / _median(samples["bypass"])
        off_rps = N_ROWS / _median(samples["off"])
        on_rps = N_ROWS / _median(samples["on"])
        off_overhead_pct = (bypass_rps - off_rps) / bypass_rps * 100.0
        tracing_secondary = {
            "bypass_rows_per_s": round(bypass_rps, 1),
            "off_rows_per_s": round(off_rps, 1),
            "on_rows_per_s": round(on_rps, 1),
            "off_overhead_pct": round(off_overhead_pct, 2),
            "on_overhead_pct": round(
                (bypass_rps - on_rps) / bypass_rps * 100.0, 2),
            "off_within_2pct": bool(off_overhead_pct < 2.0),
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        tracing_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): the observability
    # layer's cost on the DISTRIBUTED path — the mesh-level shard/device
    # instrumentation in dmap_blocks (per-shard events, per-device
    # readiness, HBM samples) must stay free when tracing is off. Same
    # interleaved order-flipped off-vs-bypass pair discipline and
    # wall-clock budget as the host-engine tracing secondary; the
    # acceptance bar is off within 2% of bypass.
    mesh_tracing_secondary = None
    mesh_budget_s = 40.0
    mesh_t0 = time.perf_counter()
    try:
        from statistics import median as _mmedian

        from tensorframes_tpu.observability import events as _mobs_events
        from tensorframes_tpu.utils import tracing as _mtracing

        mdist = distribute(df, mesh)

        def _mesh_force() -> float:
            t0 = time.perf_counter()
            out = dmap_blocks(comp, mdist, trim=True)
            jax.block_until_ready(out.columns["z"])
            return time.perf_counter() - t0

        _mtracing.disable()
        _mesh_force()  # warm the compile cache for every mode
        msamples = {"bypass": [], "off": [], "on": []}
        rounds = 0
        mesh_pair_budget_s = mesh_budget_s * 0.75
        while rounds < 250 and (time.perf_counter() - mesh_t0
                                < mesh_pair_budget_s or rounds < 2):
            if rounds % 2:
                msamples["off"].append(_mesh_force())
                with _mobs_events.bypass():
                    msamples["bypass"].append(_mesh_force())
            else:
                with _mobs_events.bypass():
                    msamples["bypass"].append(_mesh_force())
                msamples["off"].append(_mesh_force())
            rounds += 1
        # tracing-ON cost is informational (per-device readiness waits
        # serialize the gather, the documented price of TFT_TRACE=1)
        while len(msamples["on"]) < 10 and (
                time.perf_counter() - mesh_t0 < mesh_budget_s
                or not msamples["on"]):
            _mtracing.enable()
            try:
                msamples["on"].append(_mesh_force())
            finally:
                _mtracing.disable()

        mbypass_rps = N_ROWS / _mmedian(msamples["bypass"])
        moff_rps = N_ROWS / _mmedian(msamples["off"])
        mon_rps = N_ROWS / _mmedian(msamples["on"])
        moff_pct = (mbypass_rps - moff_rps) / mbypass_rps * 100.0
        mesh_tracing_secondary = {
            "bypass_rows_per_s": round(mbypass_rps, 1),
            "off_rows_per_s": round(moff_rps, 1),
            "on_rows_per_s": round(mon_rps, 1),
            "off_overhead_pct": round(moff_pct, 2),
            "on_overhead_pct": round(
                (mbypass_rps - mon_rps) / mbypass_rps * 100.0, 2),
            "off_within_2pct": bool(moff_pct < 2.0),
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        mesh_tracing_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): the serving layer
    # under a mixed 3-tenant workload — small/medium/large map_blocks
    # queries submitted concurrently through serve.QueryScheduler.
    # Reports sustained queries/sec, p99 end-to-end latency (from the
    # query_latency_seconds histogram the scheduler feeds with a tenant
    # label), and the shared compile cache's cross-tenant hits. Wall-
    # clock budgeted like the other secondaries.
    serving_secondary = None
    serve_budget_s = 40.0
    serve_t0 = time.perf_counter()
    try:
        from tensorframes_tpu.serve import (QueryScheduler, ServerStats,
                                            TenantQuota)

        sizes = {"small": 10_000, "medium": 100_000, "large": 400_000}
        frames = {t: [tft.frame({"x": np.arange(float(n)) + k},
                                num_partitions=4)
                      for k in range(8)]
                  for t, n in sizes.items()}
        quotas = {t: TenantQuota(weight=2.0 if t == "large" else 1.0,
                                 max_queue=1024)
                  for t in sizes}
        with QueryScheduler(quotas=quotas, workers=3,
                            name="bench") as sched:
            # warm the (shared) compile once so the measured window is
            # steady-state serving, not first-compile
            sched.submit(frames["small"][0],
                         lambda x: {"z": x + 3.0},
                         tenant="small").result(timeout=60)
            t0 = time.perf_counter()
            futs = []
            rounds = 0
            while time.perf_counter() - t0 < serve_budget_s * 0.5 \
                    and rounds < 8:
                for t in sizes:
                    for fr in frames[t]:
                        futs.append(sched.submit(
                            fr, lambda x: {"z": x + 3.0}, tenant=t))
                rounds += 1
            for f in futs:
                f.result(timeout=max(
                    5.0, serve_budget_s - (time.perf_counter() - t0)))
            elapsed = time.perf_counter() - t0
            stats = ServerStats(sched)
            p99 = stats.p99()
            cc = sched.compile_cache.stats()
            serving_secondary = {
                "queries": len(futs),
                "queries_per_s": round(len(futs) / elapsed, 1),
                "p99_latency_s": round(p99, 4) if p99 is not None
                else None,
                "tenants": len(sizes),
                "workers": 3,
                "compile_cache_hits": cc["hits"],
                "compile_cache_misses": cc["misses"],
            }
    except Exception as e:  # noqa: BLE001 - headline must survive
        serving_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): the streaming
    # subsystem's sustained throughput — a generator source feeding
    # map_blocks + windowed keyed aggregation through StreamHandle.step,
    # reporting batches/sec and p99 per-batch latency at steady state
    # (post-warmup: every batch is a compile-cache hit; state stays
    # bounded by the watermark). Wall-clock budgeted like the others.
    streaming_secondary = None
    stream_budget_s = 30.0
    stream_t0 = time.perf_counter()
    try:
        from tensorframes_tpu import stream as tstream

        s_rows, s_keys = 50_000, 64

        def s_gen():
            i = 0
            base_k = (np.arange(s_rows) % s_keys).astype(np.int64)
            base_v = np.arange(s_rows, dtype=np.float64)
            while True:
                yield {"k": base_k, "v": base_v + i,
                       "ts": np.full(s_rows, float(i))}
                i += 1

        s_agg = (tstream.from_source(tstream.GeneratorSource(s_gen()))
                 .map_blocks(lambda v: {"v2": v * 2.0})
                 .select(["k", "v2", "ts"])
                 .group_by("k")
                 .aggregate({"v2": "sum"}, window=tstream.tumbling(8.0),
                            time_col="ts", watermark_delay=2.0))
        sh = s_agg.start(name="bench-stream")
        for _ in range(5):  # warm the compile + merge-program caches
            sh.step()
        lat = []
        t0 = time.perf_counter()
        while (time.perf_counter() - stream_t0 < stream_budget_s * 0.8
               and len(lat) < 400):
            b0 = time.perf_counter()
            sh.step()
            lat.append(time.perf_counter() - b0)
        elapsed = time.perf_counter() - t0
        sm = sh.metrics()
        if lat and elapsed > 0:
            lat.sort()
            p99 = lat[max(0, -(-len(lat) * 99 // 100) - 1)]
            streaming_secondary = {
                "batches": len(lat),
                "rows_per_batch": s_rows,
                "batches_per_s": round(len(lat) / elapsed, 2),
                "rows_per_s": round(len(lat) * s_rows / elapsed, 1),
                "p99_batch_latency_s": round(p99, 5),
                "state_rows": sm["state_rows"],
                "windows_emitted": sm["windows_emitted"],
                "skipped": sm["batches_skipped"],
            }
        else:
            # warmup ate the whole budget (slow box): report what ran
            # instead of erroring the secondary
            streaming_secondary = {
                "batches": 0,
                "error": "warmup consumed the wall-clock budget",
                "warmup_batches": sm["batches"],
            }
    except Exception as e:  # noqa: BLE001 - headline must survive
        streaming_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): the elastic-mesh
    # layer — every mesh-op dispatch now passes through
    # parallel.elastic (device fault-site check + skew tracker + loss
    # recovery). Two numbers: (1) the healthy-mesh overhead of that
    # boundary, measured as interleaved order-flipped pairs against
    # elastic.bypass() like the tracing secondaries (acceptance bar:
    # <2%) — each sample amortizes a BATCH of forcings, because the
    # boundary's real cost (~2 us/op) sits below the per-forcing timer
    # noise of a loaded box; (2) dmap_blocks throughput after ONE
    # injected device loss — the op completes on the shrunken mesh
    # instead of raising, at proportionally lower throughput.
    # Wall-clock budgeted.
    elastic_secondary = None
    el_budget_s = 30.0
    el_t0 = time.perf_counter()
    try:
        from statistics import median as _emedian

        from tensorframes_tpu.parallel import elastic as _elastic
        from tensorframes_tpu.resilience import faults as _efaults
        from tensorframes_tpu.utils import tracing as _etracing

        edist = distribute(df, mesh)
        EL_BATCH = 20

        def _eforce(d) -> float:
            t0 = time.perf_counter()
            out = dmap_blocks(comp, d, trim=True)
            jax.block_until_ready(out.columns["z"])
            return time.perf_counter() - t0

        def _ebatch(d) -> float:
            t0 = time.perf_counter()
            for _ in range(EL_BATCH):
                out = dmap_blocks(comp, d, trim=True)
                jax.block_until_ready(out.columns["z"])
            return (time.perf_counter() - t0) / EL_BATCH

        _eforce(edist)  # warm
        esamples = {"bypass": [], "on": []}
        rounds = 0
        while rounds < 40 and (time.perf_counter() - el_t0
                               < el_budget_s * 0.5 or rounds < 2):
            if rounds % 2:
                esamples["on"].append(_ebatch(edist))
                with _elastic.bypass():
                    esamples["bypass"].append(_ebatch(edist))
            else:
                with _elastic.bypass():
                    esamples["bypass"].append(_ebatch(edist))
                esamples["on"].append(_ebatch(edist))
            rounds += 1
        eb_rps = N_ROWS / _emedian(esamples["bypass"])
        eo_rps = N_ROWS / _emedian(esamples["on"])
        e_pct = (eb_rps - eo_rps) / eb_rps * 100.0

        elastic_secondary = {
            "bypass_rows_per_s": round(eb_rps, 1),
            "on_rows_per_s": round(eo_rps, 1),
            "off_overhead_pct": round(e_pct, 2),
            "off_within_2pct": bool(e_pct < 2.0),
            "devices_full": mesh.num_devices,
        }
        if mesh.num_devices >= 2:
            # one injected device loss: the non-trim dmap recovers onto
            # the shrunken mesh and its output frame (input column
            # riding along) is the degraded-mesh workload
            lost_before = _etracing.counters.get("mesh.devices_lost")
            _efaults.arm("device", 1)
            try:
                shrunk = dmap_blocks(comp, edist).select(["x"])
            finally:
                _efaults.reset("device")
            _eforce(shrunk)  # warm the smaller-mesh compile
            deg = []
            while len(deg) < 10 and (time.perf_counter() - el_t0
                                     < el_budget_s or not deg):
                deg.append(_eforce(shrunk))
            elastic_secondary.update({
                "degraded_rows_per_s": round(N_ROWS / _emedian(deg), 1),
                "devices_degraded": shrunk.mesh.num_devices,
                "devices_lost":
                    _etracing.counters.get("mesh.devices_lost")
                    - lost_before,
            })
        else:
            # a 1-device mesh has no survivors to shrink to; the 8-way
            # recovery itself is proven by the tier-1 elastic lane on 8
            # virtual CPU devices — this secondary's loss half needs
            # real multi-chip (the TPU capture)
            elastic_secondary["degraded"] = (
                "skipped: single-device mesh (loss recovery needs >=2)")
    except Exception as e:  # noqa: BLE001 - headline must survive
        elastic_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): the out-of-core
    # memory subsystem (docs/memory.md). Two numbers: (1) the admission
    # gate's overhead on the hot engine path — interleaved order-flipped
    # pairs of amortized forcing batches, no-limit configuration vs a
    # LIVE never-pressured ledger; the <2% bar on the ledger cost
    # bounds the unlimited gate's a fortiori; (2) out-of-core sort
    # throughput: external dsort of a frame ~4x a configured budget
    # (budget-sized device runs + host k-way merge), reported with its
    # spill count. Wall-clock budgeted like every secondary.
    memory_secondary = None
    mem_budget_s = 30.0
    mem_t0 = time.perf_counter()
    try:
        from statistics import median as _mmedian

        from tensorframes_tpu import memory as _memory
        from tensorframes_tpu.utils.tracing import counters as _mcounters

        mdf = tft.frame({"x": np.arange(200_000, dtype=np.float64)},
                    num_partitions=8)
        MEM_BATCH = 10
        HUGE = 1 << 60  # a LIVE ledger that is never under pressure

        def _mbatch() -> float:
            t0 = time.perf_counter()
            for _ in range(MEM_BATCH):
                out = tft.map_blocks(lambda x: {"z": x + 3.0}, mdf,
                                 trim=True)
                out.blocks()
            return (time.perf_counter() - t0) / MEM_BATCH

        # "off" = explicit no-limit (active() is None, the one-global-
        # read gate); "ledger" = full admission arithmetic on every
        # dispatch with a huge budget (zero spills). The measured
        # ledger cost bounds the unlimited gate's from above — with
        # limit_bytes=0 both halves would run identical code and the
        # bar would be vacuous.
        _memory.configure(limit_bytes=HUGE)
        _mbatch()  # warm the compiles
        msamples = {"off": [], "ledger": []}
        rounds = 0
        while rounds < 40 and (time.perf_counter() - mem_t0
                               < mem_budget_s * 0.5 or rounds < 2):
            if rounds % 2:
                _memory.configure(limit_bytes=HUGE)
                msamples["ledger"].append(_mbatch())
                _memory.configure(limit_bytes=0)
                msamples["off"].append(_mbatch())
            else:
                _memory.configure(limit_bytes=0)
                msamples["off"].append(_mbatch())
                _memory.configure(limit_bytes=HUGE)
                msamples["ledger"].append(_mbatch())
            rounds += 1
        mb = 200_000 / _mmedian(msamples["off"])
        mo = 200_000 / _mmedian(msamples["ledger"])
        m_pct = (mb - mo) / mb * 100.0
        memory_secondary = {
            "unlimited_rows_per_s": round(mb, 1),
            "ledger_rows_per_s": round(mo, 1),
            "ledger_overhead_pct": round(m_pct, 2),
            "off_within_2pct": bool(m_pct < 2.0),
        }

        # out-of-core half: external dsort of a frame ~4x the budget
        if time.perf_counter() - mem_t0 < mem_budget_s * 0.8:
            rng_m = np.random.default_rng(7)
            oc_rows = 100_000  # 2 f64 columns = 1.6 MB
            oc_df = tft.frame(
                {"k": rng_m.integers(0, 10_000, oc_rows)
                 .astype(np.int64),
                 "v": rng_m.random(oc_rows)}, num_partitions=8)
            _memory.configure(limit_bytes=400_000)  # ~4x over budget
            spills0 = _mcounters.get("memory.spills")
            oc_dist = distribute(oc_df, mesh)
            t0 = time.perf_counter()
            from tensorframes_tpu.parallel.distributed import dsort
            out = dsort("k", oc_dist)
            out.collect_frame()
            oc_dt = time.perf_counter() - t0
            memory_secondary.update({
                "out_of_core_sort_rows_per_s": round(oc_rows / oc_dt, 1),
                "out_of_core_sort_spills":
                    _mcounters.get("memory.spills") - spills0,
                "external_sorts":
                    _mcounters.get("memory.external_sorts"),
                "budget_bytes": 400_000,
                "frame_bytes": oc_rows * 16,
            })
        else:
            memory_secondary["out_of_core"] = (
                "skipped: overhead half consumed the wall-clock budget")
    except Exception as e:  # noqa: BLE001 - headline must survive
        memory_secondary = {"error": str(e)[:300]}
    finally:
        try:
            from tensorframes_tpu import memory as _memory
            _memory._reset()  # back to env-resolved (unlimited) state
        except Exception:  # noqa: BLE001 - cleanup is best-effort
            pass

    # secondary metric (never costs the headline): the logical-plan
    # layer (docs/plan.md). Two numbers: (1) a 4-op row-local
    # map_blocks chain forced fused (one composed dispatch per block,
    # the TFT_FUSE default) vs TFT_FUSE=0 (the per-op path: one
    # dispatch + host round trip per op per block) — the whole chain
    # uncached between forcings so the per-op side re-runs every op,
    # best-of timings; (2) a pruned parquet read's bytes-touched
    # figure: a chain referencing 2 of 6 columns reads only those
    # columns' chunks (footer-driven), reported against the whole
    # file. Wall-clock budgeted like every secondary.
    fused_secondary = None
    fuse_budget_s = 40.0
    fuse_t0 = time.perf_counter()
    try:
        fx = np.arange(N_ROWS, dtype=np.float64)
        fdf = tft.frame({"x": fx, "w": np.ones_like(fx)},
                        num_partitions=16)
        fdf.cache()
        f1 = fdf.map_blocks(lambda x: {"a": x + 1.0})
        f2 = f1.map_blocks(lambda a: {"b": a * 2.0})
        f3 = f2.map_blocks(lambda b, w: {"c": b + w})
        f4 = f3.map_blocks(lambda c: {"d": c * 0.5})
        fchain = f4.select(["d"])
        fframes = [f1, f2, f3, f4, fchain]

        def _force_chain_best(reps: int = 5) -> float:
            for f in fframes:
                f.uncache()
            fchain.blocks()  # warm the compile caches for this mode
            t = float("inf")
            for _ in range(reps):
                if time.perf_counter() - fuse_t0 > fuse_budget_s * 0.6 \
                        and t < float("inf"):
                    break
                for f in fframes:
                    f.uncache()
                t0 = time.perf_counter()
                fchain.blocks()
                t = min(t, time.perf_counter() - t0)
            return t

        os.environ.pop("TFT_FUSE", None)
        fused_s = _force_chain_best()
        fused_plan = bool(fchain._plan_info)
        os.environ["TFT_FUSE"] = "0"
        unfused_s = _force_chain_best()
        os.environ.pop("TFT_FUSE", None)
        fused_secondary = {
            "chain_ops": 4,
            "fused_rows_per_s": round(N_ROWS / fused_s, 1),
            "unfused_rows_per_s": round(N_ROWS / unfused_s, 1),
            "speedup": round(unfused_s / fused_s, 3),
            "plan_executed": fused_plan,
        }

        # pruned-read half: bytes touched for a 2-of-6-column chain
        if time.perf_counter() - fuse_t0 < fuse_budget_s * 0.85:
            import shutil
            import tempfile

            from tensorframes_tpu import io as tio

            pdir = tempfile.mkdtemp(prefix="tft_fused_bench_")
            try:
                ppth = os.path.join(pdir, "pruned.parquet")
                pcols = {f"c{i}": np.arange(200_000, dtype=np.float64) + i
                         for i in range(6)}
                tio.write_parquet(tft.frame(pcols, num_partitions=4), ppth)
                import pyarrow.parquet as pq
                md = pq.ParquetFile(ppth).metadata
                col_sz = {}
                for g in range(md.num_row_groups):
                    rg = md.row_group(g)
                    for j in range(rg.num_columns):
                        c = rg.column(j)
                        base = c.path_in_schema.split(".", 1)[0]
                        col_sz[base] = col_sz.get(base, 0) \
                            + int(c.total_compressed_size)
                pruned = (tio.read_parquet(ppth)
                          .map_blocks(lambda c0, c1: {"s": c0 + c1})
                          .select(["s"]))
                pruned.blocks()
                touched = col_sz["c0"] + col_sz["c1"]
                fused_secondary.update({
                    "pruned_read_cols": 2,
                    "total_cols": 6,
                    "pruned_bytes_touched": touched,
                    "file_bytes": sum(col_sz.values()),
                    "pruned_fraction": round(
                        touched / max(sum(col_sz.values()), 1), 3),
                    "pruned_plan_executed": bool(pruned._plan_info),
                })
            finally:
                shutil.rmtree(pdir, ignore_errors=True)
        else:
            fused_secondary["pruned_read"] = (
                "skipped: chain half consumed the wall-clock budget")
    except Exception as e:  # noqa: BLE001 - headline must survive
        fused_secondary = {"error": str(e)[:300]}
    finally:
        os.environ.pop("TFT_FUSE", None)

    # secondary metric (never costs the headline): the DISTRIBUTED
    # logical plan (docs/plan.md, distributed fusion). A 4-op d-op
    # chain (dmap -> dfilter -> dmap -> monoid dreduce_blocks) on the
    # local mesh, recorded lazily and forced as ONE fused GSPMD
    # program, vs TFT_FUSE=0 (the per-op dispatches: 4 compiled mesh
    # dispatches + the dfilter survivor-count host readback between
    # ops). Reports speedup, mesh dispatch counts, and inter-stage
    # host-transfer bytes (the acceptance bar: >= 2x fewer dispatches,
    # ZERO fused inter-stage bytes). Wall-clock budgeted.
    dfused_secondary = None
    dfuse_budget_s = 40.0
    dfuse_t0 = time.perf_counter()
    try:
        from tensorframes_tpu.utils.tracing import counters as _dfc

        dmesh = mesh
        dN = 200_000
        ddf = tft.frame({"x": np.arange(dN, dtype=np.float64)})
        ddist = distribute(ddf, dmesh)
        from tensorframes_tpu.parallel.distributed import (dfilter,
                                                           dreduce_blocks)

        _m1 = lambda x: {"z": x * 2.0}          # noqa: E731
        _f1 = lambda z: z % 3.0 == 0.0          # noqa: E731
        _m2 = lambda z: {"w": z + 1.0}          # noqa: E731

        def _dchain(d):
            d = dmap_blocks(_m1, d)
            d = dfilter(_f1, d)
            d = dmap_blocks(_m2, d)
            return dreduce_blocks({"w": "sum"}, d)

        def _dbest(lazy: bool, reps: int = 7) -> float:
            _dchain(ddist.lazy() if lazy else ddist)  # warm compiles
            t = float("inf")
            for _ in range(reps):
                if time.perf_counter() - dfuse_t0 > dfuse_budget_s * 0.6 \
                        and t < float("inf"):
                    break
                t0 = time.perf_counter()
                _dchain(ddist.lazy() if lazy else ddist)
                t = min(t, time.perf_counter() - t0)
            return t

        os.environ.pop("TFT_FUSE", None)
        d0 = _dfc.get("mesh.dispatches")
        h0 = _dfc.get("mesh.interstage_host_bytes")
        fused_r = _dchain(ddist.lazy())
        fused_disp = _dfc.get("mesh.dispatches") - d0
        fused_host = _dfc.get("mesh.interstage_host_bytes") - h0
        d1 = _dfc.get("mesh.dispatches")
        h1 = _dfc.get("mesh.interstage_host_bytes")
        os.environ["TFT_FUSE"] = "0"
        perop_r = _dchain(ddist.lazy())   # lazy() is the identity: per-op
        perop_disp = _dfc.get("mesh.dispatches") - d1
        perop_host = _dfc.get("mesh.interstage_host_bytes") - h1
        os.environ.pop("TFT_FUSE", None)
        bit_identical = bool(np.array_equal(fused_r["w"], perop_r["w"]))

        dfused_s = _dbest(lazy=True)
        os.environ["TFT_FUSE"] = "0"
        dperop_s = _dbest(lazy=False)
        os.environ.pop("TFT_FUSE", None)
        dfused_secondary = {
            "chain_ops": 4,
            "fused_rows_per_s": round(dN / dfused_s, 1),
            "perop_rows_per_s": round(dN / dperop_s, 1),
            "speedup": round(dperop_s / dfused_s, 3),
            "fused_mesh_dispatches": int(fused_disp),
            "perop_mesh_dispatches": int(perop_disp),
            "dispatch_reduction_x": round(perop_disp / max(fused_disp, 1),
                                          2),
            "fused_interstage_host_bytes": int(fused_host),
            "perop_interstage_host_bytes": int(perop_host),
            "bit_identical_vs_fuse0": bit_identical,
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        dfused_secondary = {"error": str(e)[:300]}
    finally:
        os.environ.pop("TFT_FUSE", None)

    # secondary metric (never costs the headline): broadcast hash join
    # probe throughput (docs/joins.md) — a 64k-row build side
    # factorized + device-broadcast once, a 512k-row probe side joined
    # block by block (one fused gather dispatch per block through the
    # resilient executor). Reports probe rows/s and the dispatch count.
    # Wall-clock budgeted like every secondary.
    join_secondary = None
    join_budget_s = 30.0
    join_t0 = time.perf_counter()
    try:
        from tensorframes_tpu import relational as _rel
        from tensorframes_tpu.utils.tracing import counters as _jc

        jbuild_n, jprobe_n, jparts = 64_000, 512_000, 8
        jrng = np.random.default_rng(0)
        jright = tft.frame({
            "k": np.arange(jbuild_n, dtype=np.int64),
            "w": jrng.normal(0, 1, jbuild_n),
            "w2": jrng.normal(0, 1, jbuild_n)})
        jleft = tft.frame({
            "k": jrng.integers(0, jbuild_n, jprobe_n).astype(np.int64),
            "v": jrng.normal(0, 1, jprobe_n)}, num_partitions=jparts)
        build = _rel.BuildTable(jright, "k")

        def _force_join():
            out = _rel.broadcast_join(jleft, build=build, on="k",
                                      how="inner")
            return out.count()

        _force_join()  # warm the probe program
        jt = float("inf")
        rounds = 0
        d0 = _jc.get("relational.probe_dispatches")
        while (time.perf_counter() - join_t0 < join_budget_s * 0.8
               or rounds < 2) and rounds < 5:
            t0 = time.perf_counter()
            jrows = _force_join()
            jt = min(jt, time.perf_counter() - t0)
            rounds += 1
        join_secondary = {
            "build_rows": jbuild_n,
            "probe_rows": jprobe_n,
            "output_rows": int(jrows),
            "probe_rows_per_s": round(jprobe_n / jt, 1),
            "probe_dispatches_per_forcing":
                (_jc.get("relational.probe_dispatches") - d0) // max(
                    rounds, 1),
            "chunked": bool(build.chunks),
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        join_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): partitioned hash
    # join through the shuffle exchange (parallel/exchange.py) vs the
    # broadcast oracle — reports probe rows/s, the per-device build
    # residency (max shard vs global: the O(R/S) claim — the probe side
    # never collects onto one device), and bit-identity. Runs on
    # whatever mesh the chip mode provides (CPU: 1 device -> the
    # fallback path; TPU window: real shards). Wall-clock budgeted.
    pjoin_secondary = None
    pjoin_budget_s = 30.0
    pjoin_t0 = time.perf_counter()
    try:
        from tensorframes_tpu import relational as _rel

        pbuild_n, pprobe_n = 200_000, 400_000
        prng = np.random.default_rng(2)
        pright = tft.frame({
            "k": prng.integers(0, pbuild_n, pbuild_n).astype(np.int64),
            "w": prng.normal(0, 1, pbuild_n)})
        pleft = tft.frame({
            "k": prng.integers(0, pbuild_n, pprobe_n).astype(np.int64),
            "v": prng.normal(0, 1, pprobe_n)}, num_partitions=8)

        def _force_pjoin():
            out = _rel.partitioned_hash_join(pleft, pright, "k",
                                             how="inner", mesh=mesh)
            return out, out.count()

        pout, prows = _force_pjoin()  # warm the exchange programs
        pt = float("inf")
        rounds = 0
        while (time.perf_counter() - pjoin_t0 < pjoin_budget_s * 0.8
               or rounds < 1) and rounds < 3:
            t0 = time.perf_counter()
            pout, prows = _force_pjoin()
            pt = min(pt, time.perf_counter() - t0)
            rounds += 1
        pinfo = getattr(pout, "_partitioned_info", None) or {}
        oracle = _rel.broadcast_join(pleft, pright, "k", how="inner")
        pjoin_secondary = {
            "build_rows": pbuild_n,
            "probe_rows": pprobe_n,
            "output_rows": int(prows),
            "probe_rows_per_s": round(pprobe_n / pt, 1),
            "shards": pinfo.get("shards", 1),
            "max_shard_build_bytes": pinfo.get("max_build_bytes"),
            "global_build_bytes": pinfo.get("global_build_bytes"),
            "bit_identical_vs_broadcast":
                bool(int(prows) == int(oracle.count())),
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        pjoin_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): shuffle-partitioned
    # daggregate (high-cardinality keys) vs the dense monoid path —
    # each device holds O(groups/shards) state instead of every group.
    # Reports rows/s for both paths and result parity. Wall-clock
    # budgeted, chip-mode ready.
    sagg_secondary = None
    sagg_budget_s = 30.0
    sagg_t0 = time.perf_counter()
    try:
        from tensorframes_tpu.parallel import (daggregate as _dagg,
                                               shuffle_daggregate
                                               as _sagg)

        aN, aG = 400_000, 50_000
        arng = np.random.default_rng(3)
        adf = tft.frame({
            "k": arng.integers(0, aG, aN).astype(np.int64),
            "v": arng.integers(0, 1000, aN).astype(np.int64)})

        def _run(fn):
            t0 = time.perf_counter()
            out = fn({"v": "sum"}, distribute(adf, mesh), ["k"])
            n = sum(b.num_rows for b in out.blocks())
            return n, time.perf_counter() - t0

        _run(_sagg)  # warm
        _run(_dagg)
        ns, ts = _run(_sagg)
        nd, td = _run(_dagg)
        sagg_secondary = {
            "rows": aN,
            "groups": int(nd),
            "shuffle_rows_per_s": round(aN / ts, 1),
            "dense_rows_per_s": round(aN / td, 1),
            "same_group_count": bool(ns == nd),
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        sagg_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): approx_distinct
    # (HLL sketch, docs/joins.md) vs the EXACT distinct count computed
    # through two monoid aggregates (count per (g,item), then count per
    # g). Reports the speedup and the observed worst-group relative
    # error against the 1.04/sqrt(m) bound. Wall-clock budgeted.
    sketch_secondary = None
    sketch_budget_s = 30.0
    sketch_t0 = time.perf_counter()
    try:
        from tensorframes_tpu import relational as _rel

        sN, sG = 400_000, 8
        srng = np.random.default_rng(1)
        sdf = tft.frame({
            "g": srng.integers(0, sG, sN).astype(np.int64),
            "it": srng.integers(0, 50_000, sN).astype(np.int64),
            "one": np.ones(sN, np.int64)}, num_partitions=8)
        sk = _rel.approx_distinct(bits=12)

        def _exact():
            per_pair = tft.aggregate({"one": "sum"},
                                     sdf.group_by("g", "it"))
            ones2 = per_pair.map_blocks(
                lambda one: {"c": one * 0 + 1}).select(["g", "c"])
            return tft.aggregate({"c": "sum"}, ones2.group_by("g"))

        def _approx():
            return tft.aggregate({"it": sk},
                                 sdf.select(["g", "it"]).group_by("g"))

        exact_f = _exact()     # warm + truth
        approx_f = _approx()
        exact = {int(r[0]): int(r[1]) for r in exact_f.collect()}
        approx = {int(r[0]): int(r[1]) for r in approx_f.collect()}
        worst = max(abs(approx[g] - exact[g]) / exact[g]
                    for g in exact)
        te = ta = float("inf")
        rounds = 0
        while (time.perf_counter() - sketch_t0 < sketch_budget_s * 0.8
               or rounds < 1) and rounds < 3:
            t0 = time.perf_counter()
            _exact()
            te = min(te, time.perf_counter() - t0)
            t0 = time.perf_counter()
            _approx()
            ta = min(ta, time.perf_counter() - t0)
            rounds += 1
        sketch_secondary = {
            "rows": sN,
            "groups": sG,
            "exact_s": round(te, 4),
            "approx_s": round(ta, 4),
            "speedup": round(te / ta, 2),
            "worst_group_rel_error": round(worst, 4),
            "error_bound_1sigma": round(sk.relative_error, 4),
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        sketch_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): preemptible serving
    # (docs/serving.md) — a whale query preempted by small
    # higher-priority queries. Reports (a) the small-query worst-case
    # latency behind a running whale WITH vs WITHOUT preemption (the
    # p99 a high-priority tenant actually feels), and (b) the cost of
    # being preempted: park-at-half + checkpointed resume vs one cold
    # uninterrupted run. Wall-clock budgeted like every secondary.
    preempt_secondary = None
    preempt_budget_s = 45.0
    preempt_t0 = time.perf_counter()
    try:
        import threading as _threading

        from tensorframes_tpu.engine import preempt as _pp
        from tensorframes_tpu.resilience import QueryPreempted
        from tensorframes_tpu.serve import QueryScheduler, TenantQuota

        wN, sN = 400_000, 20_000

        def whale_frame(seed=0.0):
            return tft.frame(
                {"x": np.arange(float(wN)) + seed},
                num_partitions=32).map_rows(
                lambda x: {"y": x * 2.0}).map_rows(
                lambda y: {"z": y + 1.0})

        # -- resume overhead vs a cold re-run (engine-level) ---------
        cold = whale_frame()
        t0 = time.perf_counter()
        cold.blocks()  # also warms the compile caches
        t_cold0 = time.perf_counter() - t0
        t0 = time.perf_counter()
        whale_frame(1.0).blocks()
        t_cold = time.perf_counter() - t0  # steady-state cold run
        from tensorframes_tpu.utils.tracing import counters as _pc
        parked = whale_frame(2.0)
        sc = _pp.PreemptionScope("bench-whale")
        timer = _threading.Timer(t_cold / 2.0,
                                 sc.request_preempt, args=("bench",))
        timer.start()
        t0 = time.perf_counter()
        preempted = False
        try:
            with _pp.activate(sc):
                parked.blocks()
        except QueryPreempted:
            preempted = True
        t_park = time.perf_counter() - t0
        timer.cancel()
        # a timer that fired between the park and the cancel leaves a
        # stale preempt request that would immediately re-park the
        # resume; clear it
        sc._take_preempt()
        # counter DELTA around this resume only: the scheduler
        # latency runs below preempt/resume on their own and must not
        # inflate the engine-level figure
        resumed0 = _pc.get("pipeline.resumed_blocks")
        t0 = time.perf_counter()
        with _pp.activate(sc):
            parked.blocks()
        t_resume = time.perf_counter() - t0
        resumed_blocks = _pc.get("pipeline.resumed_blocks") - resumed0
        resume_overhead_pct = ((t_park + t_resume) / t_cold - 1.0) * 100.0

        # -- small-query latency behind a whale, with/without --------
        def small_worst_latency(preemption: bool) -> float:
            quotas = {"whale": TenantQuota(weight=1.0),
                      "vip": TenantQuota(weight=8.0)}
            name = "bench-pre" if preemption else "bench-nopre"
            worst = 0.0
            with QueryScheduler(quotas=quotas, workers=1,
                                preemption=preemption,
                                name=name) as sched:
                wq = sched.submit(whale_frame(3.0), tenant="whale")
                for _ in range(2000):
                    if wq.state != "queued":
                        break
                    time.sleep(0.001)
                left = max(10.0, preempt_budget_s
                           - (time.perf_counter() - preempt_t0))
                for k in range(4):
                    fr = tft.frame({"x": np.arange(float(sN)) + k},
                                   num_partitions=2)
                    t0 = time.perf_counter()
                    sched.submit(fr, lambda x: {"z": x + 3.0},
                                 tenant="vip").result(timeout=left)
                    worst = max(worst, time.perf_counter() - t0)
                wq.result(timeout=left)
            return worst

        os.environ["TFT_PREEMPT_AFTER_MS"] = "0"
        try:
            small_off = small_worst_latency(False)
            small_on = small_worst_latency(True)
        finally:
            os.environ.pop("TFT_PREEMPT_AFTER_MS", None)
        preempt_secondary = {
            "whale_rows": wN,
            "small_rows": sN,
            "whale_cold_s": round(t_cold, 4),
            "preempted_mid_run": bool(preempted),
            "park_plus_resume_s": round(t_park + t_resume, 4),
            "resume_overhead_pct": round(resume_overhead_pct, 1),
            "resumed_blocks": int(resumed_blocks),
            "small_worst_latency_no_preempt_s": round(small_off, 4),
            "small_worst_latency_preempt_s": round(small_on, 4),
            "small_latency_speedup": round(
                small_off / small_on, 2) if small_on > 0 else None,
            "first_run_with_compile_s": round(t_cold0, 4),
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        preempt_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): ADAPTIVE BLOCK
    # SIZING (docs/adaptive.md). A 4-op row-local chain (3 map_rows +
    # an atom filter) over a dispatch-bound 64-small-block layout;
    # adaptive sizing (feedback-gated coalesce to TFT_PIPELINE_DEPTH
    # full slots, original boundaries restored) vs TFT_ADAPTIVE=0 (one
    # dispatch chain per tiny block). Acceptance bar: >= 1.2x on the
    # CPU dev box. Wall-clock budgeted like every secondary.
    adaptive_secondary = None
    ad_budget_s = 40.0
    ad_t0 = time.perf_counter()
    try:
        from tensorframes_tpu.utils.tracing import counters as _adc

        aN = 400_000
        adf = tft.frame({"x": np.arange(aN, dtype=np.float64)},
                        num_partitions=64)
        adf.cache()
        _a1 = lambda x: {"a": x * 2.0}          # noqa: E731
        _a2 = lambda a: {"b": a + 1.0}          # noqa: E731
        _a3 = lambda b: {"c": b * 0.5}          # noqa: E731
        _ap = lambda c: c > 100.0               # noqa: E731
        ad1 = adf.map_rows(_a1)
        ad2 = ad1.map_rows(_a2)
        ad3 = ad2.map_rows(_a3)
        ad4 = ad3.filter(_ap)
        adchain = ad4.select(["c"])
        adframes = [ad1, ad2, ad3, ad4, adchain]
        os.environ["TFT_RESULT_CACHE"] = "0"  # measure layouts, not hits

        def _ad_force_best(reps: int = 5) -> float:
            for f in adframes:
                f.uncache()
            adchain.blocks()  # warm compiles + feedback for this mode
            t = float("inf")
            for _ in range(reps):
                if time.perf_counter() - ad_t0 > ad_budget_s * 0.45 \
                        and t < float("inf"):
                    break
                for f in adframes:
                    f.uncache()
                t0 = time.perf_counter()
                adchain.blocks()
                t = min(t, time.perf_counter() - t0)
            return t

        os.environ.pop("TFT_ADAPTIVE", None)
        layouts0 = _adc.get("plan.adaptive_layouts")
        adaptive_s = _ad_force_best()
        layouts_ran = _adc.get("plan.adaptive_layouts") - layouts0
        os.environ["TFT_ADAPTIVE"] = "0"
        static_s = _ad_force_best()
        os.environ.pop("TFT_ADAPTIVE", None)
        adaptive_secondary = {
            "chain_ops": 4,
            "leaf_blocks": 64,
            "adaptive_rows_per_s": round(aN / adaptive_s, 1),
            "static_rows_per_s": round(aN / static_s, 1),
            "speedup": round(static_s / adaptive_s, 3),
            "adaptive_layouts_ran": int(layouts_ran),
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        adaptive_secondary = {"error": str(e)[:300]}
    finally:
        os.environ.pop("TFT_ADAPTIVE", None)
        os.environ.pop("TFT_RESULT_CACHE", None)

    # secondary metric (never costs the headline): the PLAN-FINGERPRINT
    # RESULT CACHE (docs/adaptive.md). A repeated hot query (same
    # cached source, same canonical computations, rebuilt chain per
    # request — the dashboard shape) measured three ways: the hit
    # latency (zero block dispatches, asserted via pipeline counters),
    # the miss path with the cache ON (always-fresh fingerprints), and
    # TFT_RESULT_CACHE=0. Acceptance bar: ~0 dispatches on a hit and a
    # miss path within 2% of the off path. Wall-clock budgeted.
    rcache_secondary = None
    rc_budget_s = 30.0
    rc_t0 = time.perf_counter()
    try:
        from tensorframes_tpu.plan import adaptive as _rc_adaptive
        from tensorframes_tpu.utils.tracing import counters as _rcc

        rN = 200_000
        rdf = tft.frame({"x": np.arange(rN, dtype=np.float64)},
                        num_partitions=8)
        rdf.cache()
        _rf = lambda x: {"y": x * 2.0 + 1.0}    # noqa: E731

        def _rc_build(fn=None):
            return rdf.map_blocks(fn or _rf).select(["y"])

        _rc_adaptive.invalidate_results()
        os.environ.pop("TFT_RESULT_CACHE", None)
        _rc_build().blocks()   # seen
        _rc_build().blocks()   # interned
        d0 = _rcc.get("pipeline.submitted") + _rcc.get("pipeline.drained")
        t0 = time.perf_counter()
        hits = 0
        while time.perf_counter() - rc_t0 < rc_budget_s * 0.3 \
                or hits < 3:
            _rc_build().blocks()
            hits += 1
            if hits >= 50:
                break
        hit_s = (time.perf_counter() - t0) / hits
        hit_dispatches = (_rcc.get("pipeline.submitted")
                          + _rcc.get("pipeline.drained")) - d0

        def _force_fresh(reps: int) -> float:
            # a fresh lambda per forcing: always a new fingerprint, so
            # the cache-ON path runs its full lookup+offer overhead
            t = float("inf")
            for k in range(reps):
                fn = (lambda o: (lambda x: {"y": x * 2.0 + o}))(
                    float(k))
                t0 = time.perf_counter()
                _rc_build(fn).blocks()
                t = min(t, time.perf_counter() - t0)
            return t

        miss_on_s = _force_fresh(5)
        os.environ["TFT_RESULT_CACHE"] = "0"
        off_s = _force_fresh(5)
        os.environ.pop("TFT_RESULT_CACHE", None)
        rcache_secondary = {
            "rows": rN,
            "hit_s": round(hit_s, 6),
            "hit_block_dispatches": int(hit_dispatches),
            "hit_rows_per_s": round(rN / hit_s, 1),
            "miss_path_s": round(miss_on_s, 6),
            "off_path_s": round(off_s, 6),
            "miss_overhead_pct": round(
                (miss_on_s - off_s) / off_s * 100.0, 2)
            if off_s > 0 else None,
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        rcache_secondary = {"error": str(e)[:300]}
    finally:
        os.environ.pop("TFT_RESULT_CACHE", None)

    # secondary metric (never costs the headline): WARM RESTART of the
    # serving fabric (docs/serving.md). A parquet-backed hot query is
    # primed through a 2-worker ServeFabric until the result cache
    # persists to the durable tier; every worker is then rolling-
    # restarted (in-memory caches die with each epoch) and the same
    # query re-issued. Acceptance bar: the post-restart hit is served
    # WARM from disk with ZERO pipeline dispatches. Wall-clock
    # budgeted.
    restart_secondary = None
    rw_budget_s = 30.0
    rw_t0 = time.perf_counter()
    try:
        import tempfile as _rw_tempfile

        from tensorframes_tpu import io as _rw_io
        from tensorframes_tpu.plan import adaptive as _rw_adaptive
        from tensorframes_tpu.serve import ServeFabric as _RwFabric
        from tensorframes_tpu.utils.tracing import counters as _rwc

        rw_dir = _rw_tempfile.mkdtemp(prefix="tft-bench-restart-")
        rw_pq = os.path.join(rw_dir, "bench.parquet")
        rwN = 200_000
        _rw_io.write_parquet(
            tft.frame({"x": np.arange(rwN, dtype=np.float64)},
                      num_partitions=8), rw_pq)
        _rw_fn = lambda x: {"y": x * 2.0 + 1.0}    # noqa: E731
        _rw_adaptive.invalidate_results()
        with _RwFabric(workers=2, monitor=False, probe=False,
                       persist_dir=os.path.join(rw_dir, "persist"),
                       name="bench-rw") as rw_fab:
            rw_f = _rw_io.read_parquet(rw_pq)
            for _ in range(2):   # two-touch: second sighting persists
                rw_fab.submit(rw_f, _rw_fn,
                              tenant="bench").result(timeout=60)
            t0 = time.perf_counter()
            rw_fab.rolling_restart()
            restart_s = time.perf_counter() - t0
            d0 = (_rwc.get("pipeline.submitted")
                  + _rwc.get("pipeline.drained"))
            warm0 = _rwc.get("plan.result_cache_warm_hits")
            t0 = time.perf_counter()
            rw_fab.submit(rw_f, _rw_fn,
                          tenant="bench").result(timeout=60)
            warm_hit_s = time.perf_counter() - t0
            warm_dispatches = (_rwc.get("pipeline.submitted")
                               + _rwc.get("pipeline.drained")) - d0
            restart_secondary = {
                "rows": rwN,
                "rolling_restart_s": round(restart_s, 6),
                "warm_hit_s": round(warm_hit_s, 6),
                "warm_hit_rows_per_s": round(rwN / warm_hit_s, 1),
                "warm_hit_block_dispatches": int(warm_dispatches),
                "served_from_durable_tier": bool(
                    _rwc.get("plan.result_cache_warm_hits") == warm0
                    + 1),
                "budget_s": rw_budget_s,
                "elapsed_s": round(time.perf_counter() - rw_t0, 3),
            }
        shutil.rmtree(rw_dir, ignore_errors=True)
    except Exception as e:  # noqa: BLE001 - headline must survive
        restart_secondary = {"error": str(e)[:300]}

    # secondary metric (never costs the headline): the ALWAYS-ON flight
    # recorder + SLO accounting (docs/observability.md) on the serve
    # mixed workload. Unlike tracing (opt-in, measured off-vs-bypass),
    # the flight layer's default state IS on, so the acceptance bar is
    # the ON path within 2% of TFT_FLIGHT=0 (the bit-identical bypass)
    # — order-flipped interleaved pairs, medians, wall-clock budgeted
    # like every other secondary. The layer meets it by recording
    # DECISIONS (admit/start/finish per query), never blocks.
    flight_secondary = None
    flight_budget_s = 40.0
    flight_t0 = time.perf_counter()
    try:
        from statistics import median as _fl_median

        from tensorframes_tpu.observability import flight as _fl_mod
        from tensorframes_tpu.serve import (QueryScheduler as _FlSched,
                                            TenantQuota as _FlQuota)

        fl_sizes = {"small": 10_000, "medium": 50_000}
        fl_frames = {t: [tft.frame({"x": np.arange(float(n)) + k},
                                   num_partitions=4)
                         for k in range(4)]
                     for t, n in fl_sizes.items()}

        def _fl_round(sched) -> float:
            t0 = time.perf_counter()
            futs = [sched.submit(fr, lambda x: {"z": x + 3.0}, tenant=t)
                    for t in fl_sizes for fr in fl_frames[t]]
            for f in futs:
                f.result(timeout=60)
            return time.perf_counter() - t0

        def _fl_bypassed(sched) -> float:
            os.environ["TFT_FLIGHT"] = "0"
            try:
                return _fl_round(sched)
            finally:
                os.environ.pop("TFT_FLIGHT", None)

        rec0 = _fl_mod.stats()["recorded_total"]
        with _FlSched(quotas={t: _FlQuota(max_queue=1024)
                              for t in fl_sizes},
                      workers=2, name="flbench") as sched:
            # steady-state serving: warm the shared compile cache
            sched.submit(fl_frames["small"][0],
                         lambda x: {"z": x + 3.0},
                         tenant="small").result(timeout=60)
            fl_samples = {"on": [], "bypass": []}
            rounds = 0
            fl_pair_budget = flight_budget_s * 0.9
            while rounds < 60 and (
                    time.perf_counter() - flight_t0 < fl_pair_budget
                    or rounds < 2):
                if rounds % 2:
                    fl_samples["on"].append(_fl_round(sched))
                    fl_samples["bypass"].append(_fl_bypassed(sched))
                else:
                    fl_samples["bypass"].append(_fl_bypassed(sched))
                    fl_samples["on"].append(_fl_round(sched))
                rounds += 1
        fl_on = _fl_median(fl_samples["on"])
        fl_byp = _fl_median(fl_samples["bypass"])
        fl_pct = (fl_on - fl_byp) / fl_byp * 100.0
        flight_secondary = {
            "queries_per_round": sum(len(v) for v in fl_frames.values()),
            "rounds": rounds,
            "bypass_round_s": round(fl_byp, 6),
            "on_round_s": round(fl_on, 6),
            "always_on_overhead_pct": round(fl_pct, 2),
            "within_2pct": bool(fl_pct < 2.0),
            "decisions_recorded": _fl_mod.stats()["recorded_total"]
            - rec0,
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        flight_secondary = {"error": str(e)[:300]}
    finally:
        os.environ.pop("TFT_FLIGHT", None)

    # secondary metric (never costs the headline): the ALWAYS-ON
    # performance-regression sentinel (timeline sampling + per-query
    # cost capture + baseline folding; docs/observability.md) on the
    # same serve mixed workload, same protocol as the flight recorder
    # above: ON path within 2% of TFT_TIMELINE=0 (the bit-identical
    # bypass), order-flipped interleaved pairs, medians, wall-clock
    # budgeted. The layer meets it by doing per-QUERY work only (a
    # counter snapshot at capture, a vector + deque fold at finish),
    # never per-block.
    sentinel_secondary = None
    sent_budget_s = 40.0
    sent_t0 = time.perf_counter()
    try:
        from statistics import median as _sn_median

        from tensorframes_tpu.observability import baseline as _sn_bl
        from tensorframes_tpu.serve import (QueryScheduler as _SnSched,
                                            TenantQuota as _SnQuota)

        sn_sizes = {"small": 10_000, "medium": 50_000}
        sn_frames = {t: [tft.frame({"x": np.arange(float(n)) + k},
                                   num_partitions=4)
                         for k in range(4)]
                     for t, n in sn_sizes.items()}

        def _sn_round(sched) -> float:
            t0 = time.perf_counter()
            futs = [sched.submit(fr, lambda x: {"z": x + 3.0}, tenant=t)
                    for t in sn_sizes for fr in sn_frames[t]]
            for f in futs:
                f.result(timeout=60)
            return time.perf_counter() - t0

        def _sn_bypassed(sched) -> float:
            os.environ["TFT_TIMELINE"] = "0"
            try:
                return _sn_round(sched)
            finally:
                os.environ.pop("TFT_TIMELINE", None)

        comp0 = _sn_bl.perf_stats()["completions_total"]
        with _SnSched(quotas={t: _SnQuota(max_queue=1024)
                              for t in sn_sizes},
                      workers=2, name="snbench") as sched:
            sched.submit(sn_frames["small"][0],
                         lambda x: {"z": x + 3.0},
                         tenant="small").result(timeout=60)
            sn_samples = {"on": [], "bypass": []}
            rounds = 0
            sn_pair_budget = sent_budget_s * 0.9
            while rounds < 60 and (
                    time.perf_counter() - sent_t0 < sn_pair_budget
                    or rounds < 2):
                if rounds % 2:
                    sn_samples["on"].append(_sn_round(sched))
                    sn_samples["bypass"].append(_sn_bypassed(sched))
                else:
                    sn_samples["bypass"].append(_sn_bypassed(sched))
                    sn_samples["on"].append(_sn_round(sched))
                rounds += 1
        sn_on = _sn_median(sn_samples["on"])
        sn_byp = _sn_median(sn_samples["bypass"])
        sn_pct = (sn_on - sn_byp) / sn_byp * 100.0
        sn_stats = _sn_bl.perf_stats()
        sentinel_secondary = {
            "queries_per_round": sum(len(v) for v in sn_frames.values()),
            "rounds": rounds,
            "bypass_round_s": round(sn_byp, 6),
            "on_round_s": round(sn_on, 6),
            "always_on_overhead_pct": round(sn_pct, 2),
            "within_2pct": bool(sn_pct < 2.0),
            "completions_captured": sn_stats["completions_total"]
            - comp0,
            "baselines": sn_stats["baselines"],
            "timeline_samples": sn_stats["timeline"]["taken_total"],
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        sentinel_secondary = {"error": str(e)[:300]}
    finally:
        os.environ.pop("TFT_TIMELINE", None)

    # secondary metric (never costs the headline): the ALWAYS-ON
    # cross-cutting invariant auditors (docs/resilience.md) on the same
    # serve mixed workload, same protocol as the flight recorder above:
    # ON path within 2% of TFT_INVARIANTS=0 (the bit-identical bypass),
    # order-flipped interleaved pairs, medians, wall-clock budgeted.
    # The layer meets it by auditing only at quiesce points (query
    # finish, scheduler close) — a handful of lock-held count
    # comparisons per query, never per-block.
    invariant_secondary = None
    inv_budget_s = 40.0
    inv_t0 = time.perf_counter()
    try:
        from statistics import median as _iv_median

        from tensorframes_tpu.resilience import invariants as _iv_mod
        from tensorframes_tpu.serve import (QueryScheduler as _IvSched,
                                            TenantQuota as _IvQuota)
        from tensorframes_tpu.utils.tracing import counters as _iv_ctrs

        iv_sizes = {"small": 10_000, "medium": 50_000}
        iv_frames = {t: [tft.frame({"x": np.arange(float(n)) + k,
                                    "w": np.arange(float(n)) * 0.5},
                                   num_partitions=4)
                         for k in range(4)]
                     for t, n in iv_sizes.items()}

        def _iv_round(sched) -> float:
            t0 = time.perf_counter()
            futs = [sched.submit(fr, lambda x: {"z": x + 3.0}, tenant=t)
                    for t in iv_sizes for fr in iv_frames[t]]
            for f in futs:
                f.result(timeout=60)
            return time.perf_counter() - t0

        def _iv_bypassed(sched) -> float:
            os.environ["TFT_INVARIANTS"] = "0"
            try:
                return _iv_round(sched)
            finally:
                os.environ.pop("TFT_INVARIANTS", None)

        aud0 = _iv_ctrs.get("invariants.audits")
        vio0 = _iv_ctrs.get("invariants.violations")
        with _IvSched(quotas={t: _IvQuota(max_queue=1024)
                              for t in iv_sizes},
                      workers=2, name="invbench") as sched:
            sched.submit(iv_frames["small"][0],
                         lambda x: {"z": x + 3.0},
                         tenant="small").result(timeout=60)
            iv_samples = {"on": [], "bypass": []}
            rounds = 0
            iv_pair_budget = inv_budget_s * 0.9
            while rounds < 60 and (
                    time.perf_counter() - inv_t0 < iv_pair_budget
                    or rounds < 2):
                if rounds % 2:
                    iv_samples["on"].append(_iv_round(sched))
                    iv_samples["bypass"].append(_iv_bypassed(sched))
                else:
                    iv_samples["bypass"].append(_iv_bypassed(sched))
                    iv_samples["on"].append(_iv_round(sched))
                rounds += 1
        iv_on = _iv_median(iv_samples["on"])
        iv_byp = _iv_median(iv_samples["bypass"])
        iv_pct = (iv_on - iv_byp) / iv_byp * 100.0
        invariant_secondary = {
            "queries_per_round": sum(len(v) for v in iv_frames.values()),
            "rounds": rounds,
            "bypass_round_s": round(iv_byp, 6),
            "on_round_s": round(iv_on, 6),
            "always_on_overhead_pct": round(iv_pct, 2),
            "within_2pct": bool(iv_pct < 2.0),
            "audits": _iv_ctrs.get("invariants.audits") - aud0,
            "violations": _iv_ctrs.get("invariants.violations") - vio0,
            "auditors": len(_iv_mod._BUILTIN),
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        invariant_secondary = {"error": str(e)[:300]}
    finally:
        os.environ.pop("TFT_INVARIANTS", None)

    # secondary metric (never costs the headline): the ALWAYS-ON
    # durable query history (docs/observability.md) on the same serve
    # mixed workload, same protocol as the flight recorder above: the
    # ON path (archive armed via TFT_HISTORY_DIR) within 2% of
    # TFT_HISTORY=0 (the single-env-check bypass), order-flipped
    # interleaved pairs, medians, wall-clock budgeted. The layer meets
    # it with one json.dumps + one O_APPEND write() per QUERY at
    # finish, never per-block.
    history_secondary = None
    hist_budget_s = 40.0
    hist_t0 = time.perf_counter()
    import tempfile as _hi_tempfile
    hist_dir = _hi_tempfile.mkdtemp(prefix="tft-bench-history-")
    try:
        from statistics import median as _hi_median

        from tensorframes_tpu.observability import history as _hi_mod
        from tensorframes_tpu.serve import (QueryScheduler as _HiSched,
                                            TenantQuota as _HiQuota)

        os.environ["TFT_HISTORY_DIR"] = hist_dir
        hi_sizes = {"small": 10_000, "medium": 50_000}
        hi_frames = {t: [tft.frame({"x": np.arange(float(n)) + k},
                                   num_partitions=4)
                         for k in range(4)]
                     for t, n in hi_sizes.items()}

        def _hi_round(sched) -> float:
            t0 = time.perf_counter()
            futs = [sched.submit(fr, lambda x: {"z": x + 3.0}, tenant=t)
                    for t in hi_sizes for fr in hi_frames[t]]
            for f in futs:
                f.result(timeout=60)
            return time.perf_counter() - t0

        def _hi_bypassed(sched) -> float:
            os.environ["TFT_HISTORY"] = "0"
            try:
                return _hi_round(sched)
            finally:
                os.environ.pop("TFT_HISTORY", None)

        hrec0 = _hi_mod.stats()["records_written"]
        with _HiSched(quotas={t: _HiQuota(max_queue=1024)
                              for t in hi_sizes},
                      workers=2, name="histbench") as sched:
            sched.submit(hi_frames["small"][0],
                         lambda x: {"z": x + 3.0},
                         tenant="small").result(timeout=60)
            hi_samples = {"on": [], "bypass": []}
            rounds = 0
            hi_pair_budget = hist_budget_s * 0.9
            while rounds < 60 and (
                    time.perf_counter() - hist_t0 < hi_pair_budget
                    or rounds < 2):
                if rounds % 2:
                    hi_samples["on"].append(_hi_round(sched))
                    hi_samples["bypass"].append(_hi_bypassed(sched))
                else:
                    hi_samples["bypass"].append(_hi_bypassed(sched))
                    hi_samples["on"].append(_hi_round(sched))
                rounds += 1
        hi_on = _hi_median(hi_samples["on"])
        hi_byp = _hi_median(hi_samples["bypass"])
        hi_pct = (hi_on - hi_byp) / hi_byp * 100.0
        hi_stats = _hi_mod.stats()
        history_secondary = {
            "queries_per_round": sum(len(v) for v in hi_frames.values()),
            "rounds": rounds,
            "bypass_round_s": round(hi_byp, 6),
            "on_round_s": round(hi_on, 6),
            "always_on_overhead_pct": round(hi_pct, 2),
            "within_2pct": bool(hi_pct < 2.0),
            "records_archived": hi_stats["records_written"] - hrec0,
            "archive_bytes": hi_stats["bytes"],
        }
    except Exception as e:  # noqa: BLE001 - headline must survive
        history_secondary = {"error": str(e)[:300]}
    finally:
        os.environ.pop("TFT_HISTORY", None)
        os.environ.pop("TFT_HISTORY_DIR", None)
        shutil.rmtree(hist_dir, ignore_errors=True)

    # reference structure: Rows materialized in and out per block
    schema = df.schema
    t0 = time.perf_counter()
    for b in df.blocks():
        rows = columns_to_rows(b.columns, schema)          # convert
        mapped = [(r[0] + 3.0,) for r in rows]             # the computation
        rows_to_columns(mapped, schema)                    # convertBack
    ref = N_ROWS / (time.perf_counter() - t0)

    n_chips = max(1, len(jax.devices()))
    plat = jax.default_backend()
    rec = {
        "metric": "map_blocks_add_const_1M_rows",
        "value": round(ours / n_chips, 1),
        "unit": "rows/sec/chip",
        "vs_baseline": round(ours / ref, 2),
        "platform": plat,
        "n_chips": n_chips,
        "e2e_with_marshalling_rows_per_s": round(e2e, 1),
        "row_path_rows_per_s": round(ref, 1),
        "executor": executor,
        "pipelined_vs_serial": pipeline_secondary,
        "tracing_overhead": tracing_secondary,
        "mesh_tracing_overhead": mesh_tracing_secondary,
        "serving_mixed_workload": serving_secondary,
        "streaming_throughput": streaming_secondary,
        "elastic_degraded_mesh": elastic_secondary,
        "out_of_core_sort": memory_secondary,
        "fused_chain": fused_secondary,
        "dfused_chain": dfused_secondary,
        "broadcast_hash_join": join_secondary,
        "partitioned_hash_join": pjoin_secondary,
        "shuffle_daggregate": sagg_secondary,
        "approx_distinct": sketch_secondary,
        "preempt_resume": preempt_secondary,
        "adaptive_blocks": adaptive_secondary,
        "result_cache_hit": rcache_secondary,
        "restart_warm": restart_secondary,
        "flight_recorder_overhead": flight_secondary,
        "sentinel_overhead": sentinel_secondary,
        "invariant_overhead": invariant_secondary,
        "history_overhead": history_secondary,
    }

    if plat == "tpu":
        # secondary metrics never cost the headline: a stall/OOM here
        # (fresh 128 MB transfer + compile inside the parent's timeout)
        # must still leave rec printable
        try:
            def _steady_sec(fn, iters=30):
                """Pipelined steady state: async dispatches, one final
                block."""
                jax.block_until_ready(fn())
                t0 = time.perf_counter()
                for _ in range(iters):
                    r = fn()
                jax.block_until_ready(r)
                return (time.perf_counter() - t0) / iters

            # HBM-saturation secondary metric: the 1M-row headline is
            # dispatch-overhead-limited (4 MB arrays finish in ~10 us of
            # the ~36 us iteration); the SAME framework path (distribute
            # + dmap_blocks on a double column) at 16M rows amortizes
            # the launch. PER-CHIP numbers: on a mesh the rows shard, so
            # the aggregate divides by n_chips like the headline.
            big_df = tft.frame(
                {"x": np.arange(16_000_000, dtype=np.float64)},
                num_partitions=1)
            big_dist = distribute(big_df, mesh)
            big_sec = _steady_sec(lambda: dmap_blocks(
                comp, big_dist, trim=True).columns["z"])
            rec["map_blocks_16M_rows_per_s_chip"] = round(
                16_000_000 / big_sec / n_chips, 1)
            # double computes as f32 on TPU: 4 B read + 4 B written/row
            rec["hbm_gbps_16M_chip"] = round(
                16_000_000 * 8 / big_sec / 1e9 / n_chips, 1)

            # MXU secondary metric (the add-constant headline is
            # HBM-bound; this one exercises the matrix unit): bf16
            # 2048^3 matmul, device-resident, pipelined steady state.
            # MFU only when the generation's dense-bf16 peak is known.
            import jax.numpy as jnp

            M = 2048
            a = jax.device_put(jnp.ones((M, M), jnp.bfloat16))
            b = jax.device_put(jnp.ones((M, M), jnp.bfloat16))
            mm = jax.jit(lambda a, b: a @ b)
            mm_sec = _steady_sec(lambda: mm(a, b))
            matmul_tflops = 2 * M ** 3 / mm_sec / 1e12
            rec["matmul_bf16_tflops"] = round(matmul_tflops, 2)
            kind = jax.devices()[0].device_kind
            rec["device_kind"] = kind
            peaks = {  # dense bf16 TFLOP/s per chip, by kind substring
                "v4": 275.0, "v5 lite": 197.0, "v5e": 197.0,
                "v5p": 459.0, "v5": 459.0, "v6 lite": 918.0, "v6e": 918.0,
            }
            peak = next((v for k, v in peaks.items()
                         if k in kind.lower()), None)
            if peak is not None:
                rec["matmul_mfu"] = round(matmul_tflops / peak, 4)
        except Exception as e:  # noqa: BLE001 - headline must survive
            rec["secondary_error"] = str(e)[:300]

    # ROADMAP item 2 (TPU validation): every figure names the silicon
    # it ran on. The headline AND each dict-valued secondary carry
    # platform / device_kind / chip_mode, so a CPU-fallback secondary
    # quoted in isolation can never pass for chip numbers.
    try:
        kind = jax.devices()[0].device_kind
    except Exception:  # noqa: BLE001 - headline must survive
        kind = "unknown"
    chip_mode = "tpu" if plat == "tpu" else "cpu-fallback"
    rec["device_kind"] = kind
    rec["chip_mode"] = chip_mode
    for sec in rec.values():
        if isinstance(sec, dict):
            sec.setdefault("platform", plat)
            sec.setdefault("device_kind", kind)
            sec.setdefault("chip_mode", chip_mode)
    print(json.dumps(rec))


# --------------------------------------------------------------------------
# parent: orchestrate attempts, guarantee one JSON line
# --------------------------------------------------------------------------

def _attempt(platform: str, timeout_s: int):
    """Run the child; return (record|None, error string|None).

    The child runs in its own process group; on timeout the whole group
    gets SIGKILL and the parent waits only a bounded grace period — a child
    stuck in an uninterruptible TPU-driver syscall (wedged grant) must not
    keep the parent from its CPU fallback and final JSON line.
    """
    import os
    import signal

    proc = subprocess.Popen(
        [sys.executable, __file__, "--child", platform],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    try:
        stdout, stderr = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        # SIGTERM first: a SIGKILLed PJRT client never releases the
        # tunnel's server-side session lease and the grant wedges for the
        # rest of the round (observed r2/r3). Grace period, then KILL.
        for sig, grace in ((signal.SIGTERM, 15), (signal.SIGKILL, 10)):
            try:
                os.killpg(proc.pid, sig)
            except (ProcessLookupError, PermissionError):
                pass  # group already exited — still reap + drain pipes below
            try:
                proc.communicate(timeout=grace)
                break
            except subprocess.TimeoutExpired:
                continue  # escalate; if still unreapable (D state), move on
        return None, f"{platform}: timed out after {timeout_s}s"
    if proc.returncode != 0:
        tail = (stderr or "").strip().splitlines()[-1:] or ["no output"]
        return None, f"{platform}: rc={proc.returncode} ({tail[0][:300]})"
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            rec = json.loads(line)
            if isinstance(rec, dict) and "value" in rec:
                return rec, None
        except json.JSONDecodeError:
            continue
    return None, f"{platform}: produced no JSON line"


def _probe_tpu() -> "str | None":
    """Cheap liveness probe; returns None if healthy, else the reason.

    A wedged tunnel grant hangs (never errors), so before committing the
    full TPU_TIMEOUT_S budget we spend at most PROBE_TIMEOUT_S on a
    one-element dispatch in a throwaway subprocess (TERM-first kill, same
    rationale as _attempt — a SIGKILLed PJRT client wedges the lease).
    """
    # the tunnelled grant reports platform 'axon' (the proxy plugin) or
    # 'tpu' depending on the layer answering — accept both, like
    # run_chip_suite.sh's probe
    code = ("import jax; d = jax.devices(); "
            "import jax.numpy as jnp; "
            "print((jnp.ones(()) + 1).item(), d[0].platform)")
    proc = subprocess.Popen(
        [sys.executable, "-c", code],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        start_new_session=True)
    import signal
    try:
        stdout, _ = proc.communicate(timeout=PROBE_TIMEOUT_S)
    except subprocess.TimeoutExpired:
        for sig, grace in ((signal.SIGTERM, 10), (signal.SIGKILL, 5)):
            try:
                os.killpg(proc.pid, sig)
            except (ProcessLookupError, PermissionError):
                pass
            try:
                proc.communicate(timeout=grace)
                break
            except subprocess.TimeoutExpired:
                continue
        return f"probe timed out after {PROBE_TIMEOUT_S}s (grant wedged?)"
    if proc.returncode != 0:
        return f"probe rc={proc.returncode}"
    if not any(p in (stdout or "") for p in ("tpu", "axon")):
        return f"probe saw no tpu device ({(stdout or '').strip()[:80]})"
    return None


def _last_tpu_evidence() -> "dict | None":
    """Freshest chip-certified headline from benchmarks/chip_results.jsonl.

    When the grant is down at driver time, the round's real chip state
    lives in the suite log written while a grant was live; surface it in
    the one JSON line instead of silently under-reporting (round-3 weak
    #1). Capture time = the log's mtime (records carry ``captured_at``
    only from round 4 on).
    """
    try:
        best = None
        with open(CHIP_RESULTS) as f:
            for line in f:
                try:
                    r = json.loads(line)
                except json.JSONDecodeError:
                    continue
                if (r.get("platform") in ("tpu", "axon")
                        and "error" not in r
                        and r.get("metric") == "map_blocks_add_const_1M_rows"):
                    best = r  # later lines are fresher appends
        if best is None:
            return None
        out = {k: best[k] for k in
               ("metric", "value", "unit", "vs_baseline", "n_chips")
               if k in best}
        out["captured_at"] = best.get("captured_at") or time.strftime(
            "%Y-%m-%dT%H:%M:%SZ",
            time.gmtime(os.path.getmtime(CHIP_RESULTS)))
        return out
    except OSError:
        return None


def main() -> int:
    errors = []
    probe_fail = _probe_tpu()
    if probe_fail is None:
        rec, err = _attempt("tpu", TPU_TIMEOUT_S)
        if rec is None:
            errors.append(err)
    else:
        rec = None
        errors.append(f"tpu skipped: {probe_fail}")
    if rec is None:
        rec, err = _attempt("cpu", CPU_TIMEOUT_S)
        if rec is not None:
            rec["error"] = f"tpu attempt failed, cpu fallback ({errors[0]})"
    if rec is None:
        errors.append(err)
        rec = {
            "metric": "map_blocks_add_const_1M_rows",
            "value": 0.0,
            "unit": "rows/sec/chip",
            "vs_baseline": 0.0,
            "platform": "none",
            "error": "; ".join(errors),
        }
    if rec.get("platform") != "tpu":
        last = _last_tpu_evidence()
        if last is not None:
            rec["last_tpu"] = last
    print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        _child(sys.argv[2])
    else:
        sys.exit(main())
