"""Shared benchmark-script plumbing.

Kept as a thin alias so every benchmark keeps its historical import path;
the real helper lives in :mod:`tensorframes_tpu.utils.platform` (demos
need it too — see that module's docstring for why the env var alone is
not enough in this image).
"""

from __future__ import annotations

from tensorframes_tpu.utils.platform import force_cpu_if_requested

__all__ = ["force_cpu_if_requested"]
