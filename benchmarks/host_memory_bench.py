"""Peak host-memory bench for the merge paths (round-3 weak #5).

The reference streamed partitions through its UDAF shuffle and never held
the whole dataset in one buffer; this framework's host ``aggregate`` and
``order_by`` used to ``Block.concat`` the frame (~3x column bytes of HOST
copies at peak). After the round-4 blockwise rewrite, the ASSERTED
contract is on the HOST-side allocations the rewrite governs
(``tracemalloc`` peak — numpy reports through it; XLA's device buffers
and program temporaries do NOT, correctly: on a TPU host those live in
HBM, and on this CPU-backend measurement they would conflate the
device's scratch with the host data path):

    aggregate: host allocations beyond the resident input frame
               < 1x the frame's column bytes  (total < 2x, input incl.)
    order_by:  < 2x (its RESULT is a full reordered copy of the frame,
               so ~1x of that extra is the output itself)

``ru_maxrss`` (which does include XLA CPU temps) is reported alongside,
uncapped, for transparency. Each case runs in its own subprocess
(``ru_maxrss`` is a cumulative high-water mark). One JSON line per case;
nonzero exit if an assertion fails. Usage::

    python benchmarks/host_memory_bench.py [rows] [groups]
"""

import json
import resource
import subprocess
import sys
import tracemalloc

_is_child = len(sys.argv) >= 3 and sys.argv[1] == "--child"
ROWS = int(sys.argv[1]) if len(sys.argv) > 1 and not _is_child \
    else 10_000_000
GROUPS = int(sys.argv[2]) if len(sys.argv) > 2 and not _is_child \
    else 100_000

_CASES = ("aggregate_monoid", "aggregate_generic", "order_by")


def _child(case: str) -> None:
    import os

    import numpy as np

    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    # host-memory measurement: always CPU (this image's sitecustomize
    # registers the tunnelled TPU; the env var alone is not enough)
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp  # noqa: F401  (warm the import before rss0)
    import tensorframes_tpu as tft

    rng = np.random.default_rng(0)
    key = rng.integers(0, GROUPS, ROWS).astype(np.int64)
    x = rng.normal(size=ROWS)
    column_bytes = key.nbytes + x.nbytes
    df = tft.frame({"key": key, "x": x}, num_partitions=8)
    df.cache()
    df.count()  # materialize the blocks
    del key, x
    rss0_kb = resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
    tracemalloc.start()

    if case == "aggregate_monoid":
        out = tft.aggregate({"x": "sum"}, df.group_by("key"))
        out.count()
    elif case == "aggregate_generic":
        out = tft.aggregate(
            lambda x_input: {"x": x_input.sum(axis=0)},
            df.group_by("key"))
        out.count()
    elif case == "order_by":
        df.order_by("x").count()
    else:
        raise SystemExit(f"unknown case {case}")

    host_peak = tracemalloc.get_traced_memory()[1]
    tracemalloc.stop()
    rss_extra = (resource.getrusage(resource.RUSAGE_SELF).ru_maxrss
                 - rss0_kb) * 1024
    cap = 2.0 if case == "order_by" else 1.0
    rec = {
        "metric": f"host_memory_{case}",
        "rows": ROWS,
        "groups": GROUPS,
        "column_bytes": column_bytes,
        "host_alloc_peak_bytes": host_peak,
        "host_alloc_over_column_bytes": round(host_peak / column_bytes, 3),
        "rss_extra_bytes_incl_xla_temps": rss_extra,
        "asserted_cap": cap,
        "ok": bool(host_peak < cap * column_bytes),
    }
    print(json.dumps(rec), flush=True)
    if not rec["ok"]:
        raise SystemExit(1)


def main() -> int:
    rc = 0
    for case in _CASES:
        proc = subprocess.run(
            [sys.executable, __file__, "--child", case,
             str(ROWS), str(GROUPS)],
            capture_output=True, text=True, timeout=1200)
        out = [ln for ln in proc.stdout.splitlines() if ln.startswith("{")]
        print(out[-1] if out else json.dumps(
            {"metric": f"host_memory_{case}", "error":
             (proc.stderr or "no output")[-300:]}))
        rc |= proc.returncode
    return rc


if __name__ == "__main__":
    if len(sys.argv) >= 3 and sys.argv[1] == "--child":
        case = sys.argv[2]
        ROWS = int(sys.argv[3]) if len(sys.argv) > 3 else ROWS
        GROUPS = int(sys.argv[4]) if len(sys.argv) > 4 else GROUPS
        _child(case)
    else:
        sys.exit(main())
