"""Performance harnesses.

The reference ships benchmark *infrastructure* but publishes no numbers
(BASELINE.md): three self-timed ScalaTest suites, all ``ignore``d —
marshalling micro-benchmarks (``perf/ConvertPerformanceSuite.scala``,
``perf/ConvertBackPerformanceSuite.scala``) and an end-to-end map+agg run
(``perf/PerformanceSuite.scala``). This package is the TPU build's
equivalent, plus the five BASELINE.md target configs. Each module exposes
``run() -> list[dict]`` returning one record per metric; ``run_all.py``
prints them as JSON lines.
"""
