"""Sequence-parallel (ring) attention scaling — the long-context leg.

The reference predates attention; long context is first-class here
(SURVEY.md §5), so this bench gives the claim a measurable artifact:
exact ring attention (``parallel/ring.py``) over a sequence sharded
across the mesh vs single-device full attention at the same total
sequence, for growing sequence lengths.

Two signals:

- numerics: the ring result matches full attention (online-softmax
  exactness) at every size;
- memory scaling: ring peak per-device activation is O(S/n) — lengths
  whose full [S, S] score matrix would blow past a single device still
  run (the bench reports the score-matrix bytes the full path needs vs
  the ring's per-hop block).

Measured on the 8-virtual-CPU mesh the ring is also ~1.8× FASTER by
wall-clock at every size (its (S/n)² blocks stay cache-sized where the
full path streams the whole [S, S] matrix) — but the memory bound is
the point; per-device work per hop is what shrinks on silicon. Emits
one JSON line per sequence length.

Run:  python benchmarks/ring_bench.py [max_log2_seq] [devices]
"""

from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)

if __name__ == "__main__":
    _want = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    os.environ.setdefault(
        "XLA_FLAGS",
        f"--xla_force_host_platform_device_count={max(8, _want)}")
    os.environ["JAX_PLATFORMS"] = "cpu"  # image exports JAX_PLATFORMS=axon

import jax  # noqa: E402

from benchmarks._platform import force_cpu_if_requested  # noqa: E402


def bench(fn, iters=5):
    jax.block_until_ready(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn()
    jax.block_until_ready(r)
    return (time.perf_counter() - t0) / iters


def main(max_log2_seq: int = 13, n_dev: int = 8):
    import jax.numpy as jnp
    import numpy as np

    from tensorframes_tpu import parallel as par
    from tensorframes_tpu.parallel.ring import ring_attention

    mesh = par.local_mesh(n_dev)
    n_dev = mesh.num_data_shards  # report what actually ran: local_mesh
    # truncates to the visible devices, and ring_block_mb derives from it
    B, H, D = 1, 4, 64
    key = jax.random.PRNGKey(0)
    plat = jax.devices()[0].platform

    from jax.sharding import NamedSharding, PartitionSpec as P

    seq_sh = NamedSharding(mesh.mesh, P(None, mesh.data_axis))

    for log2 in range(10, max_log2_seq + 1):
        S = 1 << log2
        kq, kk, kv = jax.random.split(key, 3)
        shape = (B, S, H, D)
        q = jax.random.normal(kq, shape, jnp.float32)
        k = jax.random.normal(kk, shape, jnp.float32)
        v = jax.random.normal(kv, shape, jnp.float32)
        qs, ks, vs = (jax.device_put(a, seq_sh) for a in (q, k, v))

        ring_fn = jax.jit(
            lambda q, k, v: ring_attention(q, k, v, mesh, causal=True))
        ring_s = bench(lambda: ring_fn(qs, ks, vs))

        def full_causal(q, k, v, S=S):
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(D)
            mask = jnp.tril(jnp.ones((S, S), bool))
            s = jnp.where(mask[None, None], s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v)

        full_fn = jax.jit(full_causal)
        full_s = bench(lambda: full_fn(q, k, v))

        got = np.asarray(ring_fn(qs, ks, vs))
        want = np.asarray(full_fn(q, k, v))
        max_err = float(np.abs(got - want).max())
        assert max_err < 5e-5, max_err

        print(json.dumps({
            "seq": S, "devices": n_dev, "platform": plat,
            "ring_s": ring_s, "full_s": full_s,
            "max_abs_err": max_err,
            "full_scores_mb": B * H * S * S * 4 / 2 ** 20,
            "ring_block_mb": B * H * (S // n_dev) ** 2 * 4 / 2 ** 20,
        }))


if __name__ == "__main__":
    force_cpu_if_requested()
    m = int(sys.argv[1]) if len(sys.argv) > 1 else 13
    d = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    main(m, d)
