"""Run every benchmark suite; one JSON line per metric on stdout.

``python -m benchmarks.run_all [--light]`` — ``--light`` scales the row
counts down ~100x for a fast correctness pass (the sizes the reference's
suites used are kept as the defaults). ``bench.py`` at the repo root stays
the driver's single headline metric; this is the full sweep behind
BASELINE.md.
"""

from __future__ import annotations

import json
import sys


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    light = "--light" in argv

    from ._platform import force_cpu_if_requested

    force_cpu_if_requested()

    from . import baseline_configs, e2e_bench, marshal_bench

    records = []
    if light:
        records += marshal_bench.run_ragged(n_rows=10_000, iters=2)
        records += marshal_bench.run(n_scalar=100_000, n_vector=100_000,
                                     iters=2)
        records += e2e_bench.run(n_rows=200_000, iters=2)
        records += baseline_configs.run(heavy=False)
    else:
        records += marshal_bench.run_ragged()
        records += marshal_bench.run()
        records += e2e_bench.run()
        records += baseline_configs.run()
    for rec in records:
        print(json.dumps(rec))
    return 0


if __name__ == "__main__":
    sys.exit(main())
