"""Per-config BASELINE runner for the real chip: prints one JSON line per
config AS IT COMPLETES (a timeout loses only the configs after it, unlike
``run_all`` which buffers), and adds an MFU estimate for the MXU-heavy
configs using XLA's own cost model.

MFU convention: ``flops`` is XLA's ``cost_analysis()`` estimate for the
jitted program (analytic, pre-fusion), wall is the measured steady-state
iteration, peak is the chip's dense bf16 MXU rate (v5e/v5litepod:
1.97e14 FLOP/s) — f32 matmuls execute on the MXU through bf16-pass
decomposition, so this is the honest denominator on this part.

Usage:  python benchmarks/run_tpu_baselines.py [1 2 3 4 5]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5E_PEAK_FLOPS = 1.97e14  # dense bf16, one v5e chip


def _emit(rec):
    print(json.dumps(rec), flush=True)


def _mfu(flops_per_iter: float, sec_per_iter: float) -> float:
    return flops_per_iter / sec_per_iter / V5E_PEAK_FLOPS


def _compile_with_flops(fn, *args):
    """Compile ``fn`` ONCE; return (compiled executable, cost-model FLOPs).

    The compiled object serves both the cost analysis and the timed calls —
    compiling twice would double the slowest, most failure-prone step
    (ResNet-50's remote_compile has broken the tunnel relay mid-read).
    Returns ``(None, 0.0)`` if the compile itself fails, so the caller can
    still emit its end-to-end measurement without the MFU fields.
    """
    import jax

    try:
        comp = jax.jit(fn).lower(*args).compile()
    except Exception:
        return None, 0.0
    try:
        ca = comp.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        flops = float(ca.get("flops", 0.0)) if ca else 0.0
    except Exception:
        flops = 0.0
    return comp, flops


def _steady_state(compiled, *args, iters: int = 20):
    """Pipelined steady-state s/call of a pre-compiled executable on
    device-resident inputs: ``iters`` async dispatches, one
    ``block_until_ready`` at the end. Overlapping dispatches amortize the
    per-dispatch relay RTT (~0.5 s through this environment's tunnel), so
    this measures sustained device throughput — the right wall for MFU —
    NOT single-call latency (configs report the end-to-end per-call
    figure separately). Inputs stay in HBM: no marshalling, re-trace, or
    re-compile in the loop.
    """
    import jax

    args = jax.device_put(args)
    jax.block_until_ready(compiled(*args))  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        out = compiled(*args)
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters


def config4_resnet_mfu(batch: int = 32, image: int = 224,
                       iters: int = 5):
    """ResNet-50 batch inference + MFU (BASELINE config 4).

    Two numbers: the via-frame end-to-end path (map_blocks + marshalling
    each call), and the device-resident steady-state apply — MFU uses the
    latter, which is what the chip itself sustains.
    """
    import jax
    import numpy as np

    import tensorframes_tpu as tft
    from tensorframes_tpu.models.resnet import ResNet50

    model = ResNet50(num_classes=1000)
    params = model.init()
    imgs = np.random.default_rng(1).normal(
        size=(batch, image, image, 3)).astype(np.float32)
    df = tft.analyze(tft.frame({"image": imgs}))
    df.cache()

    def go():
        out = model.infer_via_frame(params, df, image_col="image")
        return out.blocks()

    go()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        blocks = go()
    sec = (time.perf_counter() - t0) / iters
    assert blocks[0].dense("logits").shape == (batch, 1000)

    rec = {"metric": "resnet50_infer", "value": sec, "unit": "s/batch",
           "images": batch, "images_per_s": batch / sec,
           "platform": jax.default_backend()}
    # STAGED device-resident path: six per-stage compiles instead of one
    # ResNet-sized module — the single-module remote_compile has broken
    # the tunnel relay mid-response (r3); the chain's composition equals
    # apply(), so FLOPs and MFU are the same math
    compiled_stages = []
    flops = 0.0
    x = jax.device_put(imgs)
    params_dev = jax.device_put(params)
    ok = True
    for i, f in enumerate(model.stage_fns()):
        comp, fl = _compile_with_flops(f, params_dev, x)
        if comp is None:
            ok = False
            break
        compiled_stages.append(comp)
        flops += fl
        x = comp(params_dev, x)  # doubles as the warmup pass
    if ok:
        jax.block_until_ready(x)

        def chain(p, a):
            for comp in compiled_stages:
                a = comp(p, a)
            return a

        dev_sec = _steady_state(chain, params_dev, imgs)
        rec.update(
            device_resident_s_per_batch=dev_sec,
            device_resident_images_per_s=batch / dev_sec,
            flops_per_batch=flops,
            staged_compiles=len(compiled_stages),
            mfu=round(_mfu(flops, dev_sec), 4) if flops else None)
    return rec


def config5_logreg_mfu(n: int = 262_144, d: int = 64, iters: int = 5):
    """Logreg gradient step + MFU (BASELINE config 5).

    Same two-number convention as config 4: via-frame end-to-end, plus
    device-resident steady-state grads (the MFU numerator's wall)."""
    import jax
    import numpy as np

    import tensorframes_tpu as tft
    from tensorframes_tpu.models.logreg import LogisticRegression

    rng = np.random.default_rng(2)
    w_true = rng.normal(size=d)
    x = rng.normal(size=(n, d))
    y = (x @ w_true + rng.normal(0, 0.1, n) > 0).astype(np.float64)
    df = tft.analyze(tft.frame({"features": x, "label": y},
                               num_partitions=8))
    df.cache()
    model = LogisticRegression(num_features=d)
    params = model.init()

    def go():
        return model.gradient_via_frame(params, df)

    go()
    t0 = time.perf_counter()
    for _ in range(iters):
        go()
    sec = (time.perf_counter() - t0) / iters

    xb = x.astype(np.float32)
    yb = y.astype(np.float32)
    rec = {"metric": "logreg_grad_step", "value": sec, "unit": "s/step",
           "rows": n, "rows_per_s": n / sec,
           "platform": jax.default_backend()}
    compiled, flops = _compile_with_flops(
        lambda p, xx, yy: model.grads(p, xx, yy), params, xb, yb)
    if compiled is not None:
        dev_sec = _steady_state(compiled, params, xb, yb)
        rec.update(
            device_resident_s_per_step=dev_sec,
            device_resident_rows_per_s=n / dev_sec,
            flops_per_step=flops,
            mfu=round(_mfu(flops, dev_sec), 6) if flops else None)
    return rec


def config2_with_device_resident(n: int = 100_000, width: int = 16):
    """Config 2 (reduce_sum/min) + the mesh collective-reduce rate.

    The base config times the full op path (build + marshal + reduce +
    collect) per call; through the tunnelled relay that is dominated by
    dispatch RTTs. The extra fields time the mesh reduce with the column
    already living in HBM — one compiled collective program per
    iteration, but each iteration still ends in the reduce contract's
    one-cell driver collect, so through the relay the figure includes one
    host round-trip (it is labelled ``collective_path_*``, not
    device-resident, for exactly that reason).
    """
    import jax
    import numpy as np

    import tensorframes_tpu as tft
    from benchmarks import baseline_configs as bc
    from tensorframes_tpu.parallel import distributed as par
    from tensorframes_tpu.parallel.mesh import local_mesh

    rec = bc.config2_reduce_vector(n, width)

    data = np.random.default_rng(0).normal(size=(n, width))
    df = tft.analyze(tft.frame({"x": data}, num_partitions=4))
    dist = par.distribute(df, local_mesh())

    def go():
        # the mapping form takes the monoid ICI-collective path (one
        # psum-tree shard_map program) — the BASELINE north-star path
        return par.dreduce_blocks({"x": "sum"}, dist)

    go()  # compile + warm
    iters = 10
    t0 = time.perf_counter()
    for _ in range(iters):
        out = go()
    dev_sec = (time.perf_counter() - t0) / iters
    np.testing.assert_allclose(out["x"], data.sum(0), rtol=1e-3)
    rec["collective_path_s_per_reduce"] = dev_sec
    rec["collective_path_rows_per_s"] = n / dev_sec
    return rec


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    which = [int(a) for a in argv] or [1, 2, 3, 4, 5]

    import jax

    from benchmarks._platform import force_cpu_if_requested

    force_cpu_if_requested()
    from benchmarks import baseline_configs as bc

    plat = jax.default_backend()
    runners = {
        1: bc.config1_readme_x_plus_3,
        2: config2_with_device_resident,
        3: bc.config3_dsl_map,
        4: config4_resnet_mfu,
        5: config5_logreg_mfu,
    }
    rc = 0
    for i in which:
        try:
            rec = runners[i]()
            rec.setdefault("platform", plat)
            rec["config"] = i
            _emit(rec)
        except Exception as e:  # keep going; a failed config is a line too
            _emit({"config": i, "error": f"{type(e).__name__}: {e}"[:300],
                   "platform": plat})
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
