"""Per-config BASELINE runner for the real chip: prints one JSON line per
config AS IT COMPLETES (a timeout loses only the configs after it, unlike
``run_all`` which buffers), and adds an MFU estimate for the MXU-heavy
configs using XLA's own cost model.

MFU convention: ``flops`` is XLA's ``cost_analysis()`` estimate for the
jitted program (analytic, pre-fusion), wall is the measured steady-state
iteration, peak is the chip's dense bf16 MXU rate (v5e/v5litepod:
1.97e14 FLOP/s) — f32 matmuls execute on the MXU through bf16-pass
decomposition, so this is the honest denominator on this part.

Usage:  python benchmarks/run_tpu_baselines.py [1 2 3 4 5]
"""

from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

V5E_PEAK_FLOPS = 1.97e14  # dense bf16, one v5e chip


def _emit(rec):
    print(json.dumps(rec), flush=True)


def _mfu(flops_per_iter: float, sec_per_iter: float) -> float:
    return flops_per_iter / sec_per_iter / V5E_PEAK_FLOPS


def _jit_flops(fn, *args) -> float:
    """XLA cost-model FLOPs for one call of the jitted fn."""
    import jax

    try:
        comp = jax.jit(fn).lower(*args).compile()
        ca = comp.cost_analysis()
        if isinstance(ca, list):
            ca = ca[0]
        return float(ca.get("flops", 0.0))
    except Exception:
        return 0.0


def config4_resnet_mfu(batch: int = 32, image: int = 224,
                       iters: int = 5):
    """ResNet-50 batch inference + MFU (BASELINE config 4)."""
    import jax
    import numpy as np

    import tensorframes_tpu as tft
    from tensorframes_tpu.models.resnet import ResNet50

    model = ResNet50(num_classes=1000)
    params = model.init()
    imgs = np.random.default_rng(1).normal(
        size=(batch, image, image, 3)).astype(np.float32)
    df = tft.analyze(tft.frame({"image": imgs}))
    df.cache()

    def go():
        out = model.infer_via_frame(params, df, image_col="image")
        return out.blocks()

    go()  # compile
    t0 = time.perf_counter()
    for _ in range(iters):
        blocks = go()
    sec = (time.perf_counter() - t0) / iters
    assert blocks[0].dense("logits").shape == (batch, 1000)

    flops = _jit_flops(lambda p, x: model.apply(p, x), params, imgs)
    return {"metric": "resnet50_infer", "value": sec, "unit": "s/batch",
            "images": batch, "images_per_s": batch / sec,
            "flops_per_batch": flops,
            "mfu": round(_mfu(flops, sec), 4) if flops else None,
            "platform": jax.default_backend()}


def config5_logreg_mfu(n: int = 262_144, d: int = 64, iters: int = 5):
    """Logreg gradient step + MFU (BASELINE config 5)."""
    import jax
    import numpy as np

    import tensorframes_tpu as tft
    from tensorframes_tpu.models.logreg import LogisticRegression

    rng = np.random.default_rng(2)
    w_true = rng.normal(size=d)
    x = rng.normal(size=(n, d))
    y = (x @ w_true + rng.normal(0, 0.1, n) > 0).astype(np.float64)
    df = tft.analyze(tft.frame({"features": x, "label": y},
                               num_partitions=8))
    df.cache()
    model = LogisticRegression(num_features=d)
    params = model.init()

    def go():
        return model.gradient_via_frame(params, df)

    go()
    t0 = time.perf_counter()
    for _ in range(iters):
        go()
    sec = (time.perf_counter() - t0) / iters

    xb = x.astype(np.float32)
    yb = y.astype(np.float32)
    flops = _jit_flops(lambda p, xx, yy: model.grads(p, xx, yy),
                       params, xb, yb)
    return {"metric": "logreg_grad_step", "value": sec, "unit": "s/step",
            "rows": n, "rows_per_s": n / sec,
            "flops_per_step": flops,
            "mfu": round(_mfu(flops, sec), 6) if flops else None,
            "platform": jax.default_backend()}


def main(argv=None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    which = [int(a) for a in argv] or [1, 2, 3, 4, 5]

    from benchmarks import baseline_configs as bc
    import jax

    plat = jax.default_backend()
    runners = {
        1: bc.config1_readme_x_plus_3,
        2: bc.config2_reduce_vector,
        3: bc.config3_dsl_map,
        4: config4_resnet_mfu,
        5: config5_logreg_mfu,
    }
    rc = 0
    for i in which:
        try:
            rec = runners[i]()
            rec.setdefault("platform", plat)
            rec["config"] = i
            _emit(rec)
        except Exception as e:  # keep going; a failed config is a line too
            _emit({"config": i, "error": f"{type(e).__name__}: {e}"[:300],
                   "platform": plat})
            rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
