"""End-to-end map+aggregate benchmark — the reference's PerformanceSuite.

``perf/PerformanceSuite.scala:14-26`` (ignored in CI): ``mapBlocks(z = x+x)``
followed by ``agg(sum(z))`` over a 20M-row DataFrame, 10 iterations. Here the
same pipeline runs twice:

 - ``host`` path: blocks marshalled host->device each call (the honest
   analogue of the reference's executor loop);
 - ``device`` path: the frame ``distribute``d once, map + collective reduce
   as compiled XLA dispatches (what the TPU-native design buys).
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu.engine import ops as engine_ops

N_ROWS = 20_000_000
ITERS = 5


def run(n_rows: int = N_ROWS, iters: int = ITERS) -> List[Dict]:
    import jax

    out: List[Dict] = []
    x = np.arange(n_rows, dtype=np.float64)
    df = tft.frame({"x": x}, num_partitions=8)
    df.cache()

    def host_pipeline():
        df2 = tft.map_blocks(lambda x: {"z": x + x}, df)
        return engine_ops.reduce_blocks(
            lambda z_input: {"z": z_input.sum(0)}, df2.select(["z"]))

    r = host_pipeline()  # warm + correctness
    expected = float(x.sum() * 2.0)
    # double computes as f32 on TPU: tolerance covers the representation loss
    assert abs(float(r["z"]) - expected) / expected < 1e-5
    t0 = time.perf_counter()
    for _ in range(iters):
        host_pipeline()
    sec = (time.perf_counter() - t0) / iters
    out.append({"metric": "e2e_map_agg_host", "value": sec, "unit": "s/iter",
                "rows": n_rows, "rows_per_s": n_rows / sec})

    from tensorframes_tpu.parallel.distributed import (distribute,
                                                       dmap_blocks,
                                                       dreduce_blocks)
    from tensorframes_tpu.parallel.mesh import local_mesh

    dist = distribute(df, local_mesh())
    from tensorframes_tpu.computation import Computation, TensorSpec
    from tensorframes_tpu import dtypes as _dt
    from tensorframes_tpu.shape import Shape, Unknown

    comp = Computation.trace(lambda x: {"z": x + x},
                             [TensorSpec("x", _dt.double, Shape(Unknown))])

    def device_pipeline():
        d2 = dmap_blocks(comp, dist, trim=True)
        return dreduce_blocks({"z": "sum"}, d2)

    r = device_pipeline()
    got = float(np.asarray(r["z"]))
    assert abs(got - expected) / expected < 1e-5, (got, expected)
    t0 = time.perf_counter()
    for _ in range(iters):
        jax.block_until_ready(device_pipeline()["z"])
    sec = (time.perf_counter() - t0) / iters
    out.append({"metric": "e2e_map_agg_device", "value": sec,
                "unit": "s/iter", "rows": n_rows, "rows_per_s": n_rows / sec})
    return out


if __name__ == "__main__":
    import json

    for rec in run():
        print(json.dumps(rec))
