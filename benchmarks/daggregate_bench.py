"""daggregate at scale: 1M rows x 100k groups (VERDICT round-2 weak #5/#8).

Measures the mesh keyed-aggregation path at a group count where the
reference's driver-side groupBy (and our host key-factorization path) is
dominated by key transfer + host sort, and compares the device-side key
path (``max_groups=``), where keys never leave the mesh.

Prints one JSON line per variant. Runs on whatever backend is live
(8-virtual-CPU mesh for relative numbers; the real chip for BASELINE.md).

Run:  [JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8]
      python benchmarks/daggregate_bench.py [n_rows] [n_groups]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    from benchmarks._platform import force_cpu_if_requested

    force_cpu_if_requested()

    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    n_groups = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000

    import tensorframes_tpu as tft
    from tensorframes_tpu import parallel as par

    rng = np.random.default_rng(7)
    # int (device-exact) keys: long would narrow to i32 with x64 off
    key = rng.integers(0, n_groups, n_rows).astype(np.int32)
    x = rng.standard_normal(n_rows)
    df = tft.frame({"k": key, "x": x})
    mesh = par.local_mesh()
    dist = par.distribute(df, mesh)
    platform = jax.devices()[0].platform

    def timed(fn, iters=3, cold=False, frame=None):
        """cold=True clears the frame's factorization memo per call, so
        the figure includes the key transfer/sort; warm measures the
        steady state an iterative workload sees (ids cached per frame)."""
        fn()  # compile/warm
        t0 = time.perf_counter()
        for _ in range(iters):
            if cold:
                (frame or dist)._group_ids_cache.clear()
            r = fn()
        return (time.perf_counter() - t0) / iters, r

    host = lambda: par.daggregate({"x": "sum"}, dist, "k")  # noqa: E731
    dev = lambda: par.daggregate(  # noqa: E731
        {"x": "sum"}, dist, "k", max_groups=n_groups + 8)
    sec_host_c, out_h = timed(host, cold=True)
    sec_host_w, _ = timed(host)
    sec_dev_c, out_d = timed(dev, cold=True)
    sec_dev_w, _ = timed(dev)

    # parity spot-check between the two paths
    h = {r["k"]: r["x"] for r in out_h.collect()}
    d = {r["k"]: r["x"] for r in out_d.collect()}
    assert set(h) == set(d)
    some = list(h)[:100]
    for k in some:
        assert np.isclose(h[k], d[k], rtol=1e-9), k

    results = [("host_keys", sec_host_c), ("host_keys_warm", sec_host_w),
               ("device_keys", sec_dev_c), ("device_keys_warm", sec_dev_w)]

    # composite device-side keys (mixed-radix combination): cap bound is
    # (cap+1)^2 < 2^31, so only measured at compatible group counts.
    # k2 is a function of k, so the PAIR count stays n_groups and the two
    # paths measure the same group structure
    if (n_groups + 9) ** 2 < 2 ** 31 - 1:  # radix = cap+1 must fit squared
        k2 = (key % 4).astype(np.int32)
        df2 = tft.frame({"k": key, "k2": k2, "x": x})
        dist2 = par.distribute(df2, mesh)
        sec_mk, out_mk = timed(
            lambda: par.daggregate({"x": "sum"}, dist2, ["k", "k2"],
                                   max_groups=n_groups + 8),
            iters=2, cold=True, frame=dist2)
        assert out_mk.count() == len(h)
        results.append(("multikey_device", sec_mk))

    for name, sec in results:
        print(json.dumps({
            "metric": f"daggregate_sum_{n_rows}x{n_groups}_{name}",
            "value": round(sec, 4), "unit": "s/call",
            "rows_per_s": round(n_rows / sec, 1),
            "platform": platform,
            "n_shards": mesh.num_data_shards,
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
