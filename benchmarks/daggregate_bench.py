"""daggregate at scale: 1M rows x 100k groups (VERDICT round-2 weak #5/#8).

Measures the mesh keyed-aggregation path at a group count where the
reference's driver-side groupBy (and our host key-factorization path) is
dominated by key transfer + host sort, and compares the device-side key
path (``max_groups=``), where keys never leave the mesh.

Prints one JSON line per variant. Runs on whatever backend is live
(8-virtual-CPU mesh for relative numbers; the real chip for BASELINE.md).

Run:  [JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8]
      python benchmarks/daggregate_bench.py [n_rows] [n_groups]
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    from benchmarks._platform import force_cpu_if_requested

    force_cpu_if_requested()

    n_rows = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    n_groups = int(sys.argv[2]) if len(sys.argv) > 2 else 100_000

    import tensorframes_tpu as tft
    from tensorframes_tpu import parallel as par

    rng = np.random.default_rng(7)
    # int (device-exact) keys: long would narrow to i32 with x64 off
    key = rng.integers(0, n_groups, n_rows).astype(np.int32)
    x = rng.standard_normal(n_rows)
    df = tft.frame({"k": key, "x": x})
    mesh = par.local_mesh()
    dist = par.distribute(df, mesh)
    platform = jax.devices()[0].platform

    def timed(fn, iters=3):
        fn()  # compile/warm
        t0 = time.perf_counter()
        for _ in range(iters):
            r = fn()
        return (time.perf_counter() - t0) / iters, r

    sec_host, out_h = timed(
        lambda: par.daggregate({"x": "sum"}, dist, "k"))
    sec_dev, out_d = timed(
        lambda: par.daggregate({"x": "sum"}, dist, "k",
                               max_groups=n_groups + 8))

    # parity spot-check between the two paths
    h = {r["k"]: r["x"] for r in out_h.collect()}
    d = {r["k"]: r["x"] for r in out_d.collect()}
    assert set(h) == set(d)
    some = list(h)[:100]
    for k in some:
        assert np.isclose(h[k], d[k], rtol=1e-9), k

    for name, sec in (("host_keys", sec_host), ("device_keys", sec_dev)):
        print(json.dumps({
            "metric": f"daggregate_sum_{n_rows}x{n_groups}_{name}",
            "value": round(sec, 4), "unit": "s/call",
            "rows_per_s": round(n_rows / sec, 1),
            "platform": platform,
            "n_shards": mesh.num_data_shards,
        }), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
