"""On-chip proof of the Pallas (Mosaic) kernels.

CPU tests run these kernels with ``interpret=True`` — that checks the
math, not the Mosaic compilation path. This script compiles and runs both
custom kernels on the real TPU and asserts parity with their XLA
fallbacks:

  * ``segment_sum(impl="pallas")`` — the one-hot-matmul map-side partial
    reduction kernel (MXU);
  * ``flash_attention(impl="pallas")`` — the blocked online-softmax
    attention kernel (MXU + VMEM accumulators).

Prints one JSON line of evidence for BASELINE.md.

Run:  python benchmarks/tpu_pallas_smoke.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    from benchmarks._platform import force_cpu_if_requested

    force_cpu_if_requested()
    import jax.numpy as jnp

    from tensorframes_tpu.ops.flash_attention import flash_attention
    from tensorframes_tpu.ops.segment_reduce import segment_sum

    platform = jax.devices()[0].platform
    if platform not in ("tpu", "axon"):
        print(json.dumps({"ok": False,
                          "reason": f"no TPU (platform={platform})"}))
        return 1

    rng = np.random.default_rng(0)

    v = rng.standard_normal((4096, 16)).astype(np.float32)
    ids = rng.integers(0, 64, 4096).astype(np.int32)
    seg_p = segment_sum(v, ids, 64, impl="pallas")
    seg_x = segment_sum(v, ids, 64, impl="xla")
    seg_diff = float(jnp.max(jnp.abs(seg_p - seg_x)))
    seg_ok = seg_diff < 1e-3

    q = rng.standard_normal((2, 4, 512, 64)).astype(np.float32)
    k = rng.standard_normal((2, 4, 512, 64)).astype(np.float32)
    vv = rng.standard_normal((2, 4, 512, 64)).astype(np.float32)
    fa_p = flash_attention(q, k, vv, impl="pallas")
    fa_x = flash_attention(q, k, vv, impl="xla")
    fa_diff = float(jnp.max(jnp.abs(fa_p - fa_x)))
    fa_ok = fa_diff < 5e-2  # MXU bf16 passes vs full-softmax reference

    rec = {
        "ok": bool(seg_ok and fa_ok),
        "platform": platform,
        "segment_sum_pallas_max_diff": seg_diff,
        "flash_attention_pallas_max_diff": fa_diff,
        "mosaic_compiled": True,  # impl="pallas" → interpret=False
    }
    print(json.dumps(rec))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
