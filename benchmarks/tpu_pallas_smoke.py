"""On-chip proof of the Pallas (Mosaic) kernels.

CPU tests run these kernels with ``interpret=True`` — that checks the
math, not the Mosaic compilation path. This script compiles and runs both
custom kernels on the real TPU and asserts parity with their XLA
fallbacks:

  * ``segment_sum(impl="pallas")`` — the one-hot-matmul map-side partial
    reduction kernel (MXU);
  * ``flash_attention(impl="pallas")`` — the blocked online-softmax
    attention kernel (MXU + VMEM accumulators).

Prints one JSON line of evidence for BASELINE.md.

Run:  python benchmarks/tpu_pallas_smoke.py
"""

from __future__ import annotations

import json
import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    from benchmarks._platform import force_cpu_if_requested

    force_cpu_if_requested()
    import jax.numpy as jnp

    from tensorframes_tpu.ops.flash_attention import flash_attention
    from tensorframes_tpu.ops.segment_reduce import segment_sum

    platform = jax.devices()[0].platform
    if platform not in ("tpu", "axon"):
        print(json.dumps({"ok": False,
                          "reason": f"no TPU (platform={platform})"}))
        return 1

    rng = np.random.default_rng(0)

    v = rng.standard_normal((4096, 16)).astype(np.float32)
    ids = rng.integers(0, 64, 4096).astype(np.int32)
    seg_p = segment_sum(v, ids, 64, impl="pallas")
    seg_x = segment_sum(v, ids, 64, impl="xla")
    seg_diff = float(jnp.max(jnp.abs(seg_p - seg_x)))
    seg_ok = seg_diff < 1e-3

    q = rng.standard_normal((2, 4, 512, 64)).astype(np.float32)
    k = rng.standard_normal((2, 4, 512, 64)).astype(np.float32)
    vv = rng.standard_normal((2, 4, 512, 64)).astype(np.float32)
    fa_p = flash_attention(q, k, vv, impl="pallas")
    fa_x = flash_attention(q, k, vv, impl="xla")
    fa_diff = float(jnp.max(jnp.abs(fa_p - fa_x)))
    fa_ok = fa_diff < 5e-2  # MXU bf16 passes vs full-softmax reference

    # Mosaic kernel traced INSIDE shard_map(check_vma=True): the exact
    # combination daggregate runs per shard (regression: pallas_call's
    # out_shape must declare the varying mesh axes or tracing fails)
    from jax.sharding import Mesh, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()), ("shards",))
    n_dev = len(jax.devices())
    v2 = rng.standard_normal((512 * n_dev, 8)).astype(np.float32)
    ids2 = rng.integers(0, 16, 512 * n_dev).astype(np.int32)
    shard_fn = jax.shard_map(
        lambda vv_, ii_: segment_sum(vv_, ii_, 16, impl="pallas"),
        mesh=mesh, in_specs=(P("shards"), P("shards")),
        out_specs=P("shards"), check_vma=True)
    sm_out = np.asarray(jax.jit(shard_fn)(v2, ids2))
    sm_sum = sm_out.reshape(n_dev, 16, 8).sum(axis=0)
    sm_ref = np.asarray(segment_sum(v2, ids2, 16, impl="xla"))
    sm_diff = float(np.max(np.abs(sm_sum - sm_ref)))
    sm_ok = sm_diff < 1e-3

    rec = {
        "ok": bool(seg_ok and fa_ok and sm_ok),
        "platform": platform,
        "segment_sum_pallas_max_diff": seg_diff,
        "flash_attention_pallas_max_diff": fa_diff,
        "segment_sum_in_shard_map_max_diff": sm_diff,
        "mosaic_compiled": True,  # impl="pallas" → interpret=False
    }
    print(json.dumps(rec))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
