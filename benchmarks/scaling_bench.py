"""1→N device scaling efficiency (BASELINE target metric).

Runs the headline device-resident workload (``dmap_blocks`` add-constant,
one compiled dispatch per iteration) and the collective reduce
(``dreduce_blocks`` sum) on meshes of 1, 2, 4 and 8 devices, each in its
own subprocess (``xla_force_host_platform_device_count`` must be set
before backend init), and reports per-mesh throughput + parallel
efficiency vs the 1-device run.

Only one real TPU chip exists in this environment, so the sweep uses the
8-virtual-CPU mesh — it validates the SHARDING path's scaling behavior
(the programs are the same ones a v5e-8 would run), not silicon speed;
BASELINE.md flags it as such.

Run:  python benchmarks/scaling_bench.py
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)

_CHILD = r"""
import json, sys, time
import jax
jax.config.update("jax_platforms", "cpu")
import numpy as np
sys.path.insert(0, {root!r})
import tensorframes_tpu as tft
from tensorframes_tpu import parallel as par

n_dev = int(sys.argv[1])
N = 1_000_000
df = tft.frame({{"x": np.arange(N, dtype=np.float64)}})
mesh = par.local_mesh(n_dev)
dist = par.distribute(df, mesh)

def bench(fn, iters=10):
    fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn()
    cols = getattr(r, "columns", None)
    if isinstance(cols, dict):          # DistributedFrame: async device work
        jax.block_until_ready(list(cols.values()))
    elif cols is None:                  # plain dict of arrays (dreduce)
        jax.block_until_ready(r)
    # host TensorFrame results (daggregate) are already materialized
    return (time.perf_counter() - t0) / iters

map_sec = bench(lambda: par.dmap_blocks(
    lambda x: {{"z": x + 3.0}}, dist, trim=True))
red_sec = bench(lambda: par.dreduce_blocks({{"x": "sum"}}, dist))
flt_sec = bench(lambda: par.dfilter(lambda x: x % 2.0 < 1.0, dist))
srt_sec = bench(lambda: par.dsort("x", dist, descending=True))

# keyed aggregation: 10k groups over the same rows (host-factorized ids
# are memoized per frame, so this measures the segment-reduce + psum)
keys = (np.arange(N) % 10_000).astype(np.int32)  # device-exact key dtype
kdist = par.distribute(tft.frame({{"k": keys,
                                   "x": np.arange(N, dtype=np.float64)}}),
                       mesh)
agg_sec = bench(lambda: par.daggregate({{"x": "sum"}}, kdist, "k"), iters=5)
print(json.dumps({{"n_dev": n_dev,
                   "map_rows_per_s": N / map_sec,
                   "reduce_rows_per_s": N / red_sec,
                   "filter_rows_per_s": N / flt_sec,
                   "sort_rows_per_s": N / srt_sec,
                   "aggregate_rows_per_s": N / agg_sec}}))
"""


def main() -> int:
    child = _CHILD.format(root=ROOT)
    results = []
    for n in (1, 2, 4, 8):
        env = dict(os.environ)
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "")
                            + f" --xla_force_host_platform_device_count={n}"
                            ).strip()
        env["JAX_PLATFORMS"] = "cpu"
        proc = subprocess.run([sys.executable, "-c", child, str(n)],
                              capture_output=True, text=True, env=env,
                              timeout=420)
        if proc.returncode != 0:
            print(json.dumps({"n_dev": n, "error":
                              proc.stderr.strip()[-300:]}), flush=True)
            return 1
        results.append(json.loads(proc.stdout.strip().splitlines()[-1]))

    base = results[0]
    for r in results:
        n = r["n_dev"]
        rec = {
            "metric": f"scaling_{n}dev",
            "map_rows_per_s": round(r["map_rows_per_s"], 1),
            "reduce_rows_per_s": round(r["reduce_rows_per_s"], 1),
            "filter_rows_per_s": round(r["filter_rows_per_s"], 1),
            "sort_rows_per_s": round(r["sort_rows_per_s"], 1),
            "aggregate_rows_per_s": round(r["aggregate_rows_per_s"], 1),
            "map_efficiency": round(
                r["map_rows_per_s"] / (n * base["map_rows_per_s"]), 3),
            "reduce_efficiency": round(
                r["reduce_rows_per_s"] / (n * base["reduce_rows_per_s"]),
                3),
            "platform": "cpu-virtual",
        }
        print(json.dumps(rec), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
