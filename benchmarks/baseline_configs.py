"""The five BASELINE.md target configs, measured end to end.

1. README "x + 3" map_blocks on a 10-row double frame (latency config —
   measures per-call overhead, reference ``README.md:56-87``);
2. reduce_sum / reduce_min over a vector column after ``analyze``
   (``README.md:92-124``);
3. DSL mapBlocks add-constant on a 1M-row frame (``README.md:154-172``) —
   also the headline ``bench.py`` metric;
4. ResNet-50 batch inference over an image-tensor column via map_blocks;
5. logistic-regression gradient step: per-block grads via map_blocks +
   reduce_blocks allreduce, with the mesh path when >1 device is visible.

Each returns rows/sec (or steps/sec) plus wall seconds.
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

import tensorframes_tpu as tft
from tensorframes_tpu import dsl
from tensorframes_tpu.engine import ops as engine_ops

ITERS = 10


def _timed(fn, iters=ITERS):
    fn()
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn()
    return (time.perf_counter() - t0) / iters, r


def config1_readme_x_plus_3() -> Dict:
    df = tft.frame([(float(i),) for i in range(10)], columns=["x"])
    df.cache()

    def go():
        return tft.map_blocks(lambda x: {"z": x + 3.0}, df).collect()

    sec, rows = _timed(go)
    assert [r["z"] for r in rows] == [i + 3.0 for i in range(10)]
    return {"metric": "readme_x_plus_3", "value": sec, "unit": "s/call",
            "rows": 10}


def config2_reduce_vector(n: int = 100_000, width: int = 16) -> Dict:
    import jax.numpy as jnp

    data = np.random.default_rng(0).normal(size=(n, width))
    df = tft.analyze(tft.frame({"x": data}, num_partitions=4))
    df.cache()

    def go():
        s = engine_ops.reduce_blocks(
            lambda x_input: {"x": x_input.sum(0)}, df)
        m = engine_ops.reduce_rows(
            lambda x_1, x_2: {"x": jnp.minimum(x_1, x_2)}, df)
        return s, m

    sec, (s, m) = _timed(go)
    np.testing.assert_allclose(s["x"], data.sum(0), rtol=1e-3)
    np.testing.assert_allclose(m["x"], data.min(0), rtol=1e-5)
    return {"metric": "reduce_sum_min_vector", "value": sec,
            "unit": "s/call", "rows": n, "rows_per_s": n / sec}


def config3_dsl_map(n: int = 1_000_000) -> Dict:
    df = tft.frame({"x": np.arange(n, dtype=np.float64)})
    df.cache()

    def go():
        with dsl.with_graph():
            x = tft.block(df, "x")
            z = (x + 3.0).named("z")
            out = tft.map_blocks(z, df, trim=True)
            out.blocks()
        return out

    sec, _ = _timed(go)
    return {"metric": "dsl_map_blocks_1m", "value": sec, "unit": "s/call",
            "rows": n, "rows_per_s": n / sec}


def config4_resnet_inference(batch: int = 32, image: int = 224,
                             iters: int = 3) -> Dict:
    """Frozen-model batch inference over an image-tensor column."""
    from tensorframes_tpu.models.resnet import ResNet50

    model = ResNet50(num_classes=1000)
    params = model.init()
    imgs = np.random.default_rng(1).normal(
        size=(batch, image, image, 3)).astype(np.float32)
    df = tft.analyze(tft.frame({"image": imgs}))
    df.cache()

    def go():
        out = model.infer_via_frame(params, df, image_col="image")
        return out.blocks()

    sec, blocks = _timed(go, iters)
    assert blocks[0].dense("logits").shape == (batch, 1000)
    return {"metric": "resnet50_infer", "value": sec, "unit": "s/batch",
            "images": batch, "images_per_s": batch / sec}


def config5_logreg_step(n: int = 262_144, d: int = 64) -> Dict:
    """One SGD step: map_blocks per-block grads + reduce_blocks combine;
    the v5e-8 config of BASELINE.md runs the same step over the mesh."""
    from tensorframes_tpu.models.logreg import LogisticRegression

    rng = np.random.default_rng(2)
    w_true = rng.normal(size=d)
    x = rng.normal(size=(n, d))
    y = (x @ w_true + rng.normal(0, 0.1, n) > 0).astype(np.float64)
    df = tft.analyze(tft.frame({"features": x, "label": y},
                               num_partitions=8))
    df.cache()
    model = LogisticRegression(num_features=d)
    params = model.init()

    def go():
        return model.gradient_via_frame(params, df)

    sec, grads = _timed(go, 5)
    return {"metric": "logreg_grad_step", "value": sec, "unit": "s/step",
            "rows": n, "rows_per_s": n / sec}


def run(heavy: bool = True) -> List[Dict]:
    out = [config1_readme_x_plus_3(), config2_reduce_vector(),
           config3_dsl_map()]
    if heavy:
        out.append(config4_resnet_inference())
        out.append(config5_logreg_step())
    return out


if __name__ == "__main__":
    import json

    for rec in run():
        print(json.dumps(rec))
