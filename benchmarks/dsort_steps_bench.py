"""Columnsort per-step cost breakdown (the "4 rounds" model, measured).

The distributed sort is ONE compiled program (4 fused local sorts + 2
``all_to_all`` reshuffles + 2 ``ppermute`` half-block shifts —
``parallel/distributed.py::_dsort_columnsort``), so host spans cannot
time the rounds from outside. This bench measures each primitive at the
EXACT shapes the pipeline uses — a fused multi-key ``lax.sort`` of the
per-shard rows, one all_to_all round, one half-block ppermute — plus the
full ``dsort``, and checks the additive cost model

    full  ≈  4 × local_sort + 2 × all_to_all + 2 × ppermute

On the shared-core virtual mesh the sorts serialize onto one CPU, which
is exactly why 8-shard throughput sits near 1/4 of the 1-shard local
sort (BASELINE.md's scaling table); on real chips the rounds run on S
chips in parallel. Emits one JSON line per step.

Run:  python benchmarks/dsort_steps_bench.py [rows] [devices]
"""

from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)

if __name__ == "__main__":
    _argv_devices = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    if _argv_devices > 1:
        # a multi-device sweep needs the virtual CPU mesh (the TPU grant
        # is one chip, and this image exports JAX_PLATFORMS=axon): force
        # cpu unconditionally; the helper below applies it post-import
        # too. A 1-device run keeps the live platform so the chip suite
        # can time the fused local sort on silicon.
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

from benchmarks._platform import force_cpu_if_requested  # noqa: E402


def _block(r):
    # DistributedFrame is not a pytree: block on its column arrays
    cols = getattr(r, "columns", None)
    jax.block_until_ready(list(cols.values())
                          if isinstance(cols, dict) else r)


def bench(fn, iters=20):
    _block(fn())
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn()
    _block(r)
    return (time.perf_counter() - t0) / iters


def main(n_rows: int = 1_000_000, n_dev: int = 8):
    import jax.numpy as jnp
    import numpy as np
    from tensorframes_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    import tensorframes_tpu as tft
    from tensorframes_tpu import parallel as par

    mesh = par.local_mesh(n_dev)
    axis = mesh.data_axis
    S = mesh.num_data_shards
    rng = np.random.default_rng(5)
    x = rng.normal(size=n_rows)
    dist = par.distribute(tft.frame({"x": x}), mesh)

    # the pipeline's internal per-shard row count (distributed.py:634-638)
    padded = dist.padded_rows
    r = padded // S
    need = max(r, 2 * (S - 1) * (S - 1))
    rp = ((need + 2 * S - 1) // (2 * S)) * (2 * S)
    h = rp // 2

    key = jnp.asarray(rng.normal(size=S * rp))
    flag = jnp.zeros(S * rp, jnp.int8)
    rowid = jnp.arange(S * rp, dtype=jnp.int32)
    sharded1 = mesh.row_sharding(1)
    key, flag, rowid = (jax.device_put(a, sharded1)
                        for a in (key, flag, rowid))

    spec = (P(axis), P(axis), P(axis))

    def local_sort(flag, key, rowid):
        # the colsort round: ONE fused lexicographic sort + payload gather
        m = flag.shape[0]
        ops = (flag, key, rowid, jnp.arange(m, dtype=rowid.dtype))
        s = jax.lax.sort(ops, num_keys=3)
        return s[0], s[1], s[2]

    def a2a_round(flag, key, rowid):
        def deal(a):
            a2 = a.reshape((rp // S, S) + a.shape[1:]).swapaxes(0, 1)
            a2 = jax.lax.all_to_all(a2, axis, 0, 0, tiled=False)
            return a2.reshape((rp,) + a.shape[1:])
        return deal(flag), deal(key), deal(rowid)

    def perm_round(flag, key, rowid):
        fwd = [(j, j + 1) for j in range(S - 1)]

        def shift(a):
            return jnp.concatenate(
                [jax.lax.ppermute(a[h:], axis, fwd), a[:h]])
        return shift(flag), shift(key), shift(rowid)

    def smap(f):
        return jax.jit(shard_map(f, mesh=mesh.mesh, in_specs=spec,
                                 out_specs=spec))

    steps = {
        "local_sort": smap(local_sort),
        "all_to_all": smap(a2a_round),
        "ppermute_shift": smap(perm_round),
    }
    out = {}
    for name, fn in steps.items():
        out[name] = bench(lambda fn=fn: fn(flag, key, rowid))
        print(json.dumps({"step": name, "s_per_call": out[name],
                          "per_shard_rows": rp, "devices": S}))

    full = bench(lambda: par.dsort("x", dist, descending=True), iters=5)
    model = 4 * out["local_sort"] + 2 * out["all_to_all"] \
        + 2 * out["ppermute_shift"]
    print(json.dumps({
        "step": "full_dsort", "s_per_call": full, "rows": n_rows,
        "devices": S, "model_s": model,
        "model_ratio": full / model if model else None,
        "rows_per_s": n_rows / full,
        "platform": jax.devices()[0].platform,
    }))
    return out, full, model


if __name__ == "__main__":
    force_cpu_if_requested()
    n = int(sys.argv[1]) if len(sys.argv) > 1 else 1_000_000
    d = int(sys.argv[2]) if len(sys.argv) > 2 else 8
    main(n, d)
