"""On-chip proof of the native C++ PJRT execution core.

The reference's production path was the native runtime — every graph ran
through libtensorflow C++ sessions (``TensorFlowOps.scala:46-64``); a
Python stand-in was not an option there and is not the end state here.
This script executes the engine through ``PjrtBlockExecutor`` against the
real TPU (the axon PJRT plugin) and asserts allclose parity with the
in-process jax path *on the same chip*:

  1. ``map_blocks`` add-constant (the README workload) — elementwise;
  2. a matmul-heavy two-layer computation — exercises the MXU through the
     native core, not just HBM traffic;
  3. ``reduce_blocks`` sum — the eager reduce path.

Prints one JSON line with platform/executor evidence for BASELINE.md.

Run:  python benchmarks/tpu_native_smoke.py
"""

from __future__ import annotations

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main() -> int:
    import jax

    from benchmarks._platform import force_cpu_if_requested

    force_cpu_if_requested()
    import jax.numpy as jnp

    import tensorframes_tpu as tft
    from tensorframes_tpu.engine import ops as engine_ops
    from tensorframes_tpu.engine.executor import BlockExecutor
    from tensorframes_tpu.native_pjrt import PjrtBlockExecutor, available

    platform = jax.devices()[0].platform
    if not available():
        print(json.dumps({"ok": False, "reason": "libtfrpjrt.so missing"}))
        return 1

    backend = "axon" if platform in ("tpu", "axon") else "cpu"
    native = PjrtBlockExecutor(backend=backend)
    jax_ex = BlockExecutor()
    rng = np.random.default_rng(0)

    # 1. README add-constant through the full engine path.
    x = rng.standard_normal(100_000).astype(np.float32)
    df = tft.frame({"x": x})
    def col(frame, name):
        return np.concatenate([b.dense(name) for b in frame.blocks()])

    z_native = col(engine_ops.map_blocks(lambda x: {"z": x + 3.0}, df,
                                         executor=native), "z")
    z_jax = col(engine_ops.map_blocks(lambda x: {"z": x + 3.0}, df,
                                      executor=jax_ex), "z")
    map_ok = np.allclose(z_native, z_jax, rtol=1e-6, atol=1e-6)

    # 2. Matmul-heavy: two dense layers, contraction dims sized for the MXU.
    b, d, h = 512, 512, 512
    inp = rng.standard_normal((b, d)).astype(np.float32)
    w1 = (rng.standard_normal((d, h)) / np.sqrt(d)).astype(np.float32)
    w2 = (rng.standard_normal((h, d)) / np.sqrt(h)).astype(np.float32)
    df2 = tft.frame({"img": inp})

    def mlp(img):
        return {"y": jnp.maximum(img @ w1, 0.0) @ w2}

    t0 = time.perf_counter()
    y_native = col(engine_ops.map_blocks(mlp, df2, executor=native), "y")
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    y_jax = col(engine_ops.map_blocks(mlp, df2, executor=jax_ex), "y")
    t_jax = time.perf_counter() - t0
    mm_diff = float(np.max(np.abs(y_native - y_jax)))
    mm_ok = mm_diff < 2e-2

    # 3. reduce_blocks sum (eager).
    import jax.numpy as _jnp
    r_native = engine_ops.reduce_blocks(
        lambda x_input: {"x": _jnp.sum(x_input, axis=0)}, df,
        executor=native)
    r_jax = engine_ops.reduce_blocks(
        lambda x_input: {"x": _jnp.sum(x_input, axis=0)}, df,
        executor=jax_ex)
    red_ok = np.allclose(r_native["x"], r_jax["x"], rtol=1e-4)

    rec = {
        "ok": bool(map_ok and mm_ok and red_ok),
        "jax_platform": platform,
        "native_platform": native.client.platform,
        "native_backend": native.client.backend.split("?")[0],
        "map_blocks_parity": bool(map_ok),
        "matmul_parity": bool(mm_ok),
        "reduce_parity": bool(red_ok),
        "matmul_max_abs_diff": mm_diff,
        "native_wall_s": round(t_native, 4),
        "jax_wall_s": round(t_jax, 4),
        "native_compiles": native.compile_count,
    }
    print(json.dumps(rec))
    return 0 if rec["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
