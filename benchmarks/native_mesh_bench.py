"""Native vs jax mesh dispatch overhead, and the resident-loop win.

Measures the SAME sharded program (per-shard elementwise step + a psum
collective, loop-state signature) three ways at 1/2/4/8 virtual devices:

- ``jax``: jitted ``shard_map`` with jax Arrays (device-resident — the
  framework's default dispatch);
- ``native_marshalled``: ``NativeMeshExecutor.run_sharded`` per call —
  the correctness-proof path that splits/uploads and downloads/assembles
  host numpy on EVERY dispatch (``native_mesh.py`` module docstring);
- ``native_resident``: ``NativeMeshExecutor.run_sharded_loop`` — shards
  upload once, outputs feed back as device buffers
  (``tfr_pjrt_buffer``), one final download.

The gap between the last two IS the per-dispatch host-marshalling cost;
the gap between ``native_resident`` and ``jax`` is the remaining C-ABI
dispatch overhead. Emits one JSON line per (devices, path).

Run:  python benchmarks/native_mesh_bench.py [rows] [iters]
      python benchmarks/native_mesh_bench.py [rows] [iters] --chip
        (chip mode: 1-device mesh on the LIVE platform, native executor
        against the axon PJRT plugin — the HBM-resident native loop on
        silicon; wired into run_chip_suite.sh)
"""

from __future__ import annotations

import json
import os
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
ROOT = os.path.dirname(HERE)
sys.path.insert(0, ROOT)

CHIP = "--chip" in sys.argv
# a user-supplied mesh backend is the explicit stand-in escape hatch for
# testing chip mode off-silicon (e.g. TFT_PJRT_MESH_BACKEND=cpu:1)
CHIP_BACKEND_OVERRIDDEN = "TFT_PJRT_MESH_BACKEND" in os.environ

if __name__ == "__main__":
    if not CHIP:
        os.environ.setdefault(
            "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
        # image exports JAX_PLATFORMS=axon
        os.environ["JAX_PLATFORMS"] = "cpu"
    else:
        os.environ.setdefault("TFT_PJRT_MESH_BACKEND", "axon")
    os.environ["TFT_EXECUTOR"] = "pjrt"

import jax  # noqa: E402

from benchmarks._platform import force_cpu_if_requested  # noqa: E402


def main(n_rows: int = 1_000_000, iters: int = 20, dev_counts=(1, 2, 4, 8)):
    import jax.numpy as jnp
    import numpy as np
    from tensorframes_tpu.utils.compat import shard_map
    from jax.sharding import PartitionSpec as P

    from tensorframes_tpu import parallel as par
    from tensorframes_tpu.parallel import native_mesh

    x_host = np.arange(n_rows, dtype=np.float32) / n_rows
    plat = jax.devices()[0].platform  # stamped on every line: chip-mode
    # output must be distinguishable from a 1-device CPU run

    for n_dev in dev_counts:
        mesh = par.local_mesh(n_dev)
        axis = mesh.data_axis

        def build(mesh=mesh, axis=axis):
            def step(x):
                total = jax.lax.psum(x.sum(), axis)
                return (x * 0.999 + total * 1e-9,)
            return shard_map(step, mesh=mesh.mesh, in_specs=(P(axis),),
                             out_specs=(P(axis),))

        in_sh = [mesh.row_sharding(1)]
        out_sh = [mesh.row_sharding(1)]

        # -- jax (device-resident by construction) ------------------------
        fn = jax.jit(build())
        xd = jax.device_put(jnp.asarray(x_host), in_sh[0])
        (r,) = fn(xd)
        jax.block_until_ready(r)
        t0 = time.perf_counter()
        r = xd
        for _ in range(iters):
            (r,) = fn(r)
        jax.block_until_ready(r)
        jax_s = (time.perf_counter() - t0) / iters
        print(json.dumps({"devices": n_dev, "path": "jax",
                          "s_per_dispatch": jax_s, "rows": n_rows,
                          "platform": plat}))

        ex = native_mesh.executor_for(mesh)
        if ex is None:
            print(json.dumps({"devices": n_dev, "path": "native",
                              "error": "executor unavailable",
                              "platform": plat}))
            continue

        # -- native, host-marshalled per call -----------------------------
        key = ("bench-marshalled", n_dev, n_rows)
        ex.run_sharded(key, build, [x_host], in_sh, out_sh, mesh)  # compile
        t0 = time.perf_counter()
        cur = x_host
        for _ in range(iters):
            (cur,) = ex.run_sharded(key, build, [cur], in_sh, out_sh, mesh)
        marsh_s = (time.perf_counter() - t0) / iters
        print(json.dumps({"devices": n_dev, "path": "native_marshalled",
                          "s_per_dispatch": marsh_s, "rows": n_rows,
                          "platform": plat}))

        # -- native, device-resident loop ---------------------------------
        ex.run_sharded_loop(key, build, [x_host], in_sh, out_sh, mesh,
                            iters=1)  # warm
        t0 = time.perf_counter()
        ex.run_sharded_loop(key, build, [x_host], in_sh, out_sh, mesh,
                            iters=iters)
        res_s = (time.perf_counter() - t0) / iters
        print(json.dumps({
            "devices": n_dev, "path": "native_resident",
            "s_per_dispatch": res_s, "rows": n_rows,
            "platform": plat,
            "marshalling_overhead_x": marsh_s / res_s if res_s else None,
            "vs_jax_x": res_s / jax_s if jax_s else None,
        }))


if __name__ == "__main__":
    if not CHIP:
        force_cpu_if_requested()
    elif CHIP_BACKEND_OVERRIDDEN and \
            os.environ["TFT_PJRT_MESH_BACKEND"].startswith("cpu"):
        # stand-in testing with a cpu native backend: pin the jax leg to
        # cpu too, unconditionally — otherwise sitecustomize points jax
        # at the tunnelled TPU and the two legs time different platforms
        # under one stamp
        jax.config.update("jax_platforms", "cpu")
    elif jax.devices()[0].platform not in ("tpu", "axon"):
        # chip mode on a CPU backend would tee CPU timings into
        # chip_results.jsonl as silicon evidence
        print(json.dumps({"error": "chip mode but live platform is "
                          + jax.devices()[0].platform}))
        sys.exit(2)
    pos = [a for a in sys.argv[1:] if not a.startswith("-")]
    n = int(pos[0]) if len(pos) > 0 else 1_000_000
    it = int(pos[1]) if len(pos) > 1 else 20
    main(n, it, dev_counts=(1,) if CHIP else (1, 2, 4, 8))
