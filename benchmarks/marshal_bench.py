"""Marshalling micro-benchmarks — the reference's convert/convertBack suites.

Mirrors the four ``ignore``d configs of
``perf/ConvertPerformanceSuite.scala:36-76`` and
``perf/ConvertBackPerformanceSuite.scala:35-79``: rows->columnar ("convert")
and columnar->rows ("convertBack"), for (a) 10M scalar-int rows and (b) one
row holding a 10M-element int vector. The reference timed Row boxing into
C++ tensor buffers over JNI; here the measured path is the framework's
actual host marshalling (``marshal.rows_to_columns`` / ``columns_to_rows``
with the native fast path when ``libtfruntime.so`` is built).

Iteration counts are scaled down from the reference's 100/1000 (its suites
never ran in CI anyway); wall-per-call is what's recorded.
"""

from __future__ import annotations

import os
import sys
import time
from typing import Dict, List

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tensorframes_tpu import dtypes as _dt  # noqa: E402
from tensorframes_tpu.marshal import columns_to_rows, rows_to_columns
from tensorframes_tpu.schema import Field, Schema
from tensorframes_tpu.shape import Shape, Unknown

N_SCALAR = 10_000_000
N_VECTOR = 10_000_000
ITERS = 5


def _time_per_call(fn, iters: int = ITERS) -> float:
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def run(n_scalar: int = N_SCALAR, n_vector: int = N_VECTOR,
        iters: int = ITERS) -> List[Dict]:
    out: List[Dict] = []

    scalar_schema = Schema([
        Field("x", _dt.int32, block_shape=Shape(Unknown), sql_rank=0)])
    scalar_rows = [(i,) for i in range(n_scalar)]
    sec = _time_per_call(
        lambda: rows_to_columns(scalar_rows, scalar_schema), iters)
    out.append({"metric": "convert_scalar_rows", "value": sec, "unit":
                "s/call", "rows": n_scalar,
                "rows_per_s": n_scalar / sec})

    scalar_cols = rows_to_columns(scalar_rows, scalar_schema)
    sec = _time_per_call(
        lambda: columns_to_rows(scalar_cols, scalar_schema), iters)
    out.append({"metric": "convertBack_scalar_rows", "value": sec,
                "unit": "s/call", "rows": n_scalar,
                "rows_per_s": n_scalar / sec})

    vec_schema = Schema([
        Field("x", _dt.int32, block_shape=Shape(Unknown, n_vector),
              sql_rank=1)])
    vec_rows = [(np.arange(n_vector, dtype=np.int32),)]
    sec = _time_per_call(lambda: rows_to_columns(vec_rows, vec_schema), iters)
    out.append({"metric": "convert_1row_vector", "value": sec,
                "unit": "s/call", "elements": n_vector})

    vec_cols = rows_to_columns(vec_rows, vec_schema)
    sec = _time_per_call(lambda: columns_to_rows(vec_cols, vec_schema), iters)
    out.append({"metric": "convertBack_1row_vector", "value": sec,
                "unit": "s/call", "elements": n_vector})
    return out


def run_ragged(n_rows: int = 1_000_000, max_len: int = 16,
               iters: int = 3) -> List[Dict]:
    """Ragged parquet ingest at scale (r4 weak #4): a variable-length
    list column of ``n_rows`` cells, loaded three ways —

    - ``boxed``: per-cell Python boxing (``to_pylist``), the reference's
      acknowledged per-row weakness (``DataOps.scala:30-33``) reproduced
      as the baseline;
    - ``cells``: the framework's ragged decode (offsets+values buffer
      slicing, one numpy view per cell);
    - ``padded``: ``read_parquet(pad_ragged=True)`` — dense [rows, L] +
      mask/len, the block-ops-ready layout.
    """
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    from tensorframes_tpu import io as tio

    rng = np.random.default_rng(7)
    lens = rng.integers(0, max_len, n_rows)
    flat = rng.normal(size=int(lens.sum()))
    offs = np.zeros(n_rows + 1, np.int64)
    np.cumsum(lens, out=offs[1:])
    arr = pa.ListArray.from_arrays(pa.array(offs, pa.int64()),
                                   pa.array(flat))
    out: List[Dict] = []
    with tempfile.NamedTemporaryFile(suffix=".parquet") as f:
        pq.write_table(pa.table({"v": arr}), f.name)

        def boxed():
            with pq.ParquetFile(f.name) as pf:
                cells = []
                for rg in range(pf.num_row_groups):
                    col = pf.read_row_group(rg, columns=["v"]).column("v")
                    cells.extend(np.asarray(c) for c in col.to_pylist())
            return cells

        sec_boxed = _time_per_call(boxed, iters)
        out.append({"metric": "ragged_load_boxed_reference",
                    "value": sec_boxed, "unit": "s/call", "rows": n_rows,
                    "rows_per_s": n_rows / sec_boxed})

        sec = _time_per_call(lambda: tio.read_parquet(f.name), iters)
        out.append({"metric": "ragged_load_cells", "value": sec,
                    "unit": "s/call", "rows": n_rows,
                    "rows_per_s": n_rows / sec,
                    "vs_boxed": sec_boxed / sec})

        sec = _time_per_call(
            lambda: tio.read_parquet(f.name, pad_ragged=True), iters)
        out.append({"metric": "ragged_load_padded", "value": sec,
                    "unit": "s/call", "rows": n_rows,
                    "rows_per_s": n_rows / sec,
                    "vs_boxed": sec_boxed / sec})
    return out


if __name__ == "__main__":
    import json

    for rec in run():
        print(json.dumps(rec))
    for rec in run_ragged():
        print(json.dumps(rec))
