"""Marshalling micro-benchmarks — the reference's convert/convertBack suites.

Mirrors the four ``ignore``d configs of
``perf/ConvertPerformanceSuite.scala:36-76`` and
``perf/ConvertBackPerformanceSuite.scala:35-79``: rows->columnar ("convert")
and columnar->rows ("convertBack"), for (a) 10M scalar-int rows and (b) one
row holding a 10M-element int vector. The reference timed Row boxing into
C++ tensor buffers over JNI; here the measured path is the framework's
actual host marshalling (``marshal.rows_to_columns`` / ``columns_to_rows``
with the native fast path when ``libtfruntime.so`` is built).

Iteration counts are scaled down from the reference's 100/1000 (its suites
never ran in CI anyway); wall-per-call is what's recorded.
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

from tensorframes_tpu import dtypes as _dt
from tensorframes_tpu.marshal import columns_to_rows, rows_to_columns
from tensorframes_tpu.schema import Field, Schema
from tensorframes_tpu.shape import Shape, Unknown

N_SCALAR = 10_000_000
N_VECTOR = 10_000_000
ITERS = 5


def _time_per_call(fn, iters: int = ITERS) -> float:
    fn()  # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    return (time.perf_counter() - t0) / iters


def run(n_scalar: int = N_SCALAR, n_vector: int = N_VECTOR,
        iters: int = ITERS) -> List[Dict]:
    out: List[Dict] = []

    scalar_schema = Schema([
        Field("x", _dt.int32, block_shape=Shape(Unknown), sql_rank=0)])
    scalar_rows = [(i,) for i in range(n_scalar)]
    sec = _time_per_call(
        lambda: rows_to_columns(scalar_rows, scalar_schema), iters)
    out.append({"metric": "convert_scalar_rows", "value": sec, "unit":
                "s/call", "rows": n_scalar,
                "rows_per_s": n_scalar / sec})

    scalar_cols = rows_to_columns(scalar_rows, scalar_schema)
    sec = _time_per_call(
        lambda: columns_to_rows(scalar_cols, scalar_schema), iters)
    out.append({"metric": "convertBack_scalar_rows", "value": sec,
                "unit": "s/call", "rows": n_scalar,
                "rows_per_s": n_scalar / sec})

    vec_schema = Schema([
        Field("x", _dt.int32, block_shape=Shape(Unknown, n_vector),
              sql_rank=1)])
    vec_rows = [(np.arange(n_vector, dtype=np.int32),)]
    sec = _time_per_call(lambda: rows_to_columns(vec_rows, vec_schema), iters)
    out.append({"metric": "convert_1row_vector", "value": sec,
                "unit": "s/call", "elements": n_vector})

    vec_cols = rows_to_columns(vec_rows, vec_schema)
    sec = _time_per_call(lambda: columns_to_rows(vec_cols, vec_schema), iters)
    out.append({"metric": "convertBack_1row_vector", "value": sec,
                "unit": "s/call", "elements": n_vector})
    return out


if __name__ == "__main__":
    import json

    for rec in run():
        print(json.dumps(rec))
