#!/usr/bin/env bash
# One-shot chip evidence suite: run everything BASELINE.md still lists as
# "re-run pending chip availability", each step with its own timeout so a
# wedged grant loses one step, not the suite. Appends JSON lines to
# benchmarks/chip_results.jsonl (gitignored artifacts aside, the numbers
# land in BASELINE.md by hand).
set -uo pipefail
cd "$(dirname "$0")/.."

OUT=benchmarks/chip_results.jsonl

# persistent compilation cache: a relay drop mid-suite must not restart
# every compile from zero on the retry (jax warns + continues if the
# plugin cannot serialize executables)
export JAX_COMPILATION_CACHE_DIR="${JAX_COMPILATION_CACHE_DIR:-$PWD/benchmarks/.jax_cache}"
export JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS=0
mkdir -p "$JAX_COMPILATION_CACHE_DIR"

probe() {
  timeout 90 python -c "import jax; assert jax.devices()[0].platform in ('tpu','axon')" 2>/dev/null
}

if ! probe; then
  echo "no TPU grant available; aborting" >&2
  exit 2
fi

# the native smoke needs the C++ core; build it up front so a fresh
# checkout doesn't burn its one grant on a "libtfrpjrt.so missing" step
make -C native -j4 >/dev/null 2>&1 || true

stamp() {  # annotate each JSON line with capture time (bench.py last_tpu reads it)
  python -c '
import sys, json, time
for line in sys.stdin:
    s = line.strip()
    if not s:
        continue
    try:
        r = json.loads(s)
        if isinstance(r, dict):
            r.setdefault("captured_at",
                         time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()))
        print(json.dumps(r), flush=True)
    except ValueError:
        print(s, flush=True)
'
}

run() {  # run <label> <timeout_s> <cmd...>
  local label=$1 t=$2; shift 2
  echo "== $label =="
  # SIGTERM first and only escalate to SIGKILL after a 20s grace: a
  # KILLed PJRT client leaves the server-side session lease held and the
  # relay wedges for the rest of the round (observed r2 and r3)
  timeout -k 20 "$t" "$@" 2>>"$OUT.err" | stamp | tee -a "$OUT" || \
    echo "{\"step\": \"$label\", \"error\": \"rc=$? (timeout or failure)\"}" | tee -a "$OUT"
}

run native_smoke   400 python benchmarks/tpu_native_smoke.py
run pallas_smoke   400 python benchmarks/tpu_pallas_smoke.py
run baseline_1_2_3 500 python benchmarks/run_tpu_baselines.py 1 2 3
run baseline_4     580 python benchmarks/run_tpu_baselines.py 4
run baseline_5     580 python benchmarks/run_tpu_baselines.py 5
run daggregate     580 python benchmarks/daggregate_bench.py 1000000 100000
# 1-device run keeps the live platform: the fused local-sort round's
# chip-side constant (columnsort's cost model, BASELINE.md)
run dsort_local    400 python benchmarks/dsort_steps_bench.py 1000000 1
# HBM-resident native loop vs jax on the chip (device buffers held by
# the C++ core across dispatches; BASELINE.md native-dispatch table)
run native_mesh    400 python benchmarks/native_mesh_bench.py 1000000 20 --chip
run headline       580 python bench.py
echo "chip suite complete; results in $OUT"
