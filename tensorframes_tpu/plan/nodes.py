"""Plan-node IR: one node per lazy frame op, plus the two leaf kinds.

A node records WHAT an op computes (its canonical
:class:`~..computation.Computation`, its projection, its output schema)
— never HOW it will run; the optimizer (:mod:`.optimize`) decides that
at forcing time. Nodes are built alongside the existing lazy thunks
(:func:`attach` is called by ``engine.ops`` and ``TensorFrame.select``),
so a frame always has its per-op path available as the fallback.

Estimates: every node answers :meth:`PlanNode.estimate` with
``(rows, {column: total_bytes})`` — per-COLUMN byte accounting threaded
from measured leaf sizes (exact block bytes for in-memory sources,
footer column-chunk sizes for parquet scans), so projections and fetch
columns are priced individually instead of by the whole-schema row-byte
ratio. ``memory.estimate.frame_estimate`` consults this for unforced
frames; serve admission and quotas read it from there.
"""

from __future__ import annotations

import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..computation import Computation, TensorSpec
from ..schema import Schema
from ..utils.logging import get_logger

__all__ = ["PlanNode", "SourceNode", "ParquetScanNode", "MapBlocksNode",
           "MapRowsNode", "FilterNode", "SelectNode", "attach", "node_for",
           "record_selectivity", "observed_selectivity"]

_log = get_logger("plan.nodes")

# ---------------------------------------------------------------------------
# feedback selectivity (ROADMAP item 2a, first slice)
# ---------------------------------------------------------------------------
#
# When a filter stage FORCES, the observed rows-in/rows-out land on the
# predicate's canonical Computation (computations are cached per fetches
# object — engine.ops.cached_map_computation — so every plan built from
# the same predicate shares one record: subsequent forcings, per-batch
# streaming frames, and the mesh dfilter all see it). Estimates then use
# the observed ratio instead of the keeps-everything upper bound.

_sel_lock = __import__("threading").Lock()

# bumped on every recorded observation: estimate caches key on it, so
# an upstream filter's sharpened selectivity invalidates EVERY cached
# downstream estimate (a MapBlocksNode whose input is a filter must not
# keep pricing the pre-observation upper bound forever)
_sel_epoch = 0


def record_selectivity(comp, rows_in: int, rows_out: int) -> None:
    """Accumulate one forcing's observed filter selectivity on its
    predicate computation (best-effort: unsettable comps are skipped)."""
    global _sel_epoch
    if rows_in <= 0:
        return
    try:
        with _sel_lock:
            tin, tout = getattr(comp, "_tft_observed_sel", (0, 0))
            comp._tft_observed_sel = (tin + int(rows_in),
                                      tout + int(rows_out))
            _sel_epoch += 1
    except Exception as e:  # noqa: BLE001 - feedback is advisory
        _log.debug("could not record selectivity on %r: %s", comp, e)


def observed_selectivity(comp) -> Optional[float]:
    """The accumulated rows-out/rows-in ratio of a predicate, or
    ``None`` before its first observed forcing."""
    rec = getattr(comp, "_tft_observed_sel", None)
    if not rec or rec[0] <= 0:
        return None
    return min(1.0, rec[1] / rec[0])

# (rows, per-column total bytes) — either half may be None when unknown
Estimate = Tuple[Optional[float], Optional[Dict[str, int]]]

OP_KINDS = ("map_blocks", "map_rows", "filter", "select")


def _col_nbytes(col) -> int:
    """Host bytes of one column — delegates to the shared definition so
    plan estimates and block accounting can never drift."""
    from ..memory.estimate import column_nbytes
    return column_nbytes(col)


def _cell_bytes(dtype, dims: Sequence) -> int:
    """Bytes per row of a cell shape (Unknown dims floor at 1, the same
    deliberate floor ``schema_row_bytes`` uses)."""
    cells = 1
    for d in dims:
        if isinstance(d, int) and d > 0:
            cells *= d
    return cells * int(np.dtype(dtype.np_storage).itemsize)


def _field_row_bytes(field) -> int:
    if not field.dtype.tensor:
        return 8  # strings count a pointer, like schema_row_bytes
    cell = field.cell_shape
    return _cell_bytes(field.dtype, cell.dims if cell is not None else ())


class PlanNode:
    """Base: an op node with one input, or a leaf with ``input=None``."""

    kind = "node"

    def __init__(self, input: Optional["PlanNode"], schema: Schema):
        self.input = input
        self.schema = schema
        # weakref to the frame this node produced (set by attach):
        # linearization stops at an upstream frame whose block cache is
        # already materialized — re-deriving it would waste work the
        # per-op path gets for free
        self.result_ref: Optional[weakref.ref] = None

    def describe(self) -> str:
        return self.kind

    def estimate(self) -> Estimate:
        """Cached per selectivity epoch: computed once per node (chain
        building stays O(n), not O(n^2) walks) and recomputed only
        after a new filter observation landed anywhere in the process
        (``record_selectivity`` bumps the epoch) — so a sharpened
        upstream selectivity propagates through cached downstream
        estimates. Callers get a copy of the column dict."""
        cached = getattr(self, "_est_cache", None)
        if cached is None or cached[0] != _sel_epoch:
            cached = self._est_cache = (_sel_epoch, self._estimate())
        rows, cols = cached[1]
        return rows, (dict(cols) if cols is not None else None)

    def _estimate(self) -> Estimate:
        return None, None


class SourceNode(PlanNode):
    """Leaf over any frame without a plan of its own (eager constructors,
    ``order_by``/``repartition``/``limit`` results, cached upstreams)."""

    kind = "source"

    def __init__(self, frame):
        super().__init__(None, frame.schema)
        self.frame = frame

    def describe(self) -> str:
        return f"source[{self.frame._plan}]"

    def _estimate(self) -> Estimate:
        blocks = getattr(self.frame, "_cache", None)
        if blocks:
            rows = 0
            col_bytes: Dict[str, int] = {f.name: 0 for f in self.schema}
            for b in blocks:
                rows += int(b.num_rows)
                for name, col in b.columns.items():
                    if name in col_bytes:
                        col_bytes[name] += _col_nbytes(col)
            return float(rows), col_bytes
        rows = getattr(self.frame, "_rows_hint", None)
        rows_f = float(rows) if rows is not None else None
        cb = getattr(self.frame, "_col_bytes_hint", None)
        if cb is not None:
            return rows_f, dict(cb)
        total = getattr(self.frame, "_bytes_hint", None)
        if total is None:
            return rows_f, None
        # only a whole-frame hint exists: distribute it over the declared
        # per-row column widths so downstream projections still prune
        widths = {f.name: _field_row_bytes(f) for f in self.schema}
        denom = sum(widths.values()) or 1
        return rows_f, {n: int(total * w / denom)
                        for n, w in widths.items()}


class ParquetScanNode(PlanNode):
    """Leaf over a lazily-read parquet range: the pruning target.

    ``columns`` is the full requested projection (file order);
    :meth:`read_blocks` reads any subset of it at force time — one
    footer read decided everything else (rows, per-column bytes,
    partition count) at construction.
    """

    kind = "parquet"

    def __init__(self, path: str, columns: Sequence[str],
                 row_group_offset: int, row_group_limit: int,
                 num_partitions: Optional[int], schema: Schema,
                 rows: int, col_bytes: Dict[str, int]):
        super().__init__(None, schema)
        self.path = path
        self.columns = tuple(columns)
        self.row_group_offset = int(row_group_offset)
        # pinned at footer time: a tailed file growing between build and
        # force must not change what this frame reads
        self.row_group_limit = int(row_group_limit)
        self.num_partitions = num_partitions
        self.rows = int(rows)
        self.col_bytes = dict(col_bytes)
        self.frame_ref: Optional[weakref.ref] = None

    def describe(self) -> str:
        import os
        return f"parquet[{os.path.basename(self.path)}]"

    def _estimate(self) -> Estimate:
        return float(self.rows), dict(self.col_bytes)

    def read_blocks(self, names: Sequence[str]) -> List:
        """Blocks holding (at least) ``names`` — the already-forced frame
        cache when it exists, a pruned read otherwise."""
        frame = self.frame_ref() if self.frame_ref is not None else None
        if frame is not None and getattr(frame, "_cache", None):
            return frame._cache
        from ..io import _read_parquet_eager
        want = [n for n in self.columns if n in set(names)]
        return _read_parquet_eager(
            self.path, columns=want, num_partitions=self.num_partitions,
            pad_ragged=False, row_group_offset=self.row_group_offset,
            row_group_limit=self.row_group_limit).blocks()


class MapBlocksNode(PlanNode):
    kind = "map_blocks"

    def __init__(self, input: PlanNode, schema: Schema, comp: Computation,
                 trim: bool):
        super().__init__(input, schema)
        self.comp = comp
        self.trim = bool(trim)

    def describe(self) -> str:
        return "map_blocks[trim]" if self.trim else "map_blocks"

    def _estimate(self) -> Estimate:
        rows, cols = self.input.estimate()
        if self.trim:
            # the computation owns the row count; nothing is knowable
            return None, None
        if rows is None or cols is None:
            return rows, None
        out = dict(cols)
        for s in self.comp.outputs:
            out[s.name] = int(rows * _cell_bytes(s.dtype, s.shape.dims[1:]))
        return rows, out


class MapRowsNode(PlanNode):
    kind = "map_rows"

    def __init__(self, input: PlanNode, schema: Schema, comp: Computation,
                 vcomp: Optional[Computation]):
        super().__init__(input, schema)
        self.comp = comp    # row-level user computation
        self.vcomp = vcomp  # its cached vmapped (block-level) twin

    def _estimate(self) -> Estimate:
        rows, cols = self.input.estimate()
        if rows is None or cols is None:
            return rows, None
        out = dict(cols)
        for s in self.comp.outputs:  # row-level: dims ARE the cell dims
            out[s.name] = int(rows * _cell_bytes(s.dtype, s.shape.dims))
        return rows, out


class FilterNode(PlanNode):
    kind = "filter"

    def __init__(self, input: PlanNode, schema: Schema, comp: Computation):
        super().__init__(input, schema)
        self.comp = comp
        # observed (rows_in, rows_out) of THIS node's own forcings —
        # recorded by plan.execute; the cross-plan record lives on the
        # comp (record_selectivity) so fresh nodes over the same
        # predicate inherit it
        self.observed: Optional[Tuple[int, int]] = None

    def _estimate(self) -> Estimate:
        # the epoch-keyed base cache re-invokes this after every new
        # observation, so the ratio is always current
        rows, cols = self.input.estimate()
        sel = observed_selectivity(self.comp)
        if sel is None or rows is None:
            # an upper bound, like the per-op hint: a filter keeps at
            # most its input
            return rows, cols
        return rows * sel, ({n: int(b * sel) for n, b in cols.items()}
                            if cols is not None else None)


class SelectNode(PlanNode):
    kind = "select"

    def __init__(self, input: PlanNode, schema: Schema,
                 names: Sequence[str]):
        super().__init__(input, schema)
        self.names = tuple(names)

    def describe(self) -> str:
        return f"select{list(self.names)}"

    def _estimate(self) -> Estimate:
        rows, cols = self.input.estimate()
        if cols is None:
            return rows, None
        return rows, {n: cols[n] for n in self.names if n in cols}


def node_for(frame) -> PlanNode:
    """The plan node producing ``frame``: its recorded op node, or a
    fresh :class:`SourceNode` leaf when it has none."""
    node = getattr(frame, "_plan_node", None)
    return node if node is not None else SourceNode(frame)


def attach(frame, node: PlanNode) -> None:
    """Record ``node`` as the plan of ``frame`` (called by the lazy ops
    right after they build the result frame)."""
    node.result_ref = weakref.ref(frame)
    frame._plan_node = node
