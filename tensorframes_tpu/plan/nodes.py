"""Plan-node IR: one node per lazy frame op, plus the two leaf kinds.

A node records WHAT an op computes (its canonical
:class:`~..computation.Computation`, its projection, its output schema)
— never HOW it will run; the optimizer (:mod:`.optimize`) decides that
at forcing time. Nodes are built alongside the existing lazy thunks
(:func:`attach` is called by ``engine.ops`` and ``TensorFrame.select``),
so a frame always has its per-op path available as the fallback.

Estimates: every node answers :meth:`PlanNode.estimate` with
``(rows, {column: total_bytes})`` — per-COLUMN byte accounting threaded
from measured leaf sizes (exact block bytes for in-memory sources,
footer column-chunk sizes for parquet scans), so projections and fetch
columns are priced individually instead of by the whole-schema row-byte
ratio. ``memory.estimate.frame_estimate`` consults this for unforced
frames; serve admission and quotas read it from there.
"""

from __future__ import annotations

import weakref
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..computation import Computation, TensorSpec
from ..schema import Schema
from ..utils.logging import get_logger

__all__ = ["PlanNode", "SourceNode", "ParquetScanNode", "MapBlocksNode",
           "MapRowsNode", "FilterNode", "SelectNode", "JoinNode",
           "attach", "node_for", "record_selectivity",
           "observed_selectivity"]

_log = get_logger("plan.nodes")

# ---------------------------------------------------------------------------
# feedback selectivity (ROADMAP item 2a, first slice)
# ---------------------------------------------------------------------------
#
# When a filter stage FORCES, the observed rows-in/rows-out land on the
# predicate's canonical Computation (computations are cached per fetches
# object — engine.ops.cached_map_computation — so every plan built from
# the same predicate shares one record: subsequent forcings, per-batch
# streaming frames, and the mesh dfilter all see it). Estimates then use
# the observed ratio instead of the keeps-everything upper bound.

_sel_lock = __import__("threading").Lock()

# bumped on every recorded observation: estimate caches key on it, so
# an upstream filter's sharpened selectivity invalidates EVERY cached
# downstream estimate (a MapBlocksNode whose input is a filter must not
# keep pricing the pre-observation upper bound forever)
_sel_epoch = 0


def record_selectivity(comp, rows_in: int, rows_out: int) -> None:
    """Accumulate one forcing's observed filter selectivity on its
    predicate computation (best-effort: unsettable comps are skipped)."""
    global _sel_epoch
    if rows_in <= 0:
        return
    try:
        with _sel_lock:
            tin, tout = getattr(comp, "_tft_observed_sel", (0, 0))
            comp._tft_observed_sel = (tin + int(rows_in),
                                      tout + int(rows_out))
            _sel_epoch += 1
    except Exception as e:  # noqa: BLE001 - feedback is advisory
        _log.debug("could not record selectivity on %r: %s", comp, e)


def observed_selectivity(comp) -> Optional[float]:
    """The accumulated rows-out/rows-in ratio of a predicate, or
    ``None`` before its first observed forcing."""
    rec = getattr(comp, "_tft_observed_sel", None)
    if not rec or rec[0] <= 0:
        return None
    return min(1.0, rec[1] / rec[0])

# (rows, per-column total bytes) — either half may be None when unknown
Estimate = Tuple[Optional[float], Optional[Dict[str, int]]]

OP_KINDS = ("map_blocks", "map_rows", "filter", "select")


def _col_nbytes(col) -> int:
    """Host bytes of one column — delegates to the shared definition so
    plan estimates and block accounting can never drift."""
    from ..memory.estimate import column_nbytes
    return column_nbytes(col)


def _cell_bytes(dtype, dims: Sequence) -> int:
    """Bytes per row of a cell shape (Unknown dims floor at 1, the same
    deliberate floor ``schema_row_bytes`` uses)."""
    cells = 1
    for d in dims:
        if isinstance(d, int) and d > 0:
            cells *= d
    return cells * int(np.dtype(dtype.np_storage).itemsize)


def _field_row_bytes(field) -> int:
    if not field.dtype.tensor:
        return 8  # strings count a pointer, like schema_row_bytes
    cell = field.cell_shape
    return _cell_bytes(field.dtype, cell.dims if cell is not None else ())


class PlanNode:
    """Base: an op node with one input, or a leaf with ``input=None``."""

    kind = "node"

    def __init__(self, input: Optional["PlanNode"], schema: Schema):
        self.input = input
        self.schema = schema
        # weakref to the frame this node produced (set by attach):
        # linearization stops at an upstream frame whose block cache is
        # already materialized — re-deriving it would waste work the
        # per-op path gets for free
        self.result_ref: Optional[weakref.ref] = None

    def describe(self) -> str:
        return self.kind

    def estimate(self) -> Estimate:
        """Cached per selectivity epoch: computed once per node (chain
        building stays O(n), not O(n^2) walks) and recomputed only
        after a new filter observation landed anywhere in the process
        (``record_selectivity`` bumps the epoch) — so a sharpened
        upstream selectivity propagates through cached downstream
        estimates. Callers get a copy of the column dict."""
        cached = getattr(self, "_est_cache", None)
        if cached is None or cached[0] != _sel_epoch:
            cached = self._est_cache = (_sel_epoch, self._estimate())
        rows, cols = cached[1]
        return rows, (dict(cols) if cols is not None else None)

    def _estimate(self) -> Estimate:
        return None, None


class SourceNode(PlanNode):
    """Leaf over any frame without a plan of its own (eager constructors,
    ``order_by``/``repartition``/``limit`` results, cached upstreams)."""

    kind = "source"

    def __init__(self, frame):
        super().__init__(None, frame.schema)
        self.frame = frame

    def describe(self) -> str:
        return f"source[{self.frame._plan}]"

    def _estimate(self) -> Estimate:
        blocks = getattr(self.frame, "_cache", None)
        if blocks:
            rows = 0
            col_bytes: Dict[str, int] = {f.name: 0 for f in self.schema}
            for b in blocks:
                rows += int(b.num_rows)
                for name, col in b.columns.items():
                    if name in col_bytes:
                        col_bytes[name] += _col_nbytes(col)
            return float(rows), col_bytes
        rows = getattr(self.frame, "_rows_hint", None)
        rows_f = float(rows) if rows is not None else None
        cb = getattr(self.frame, "_col_bytes_hint", None)
        if cb is not None:
            return rows_f, dict(cb)
        total = getattr(self.frame, "_bytes_hint", None)
        if total is None:
            return rows_f, None
        # only a whole-frame hint exists: distribute it over the declared
        # per-row column widths so downstream projections still prune
        widths = {f.name: _field_row_bytes(f) for f in self.schema}
        denom = sum(widths.values()) or 1
        return rows_f, {n: int(total * w / denom)
                        for n, w in widths.items()}


class ParquetScanNode(PlanNode):
    """Leaf over a lazily-read parquet range: the pruning target.

    ``columns`` is the full requested projection (file order);
    :meth:`read_blocks` reads any subset of it at force time — one
    footer read decided everything else (rows, per-column bytes,
    partition count) at construction.
    """

    kind = "parquet"

    def __init__(self, path: str, columns: Sequence[str],
                 row_group_offset: int, row_group_limit: int,
                 num_partitions: Optional[int], schema: Schema,
                 rows: int, col_bytes: Dict[str, int]):
        super().__init__(None, schema)
        self.path = path
        self.columns = tuple(columns)
        self.row_group_offset = int(row_group_offset)
        # pinned at footer time: a tailed file growing between build and
        # force must not change what this frame reads
        self.row_group_limit = int(row_group_limit)
        self.num_partitions = num_partitions
        self.rows = int(rows)
        self.col_bytes = dict(col_bytes)
        self.frame_ref: Optional[weakref.ref] = None

    def describe(self) -> str:
        import os
        return f"parquet[{os.path.basename(self.path)}]"

    def _estimate(self) -> Estimate:
        return float(self.rows), dict(self.col_bytes)

    def _group_stats(self):
        """Per-row-group footer statistics for this scan's pinned range:
        a list of ``(num_rows, {column: (min, max)})`` — one footer
        read, cached on the node. ``None`` stats never refute."""
        cached = getattr(self, "_rg_stats", None)
        if cached is not None:
            return cached
        stats = []
        try:
            import pyarrow.parquet as pq
            with pq.ParquetFile(self.path) as pf:
                md = pf.metadata
                want = set(self.columns)
                end = min(md.num_row_groups,
                          self.row_group_offset + self.row_group_limit)
                for g in range(self.row_group_offset, end):
                    rg = md.row_group(g)
                    per = {}
                    nbytes = {}
                    for j in range(rg.num_columns):
                        c = rg.column(j)
                        base = c.path_in_schema.split(".", 1)[0]
                        if base not in want:
                            continue
                        nbytes[base] = int(c.total_uncompressed_size)
                        s = c.statistics
                        if s is not None and s.has_min_max:
                            per[base] = (s.min, s.max)
                    stats.append((rg.num_rows, per, nbytes))
        except Exception as e:  # noqa: BLE001 - no stats, no pushdown
            _log.debug("row-group stats unavailable for %s (%s); "
                       "pushdown disabled", self.path, e)
            stats = []
        self._rg_stats = stats
        return stats

    def refuted_groups(self, atoms) -> List[int]:
        """Row-group indices (0-based within this scan's range) whose
        footer stats PROVE every row fails some pushdown atom."""
        if not atoms:
            return []
        from .. import dtypes as _dt
        from .predicates import refutes
        stats = self._group_stats()
        if len(stats) != self.row_group_limit:
            return []
        out = []
        for gi, (_, per, _nb) in enumerate(stats):
            for a in atoms:
                f = self.schema.get(a.column)
                mm = per.get(a.column)
                if f is None or mm is None or not f.dtype.tensor:
                    continue
                if refutes(a, mm[0], mm[1], _dt.device_dtype(f.dtype)):
                    out.append(gi)
                    break
        return out

    def _empty_block(self, names: Sequence[str]):
        """A 0-row block typed like this scan's columns (the stand-in
        for a pushdown-skipped row group; only ever observed at 0 rows,
        where the per-op empty replay makes the shapes unobservable)."""
        from ..frame import Block
        cols = {}
        for n in names:
            f = self.schema[n]
            cell = f.cell_shape
            dims = tuple(0 if d == -1 else d
                         for d in (cell.dims if cell else ()))
            cols[n] = np.empty((0,) + dims, f.dtype.np_storage)
        return Block(cols, 0)

    def read_blocks(self, names: Sequence[str], atoms=None) -> List:
        """Blocks holding (at least) ``names`` — the already-forced frame
        cache when it exists, a pruned read otherwise.

        ``atoms`` (pushdown predicates, :mod:`.predicates`) skip whole
        row groups whose footer statistics refute them — bit-identical
        downstream, because every skipped row was about to fail the
        filter anyway (``plan.pushdown_groups_skipped`` /
        ``plan.pushdown_bytes_skipped`` count what was never read). On
        1:1 group->partition scans (``num_partitions is None``) a
        skipped group's partition becomes a typed 0-row block;
        explicitly re-partitioned scans remap the surviving groups'
        rows onto the exact partition spans the unpushed read would
        have produced (skipped rows simply absent from their spans —
        the filter was about to drop them)."""
        frame = self.frame_ref() if self.frame_ref is not None else None
        if frame is not None and getattr(frame, "_cache", None):
            return frame._cache
        from ..io import _read_parquet_eager
        want = [n for n in self.columns if n in set(names)]
        skip = set(self.refuted_groups(atoms) if atoms else [])
        if not skip:
            return _read_parquet_eager(
                self.path, columns=want,
                num_partitions=self.num_partitions,
                pad_ragged=False, row_group_offset=self.row_group_offset,
                row_group_limit=self.row_group_limit).blocks()
        from ..utils.tracing import counters
        stats = self._group_stats()
        skipped_bytes = 0
        for gi in skip:
            _, _, nbytes = stats[gi]
            # footer chunk sizes of the READ projection only
            skipped_bytes += sum(int(nbytes.get(n, 0)) for n in want)
        counters.inc("plan.pushdown_groups_skipped", len(skip))
        counters.inc("plan.pushdown_bytes_skipped", skipped_bytes)
        _log.info("parquet pushdown: skipped %d/%d row group(s) "
                  "(~%d B) of %s", len(skip), self.row_group_limit,
                  skipped_bytes, self.path)
        # read surviving groups in contiguous runs; skipped positions
        # stay None
        blocks: List = [None] * self.row_group_limit
        run_start = None
        for gi in range(self.row_group_limit + 1):
            live = gi < self.row_group_limit and gi not in skip
            if live and run_start is None:
                run_start = gi
            elif not live and run_start is not None:
                got = _read_parquet_eager(
                    self.path, columns=want, num_partitions=None,
                    pad_ragged=False,
                    row_group_offset=self.row_group_offset + run_start,
                    row_group_limit=gi - run_start).blocks()
                for k, b in enumerate(got):
                    blocks[run_start + k] = b
                run_start = None
        if self.num_partitions is not None:
            return self._remap_partitions(blocks, want, stats)
        # group->partition is 1:1: splice typed empties at skipped spots
        empty = self._empty_block(want)
        return [b if b is not None else empty for b in blocks]

    def _remap_partitions(self, gblocks: List, names: Sequence[str],
                          stats) -> List:
        """Surviving per-group blocks -> the ``num_partitions`` blocks
        of an explicitly re-partitioned scan. Partition spans are cut
        over the TOTAL row count (footer group sizes, refuted groups
        included) with the same ``_split_even`` the unpushed read uses,
        so partition count and each surviving row's partition match the
        unpushed path exactly; refuted groups' rows are simply absent
        from their spans."""
        from ..frame import Block, _split_even
        group_rows = [int(st[0]) for st in stats]
        offsets = np.concatenate([[0], np.cumsum(group_rows)])
        total = int(offsets[-1])
        sel_schema = self.schema.select(list(names))
        spans = _split_even(total, self.num_partitions)
        out: List = []
        for a, b in spans:
            pieces: List = []
            for gi, blk in enumerate(gblocks):
                if blk is None:
                    continue  # refuted: its rows were about to fail
                ga, gb = int(offsets[gi]), int(offsets[gi + 1])
                lo, hi = max(a, ga), min(b, gb)
                if lo >= hi:
                    continue
                if lo == ga and hi == gb:
                    pieces.append(blk)
                    continue
                s0, s1 = lo - ga, hi - ga
                pieces.append(Block(
                    {k: (v[s0:s1] if isinstance(v, np.ndarray)
                         else list(v[s0:s1]))
                     for k, v in blk.columns.items()}, s1 - s0))
            if pieces:
                out.append(Block.concat(pieces, sel_schema))
            else:
                out.append(self._empty_block(names))
        return out


class MapBlocksNode(PlanNode):
    kind = "map_blocks"

    def __init__(self, input: PlanNode, schema: Schema, comp: Computation,
                 trim: bool):
        super().__init__(input, schema)
        self.comp = comp
        self.trim = bool(trim)

    def describe(self) -> str:
        return "map_blocks[trim]" if self.trim else "map_blocks"

    def _estimate(self) -> Estimate:
        rows, cols = self.input.estimate()
        if self.trim:
            # the computation owns the row count; nothing is knowable
            return None, None
        if rows is None or cols is None:
            return rows, None
        out = dict(cols)
        for s in self.comp.outputs:
            out[s.name] = int(rows * _cell_bytes(s.dtype, s.shape.dims[1:]))
        return rows, out


class MapRowsNode(PlanNode):
    kind = "map_rows"

    def __init__(self, input: PlanNode, schema: Schema, comp: Computation,
                 vcomp: Optional[Computation]):
        super().__init__(input, schema)
        self.comp = comp    # row-level user computation
        self.vcomp = vcomp  # its cached vmapped (block-level) twin

    def _estimate(self) -> Estimate:
        rows, cols = self.input.estimate()
        if rows is None or cols is None:
            return rows, None
        out = dict(cols)
        for s in self.comp.outputs:  # row-level: dims ARE the cell dims
            out[s.name] = int(rows * _cell_bytes(s.dtype, s.shape.dims))
        return rows, out


class FilterNode(PlanNode):
    kind = "filter"

    def __init__(self, input: PlanNode, schema: Schema, comp: Computation):
        super().__init__(input, schema)
        self.comp = comp
        # observed (rows_in, rows_out) of THIS node's own forcings —
        # recorded by plan.execute; the cross-plan record lives on the
        # comp (record_selectivity) so fresh nodes over the same
        # predicate inherit it
        self.observed: Optional[Tuple[int, int]] = None

    def _estimate(self) -> Estimate:
        # the epoch-keyed base cache re-invokes this after every new
        # observation, so the ratio is always current
        rows, cols = self.input.estimate()
        sel = observed_selectivity(self.comp)
        if sel is None or rows is None:
            # an upper bound, like the per-op hint: a filter keeps at
            # most its input
            return rows, cols
        return rows * sel, ({n: int(b * sel) for n, b in cols.items()}
                            if cols is not None else None)


class SelectNode(PlanNode):
    kind = "select"

    def __init__(self, input: PlanNode, schema: Schema,
                 names: Sequence[str]):
        super().__init__(input, schema)
        self.names = tuple(names)

    def describe(self) -> str:
        return f"select{list(self.names)}"

    def _estimate(self) -> Estimate:
        rows, cols = self.input.estimate()
        if cols is None:
            return rows, None
        return rows, {n: cols[n] for n in self.names if n in cols}


class JoinNode(PlanNode):
    """Leaf over a lazy join (``relational/join.py``): downstream
    chains fuse over the join result like any source, column pruning
    reaches INTO the join through :meth:`read_blocks` (build columns
    the chain never references are not gathered, probe passthrough
    columns not materialized — for the partitioned strategy the pruned
    columns also never ride the shuffle exchange), and :meth:`estimate`
    prices join output per column for serve admission / quotas.
    ``strategy`` is ``"broadcast"``, ``"sort_merge"``, or
    ``"partitioned"`` (the shuffle-exchange hash join).
    """

    kind = "join"

    def __init__(self, left: PlanNode, right: Optional[PlanNode],
                 schema: Schema, on, how: str, strategy: str,
                 materialize):
        super().__init__(None, schema)
        self.left = left
        self.right = right
        self.on = tuple(on)
        self.how = how
        self.strategy = strategy
        self._materialize = materialize
        self.build = None  # the broadcast BuildTable, when that path

    def describe(self) -> str:
        return f"join[{self.strategy},{self.how}]{list(self.on)}"

    @property
    def frame(self):
        """The join result frame (the leaf-execution surface the plan
        executor's generic path uses)."""
        return self.result_ref() if self.result_ref is not None else None

    def read_blocks(self, names: Sequence[str]) -> List:
        frame = self.frame
        if frame is not None and getattr(frame, "_cache", None):
            return frame._cache
        return self._materialize(list(names))

    def _rows_estimate(self, rows_l: Optional[float]) -> Optional[float]:
        """Sketch-based output cardinality (docs/adaptive.md, the PR 12
        follow-on): a broadcast BuildTable prices the per-probe-row
        expansion from its unique-key count (``build_rows /
        num_groups`` — exactly 1 for unique keys, so 1:1 left joins
        stay exact); a sort-merge or partitioned join over forced
        sides prices ``|L|·|R| / max(V(L), V(R))`` with HLL
        ``approx_key_distinct`` probes (both carry their right node
        here). Anything unprobeable keeps the probe-side row count
        (the prior upper-bound-ish heuristic)."""
        if not rows_l:
            return rows_l
        build = self.build
        if build is not None and build.num_groups:
            avg_span = build.build_rows / build.num_groups
            rows = rows_l * avg_span
            return max(rows, rows_l) if self.how == "left" else rows
        if self.right is not None:
            rows_r, _ = self.right.estimate()
            if rows_r:
                from ..relational.join import approx_key_distinct
                lf = (self.left.result_ref()
                      if self.left.result_ref is not None else None) \
                    or getattr(self.left, "frame", None)
                rf = (self.right.result_ref()
                      if self.right.result_ref is not None else None) \
                    or getattr(self.right, "frame", None)
                d_l = approx_key_distinct(lf, self.on) \
                    if lf is not None else None
                d_r = approx_key_distinct(rf, self.on) \
                    if rf is not None else None
                d = max([v for v in (d_l, d_r) if v] or [0.0])
                if d >= 1.0:
                    rows = rows_l * rows_r / d
                    return max(rows, rows_l) if self.how == "left" \
                        else rows
        return rows_l

    def _estimate(self) -> Estimate:
        rows_l, cols_l = self.left.estimate()
        rows = self._rows_estimate(rows_l)
        out: Dict[str, int] = {}
        if cols_l is not None:
            # left columns replicate with the expansion factor
            scale_l = (rows / rows_l) if rows_l and rows else 1.0
            out.update({n: int(b * scale_l) for n, b in cols_l.items()
                        if n in self.schema})
        build = self.build
        if build is not None and build.build_rows and rows:
            scale = rows / build.build_rows
            for f in build.value_fields:
                if f.name not in self.schema:
                    continue
                if f.name in build.tensor_names:
                    nb = int(build._sorted_host[f.name].nbytes * scale)
                else:
                    nb = int(8 * rows)
                out[f.name] = nb
        elif self.right is not None:
            rows_r, cols_r = self.right.estimate()
            if cols_r is not None and rows_r and rows:
                for n, b in cols_r.items():
                    if n in self.schema and n not in out:
                        out[n] = int(b * rows / rows_r)
        return rows, (out or None)


def node_for(frame) -> PlanNode:
    """The plan node producing ``frame``: its recorded op node, or a
    fresh :class:`SourceNode` leaf when it has none."""
    node = getattr(frame, "_plan_node", None)
    return node if node is not None else SourceNode(frame)


def attach(frame, node: PlanNode) -> None:
    """Record ``node`` as the plan of ``frame`` (called by the lazy ops
    right after they build the result frame)."""
    node.result_ref = weakref.ref(frame)
    frame._plan_node = node
