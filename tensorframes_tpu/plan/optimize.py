"""Plan optimizer: fusion legality, column pruning, stage building.

The optimizer turns a linear chain of plan nodes into an
:class:`ExecPlan` — a list of :class:`Stage`\\ s, each one composed
:class:`~..computation.Computation` dispatched ONCE per block — or
returns ``None``, in which case the frame's unchanged per-op thunk runs
(the always-correct fallback; also the whole path under ``TFT_FUSE=0``).

Correctness is proof-driven, never assumed:

- a non-trim ``map_blocks`` fuses only when a symbolic abstract
  evaluation PROVES every fetch preserves the shared row symbol (the
  per-op path's runtime row-count check, discharged statically — a
  computation that violates it falls back and raises exactly as today);
- a trim ``map_blocks`` fuses only when all fetches provably share one
  lead expression; a filter predicate only when its mask provably has
  block length; ``map_rows`` is row-preserving by vmap construction;
- a filter ends its fusion stage: its mask is computed INSIDE the fused
  program (one extra output) but applied host-side, because a
  data-dependent row count is not expressible in one static-shape XLA
  program — the next stage then consumes the gathered, still
  device-resident columns;
- column pruning is a backward pass over the chain: only columns that
  feed a computation or survive to the final schema are read
  (``ParquetScanNode``), marshalled, or materialized as program outputs.

Composed computations are cached structurally (weakly anchored on their
first member computation), so repeated forcings — per-batch streaming
frames included — re-dispatch one compiled program instead of
re-tracing, and the serve layer's :class:`~..serve.cache
.SharedCompileCache` interns them across tenants like any other
computation.
"""

from __future__ import annotations

import threading
import weakref
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..computation import Computation, TensorSpec, _sym_avals
from ..resilience import env_bool
from ..utils.logging import get_logger
from . import nodes as _n

__all__ = ["enabled", "build_plan", "ExecPlan", "Stage", "MASK"]

_log = get_logger("plan.optimize")

# the fused filter mask's reserved output name; a user column with this
# name disables planning for the chain (checked in build_plan)
MASK = "_tft_mask"


def enabled() -> bool:
    """``TFT_FUSE`` gate (default on). ``TFT_FUSE=0`` disables every
    pass; forcing then runs the per-op thunks, bit-identical to the
    pre-plan engine by construction."""
    return env_bool("TFT_FUSE", True)


# ---------------------------------------------------------------------------
# symbolic legality proofs (cached per Computation)
# ---------------------------------------------------------------------------

def _abstract_outputs(comp: Computation):
    """``(eval_shape outputs, shared lead symbol)`` under symbolic
    avals, or ``None`` for symbolic-hostile / row-dim-free computations.
    Cached on the computation — one abstract eval per comp per process."""
    cached = getattr(comp, "_tft_plan_absout", False)
    if cached is not False:
        return cached
    res = None
    try:
        import jax
        avals, _ = _sym_avals(comp.inputs, share_lead_symbol=True)
        lead = None
        for spec, av in zip(comp.inputs, avals):
            if spec.shape.ndim > 0 and spec.shape.head == -1:
                lead = av.shape[0]
                break
        if lead is not None:
            out = jax.eval_shape(
                comp.fn, {s.name: a for s, a in zip(comp.inputs, avals)})
            res = (out, lead)
    except Exception as e:
        # not an error: the computation simply stays unfused
        _log.debug("abstract eval for fusion proof failed (%s: %s); "
                   "computation stays unfused", type(e).__name__, e)
        res = None
    try:
        comp._tft_plan_absout = res
    except Exception as e:
        _log.debug("could not cache fusion proof on %r: %s", comp, e)
    return res


def _row_preserving(comp: Computation) -> bool:
    """Every fetch provably keeps the shared input row symbol — the
    static discharge of the per-op runtime row-count check."""
    r = _abstract_outputs(comp)
    if r is None:
        return False
    out, lead = r
    for name in comp.output_names:
        sh = out[name].shape
        if len(sh) == 0 or not bool(sh[0] == lead):
            return False
    return True


def _uniform_lead(comp: Computation) -> bool:
    """All fetches provably share ONE lead expression (the trim
    contract: fetches may change the row count, but must agree)."""
    r = _abstract_outputs(comp)
    if r is None:
        return False
    out, _ = r
    first = None
    for name in comp.output_names:
        sh = out[name].shape
        if len(sh) == 0:
            return False
        if first is None:
            first = sh[0]
        elif not bool(sh[0] == first):
            return False
    return True


def _mask_shaped(comp: Computation) -> bool:
    """The filter predicate provably yields one block-length vector."""
    r = _abstract_outputs(comp)
    if r is None:
        return False
    out, lead = r
    sh = out[comp.output_names[0]].shape
    return len(sh) == 1 and bool(sh[0] == lead)


# ---------------------------------------------------------------------------
# chain linearization
# ---------------------------------------------------------------------------

def linearize(frame):
    """``(leaf_node, [op nodes leaf->final])`` or ``None``.

    Walks ``input`` links from the frame's node; an upstream op whose
    own frame is already forced becomes the leaf (its cached blocks are
    free — exactly what the per-op thunk would reuse)."""
    node = getattr(frame, "_plan_node", None)
    if node is None:
        return None
    chain: List[_n.PlanNode] = []
    while node is not None:
        if node.kind not in _n.OP_KINDS:
            chain.reverse()
            return node, chain
        rf = node.result_ref() if node.result_ref is not None else None
        if rf is not None and rf is not frame \
                and getattr(rf, "_cache", None) is not None:
            chain.reverse()
            return _n.SourceNode(rf), chain
        chain.append(node)
        node = node.input
    return None


# ---------------------------------------------------------------------------
# composed computations (structurally cached)
# ---------------------------------------------------------------------------

# anchor comp (weak) -> {structural key: (composed, [strong member refs])}
# The strong refs keep the other members' id()s valid for as long as the
# entry lives; the anchor itself must NOT be held strongly by its own
# entry (a value->key reference in a WeakKeyDictionary would leak).
_composed_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_composed_lock = threading.Lock()

# a stage member is ("mb", comp, trim) | ("mr", vcomp) | ("f", comp)
# | ("sel", names)
Member = Tuple


def _compose(members: Sequence[Member], in_specs: List[TensorSpec],
             out_specs: List[TensorSpec]) -> Computation:
    mem = tuple(members)
    data_names = tuple(s.name for s in out_specs if s.name != MASK)
    has_mask = any(s.name == MASK for s in out_specs)

    def fused_fn(d):
        env = dict(d)
        mask = None
        for m in mem:
            if m[0] == "sel":
                keep = set(m[1])
                env = {k: v for k, v in env.items() if k in keep}
                continue
            comp = m[1]
            out = comp.fn({n: env[n] for n in comp.input_names})
            if m[0] == "f":
                mask = out[comp.output_names[0]]
            elif m[0] == "mb" and m[2]:
                env = dict(out)  # trim: only the fetches survive
            else:
                env.update(out)
        res = {n: env[n] for n in data_names}
        if has_mask:
            res[MASK] = mask
        return res

    return Computation(fused_fn, in_specs, out_specs)


def _member_key(m: Member):
    if m[0] == "sel":
        return ("sel", m[1])
    if m[0] == "mb":
        return ("mb", id(m[1]), m[2])
    return (m[0], id(m[1]))


def _composed_cached(members: Sequence[Member], in_specs: List[TensorSpec],
                     out_specs: List[TensorSpec]) -> Computation:
    anchor = next((m[1] for m in members if m[0] != "sel"), None)
    if anchor is None:
        return _compose(members, in_specs, out_specs)
    key = (tuple(_member_key(m) for m in members),
           tuple(s.name for s in in_specs),
           tuple((s.name, s.dtype.name, tuple(s.shape.dims))
                 for s in out_specs))
    try:
        with _composed_lock:
            per = _composed_cache.setdefault(anchor, {})
            hit = per.get(key)
    except TypeError:  # unweakrefable anchor: compose fresh
        return _compose(members, in_specs, out_specs)
    if hit is not None:
        return hit[0]
    comp = _compose(members, in_specs, out_specs)
    strong = [m[1] for m in members
              if m[0] != "sel" and m[1] is not anchor]
    with _composed_lock:
        per = _composed_cache.setdefault(anchor, {})
        hit = per.setdefault(key, (comp, strong))
    return hit[0]


# ---------------------------------------------------------------------------
# stages and the executable plan
# ---------------------------------------------------------------------------

class Stage:
    """One fused dispatch: a composed program plus the host-side glue
    around it (passthrough columns, the filter mask, the boundary
    schema for mid-chain empty results)."""

    __slots__ = ("comp", "inputs", "outputs", "passthrough", "mask",
                 "label", "op_end", "boundary_schema", "row_local")

    def __init__(self, comp: Optional[Computation], inputs: Tuple[str, ...],
                 outputs: Tuple[str, ...], passthrough: Tuple[str, ...],
                 mask: bool, label: str, op_end: int, boundary_schema,
                 row_local: bool):
        self.comp = comp
        self.inputs = inputs
        self.outputs = outputs
        self.passthrough = passthrough
        self.mask = mask
        self.label = label
        self.op_end = op_end  # index into ExecPlan.ops of the last op
        self.boundary_schema = boundary_schema
        # every member is a vmapped map_rows: rows are independent BY
        # CONSTRUCTION, so the stage keeps the per-op map_rows executor
        # semantics — bucketed padding and the reactive OOM split. A
        # stage with a map_blocks/filter member may be cross-row
        # (z = x - mean(x)) and must run exact-shape, like its per-op
        # twin does through the default executor.
        self.row_local = row_local


class ExecPlan:
    """The optimizer's output: leaf + stages + pruning decisions."""

    __slots__ = ("leaf", "ops", "stages", "final_schema", "leaf_required",
                 "scan_names", "device_ops", "pruned", "scan_atoms",
                 "row_local_chain", "priced_sel", "reordered")

    def __init__(self, leaf, ops, stages, final_schema, leaf_required,
                 scan_names, device_ops, pruned, scan_atoms=(),
                 row_local_chain=False, priced_sel=None, reordered=False):
        self.leaf = leaf
        self.ops = ops
        self.stages = stages
        self.final_schema = final_schema
        self.leaf_required = leaf_required  # leaf columns actually needed
        self.scan_names = scan_names        # leaf columns feeding programs
        self.device_ops = device_ops
        self.pruned = pruned                # leaf columns NOT read
        self.scan_atoms = scan_atoms        # parquet pushdown predicates
        # every device op provably row-local (vmapped map_rows, selects,
        # atom-proven filter predicates): the adaptive block-sizing pass
        # may legally re-bucket the leaf stream (docs/adaptive.md)
        self.row_local_chain = row_local_chain
        # selectivity each filter op was PRICED at when this plan was
        # built (None = the keeps-everything upper bound); the executor
        # compares observations against these to trigger a re-plan
        self.priced_sel = priced_sel or {}
        self.reordered = reordered  # filter run re-ordered by feedback

    def describe(self) -> List[str]:
        """``explain()``'s plan section: fused groups, pruned columns,
        resident edges."""
        lines = [f"  plan     : {len(self.ops) + 1} node(s) -> "
                 f"{len(self.stages)} fused stage(s), "
                 f"{self.device_ops} device op(s) fused (TFT_FUSE=1)"]
        src = self.leaf.describe()
        if self.pruned:
            lines.append(
                f"    source : {src} · read {len(self.leaf_required)}/"
                f"{len(self.leaf.schema)} column(s) "
                f"{list(self.leaf_required)} (pruned {list(self.pruned)})")
        else:
            lines.append(f"    source : {src} · "
                         f"{len(self.leaf_required)} column(s)")
        if self.scan_atoms:
            preds = ", ".join(f"{a.column} {a.op} {a.value:g}"
                              for a in self.scan_atoms)
            lines.append(
                f"    pushdown: [{preds}] checked against row-group "
                f"footer statistics (refuted groups never read)")
        if self.reordered:
            lines.append(
                "    adaptive: conjunctive filters re-ordered by "
                "observed selectivity (TFT_ADAPTIVE=1, "
                "docs/adaptive.md)")
        for i, st in enumerate(self.stages):
            edge = ("host rows" if i == 0 else "device-resident")
            mask_s = " · mask applied host-side" if st.mask else ""
            lines.append(
                f"    stage {i}: {st.label} -> 1 dispatch/block "
                f"(in: {edge}){mask_s}")
        return lines


def _atom_filter(comp) -> bool:
    """True when the predicate's sole output is PROVEN a conjunction of
    column-vs-literal comparisons (:mod:`.predicates`) — i.e. the
    predicate is row-local: its mask row depends only on that row."""
    from .predicates import extract_atoms
    return bool(extract_atoms(comp))


def _reorder_filters(ops):
    """Adaptive re-planning (docs/adaptive.md): runs of ADJACENT
    filters whose predicates are all atom-proven (row-local, so they
    commute — same final row set, same order, same block boundaries)
    re-order most-selective-first by observed selectivity, so later
    filter dispatches see fewer rows. Unobserved predicates price at
    the keeps-everything upper bound and keep their position
    (stable sort). Returns ``(ops, reordered)``."""
    from .adaptive import enabled as _adaptive_on
    if not _adaptive_on():
        return ops, False
    out = list(ops)
    changed = False
    i = 0
    while i < len(out):
        if out[i].kind != "filter" or not _atom_filter(out[i].comp):
            i += 1
            continue
        j = i
        while j < len(out) and out[j].kind == "filter" \
                and _atom_filter(out[j].comp):
            j += 1
        if j - i > 1:
            run = out[i:j]
            sels = [_n.observed_selectivity(o.comp) for o in run]
            if any(s is not None for s in sels):
                order = sorted(range(len(run)),
                               key=lambda k: (sels[k] if sels[k]
                                              is not None else 1.0, k))
                if order != list(range(len(run))):
                    out[i:j] = [run[k] for k in order]
                    changed = True
                    from ..observability import flight as _flight
                    _flight.record(
                        "plan.filter_reorder", order=order,
                        selectivities=[round(s, 6) if s is not None
                                       else None for s in sels])
        i = j
    if changed:
        from ..utils.tracing import counters
        counters.inc("plan.filter_reorders")
    return out, changed


def build_plan(frame) -> Optional[ExecPlan]:
    """Optimize ``frame``'s recorded chain, or ``None`` for the per-op
    fallback. Never raises for an unsupported chain — unsupported means
    unplanned, not failed."""
    if not enabled():
        return None
    from ..engine.executor import BlockExecutor, default_executor
    if type(default_executor()) is not BlockExecutor:
        # a non-default process executor (native PJRT core) keeps the
        # per-op path: fused chaining relies on keep_device dispatches
        return None
    lin = linearize(frame)
    if lin is None:
        return None
    leaf, ops = lin
    if not ops:
        return None
    device_ops = sum(1 for o in ops
                     if o.kind in ("map_blocks", "map_rows", "filter"))
    # parquet scans prune their read; join leaves prune the columns
    # the join materializes (docs/joins.md)
    prunable_leaf = leaf.kind in ("parquet", "join")
    if device_ops < 2 and not prunable_leaf:
        return None  # nothing to win; per-op semantics stay canonical
    if MASK in leaf.schema or any(MASK in o.schema for o in ops):
        return None
    # adaptive re-plan (docs/adaptive.md): order observed-selective
    # conjunctive filters first — on every forcing AND between stream
    # batches, since each batch builds a fresh plan over the shared
    # canonical computations carrying the observations
    ops, reordered = _reorder_filters(ops)

    # legality: every device op must carry a proof, or the chain falls
    # back wholesale (all-or-nothing keeps error contracts identical)
    for o in ops:
        if o.kind == "map_blocks":
            if getattr(o.comp, "_native_dynamic", None) is not None:
                return None  # foreign/static modules stay per-op
            if o.trim:
                if not _uniform_lead(o.comp):
                    return None
            elif not _row_preserving(o.comp):
                return None
        elif o.kind == "map_rows":
            if o.vcomp is None \
                    or getattr(o.comp, "_native_dynamic", None) is not None:
                return None
        elif o.kind == "filter":
            if not _mask_shaped(o.comp):
                return None

    # backward pass: required columns after (and before) every op
    final_schema = ops[-1].schema
    need: Set[str] = set(final_schema.names)
    req_after: List[Set[str]] = [set()] * len(ops)
    for i in range(len(ops) - 1, -1, -1):
        req_after[i] = set(need)
        o = ops[i]
        if o.kind == "map_blocks":
            if o.trim:
                need = set(o.comp.input_names)
            else:
                need = (need - set(o.comp.output_names)) \
                    | set(o.comp.input_names)
        elif o.kind == "map_rows":
            need = (need - set(o.comp.output_names)) \
                | set(o.comp.input_names)
        elif o.kind == "filter":
            need = need | set(o.comp.input_names)
        # select: need is already a subset of the selected names
    if not len(final_schema):
        # select([]) chains: the per-op path owns the empty-schema
        # corner (a zero-output fused program cannot carry the row
        # count a mid-chain trim may have changed)
        return None
    leaf_required = tuple(f.name for f in leaf.schema if f.name in need)
    if not leaf_required and len(leaf.schema):
        return None  # empty projection: a corner the per-op path owns

    # forward simulation: group ops into stages, resolve program
    # inputs/outputs, keep per-name block-level specs
    spec_of: Dict[str, TensorSpec] = {}
    origin: Dict[str, str] = {}
    for f in leaf.schema:
        if f.name not in need:
            continue
        spec_of[f.name] = (TensorSpec(f.name, f.dtype, f.block_shape)
                           if f.dtype.tensor and f.block_shape is not None
                           else None)
        origin[f.name] = "leaf"
    live: Set[str] = set(leaf_required)
    stages: List[Stage] = []
    members: List[Member] = []
    ext: Dict[str, TensorSpec] = {}
    internal: Set[str] = set()
    labels: List[str] = []
    scan_names: Set[str] = set()

    def close(idx: int, mask_member: Optional[Member]) -> None:
        nonlocal live, members, ext, internal, labels
        req = req_after[idx]
        produced = tuple(n for n in sorted(live)
                         if n in internal and n in req)
        passthrough = tuple(n for n in sorted(live)
                            if n not in internal and n in req)
        out_specs = [spec_of[n] for n in produced]
        if mask_member is not None:
            mspec = mask_member[1].outputs[0]
            out_specs.append(TensorSpec(MASK, mspec.dtype, mspec.shape))
        comp = None
        if any(m[0] != "sel" for m in members) or mask_member is not None:
            mem = list(members) + ([mask_member] if mask_member else [])
            in_specs = [ext[n] for n in sorted(ext)]
            comp = _composed_cached(mem, in_specs, out_specs)
        if comp is not None:
            row_local = (mask_member is None
                         and all(m[0] in ("mr", "sel") for m in members)
                         and any(m[0] == "mr" for m in members))
            stages.append(Stage(
                comp, tuple(sorted(ext)), produced, passthrough,
                mask_member is not None, "+".join(labels) or "pass",
                idx, ops[idx].schema, row_local))
        live = set(produced) | set(passthrough)
        for n in produced:
            origin[n] = "computed"
        members, ext, internal, labels = [], {}, set(), []

    bailed = False
    for i, o in enumerate(ops):
        if o.kind == "select":
            keep = set(o.names)
            live &= keep
            internal &= keep
            members.append(("sel", tuple(o.names)))
            continue
        comp = o.vcomp if o.kind == "map_rows" else o.comp
        ok = True
        for n in comp.input_names:
            if n in internal:
                continue
            sp = spec_of.get(n)
            if n not in live or sp is None:
                ok = False
                break
            ext.setdefault(n, sp)
            if origin.get(n) == "leaf":
                scan_names.add(n)
        if not ok:
            bailed = True
            break
        if o.kind == "filter":
            labels.append("filter")
            close(i, ("f", comp))
            continue
        trim = o.kind == "map_blocks" and o.trim
        members.append(("mb", comp, trim) if o.kind == "map_blocks"
                       else ("mr", comp))
        labels.append(o.kind + ("[trim]" if trim else ""))
        if trim:
            live, internal = set(), set()
        for s in comp.outputs:
            live.add(s.name)
            internal.add(s.name)
            spec_of[s.name] = s
            origin[s.name] = "computed"
    if bailed:
        return None
    if members:
        close(len(ops) - 1, None)
    if not stages:
        # a pure-projection chain still plans when it prunes a parquet
        # read; otherwise the per-op path is already minimal
        if not (prunable_leaf and len(leaf_required) < len(leaf.schema)):
            return None
    pruned = tuple(f.name for f in leaf.schema if f.name not in need) \
        if prunable_leaf else ()
    # adaptive legality + priced selectivities (docs/adaptive.md): the
    # block re-bucketing pass may only touch chains whose every device
    # op is provably row-local — vmapped map_rows, selects, and
    # atom-proven filter predicates (cross-row map_blocks statistics
    # would change under coalescing); filters record the selectivity
    # this plan priced them at, the re-plan trigger's baseline
    row_local_chain = bool(stages) and all(
        o.kind in ("map_rows", "select")
        or (o.kind == "filter" and _atom_filter(o.comp))
        for o in ops)
    priced_sel = {i: _n.observed_selectivity(o.comp)
                  for i, o in enumerate(ops) if o.kind == "filter"}
    return ExecPlan(leaf, list(ops), stages, final_schema, leaf_required,
                    frozenset(scan_names), device_ops, pruned,
                    _scan_atoms(leaf, ops), row_local_chain=row_local_chain,
                    priced_sel=priced_sel, reordered=reordered)


def _scan_atoms(leaf, ops):
    """Pushdown atoms for a parquet leaf (ROADMAP 2c): conjunctive
    ``column <op> literal`` filter predicates over SCAN columns,
    extractable from any filter BEFORE the first trim (a trim replaces
    the schema, severing column identity; non-trim maps only append —
    fetch-name collisions are rejected — so a leaf-named column still
    carries the leaf's values at every later filter). Sound for
    whole-group skipping regardless of earlier filters: a group whose
    every row fails the predicate contributes nothing downstream.
    Explicitly re-partitioned scans (``num_partitions=``) push down
    too: the scan node remaps surviving group rows onto the partition
    spans the unpushed read would have produced (``docs/plan.md``)."""
    if leaf.kind != "parquet":
        return ()
    from .predicates import extract_atoms
    leaf_cols = set(leaf.schema.names)
    atoms = []
    for o in ops:
        if o.kind == "map_blocks" and o.trim:
            break
        if o.kind != "filter":
            continue
        for a in extract_atoms(o.comp):
            if a.column in leaf_cols:
                atoms.append(a)
    return tuple(atoms)
