"""Plan executor: run an :class:`~.optimize.ExecPlan` block by block.

Execution contract (what makes fused == unfused bit-identical):

- every fused program dispatches through the process-default
  :class:`~..engine.executor.BlockExecutor` — the same retry loop, OOM
  split, fault sites, memory admission, compile caches, and serve
  interner as the per-op path;
- intermediates between stages stay DEVICE-resident
  (``keep_device=True`` dispatches feed the next stage's inputs
  buffer-to-buffer); the storage-dtype host round trip they skip is
  value-exact (f32 -> f64 -> f32 and friends are lossless in that
  direction), and the final stage converts to storage dtypes with the
  executor's own rules;
- filter masks are computed inside the fused program but applied on the
  host (a data-dependent row count cannot live in one static-shape XLA
  program): the mask row is the only D2H transfer at a stage boundary —
  value columns gather on device;
- 0-row blocks (empty partitions, filters that drop everything) replay
  the per-op chain's EMPTY-block semantics op by op on the host, so
  even degenerate shapes/dtypes match the unfused path exactly;
- a runtime condition the optimizer could not see (a ragged column
  feeding a program) abandons the plan BEFORE any work and returns
  ``None`` — the caller then runs the unchanged per-op thunk.
"""

from __future__ import annotations

import contextlib
from typing import Dict, List, Optional

import numpy as np

from ..engine import preempt as _preempt
from ..resilience import invariants as _invariants
from ..shape import Unknown
from ..utils.logging import get_logger
from ..utils.tracing import counters, span
from .optimize import MASK, ExecPlan, build_plan

__all__ = ["maybe_run"]

_log = get_logger("plan.execute")


def maybe_run(frame) -> Optional[List]:
    """Force ``frame`` through its optimized plan; ``None`` defers to
    the per-op thunk (fusion off, unplannable chain, ragged inputs).

    Plan BUILD problems are never fatal (unplanned, not failed);
    execution errors propagate — they come out of the same resilient
    executor the per-op path uses, after the same recovery attempts.
    """
    try:
        plan = build_plan(frame)
    except Exception as e:
        _log.debug("plan build failed (%s: %s); using the per-op path",
                   type(e).__name__, e)
        plan = None
    if plan is None:
        frame._plan_info = None
        return None
    leaf = plan.leaf
    if leaf.kind == "parquet":
        leaf_blocks = leaf.read_blocks(plan.leaf_required,
                                       atoms=plan.scan_atoms)
    else:
        if leaf.kind == "join":
            # pruning reaches INTO the join: only the columns this
            # chain needs are gathered/materialized (docs/joins.md)
            leaf_blocks = leaf.read_blocks(plan.leaf_required)
        else:
            leaf_blocks = leaf.frame.blocks()
        for b in leaf_blocks:
            for n in plan.scan_names:
                if b.num_rows and b.is_ragged(n):
                    # ragged computation inputs belong to the per-op
                    # path (map_rows' per-signature grouping)
                    frame._plan_info = None
                    return None
    try:
        with span("plan.execute"):
            blocks = _run(plan, leaf_blocks, frame)
    except Exception as e:
        from ..resilience import is_oom
        if is_oom(e):
            # recovery parity: stages that are not provably row-local
            # cannot split an OOM'd fused dispatch — the per-op path
            # can (op-granular splits), so hand the forcing back to it
            # instead of failing a query the unfused engine survives
            counters.inc("plan.oom_fallbacks")
            from ..observability import flight as _flight
            _flight.record("plan.oom_fallback",
                           error=type(e).__name__)
            _log.warning(
                "fused plan hit an OOM its stage could not split (%s); "
                "re-running through the per-op path", e)
            frame._plan_info = None
            return None
        raise
    counters.inc("plan.fused_queries")
    frame._plan_info = plan.describe()
    return blocks


# ---------------------------------------------------------------------------
# empty-block replay (per-op semantics, host-only)
# ---------------------------------------------------------------------------

def _empty_chain(ops, b):
    """Apply each op's per-op EMPTY-block behavior to a 0-row block —
    delegating to the ops module's own constructions so the two paths
    can never drift."""
    from ..engine.ops import empty_fetch_columns, empty_schema_block
    for o in ops:
        if o.kind == "select":
            b = b.select(list(o.names))
        elif o.kind == "filter":
            pass  # per-op filter returns 0-row blocks unchanged
        elif o.kind == "map_blocks":
            b = empty_schema_block(o.schema)
        else:  # map_rows appends empty fetch columns
            b = empty_fetch_columns(b, o.comp.outputs)
    return b


# ---------------------------------------------------------------------------
# value plumbing
# ---------------------------------------------------------------------------

def _is_device(v) -> bool:
    import jax
    return isinstance(v, jax.Array)


def _mask_value(v, mask: np.ndarray, idx: np.ndarray):
    if isinstance(v, np.ndarray):
        return v[mask]
    if _is_device(v):
        return v[idx]  # device gather; stays resident
    return [v[i] for i in idx]  # ragged / list-backed passengers


def _to_storage(v, field) -> object:
    """Executor ``_convert_back`` rules for one final column (host
    values passed through untouched, like per-op passthrough)."""
    if isinstance(v, np.ndarray) or isinstance(v, list):
        return v
    from ..engine.executor import to_storage_dtype
    return to_storage_dtype(np.asarray(v), field.dtype)


def _env_to_block(env: Dict[str, object], schema, num_rows: int):
    """A boundary-schema block from the (possibly pruned) env. Pruned
    columns are rebuilt empty from their field spec — only legal at 0
    rows, where the per-op path's own empty reconstruction does the
    same; a pruned column can never reach the final schema."""
    from ..frame import Block
    cols = {}
    for f in schema:
        if f.name in env:
            cols[f.name] = _to_storage(env[f.name], f)
        else:
            cell = f.cell_shape
            dims = tuple(0 if d == Unknown else d
                         for d in (cell.dims if cell else ()))
            cols[f.name] = np.empty((0,) + dims, f.dtype.np_storage)
    return Block(cols, num_rows)


def _final_block(plan: ExecPlan, env: Dict[str, object], n_rows: int):
    from ..frame import Block
    cols = {}
    for f in plan.final_schema:
        cols[f.name] = _to_storage(env[f.name], f)
    if cols:
        first = next(iter(cols.values()))
        n_rows = len(first)
    return Block(cols, n_rows)


# ---------------------------------------------------------------------------
# stage execution
# ---------------------------------------------------------------------------

def _apply_stage_result(plan, st, env, out, n_rows, aux=None):
    """Merge a stage's outputs into a fresh env; apply the mask. Returns
    ``(env, n_rows, short_circuit_block, aux)`` — the block is non-None
    when the mask dropped every row and the rest of the chain replays
    the empty-block semantics. ``aux`` is the adaptive layout's
    original-block-id row vector (``docs/adaptive.md``), masked
    alongside the env so the final outputs can be re-split on the
    original block boundaries; ``None`` (the static layout) is passed
    through untouched."""
    new_env = {n: env[n] for n in st.passthrough}
    new_env.update({n: out[n] for n in st.outputs})
    if st.mask:
        mask = np.asarray(out[MASK]).astype(bool)
        keep = int(mask.sum())
        # feedback selectivity: the forced filter's observed
        # rows-in/rows-out sharpen PlanNode.estimate() for subsequent
        # forcings and stream batches of the same predicate
        fnode = plan.ops[st.op_end]
        if fnode.kind == "filter":
            from .nodes import record_selectivity
            record_selectivity(fnode.comp, mask.size, keep)
            tin, tout = fnode.observed or (0, 0)
            fnode.observed = (tin + int(mask.size), tout + keep)
        # row-conservation ledger: the masked-out rows are FILTERED,
        # not lost (noted before the keep==0 short-circuit so a
        # drop-everything mask balances too)
        _invariants.note_filtered(mask.size - keep)
        if keep == 0:
            empty = {k: _mask_value(v, mask, np.empty(0, np.int64))
                     for k, v in new_env.items()}
            bb = _env_to_block(empty, st.boundary_schema, 0)
            if aux is not None:
                aux = aux[:0]
            return None, 0, _empty_chain(plan.ops[st.op_end + 1:], bb), aux
        # compare against the MASK length, not the stage-input row
        # count: a trim member inside the stage may have changed the
        # row count before the predicate ran
        if keep != mask.size:
            idx = np.flatnonzero(mask)
            new_env = {k: _mask_value(v, mask, idx)
                       for k, v in new_env.items()}
            if aux is not None:
                aux = aux[mask]
        n_rows = keep
    return new_env, n_rows, None, aux


def _stage_executor(st, first: bool = True):
    """The per-op executor-choice parity: pure-map_rows stages keep the
    bucketed-padding executor (and with it the reactive OOM split —
    rows independent under vmap); anything else runs exact-shape
    through the default executor, like its per-op twin.

    Only the FIRST stage (host-rows inputs) pads: ``_pad_inputs``
    stages through host buffers, which would drag a later stage's
    device-resident inputs back to host — the exact round trip the
    resident edges exist to skip."""
    from ..engine.executor import default_executor, default_padding_executor
    if st.row_local and first:
        return default_padding_executor(), True
    return default_executor(), False


def _run_rest(plan: ExecPlan, env: Dict[str, object], n_rows: int,
              start: int, aux=None):
    """Stages ``start..`` over an env, device-resident between stages.
    Returns ``(final block, aux)``."""
    for si in range(start, len(plan.stages)):
        st = plan.stages[si]
        ex, pad_ok = _stage_executor(st, first=si == 0)
        out = ex.run(st.comp, {n: env[n] for n in st.inputs},
                     pad_ok=pad_ok, keep_device=True)
        env, n_rows, short, aux = _apply_stage_result(plan, st, env, out,
                                                      n_rows, aux)
        if short is not None:
            return short, aux
    return _final_block(plan, env, n_rows), aux


def _full_leaf_empty(plan: ExecPlan, b):
    """A 0-row leaf block widened back to the FULL leaf schema: column
    pruning may have dropped columns the per-op empty replay's selects
    still name (pruned columns can never reach the final schema, so
    their spec-derived empty dims are unobservable)."""
    from ..frame import Block
    if all(f.name in b.columns for f in plan.leaf.schema):
        return b
    from ..engine.ops import empty_schema_block
    cols = dict(empty_schema_block(plan.leaf.schema).columns)
    cols.update(b.columns)  # keep the actually-decoded empties
    return Block(cols, 0)


def _plan_tag(plan: ExecPlan) -> str:
    """Stable stream identity of a plan shape: preemption checkpoints
    key on it, and the adaptive feedback registry uses it to correlate
    repeated forcings of the same chain (``docs/adaptive.md``)."""
    return (f"plan[{plan.leaf.describe()};"
            f"{','.join(o.kind for o in plan.ops)};"
            f"{sorted(plan.leaf_required)}]"
            f"({plan.final_schema.names})")


def _run(plan: ExecPlan, leaf_blocks, frame=None) -> List:
    import time as _time

    from ..engine import pipeline as _pipeline
    from . import adaptive as _adaptive
    if not plan.stages:
        # pure projection over a pruned scan: no device work at all
        out = []
        for b in leaf_blocks:
            if b.num_rows == 0:
                out.append(_empty_chain(plan.ops,
                                        _full_leaf_empty(plan, b)))
            else:
                env = {n: b.columns[n] for n in plan.leaf_required}
                out.append(_final_block(plan, env, b.num_rows))
        return out
    tag = _plan_tag(plan)
    layout = None
    if _adaptive.enabled() and plan.row_local_chain \
            and _preempt.current_scope() is None and leaf_blocks:
        # re-bucket the stream to TFT_PIPELINE_DEPTH full slots within
        # ledger headroom; outputs are re-split on the original block
        # boundaries, so the run stays bit-identical. Skipped under an
        # active preemption scope (checkpoint tags pin the block count).
        layout = _adaptive.choose_layout(
            plan, leaf_blocks, _pipeline.pipeline_depth(None), tag)
    t0 = _time.perf_counter()
    # the regression drill's deterministic slowdown (TFT_FAULTS=perf:N)
    # lands INSIDE the measured forcing wall, so the sentinel attributes
    # it to stage_wall_s like any real stage-level slowdown
    from ..resilience import faults as _faults
    _faults.slowdown("perf")
    rows_in = sum(b.num_rows for b in leaf_blocks)
    # per-query row conservation (resilience/invariants.py): a
    # row-local chain only ever drops rows through filter masks, so
    # rows in == rows out + rows filtered must balance exactly; a
    # preemption resume taints the ledger instead (the restored
    # prefix's filter counts belong to the prior attempt)
    ledger = (_invariants.row_ledger(rows_in, tag)
              if plan.row_local_chain else contextlib.nullcontext())
    with ledger:
        if layout is not None:
            out = _run_adaptive(plan, layout, frame)
        else:
            out = _run_static(plan, leaf_blocks, tag)
        _invariants.note_emitted(sum(b.num_rows for b in out))
    _adaptive.record_stream_feedback(
        tag, blocks=len(leaf_blocks), rows=rows_in,
        wall_s=_time.perf_counter() - t0,
        occupancy=_pipeline.last_occupancy())
    return out


# ---------------------------------------------------------------------------
# static layout (the pre-adaptive path, verbatim)
# ---------------------------------------------------------------------------

def _run_static(plan: ExecPlan, leaf_blocks, tag: str) -> List:
    from ..engine import pipeline as _pipeline
    from ..frame import Block
    # the FIRST stage pipelines through the executor's async
    # submit/drain halves like any per-op stream (multi-stage plans
    # drain device-resident outputs — keep_device — and complete the
    # remaining stages inside the drain, so later-stage dispatches and
    # host mask work overlap the next blocks' first-stage compute)
    st0 = plan.stages[0]
    ex0, pad0 = _stage_executor(st0, first=True)
    multi = len(plan.stages) > 1

    def finish(b, out) -> Block:
        env = {n: b.columns[n] for n in st0.passthrough}
        env, n_rows, short, _ = _apply_stage_result(plan, st0, env, out,
                                                    b.num_rows)
        if short is not None:
            return short
        if multi:
            return _run_rest(plan, env, n_rows, 1)[0]
        return _final_block(plan, env, n_rows)

    def serial_fn(b):
        if b.num_rows == 0:
            return _empty_chain(plan.ops, _full_leaf_empty(plan, b))
        out = ex0.run(st0.comp, {n: b.columns[n] for n in st0.inputs},
                      pad_ok=pad0, keep_device=multi)
        return finish(b, out)

    def submit_fn(b):
        if b.num_rows == 0:
            # finished: flows through the window
            return _empty_chain(plan.ops, _full_leaf_empty(plan, b))
        return ex0.submit(st0.comp,
                          {n: b.columns[n] for n in st0.inputs},
                          pad_ok=pad0, keep_device=multi)

    def drain_fn(pending, b):
        if isinstance(pending, Block):
            return pending
        return finish(b, pending.drain())

    return _pipeline.run_pipelined(
        leaf_blocks, serial_fn, submit_fn, drain_fn,
        depth=_pipeline.stream_depth(ex0),
        # stream identity for preemption checkpoints: a resume whose
        # forcing no longer takes the fused path (e.g. after an OOM
        # fallback) must discard, not restore these FINAL per-block
        # results into a per-op stream of the same length — and two
        # sibling plans in one query must not collide, so the tag
        # carries the leaf identity (scan path / source plan), the op
        # kinds, the read columns, and the output schema
        tag=tag)


# ---------------------------------------------------------------------------
# adaptive layout (docs/adaptive.md): re-bucketed stream + restore
# ---------------------------------------------------------------------------

def _unit_fns(plan: ExecPlan):
    """serial/submit/drain halves over layout units ``(block,
    orig_ids, orig_list)``; results are ``(final block, surviving
    orig_ids)`` pairs. Units are never empty (0-row originals are
    excluded from the layout and replayed verbatim at restore)."""
    st0 = plan.stages[0]
    ex0, pad0 = _stage_executor(st0, first=True)
    multi = len(plan.stages) > 1

    def finish(b, ids, out):
        env = {n: b.columns[n] for n in st0.passthrough}
        env, n_rows, short, ids = _apply_stage_result(plan, st0, env,
                                                      out, b.num_rows,
                                                      ids)
        if short is not None:
            return short, ids
        if multi:
            return _run_rest(plan, env, n_rows, 1, ids)
        return _final_block(plan, env, n_rows), ids

    def serial_fn(unit):
        b, ids, _ = unit
        out = ex0.run(st0.comp, {n: b.columns[n] for n in st0.inputs},
                      pad_ok=pad0, keep_device=multi)
        return finish(b, ids, out)

    def submit_fn(unit):
        b, _, _ = unit
        return ex0.submit(st0.comp,
                          {n: b.columns[n] for n in st0.inputs},
                          pad_ok=pad0, keep_device=multi)

    def drain_fn(pending, unit):
        if isinstance(pending, tuple):
            return pending
        return finish(unit[0], unit[1], pending.drain())

    return serial_fn, submit_fn, drain_fn, ex0


def _should_replan(plan: ExecPlan):
    """The worst ``(priced, observed)`` selectivity pair deviating past
    ``TFT_REPLAN_RATIO``, or ``None`` (plan still priced right). The
    pair is the re-plan decision's recorded INPUT — what the plan
    believed vs what the blocks showed (docs/observability.md)."""
    from . import adaptive as _adaptive
    from .nodes import observed_selectivity
    ratio = _adaptive.replan_ratio()
    worst = None
    worst_dev = ratio
    for i, sel0 in plan.priced_sel.items():
        cur = observed_selectivity(plan.ops[i].comp)
        if cur is None:
            continue
        a = max(sel0 if sel0 is not None else 1.0, 1e-6)
        b = max(cur, 1e-6)
        dev = max(a, b) / min(a, b)
        if dev > worst_dev:
            worst_dev = dev
            worst = (a, b)
    return worst


def _run_adaptive(plan: ExecPlan, layout, frame) -> List:
    from ..engine import pipeline as _pipeline
    from ..observability import flight as _flight
    from ..observability.events import add_event
    from ..utils.tracing import counters as _counters
    serial_fn, submit_fn, drain_fn, ex0 = _unit_fns(plan)
    units = layout.units
    add_event("adaptive_layout", name=plan.leaf.describe(),
              blocks=layout.n_orig, units=len(units),
              coalesced=layout.coalesced_from, splits=layout.splits)
    _flight.record("plan.adaptive_layout", blocks=layout.n_orig,
                   units=len(units), coalesced=layout.coalesced_from,
                   splits=layout.splits,
                   depth=_pipeline.pipeline_depth(None))
    # probe the first unit serially: its observed selectivities are the
    # re-plan trigger for the remaining stages (ROADMAP 2d) — a
    # mid-plan boundary, not a new forcing
    outs = [serial_fn(units[0])]
    rest_plan = plan
    deviation = (_should_replan(plan)
                 if len(units) > 1 and frame is not None else None)
    if deviation is not None:
        try:
            from .optimize import build_plan
            new_plan = build_plan(frame)
        except Exception as e:  # noqa: BLE001 - replan is best-effort
            _log.debug("mid-plan replan failed (%s); keeping the "
                       "current plan", e)
            new_plan = None
        # adopt the re-planned stages only when they are shape-safe
        # (same read set, still row-local) AND actually different
        if new_plan is not None and new_plan.row_local_chain \
                and new_plan.leaf_required == plan.leaf_required \
                and [id(o.comp) for o in new_plan.ops
                     if o.kind != "select"] \
                != [id(o.comp) for o in plan.ops if o.kind != "select"]:
            rest_plan = new_plan
            _counters.inc("plan.replans")
            add_event("replan", name=plan.leaf.describe(),
                      at_block=int(len(units[0][2])))
            from .adaptive import replan_ratio as _replan_ratio
            _flight.record("plan.replan",
                           at_block=int(len(units[0][2])),
                           priced=round(deviation[0], 6),
                           observed=round(deviation[1], 6),
                           ratio=_replan_ratio())
            _log.info("mid-plan replan: observed selectivity deviated "
                      "past TFT_REPLAN_RATIO; re-ordered the remaining "
                      "filter stages")
            serial_fn, submit_fn, drain_fn, ex0 = _unit_fns(rest_plan)
    outs.extend(_pipeline.run_pipelined(
        units[1:], serial_fn, submit_fn, drain_fn,
        depth=_pipeline.stream_depth(ex0), tag=None))
    return _restore_layout(rest_plan, layout, outs)


def _slice_final(block, lo: int, hi: int):
    from ..frame import Block
    from .adaptive import _slice_cols
    return Block(_slice_cols(block, list(block.columns), lo, hi),
                 hi - lo)


def _restore_layout(plan: ExecPlan, layout, outs) -> List:
    """Re-split the adaptive units' outputs on the ORIGINAL block
    boundaries (the ids vector survived every mask), splice the empty
    originals' verbatim empty-chain replays back in — the result is
    bit-identical to the static layout, boundaries included."""
    per: List[List] = [[] for _ in range(layout.n_orig)]
    for (blk, ids), (_, _, orig_list) in zip(outs, layout.units):
        present = set()
        if ids is not None and len(ids):
            cuts = np.flatnonzero(np.diff(ids)) + 1
            bounds = np.concatenate(([0], cuts, [len(ids)]))
            for lo, hi in zip(bounds[:-1], bounds[1:]):
                oid = int(ids[int(lo)])
                present.add(oid)
                per[oid].append(_slice_final(blk, int(lo), int(hi)))
        for oid in orig_list:
            if oid not in present:
                # every row of this original was filtered out: a 0-row
                # slice of the unit's (final-schema) output carries the
                # exact dtypes/cell dims the static path produces
                per[oid].append(_slice_final(blk, 0, 0))
    from ..frame import Block
    out: List = []
    empties = dict(layout.empty_blocks)
    for i in range(layout.n_orig):
        if i in empties:
            out.append(_empty_chain(plan.ops,
                                    _full_leaf_empty(plan, empties[i])))
        elif len(per[i]) == 1:
            out.append(per[i][0])
        else:
            # split originals: stitch the sub-units' outputs back with
            # the ONE canonical concat (frame.Block.concat), so shape
            # unification and ragged handling can never drift from it
            out.append(Block.concat(per[i], plan.final_schema))
    return out
