"""Adaptive execution: runtime feedback closes the planning loop.

The plan IR prices everything at construction time — filters at their
upper bound, block sizes fixed by the source layout, join strategies
chosen once from static estimates. This module is the feedback half
(``docs/adaptive.md``), in three legs:

1. **Adaptive block sizing** (ROADMAP 2b): a per-plan
   :class:`StreamFeedback` record — observed blocks/rows/wall and the
   pipeline stream's mean window occupancy — gates and sizes a block
   coalesce/split pass (:func:`choose_layout`) that the plan executor
   runs between the leaf and its first fused stage. Small blocks waste
   dispatch, big blocks fight the memory ledger, so the chosen size
   targets ``TFT_PIPELINE_DEPTH`` full slots within ledger headroom.
   The pass engages only for chains every one of whose device ops is
   provably ROW-LOCAL (vmapped ``map_rows``, ``select``, and filters
   whose predicates are proven conjunctions of column-vs-literal
   atoms — :mod:`.predicates`), and only after a first measured
   forcing of the same plan shape; the executor restores the original
   block boundaries afterwards, so the re-bucketed run is bit-identical
   to the static layout, boundaries included. Re-bucketed dispatches
   reuse the padded-bucket compile cache (row-local first stages run
   through the padding executor, whose power-of-two row buckets are
   size-oblivious by construction).

2. **Mid-plan re-planning** (ROADMAP 2d): at stage boundaries the
   executor compares observed filter selectivities against what the
   plan priced at build time; off by more than ``TFT_REPLAN_RATIO``
   the optimizer re-runs over the remaining blocks with the observed
   values as leaf estimates (``plan.replans``), concretely re-ordering
   conjunctive filter stages by observed selectivity
   (:func:`~.optimize.build_plan`'s reorder pass) — and, through the
   epoch-keyed estimate caches of :mod:`.nodes`, re-pricing every
   subsequent forcing and stream batch. Join cardinality from sketches
   (``relational/join.py:approx_key_distinct`` + the BuildTable's
   unique-key spans) feeds the broadcast-vs-chunked decision the same
   way.

3. **Plan-fingerprint result cache** (ROADMAP 3d): ``(structural plan
   fingerprint, source versions)`` → collected result, so a repeated
   hot query costs zero dispatches. Fingerprints intern the leaf's
   identity (parquet footer identity — path, mtime, size, row-group
   range — or a forced source frame's identity + version counter) plus
   the canonical Computation objects of every op (stable across
   rebuilt chains because ``engine.ops`` caches computations per
   fetches object). Admission is two-touch: a fingerprint must be SEEN
   twice before its result is stored, so one-off queries and streaming
   batches (fresh leaf per batch) never pollute the cache. Entries are
   LRU-evicted under ``TFT_RESULT_CACHE_BYTES`` /
   ``TFT_RESULT_CACHE_ENTRIES`` with their host bytes on the cache's
   own ``tft_plan_result_cache_bytes`` gauge (frames served from an
   entry register the SHARED block list with the frame-cache
   accounting themselves — a second registration would double-count);
   any source-version change
   (parquet append, ``uncache()``) changes the key, so stale entries
   can never hit and age out of the LRU. ``TFT_RESULT_CACHE=0`` turns
   the whole leg off. When the durable tier is on
   (``TFT_PERSIST_DIR``, ``memory/persist.py``), parquet-rooted
   entries also write through under a PORTABLE fingerprint
   (:func:`portable_fingerprint` — footer identity + structural
   computation signatures, no process-local ``id()``s), and a memory
   miss falls through to disk before reporting cold: a restarted
   worker serves the same plan with zero dispatches, counted
   separately as ``plan.result_cache_warm_hits``.

``TFT_ADAPTIVE=0`` disables legs 1 and 2 wholesale; every unprovable
case (non-row-local ops, ragged inputs, an active preemption scope —
whose checkpoint tags pin the static block count) falls back to
today's layout bit-identically.
"""

from __future__ import annotations

import hashlib
import os
import threading
import time
import weakref
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..resilience import env_bool, env_float, env_int
from ..utils.logging import get_logger
from ..utils.tracing import counters, gauge

__all__ = ["enabled", "result_cache_enabled", "replan_ratio",
           "StreamFeedback", "record_stream_feedback", "stream_feedback",
           "Layout", "choose_layout", "fingerprint",
           "portable_fingerprint", "cached_result",
           "offer_result", "invalidate_results", "result_cache_stats",
           "AdaptiveBatcher"]

_log = get_logger("plan.adaptive")


# ---------------------------------------------------------------------------
# knobs
# ---------------------------------------------------------------------------

def enabled() -> bool:
    """``TFT_ADAPTIVE`` gate (default on): adaptive block sizing and
    mid-plan re-planning. ``TFT_ADAPTIVE=0`` is bit-identical to the
    static layout by construction."""
    return env_bool("TFT_ADAPTIVE", True)


def result_cache_enabled() -> bool:
    """``TFT_RESULT_CACHE`` gate (default on) for the plan-fingerprint
    result cache."""
    return env_bool("TFT_RESULT_CACHE", True)


def replan_ratio() -> float:
    """Observed-vs-priced selectivity deviation (either direction)
    beyond which the executor re-plans the remaining stages
    (``TFT_REPLAN_RATIO``, default 4)."""
    return max(env_float("TFT_REPLAN_RATIO", 4.0), 1.0)


def _max_block_bytes(depth: int) -> int:
    """Per-block ceiling for the re-bucketed layout: the ledger's
    budget split across a full pipeline window (with 2x dispatch
    headroom, the executor's own reservation estimate) when a budget
    exists, else ``TFT_ADAPTIVE_MAX_BLOCK_BYTES`` (default 64 MiB)."""
    cap = env_int("TFT_ADAPTIVE_MAX_BLOCK_BYTES", 64 << 20)
    from .. import memory as _memory
    mgr = _memory.active()
    if mgr is not None and mgr.limit is not None:
        cap = min(cap, max(1, mgr.limit // max(2 * depth, 2)))
    return max(cap, 1)


# ---------------------------------------------------------------------------
# per-plan stream feedback (leg 1's measurement half)
# ---------------------------------------------------------------------------

class StreamFeedback:
    """Accumulated observations of one plan shape's forcings."""

    __slots__ = ("forcings", "blocks", "rows", "wall_s", "occupancy")

    def __init__(self):
        self.forcings = 0
        self.blocks = 0
        self.rows = 0
        self.wall_s = 0.0
        self.occupancy: Optional[float] = None  # latest mean window occ

    def mean_block_rows(self) -> float:
        return self.rows / max(self.blocks, 1)

    def per_block_wall(self) -> float:
        return self.wall_s / max(self.blocks, 1)


_fb_lock = threading.Lock()
_feedback: "OrderedDict[str, StreamFeedback]" = OrderedDict()
_FEEDBACK_CAP = 256


def record_stream_feedback(key: str, blocks: int, rows: int,
                           wall_s: float,
                           occupancy: Optional[float] = None) -> None:
    """Fold one forcing's observations into the plan shape's record
    (LRU-capped registry; keys are the plan's stable stream tags)."""
    with _fb_lock:
        fb = _feedback.get(key)
        if fb is None:
            fb = _feedback[key] = StreamFeedback()
        _feedback.move_to_end(key)
        fb.forcings += 1
        fb.blocks += int(blocks)
        fb.rows += int(rows)
        fb.wall_s += float(wall_s)
        if occupancy is not None:
            fb.occupancy = float(occupancy)
        while len(_feedback) > _FEEDBACK_CAP:
            _feedback.popitem(last=False)
    # one hook covers every feedback site (plan/execute forcings and
    # all three plan/dist fused-stage paths): the same measured wall
    # that calibrates layouts also attributes the serving query's cost
    from ..observability import baseline as _baseline
    _baseline.note_stage_wall(wall_s)


def stream_feedback(key: str) -> Optional[StreamFeedback]:
    with _fb_lock:
        fb = _feedback.get(key)
        if fb is not None:
            _feedback.move_to_end(key)
        return fb


# ---------------------------------------------------------------------------
# adaptive block layout (leg 1's decision half)
# ---------------------------------------------------------------------------

def _col_bytes(col) -> int:
    if isinstance(col, np.ndarray):
        return int(col.nbytes)
    return 8 * len(col)  # ragged ride-alongs: pointer-priced


class Layout:
    """A re-bucketed execution layout over one forcing's leaf blocks.

    ``units`` is the list the executor actually streams: each entry is
    ``(block, orig_ids, orig_list)`` — a coalesced (or split) block, an
    int32 per-row original-block index, and the ordered original
    indices the unit covers. ``empty_blocks`` are the 0-row originals
    (excluded from execution; the executor replays their empty-chain
    semantics verbatim). The executor threads ``orig_ids`` through
    every host-side mask and re-splits the final outputs on the
    original boundaries, so the adaptive run is bit-identical to the
    static one, block boundaries included.
    """

    __slots__ = ("units", "empty_blocks", "n_orig", "coalesced_from",
                 "splits")

    def __init__(self, units, empty_blocks, n_orig, coalesced_from,
                 splits):
        self.units = units
        self.empty_blocks = empty_blocks  # [(orig index, block)]
        self.n_orig = n_orig
        self.coalesced_from = coalesced_from
        self.splits = splits


def _slice_cols(block, names: Sequence[str], lo: int, hi: int):
    out: Dict[str, object] = {}
    for n in names:
        c = block.columns[n]
        out[n] = c[lo:hi] if isinstance(c, np.ndarray) else list(c[lo:hi])
    return out


def choose_layout(plan, leaf_blocks, depth: int,
                  key: str) -> Optional["Layout"]:
    """The coalesce/split pass, or ``None`` for the static layout.

    Engages only (a) after a prior measured forcing of the same plan
    shape (:func:`record_stream_feedback` — the first forcing is
    always static, so the decision is fed by observation, not
    guesswork), and (b) when the re-bucketing actually changes the
    stream: more blocks than ``depth`` full slots need (coalesce), or
    a single block past twice the ledger-derived per-block ceiling
    (split). The chosen size targets ``depth`` equally-full slots
    within that ceiling.
    """
    fb = stream_feedback(key)
    if fb is None:
        return None  # first forcing: measure before adapting
    from ..frame import Block
    names = list(plan.leaf_required)
    # the restricted leaf schema drives Block.concat — the ONE
    # canonical column-merge (shape unification, ragged fallback), so
    # coalesced leaves can never drift from frame semantics
    try:
        concat_schema = plan.leaf.schema.select(names)
    except Exception as e:  # noqa: BLE001 - a leaf shape we can't cut
        _log.debug("adaptive layout: leaf schema unselectable (%s); "
                   "keeping the static layout", e)
        return None
    entries = []  # (orig index, block, bytes)
    empty_blocks = []
    for i, b in enumerate(leaf_blocks):
        if b.num_rows == 0:
            empty_blocks.append((i, b))
            continue
        if any(n not in b.columns for n in names):
            return None  # a leaf shape the pass did not expect
        entries.append((i, b, sum(_col_bytes(b.columns[n])
                                  for n in names)))
    if not entries:
        return None
    total_bytes = sum(e[2] for e in entries)
    max_bytes = _max_block_bytes(depth)
    ideal = max(depth, -(-total_bytes // max_bytes))
    needs_coalesce = len(entries) > max(ideal, 1) * 2
    needs_split = any(e[2] > 2 * max_bytes for e in entries)
    if not needs_coalesce and not needs_split:
        return None
    target_bytes = max(1, min(max_bytes, -(-total_bytes // ideal)))
    units = []
    coalesced_from = 0
    splits = 0
    run: List[Tuple[int, object, int]] = []
    run_bytes = 0

    def flush_run():
        nonlocal run, run_bytes, coalesced_from
        if not run:
            return
        blocks = [e[1] for e in run]
        ids = np.concatenate([np.full(e[1].num_rows, e[0], np.int32)
                              for e in run])
        if len(run) == 1:
            unit_block = blocks[0]
        else:
            unit_block = Block.concat(blocks, concat_schema)
            coalesced_from += len(run)
        units.append((unit_block, ids, [e[0] for e in run]))
        run, run_bytes = [], 0

    for i, b, nb in entries:
        if nb > 2 * max_bytes and b.num_rows > 1:
            # oversized block: split row-even into ceiling-sized parts
            flush_run()
            parts = min(int(-(-nb // max_bytes)), b.num_rows)
            bounds = np.linspace(0, b.num_rows, parts + 1).astype(int)
            for lo, hi in zip(bounds[:-1], bounds[1:]):
                if hi <= lo:
                    continue
                pb = Block(_slice_cols(b, names, int(lo), int(hi)),
                           int(hi - lo))
                units.append((pb, np.full(pb.num_rows, i, np.int32),
                              [i]))
            splits += 1
            continue
        if run and run_bytes + nb > target_bytes:
            flush_run()
        run.append((i, b, nb))
        run_bytes += nb
    flush_run()
    if len(units) == len(entries) and not splits:
        return None  # the pass changed nothing; keep the static stream
    counters.inc("plan.adaptive_layouts")
    if coalesced_from:
        counters.inc("plan.adaptive_coalesces")
    if splits:
        counters.inc("plan.adaptive_splits", splits)
    _log.debug("adaptive layout: %d leaf block(s) -> %d unit(s) "
               "(coalesced %d, split %d; target %d B/block, depth %d)",
               len(leaf_blocks), len(units), coalesced_from, splits,
               target_bytes, depth)
    return Layout(units, empty_blocks, len(leaf_blocks), coalesced_from,
                  splits)


# ---------------------------------------------------------------------------
# adaptive stream batch sizing (leg 1, streaming half)
# ---------------------------------------------------------------------------

class AdaptiveBatcher:
    """AIMD row-target sizer for a stream's batches
    (``docs/streaming.md``): a batch that finished faster than
    ``TFT_ADAPTIVE_BATCH_MIN_S`` (default 5 ms) was dispatch-bound —
    double the row target; one slower than ``TFT_ADAPTIVE_BATCH_MAX_S``
    (default 100 ms) risks the ledger and latency — halve it. The
    target is capped so one batch stays within the ledger-derived
    per-block ceiling. With ``TFT_ADAPTIVE=0`` the sizer reports the
    pass-through target (one source block per batch)."""

    __slots__ = ("target", "row_bytes", "_min_s", "_max_s")

    def __init__(self, row_bytes: int = 8):
        self.target = 0  # 0 = pass-through until first observation
        self.row_bytes = max(int(row_bytes), 1)
        self._min_s = env_float("TFT_ADAPTIVE_BATCH_MIN_S", 0.005)
        self._max_s = env_float("TFT_ADAPTIVE_BATCH_MAX_S", 0.100)

    def cap_rows(self) -> int:
        from ..engine.pipeline import pipeline_depth
        return max(1, _max_block_bytes(pipeline_depth())
                   // self.row_bytes)

    def observe(self, rows: int, wall_s: float) -> None:
        if not enabled() or rows <= 0:
            return
        if self.target <= 0:
            self.target = int(rows)
        if wall_s < self._min_s:
            self.target = min(self.target * 2, self.cap_rows())
            counters.inc("stream.batch_grows")
        elif wall_s > self._max_s:
            self.target = max(self.target // 2, 1)
            counters.inc("stream.batch_shrinks")

    def want_more(self, buffered_rows: int) -> bool:
        """True while the handle should keep polling the source to fill
        the current batch."""
        return (enabled() and self.target > 0
                and buffered_rows < self.target
                and buffered_rows < self.cap_rows())


# ---------------------------------------------------------------------------
# plan-fingerprint result cache (leg 3)
# ---------------------------------------------------------------------------

class _CacheEntry:
    """One interned result. Its host bytes are accounted by the
    cache's OWN gauge (``tft_plan_result_cache_bytes``), not the
    frame-cache gauge: every frame served from the entry registers the
    same shared block list there already, and a second registration
    would double-count the bytes."""

    __slots__ = ("key", "_cache", "nbytes", "comps", "validators",
                 "__weakref__")

    def __init__(self, key, blocks, nbytes, comps, validators):
        self.key = key
        self._cache = blocks
        self.nbytes = nbytes
        self.comps = comps            # strong: pins the comp identities
        self.validators = validators  # [(frame weakref, version)]

    def valid(self) -> bool:
        # every pinned source must still be alive at the version it was
        # fingerprinted at (uncache() bumps _version; id() reuse after
        # GC is ruled out by the liveness check itself)
        for ref, version in self.validators:
            f = ref()
            if f is None or getattr(f, "_version", 0) != version:
                return False
        return True


_rc_lock = threading.Lock()
_results: "OrderedDict[tuple, _CacheEntry]" = OrderedDict()
_seen: "OrderedDict[tuple, float]" = OrderedDict()  # two-touch admission
_SEEN_CAP = 512


def _rc_budget() -> Tuple[int, int]:
    return (env_int("TFT_RESULT_CACHE_BYTES", 256 << 20),
            env_int("TFT_RESULT_CACHE_ENTRIES", 64))


def _node_fp(node, validators, comps, depth: int) -> Optional[tuple]:
    """Structural fingerprint of one plan node (None = unfingerprintable
    — the forcing is simply not cached). Join children recurse through
    :func:`_chain_fp` so their FULL upstream chains key the entry."""
    kind = node.kind
    if kind == "parquet":
        try:
            st = os.stat(node.path)
        except OSError:
            return None
        return ("pq", node.path, st.st_mtime_ns, st.st_size,
                node.row_group_offset, node.row_group_limit,
                node.columns, node.num_partitions)
    if kind == "source":
        f = node.frame
        if f is None or getattr(f, "_cache", None) is None:
            return None  # unforced source: no stable version to pin
        validators.append((weakref.ref(f), getattr(f, "_version", 0)))
        return ("src", id(f), getattr(f, "_version", 0))
    if kind == "join":
        left = _chain_fp(node.left, validators, comps, depth + 1)
        if left is None:
            return None
        if node.right is not None:
            right = _chain_fp(node.right, validators, comps, depth + 1)
            if right is None:
                return None
        elif node.build is not None:
            # pin the BuildTable itself: its identity IS the built
            # right side's content at build time
            validators.append((weakref.ref(node.build), 0))
            right = ("build", id(node.build))
        else:
            return None
        return ("join", left, right, node.on, node.how, node.strategy)
    if kind == "map_blocks":
        return ("mb", id(node.comp), node.trim)
    if kind == "map_rows":
        return ("mr", id(node.comp))
    if kind == "filter":
        return ("f", id(node.comp))
    if kind == "select":
        return ("sel", node.names)
    return None


def _chain_fp(node, validators, comps, depth: int) -> Optional[tuple]:
    """Fingerprint a whole ``input``-linked chain, leaf included."""
    parts: List[tuple] = []
    while node is not None and depth < 256:
        fp = _node_fp(node, validators, comps, depth)
        if fp is None:
            return None
        parts.append(fp)
        comp = getattr(node, "comp", None)
        if comp is not None:
            comps.append(comp)
        if node.kind == "join":
            node = None  # joins are leaves; children folded in above
        else:
            node = node.input
        depth += 1
    if node is not None:
        return None  # depth guard tripped: give up rather than collide
    return tuple(parts)


def fingerprint(frame) -> Optional[Tuple[tuple, list, list]]:
    """``(key, validators, comps)`` of a frame's recorded chain, or
    ``None`` when any node is unfingerprintable (fresh per-call
    computations, unforced sources, exotic leaves)."""
    node = getattr(frame, "_plan_node", None)
    if node is None:
        return None
    validators: List = []
    comps: List = []
    parts = _chain_fp(node, validators, comps, 0)
    if parts is None or len(parts) < 2:
        return None  # a bare leaf: its own block cache already covers it
    key = (parts, getattr(frame, "_version", 0))
    return key, validators, comps


def _portable_node_fp(node) -> Optional[tuple]:
    """Process-independent fingerprint of one plan node, or ``None``
    when the node's identity is process-local (``source`` pins a live
    frame by ``id()``; joins fold those in). Parquet leaves are already
    portable (footer identity: path + mtime + size); computations swap
    their ``id()`` for the structural signature the compile cache
    shares across workers (``serve/cache.py``)."""
    kind = node.kind
    if kind == "parquet":
        try:
            st = os.stat(node.path)
        except OSError:
            return None
        return ("pq", node.path, st.st_mtime_ns, st.st_size,
                node.row_group_offset, node.row_group_limit,
                node.columns, node.num_partitions)
    if kind in ("map_blocks", "map_rows", "filter"):
        from ..serve.cache import computation_signature
        sig = computation_signature(node.comp)
        if sig is None:
            return None
        if kind == "map_blocks":
            return ("mb", sig, node.trim)
        return ("mr" if kind == "map_rows" else "f", sig)
    if kind == "select":
        return ("sel", node.names)
    return None  # source/join: identity is this process's memory


def portable_fingerprint(frame) -> Optional[str]:
    """A fingerprint of ``frame``'s chain that means the same thing in
    ANOTHER process, or ``None`` when the chain has no portable
    identity. This is the durable result tier's key
    (``memory/persist.py``): a restarted worker that rebuilds the same
    parquet-rooted chain derives the same digest and serves the
    persisted result with zero dispatches — a warm hit. Chains rooted
    in in-memory frames are never persisted (their identity dies with
    the process that built them)."""
    node = getattr(frame, "_plan_node", None)
    if node is None:
        return None
    parts: List[tuple] = []
    has_pq = False
    depth = 0
    while node is not None and depth < 256:
        fp = _portable_node_fp(node)
        if fp is None:
            return None
        has_pq = has_pq or fp[0] == "pq"
        parts.append(fp)
        node = node.input
        depth += 1
    if node is not None or len(parts) < 2 or not has_pq:
        return None
    raw = repr((tuple(parts), getattr(frame, "_version", 0)))
    return hashlib.sha256(raw.encode()).hexdigest()


def query_fingerprint(frame) -> Optional[Tuple[str, bool]]:
    """``(digest, portable)`` identity of a frame's chain for the
    performance sentinel's cost baselines
    (``observability/baseline.py``), or ``None`` when the chain has no
    usable identity. Portable (parquet-rooted) chains reuse
    :func:`portable_fingerprint` verbatim, so the baseline key matches
    the durable result tier's and survives restarts. In-memory-rooted
    chains get a process-local digest: structural computation
    signatures where available, the source frame's SCHEMA and row
    estimate at the leaf — stable across repeated re-submissions of
    the same logical query (fresh frame objects per request, same
    shape of data: the recurring-query case the sentinel exists for),
    never persisted."""
    pfp = portable_fingerprint(frame)
    if pfp is not None:
        return pfp, True
    node = getattr(frame, "_plan_node", None)
    if node is None:
        return None
    parts: List[tuple] = []
    depth = 0
    while node is not None and depth < 256:
        fp = _portable_node_fp(node)
        if fp is None:
            if node.kind == "source" and node.frame is not None:
                f = node.frame
                try:
                    rows = f.estimated_rows()
                except Exception:  # noqa: BLE001 - lazy source
                    rows = None
                fp = ("src", repr(getattr(f, "schema", None)), rows)
            else:
                return None  # join/exotic leaf: ambiguous, no baseline
        parts.append(fp)
        node = node.input
        depth += 1
    if node is not None or len(parts) < 2:
        return None
    raw = repr((tuple(parts), getattr(frame, "_version", 0)))
    return hashlib.sha256(raw.encode()).hexdigest(), False


def _warm_lookup(frame, key, validators, comps) -> Optional[List]:
    """The durable tier's half of a miss: load the persisted result
    for the frame's PORTABLE fingerprint and re-admit it into the
    in-memory LRU under the live key. Counted separately
    (``plan.result_cache_warm_hits``) — a warm hit is a restart
    surviving, not a repeat forcing."""
    from ..memory import persist as _persist
    if not _persist.enabled():
        return None
    pfp = portable_fingerprint(frame)
    if pfp is None:
        return None
    blocks = _persist.load_result(pfp)
    if blocks is None:
        return None
    from ..memory.estimate import blocks_estimate
    _, nbytes = blocks_estimate(blocks)
    max_bytes, max_entries = _rc_budget()
    if nbytes <= max_bytes:
        entry = _CacheEntry(key, list(blocks), int(nbytes), comps,
                            validators)
        with _rc_lock:
            if key not in _results:
                _admit_locked(key, entry, max_bytes, max_entries)
    counters.inc("plan.result_cache_warm_hits")
    counters.inc("plan.result_cache_hit_bytes", int(nbytes))
    from ..observability import flight as _flight
    from ..observability.events import add_event
    add_event("result_cache_warm_hit", name=frame._plan,
              bytes=int(nbytes), blocks=len(blocks))
    _flight.record("plan.result_cache_warm_hit", bytes=int(nbytes),
                   blocks=len(blocks), fingerprint=pfp[:16])
    _log.info("warm result-cache hit for %s from the durable tier "
              "(%d block(s), %d B)", frame._plan, len(blocks), nbytes)
    return list(blocks)


def cached_result(frame) -> Optional[List]:
    """The interned blocks for ``frame``'s fingerprint, or ``None``
    (miss / disabled / unfingerprintable). A memory miss falls through
    to the durable tier (:func:`_warm_lookup`) before reporting cold."""
    if not result_cache_enabled():
        return None
    fp = fingerprint(frame)
    if fp is None:
        return None
    key, validators, comps = fp
    with _rc_lock:
        entry = _results.get(key)
        if entry is not None and not entry.valid():
            _results.pop(key, None)
            counters.inc("plan.result_cache_invalidations")
            entry = None
        if entry is not None:
            _results.move_to_end(key)
    if entry is None:
        warm = _warm_lookup(frame, key, validators, comps)
        if warm is not None:
            return warm
        # the "seen" mark is recorded by offer_result AFTER the
        # forcing, so admission counts FORCINGS, not lookups
        counters.inc("plan.result_cache_misses")
        return None
    counters.inc("plan.result_cache_hits")
    counters.inc("plan.result_cache_hit_bytes", entry.nbytes)
    from ..observability import flight as _flight
    from ..observability.events import add_event
    add_event("result_cache_hit", name=frame._plan, bytes=entry.nbytes,
              blocks=len(entry._cache))
    _flight.record("plan.result_cache_hit", bytes=entry.nbytes,
                   blocks=len(entry._cache))
    _log.debug("result cache hit for %s (%d block(s), %d B)",
               frame._plan, len(entry._cache), entry.nbytes)
    return list(entry._cache)


def _admit_locked(key, entry: _CacheEntry, max_bytes: int,
                  max_entries: int) -> List[_CacheEntry]:
    """Insert ``entry`` and LRU-sweep to budget. Caller holds
    ``_rc_lock``. Returns the evicted entries."""
    evicted: List[_CacheEntry] = []
    _results[key] = entry
    total = sum(e.nbytes for e in _results.values())
    while _results and (total > max_bytes
                        or len(_results) > max_entries):
        _, old = _results.popitem(last=False)
        total -= old.nbytes
        evicted.append(old)
    counters.inc("plan.result_cache_insertions")
    if evicted:
        counters.inc("plan.result_cache_evictions", len(evicted))
    gauge("plan.result_cache_bytes", total)
    gauge("plan.result_cache_entries", len(_results))
    return evicted


def offer_result(frame, blocks) -> None:
    """Intern a just-forced result. Two-touch admission: stored only
    when the same fingerprint was already seen once (hot queries repeat;
    one-off forcings and per-batch stream chains never re-key)."""
    if not result_cache_enabled() or not blocks:
        return
    fp = fingerprint(frame)
    if fp is None:
        return
    key, validators, comps = fp
    from ..memory.estimate import blocks_estimate
    _, nbytes = blocks_estimate(blocks)
    max_bytes, max_entries = _rc_budget()
    if nbytes > max_bytes:
        return
    evicted: List[_CacheEntry] = []
    with _rc_lock:
        if key in _results:
            return
        if _seen.pop(key, None) is None:
            # first sighting: record it, store nothing yet
            _seen[key] = time.monotonic()
            while len(_seen) > _SEEN_CAP:
                _seen.popitem(last=False)
            return
        entry = _CacheEntry(key, list(blocks), int(nbytes), comps,
                            validators)
        evicted = _admit_locked(key, entry, max_bytes, max_entries)
    from ..observability import flight as _flight
    _flight.record("plan.result_cache_admit", bytes=int(nbytes),
                   entries=len(blocks))
    if evicted:
        _flight.record("plan.result_cache_evict", entries=len(evicted),
                       bytes=sum(e.nbytes for e in evicted))
    from ..memory import persist as _persist
    if _persist.enabled():
        # write-through to the durable tier under the PORTABLE key:
        # a rolling restart then serves this result warm, zero
        # dispatches (process-local chains have no portable key and
        # stay memory-only)
        pfp = portable_fingerprint(frame)
        if pfp is not None and _persist.save_result(pfp, list(blocks)):
            _flight.record("plan.result_cache_persist",
                           bytes=int(nbytes), fingerprint=pfp[:16])


def invalidate_results() -> None:
    """Drop every interned result (tests; explicit source rewrites)."""
    with _rc_lock:
        _results.clear()
        _seen.clear()
        gauge("plan.result_cache_bytes", 0)
        gauge("plan.result_cache_entries", 0)


def result_cache_stats() -> Dict[str, int]:
    with _rc_lock:
        return {"entries": len(_results),
                "bytes": sum(e.nbytes for e in _results.values())}


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

_FAMILIES = (
    ("plan.result_cache_hits", "tft_plan_result_cache_hits_total",
     "Forcings served from the plan-fingerprint result cache."),
    ("plan.result_cache_misses", "tft_plan_result_cache_misses_total",
     "Result-cache lookups that missed."),
    ("plan.result_cache_warm_hits",
     "tft_plan_result_cache_warm_hits_total",
     "Memory misses served from the durable tier (restart survived)."),
    ("plan.result_cache_hit_bytes",
     "tft_plan_result_cache_hit_bytes_total",
     "Host bytes served from the result cache."),
    ("plan.result_cache_insertions",
     "tft_plan_result_cache_insertions_total",
     "Results interned (two-touch admission)."),
    ("plan.result_cache_evictions",
     "tft_plan_result_cache_evictions_total",
     "Entries LRU-evicted under the byte/entry budget."),
    ("plan.result_cache_invalidations",
     "tft_plan_result_cache_invalidations_total",
     "Entries dropped because a pinned source died or re-versioned."),
    ("plan.adaptive_layouts", "tft_plan_adaptive_layouts_total",
     "Forcings that ran a re-bucketed (coalesced/split) block layout."),
    ("plan.adaptive_coalesces", "tft_plan_adaptive_coalesces_total",
     "Adaptive layouts that merged dispatch-bound small blocks."),
    ("plan.adaptive_splits", "tft_plan_adaptive_splits_total",
     "Oversized blocks split to fit the ledger-derived ceiling."),
    ("plan.replans", "tft_plan_replans_total",
     "Mid-plan re-plans after an estimate missed by TFT_REPLAN_RATIO."),
    ("plan.filter_reorders", "tft_plan_filter_reorders_total",
     "Conjunctive filter runs re-ordered by observed selectivity."),
    ("stream.batch_grows", "tft_stream_batch_grows_total",
     "Adaptive stream batch targets doubled (dispatch-bound batches)."),
    ("stream.batch_shrinks", "tft_stream_batch_shrinks_total",
     "Adaptive stream batch targets halved (over-long batches)."),
)


def _render_metrics() -> List[str]:
    snap = counters.snapshot()
    lines: List[str] = []
    for key, fam, help_text in _FAMILIES:
        lines.append(f"# HELP {fam} {help_text}")
        lines.append(f"# TYPE {fam} counter")
        lines.append(f"{fam} {snap.get(key, 0)}")
    stats = result_cache_stats()
    lines.append("# HELP tft_plan_result_cache_bytes Host bytes "
                 "currently interned in the result cache.")
    lines.append("# TYPE tft_plan_result_cache_bytes gauge")
    lines.append(f"tft_plan_result_cache_bytes {stats['bytes']}")
    return lines


from ..observability import metrics as _metrics  # noqa: E402

_metrics.register_metrics_provider("plan.adaptive", _render_metrics)
