"""Lazy logical-plan IR between the frame surface and the engine.

The per-op engine path dispatches every ``map_blocks`` / ``map_rows`` /
``filter_rows`` / ``select`` in a chain as its own engine dispatch with
its own host↔device round trip — the measured gap between end-to-end
execution with marshalling and device-resident execution is exactly that
per-op tax (ROADMAP item 1). This package closes it without touching the
per-op path's semantics:

- every lazy frame op *additionally* records a :class:`~.nodes.PlanNode`
  on its result frame (the thunk chain stays exactly as it was);
- forcing (``blocks()`` — and therefore ``collect``/``count``/reductions/
  ``submit()``) first offers the chain to the optimizer
  (:func:`~.execute.maybe_run`): adjacent row-local ops fuse into ONE
  composed :class:`~..computation.Computation` dispatched once per block
  through the existing resilient executor (so retries, OOM splits, fault
  injection, memory admission, and the serve layer's shared compile
  cache all apply to the fused program unchanged); column pruning walks
  the plan and pushes the referenced-column set down into
  ``io.read_parquet(columns=)``; intermediates between non-fusible stage
  boundaries stay device-resident (``keep_device`` dispatches chained
  buffer-to-buffer) instead of round-tripping through host rows;
- any chain the optimizer cannot *prove* equivalent (non-row-preserving
  computations, ragged inputs, foreign/static computations, explicit
  ``executor=`` overrides, a non-default process executor) falls back to
  the unchanged per-op thunk — which is also the whole path when
  ``TFT_FUSE=0``, making the kill switch bit-identical by construction;
- plan nodes carry per-column row/byte estimates
  (:meth:`~.nodes.PlanNode.estimate`) that replace the whole-schema-ratio
  heuristics for UNFORCED frames (``memory.estimate.frame_estimate`` —
  what serve admission, quotas, and proactive splits consume);
- execution feeds measurement BACK into the plan (:mod:`.adaptive`):
  feedback-gated block re-bucketing, observed-selectivity filter
  re-ordering and mid-plan re-plans, and a plan-fingerprint result
  cache that serves repeated hot queries with zero dispatches —
  ``TFT_ADAPTIVE=0`` / ``TFT_RESULT_CACHE=0`` restore the static
  engine bit-identically.

See ``docs/plan.md`` and ``docs/adaptive.md``.
"""

from __future__ import annotations

from .nodes import (FilterNode, MapBlocksNode, MapRowsNode, ParquetScanNode,
                    PlanNode, SelectNode, SourceNode, attach, node_for,
                    observed_selectivity, record_selectivity)
from .optimize import enabled
from .execute import maybe_run
from . import adaptive

__all__ = [
    "PlanNode", "SourceNode", "ParquetScanNode", "MapBlocksNode",
    "MapRowsNode", "FilterNode", "SelectNode", "attach", "node_for",
    "enabled", "maybe_run", "record_selectivity", "observed_selectivity",
    "adaptive", "dist",
]


def __getattr__(name):
    # plan.dist imports parallel.distributed (which imports engine.ops,
    # which imports this package): resolve the submodule lazily so the
    # package import graph stays acyclic. importlib (not `from . import
    # dist`) because a from-import probes this very __getattr__ first.
    if name == "dist":
        import importlib
        return importlib.import_module(__name__ + ".dist")
    raise AttributeError(name)
