"""Distributed logical plan: lazy d-op chains fused into ONE GSPMD
program per mesh stage, with device-resident shard intermediates.

PR 10's plan IR stops at the single-device boundary: ``dmap_blocks`` /
``dfilter`` / ``dreduce_blocks`` / ``daggregate`` dispatch eagerly,
per-op — a chain of N row-local mesh ops costs N compiled dispatches
(and, for ``dfilter``, a host readback of the per-shard survivor counts
between every pair of ops). This module is the distributed twin of the
``keep_device`` edges: a chain recorded on a lazy
:class:`LazyDistributedFrame` forces as ONE ``jax.jit`` program whose
body is the per-op program fragments composed verbatim —

- row-preserving ``dmap_blocks`` computations run on the GLOBAL sharded
  arrays exactly as their per-op jit would (GSPMD inserts the same
  collectives for cross-row programs);
- each ``dfilter`` embeds the per-op ``shard_map`` compaction fragment
  (mask, per-shard stable compaction, survivor counts) — the counts stay
  TRACED between ops instead of round-tripping through the host;
- a terminal monoid ``dreduce_blocks`` / ``daggregate`` folds INTO the
  program as its last fragment (the DrJAX-style in-jaxpr reduction),
  instead of cutting a stage at the reduction;

so shard intermediates never leave their devices and the producer's
output sharding IS the consumer's input sharding (the SNIPPETS.md pjit
rule: matching ``out_axis_resources``/``in_axis_resources`` skip the
repartition entirely).

Legality is proof-driven like PR 10: a map records only when its
computation is PROVEN row-preserving (symbolic eval under the shared
row symbol, ``optimize._row_preserving``), a filter only when its mask
provably has block length. Anything else — trim/global maps, generic
(non-monoid) reductions, ``dsort``, the native ``TFT_EXECUTOR=pjrt``
route, multi-process meshes — materializes the pending chain and takes
the unchanged per-op path. ``TFT_FUSE=0`` makes ``lazy()`` the identity,
so the kill switch is bit-identical by construction; a fused execution
failure the elastic layer cannot recover (an OOM, a permanent fault)
replays the chain per-op (``dplan.fallbacks``) — fused execution never
fails a query the per-op d-ops survive.

The elastic machinery applies at the FUSED boundary: the whole forcing
runs through :func:`~..parallel.elastic.elastic_call`, so a classified
device loss mid-program shrinks the mesh, re-shards the SOURCE frame,
and re-runs the entire fused program on the survivors — bit-identical
for row-local ops and integer reductions, exactly the per-op contract.
The memory ledger admits the fused dispatch (``make_room`` on the plan's
output estimate) and the forced result's columns register as ONE LRU
spill candidate, so resident shard edges spill to pinned host under
pressure and fault back transparently.

See ``docs/plan.md`` (distributed fusion section).
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..observability import flight as _flight
from ..observability.events import add_event, current_trace, traced_query
from ..utils.compat import shard_map
from ..utils.logging import get_logger
from ..utils.tracing import counters, span
from .adaptive import record_stream_feedback, stream_feedback
from .nodes import _cell_bytes, observed_selectivity, record_selectivity
from .optimize import _mask_shaped, _row_preserving
from .optimize import enabled as fuse_enabled

__all__ = ["LazyDistributedFrame", "lazy_frame", "record_map",
           "record_filter", "record_reduce", "record_aggregate",
           "materialize", "mesh_segment_partial"]

_log = get_logger("plan.dist")


class _Unfusable(RuntimeError):
    """A runtime condition the recorder could not see; the caller
    replays the chain per-op (unplanned, not failed)."""


class _EmptyReduceError(ValueError):
    """The per-op "reduce on an empty distributed frame" contract,
    discovered POST-dispatch (a filter emptied the frame). A sentinel
    subclass so the fallback handler can re-raise exactly this while
    any other ``ValueError`` out of the fused program still replays
    per-op — fused execution must never fail a query the per-op d-ops
    survive."""


# ---------------------------------------------------------------------------
# plan nodes (the distributed chain IR)
# ---------------------------------------------------------------------------

class DistNode:
    """One recorded d-op (or the source leaf) of a lazy mesh chain."""

    kind = "dnode"

    def __init__(self, input: Optional["DistNode"], schema):
        self.input = input
        self.schema = schema

    def describe(self) -> str:
        return self.kind

    def estimate(self) -> Tuple[Optional[float], Optional[Dict[str, int]]]:
        """``(rows, {column: device bytes})`` — the distributed twin of
        :meth:`~.nodes.PlanNode.estimate`, consumed by the fused
        dispatch's ledger admission and ``memory.estimate``."""
        return None, None


class DSourceNode(DistNode):
    kind = "dsource"

    def __init__(self, frame):
        super().__init__(None, frame.schema)
        self.frame_ref = weakref.ref(frame)

    def describe(self) -> str:
        f = self.frame_ref()
        return (f"dsource[{f.num_rows} rows]" if f is not None
                else "dsource[collected]")

    def estimate(self):
        f = self.frame_ref()
        if f is None:
            return None, None
        from .. import memory as _memory
        cols: Dict[str, int] = {}
        for fl in f.schema:
            try:
                cols[fl.name] = int(_memory.value_nbytes(f.columns, fl.name))
            except Exception:
                cols[fl.name] = 0
        return float(f.num_rows), cols


class DMapNode(DistNode):
    """A proven row-preserving (non-trim) ``dmap_blocks``."""

    kind = "dmap"

    def __init__(self, input, schema, comp):
        super().__init__(input, schema)
        self.comp = comp

    def describe(self) -> str:
        return "dmap_blocks"

    def estimate(self):
        rows, cols = self.input.estimate()
        if rows is None or cols is None:
            return rows, cols
        out = dict(cols)
        for s in self.comp.outputs:
            out[s.name] = int(rows * _cell_bytes(s.dtype, s.shape.dims[1:]))
        return rows, out


class DFilterNode(DistNode):
    kind = "dfilter"

    def __init__(self, input, schema, comp):
        super().__init__(input, schema)
        self.comp = comp

    def describe(self) -> str:
        sel = observed_selectivity(self.comp)
        return ("dfilter" if sel is None
                else f"dfilter[sel~{sel:.2f} observed]")

    def estimate(self):
        # feedback selectivity (ROADMAP 2a): once any forcing of this
        # predicate observed rows-in/rows-out, estimate with the
        # observed ratio instead of the upper bound
        rows, cols = self.input.estimate()
        sel = observed_selectivity(self.comp)
        if sel is None or rows is None:
            return rows, cols
        return rows * sel, ({n: int(b * sel) for n, b in cols.items()}
                            if cols is not None else None)


class DSelectNode(DistNode):
    kind = "dselect"

    def __init__(self, input, schema, names: Sequence[str]):
        super().__init__(input, schema)
        self.names = tuple(names)

    def describe(self) -> str:
        return f"dselect{list(self.names)}"

    def estimate(self):
        rows, cols = self.input.estimate()
        if cols is None:
            return rows, cols
        return rows, {n: cols[n] for n in self.names if n in cols}


# ---------------------------------------------------------------------------
# the lazy frame
# ---------------------------------------------------------------------------

def _dist():
    from ..parallel import distributed
    return distributed


class LazyDistributedFrame:
    """A :class:`~..parallel.distributed.DistributedFrame` whose columns
    are a RECORDED d-op chain, not materialized arrays.

    Built by :meth:`DistributedFrame.lazy`; every further
    ``dmap_blocks`` / ``dfilter`` / ``select`` on it records a node and
    stays lazy. Any access to data (``columns`` / ``num_rows`` /
    ``collect_frame`` / an unfusable op) FORCES the chain: the optimizer
    fuses it into one GSPMD program (module docstring); ``TFT_FUSE=0``
    and unsupported shapes replay the recorded ops per-op,
    bit-identical. Thread-safe: concurrent forcings converge on one
    result.
    """

    _tft_lazy_dist = True

    def __init__(self, source, node: DistNode, chain: Tuple[DistNode, ...],
                 schema):
        self._source = source          # the materialized chain root
        self._dplan_node = node
        self._chain = chain            # op nodes, leaf -> final order
        self._mesh = source.mesh
        self.schema = schema
        self._forced = None
        self._force_lock = threading.Lock()
        self._dplan_info: Optional[List[str]] = None
        self._group_ids_cache: "OrderedDict" = OrderedDict()

    # -- laziness ----------------------------------------------------------
    def lazy(self):
        return self

    def _force(self):
        f = self._forced
        if f is not None:
            return f
        with self._force_lock:
            if self._forced is None:
                self._forced = _force_chain(self)
            return self._forced

    @property
    def mesh(self):
        # a forced chain may have recovered onto a SHRUNKEN mesh; the
        # record-time mesh stands until then
        f = self._forced
        return f.mesh if f is not None else self._mesh

    @property
    def columns(self):
        return self._force().columns

    @property
    def num_rows(self) -> int:
        return self._force().num_rows

    @property
    def shard_valid(self):
        return self._force().shard_valid

    # -- recorded ops ------------------------------------------------------
    def select(self, names) -> "LazyDistributedFrame":
        if isinstance(names, str):
            names = [names]
        names = list(names)
        missing = [n for n in names if n not in self.schema]
        if missing:
            raise KeyError(
                f"No column(s) {missing}; columns: {self.schema.names}")
        out_schema = self.schema.select(names)
        node = DSelectNode(self._dplan_node, out_schema, names)
        return LazyDistributedFrame(self._source, node,
                                    self._chain + (node,), out_schema)

    # -- estimates (no forcing) -------------------------------------------
    def estimated_rows(self):
        """Plan-derived row estimate WITHOUT forcing (filters priced at
        their observed selectivity once recorded) — the distributed
        twin of ``TensorFrame.estimated_rows``."""
        from ..memory.estimate import dist_frame_estimate
        return dist_frame_estimate(self)[0]

    def estimated_bytes(self):
        from ..memory.estimate import dist_frame_estimate
        return dist_frame_estimate(self)[1]

    # -- forwarding (everything else behaves like the forced frame) -------
    def count(self) -> int:
        return self.num_rows

    def explain(self) -> str:
        forced = self._force()
        report = forced.explain()
        if self._dplan_info and getattr(forced, "_dplan_info", None) \
                != self._dplan_info:
            report += "\n" + "\n".join(self._dplan_info)
        return report

    def __getattr__(self, name):
        # anything not defined here (collect_frame, per_shard_valid,
        # host_read_padded, valid_row_mask, padded_rows, ...) forces and
        # delegates — the forced frame IS this frame's value
        if name.startswith("_"):
            raise AttributeError(name)
        return getattr(self._force(), name)

    def __repr__(self):
        state = ("forced" if self._forced is not None
                 else f"{len(self._chain)} pending op(s)")
        return (f"LazyDistributedFrame[{', '.join(self.schema.names)}] "
                f"({state}) mesh={self._mesh!r}")


def lazy_frame(dist):
    """``DistributedFrame.lazy()`` backend: a recording view over
    ``dist``, or ``dist`` itself when recording cannot help
    (``TFT_FUSE=0``, the native ``pjrt`` executor, multi-process meshes,
    frames whose rows do not tile the data axis)."""
    import os

    if getattr(dist, "_tft_lazy_dist", False):
        return dist
    if not fuse_enabled():
        return dist
    if os.environ.get("TFT_EXECUTOR") == "pjrt":
        return dist  # the native route keeps the per-op dispatches
    if jax.process_count() > 1:
        return dist
    S = dist.mesh.num_data_shards
    if S < 1 or dist.padded_rows % S != 0:
        return dist  # non-tiling (global-result) frames stay per-op
    node = DSourceNode(dist)
    return LazyDistributedFrame(dist, node, (), dist.schema)


def materialize(dist):
    """The materialized frame behind ``dist`` (forcing a lazy chain)."""
    if getattr(dist, "_tft_lazy_dist", False):
        return dist._force()
    return dist


# ---------------------------------------------------------------------------
# recording (called by the d-op entry points on lazy inputs)
# ---------------------------------------------------------------------------

def record_map(fetches, lazy: LazyDistributedFrame, trim: bool,
               row_aligned) -> Optional[LazyDistributedFrame]:
    """Record a ``dmap_blocks`` on a lazy frame, or ``None`` when the op
    must materialize + run per-op (trim/global programs, unprovable
    row preservation, foreign/static computations)."""
    from ..engine import ops as _ops

    if row_aligned is False and not trim:
        # the eager op's argument validation, raised at RECORD time — a
        # bad call must not first execute the whole pending chain
        raise ValueError(
            "row_aligned=False only makes sense for trim=True outputs: "
            "without trim the untrimmed input columns ride along and "
            "still contain pad rows, which declaring every output row "
            "real would surface as data")
    if trim or not fuse_enabled():
        return None
    comp = _ops.cached_map_computation(fetches, lazy.schema,
                                       block_level=True)
    # record-time validation: the same errors the eager op raises at
    # call time (schema mismatches must not move to force time)
    out_schema = _ops._validate_map(comp, lazy.schema, block_level=True,
                                    trim=False)
    if getattr(comp, "_native_dynamic", None) is not None:
        return None
    if not _row_preserving(comp):
        return None  # the per-op runtime row-count check owns this
    counters.inc("dplan.recorded_ops")
    node = DMapNode(lazy._dplan_node, out_schema, comp)
    return LazyDistributedFrame(lazy._source, node, lazy._chain + (node,),
                                out_schema)


def record_filter(predicate,
                  lazy: LazyDistributedFrame
                  ) -> Optional[LazyDistributedFrame]:
    from ..engine import ops as _ops

    if not fuse_enabled():
        return None
    comp = _ops._filter_computation(predicate, lazy.schema)
    bad = [n for n in comp.input_names
           if (f := lazy.schema.get(n)) is not None and not f.dtype.tensor]
    if bad:
        # the eager op's error, raised at record time (error parity
        # without forcing the pending chain first)
        raise _ops.InvalidTypeError(
            f"dfilter predicate reads host-side (non-tensor) column(s) "
            f"{bad}: string columns ride along on the mesh but cannot "
            f"enter the sharded program. Filter on the host instead "
            f"(tensorframes_tpu.filter_rows / TensorFrame.filter) before "
            f"distribute().")
    if not _mask_shaped(comp):
        return None
    counters.inc("dplan.recorded_ops")
    node = DFilterNode(lazy._dplan_node, lazy.schema, comp)
    return LazyDistributedFrame(lazy._source, node, lazy._chain + (node,),
                                lazy.schema)


# ---------------------------------------------------------------------------
# chain planning
# ---------------------------------------------------------------------------

class _DPlan:
    """The fused-stage layout of one recorded chain (+ optional folded
    terminal reduction)."""

    __slots__ = ("ops", "members", "in_names", "out_names", "passthrough",
                 "host_names", "has_filter", "n_filters", "final_schema",
                 "reduce_names", "reduce_combs", "agg_combiners", "labels",
                 "filter_nodes", "est_bytes")

    def __init__(self):
        self.est_bytes = None  # plan-derived result size (ledger admission)
        self.ops = []
        self.members = []
        self.in_names = ()
        self.out_names = ()
        self.passthrough = ()
        self.host_names = ()
        self.has_filter = False
        self.n_filters = 0
        self.final_schema = None
        self.reduce_names = None   # sorted fetch names of a folded reduce
        self.reduce_combs = None   # {name: Combiner}
        self.agg_combiners = None  # {name: combiner-name} of a folded agg
        self.labels = []
        self.filter_nodes = []

    @property
    def n_ops(self) -> int:
        return len(self.ops) + (1 if (self.reduce_names is not None
                                      or self.agg_combiners) else 0)

    def describe(self, executed: Optional[str] = None) -> List[str]:
        term = ""
        if self.reduce_names is not None:
            term = " + dreduce_blocks[folded]"
        elif self.agg_combiners:
            term = " + daggregate[folded]"
        state = executed or "planned"
        lines = [f"  dplan    : {self.n_ops} op(s) -> 1 fused GSPMD "
                 f"program ({state})",
                 f"    stage 0: {'+'.join(self.labels) or 'pass'}{term} "
                 f"-> 1 mesh dispatch"]
        if self.passthrough:
            lines.append(f"    resident: {list(self.passthrough)} "
                         f"pass through device-resident (no program I/O)")
        if self.has_filter:
            lines.append(
                f"    filters : {self.n_filters} compacted in-program "
                f"(survivor counts stay traced; no inter-op host "
                f"readback)")
        return lines


def _plan_chain(source_schema, ops: Sequence[DistNode], final_schema,
                reduce_spec: Optional[Mapping[str, str]] = None,
                agg_value_names: Optional[Sequence[str]] = None
                ) -> Optional[_DPlan]:
    """Lay one fused stage out of the recorded ``ops``; ``None`` means
    the chain has nothing to fuse (select-only, no terminal)."""
    from ..parallel.collectives import COMBINERS

    plan = _DPlan()
    plan.ops = list(ops)
    plan.final_schema = final_schema

    # backward need pass (column pruning): a column is read/carried only
    # when it feeds a computation or survives to the final schema
    if reduce_spec is not None:
        need = set(reduce_spec)
    elif agg_value_names is not None:
        need = set(agg_value_names)
    else:
        need = {f.name for f in final_schema}
    for o in reversed(ops):
        if o.kind == "dmap":
            need = (need - set(o.comp.output_names)) \
                | set(o.comp.input_names)
        elif o.kind == "dfilter":
            need = need | set(o.comp.input_names)
        # select: need is already a subset of the selected names

    leaf_required = [f.name for f in source_schema
                     if f.dtype.tensor and f.name in need]
    plan.host_names = tuple(
        f.name for f in final_schema
        if not f.dtype.tensor) if reduce_spec is None \
        and agg_value_names is None else ()
    plan.in_names = tuple(leaf_required)

    # forward simulation: compose members, track the live tensor env in
    # deterministic order (leaf order, then map outputs by name)
    order: List[str] = list(leaf_required)
    env = set(order)
    produced: set = set()
    for o in ops:
        if o.kind == "dselect":
            keep = set(o.names)
            order = [n for n in order if n in keep]
            env &= keep
            produced &= keep
            plan.members.append(("sel", tuple(order)))
        elif o.kind == "dmap":
            if not set(o.comp.input_names) <= env:
                return None  # defensive: recorder guarantees this
            plan.members.append(("map", o.comp))
            plan.labels.append("dmap_blocks")
            for s in o.comp.outputs:
                if s.name not in env:
                    order.append(s.name)
                env.add(s.name)
                produced.add(s.name)
        else:  # dfilter
            if not set(o.comp.input_names) <= env:
                return None
            plan.members.append(("filter", o.comp, tuple(order)))
            plan.labels.append("dfilter")
            plan.has_filter = True
            plan.n_filters += 1
            plan.filter_nodes.append(o)
            produced = set(order)  # everything is permuted now

    if reduce_spec is not None:
        plan.reduce_names = sorted(reduce_spec)
        plan.reduce_combs = {n: COMBINERS[reduce_spec[n]]
                             for n in plan.reduce_names}
        if not set(plan.reduce_names) <= env:
            return None
        return plan
    if agg_value_names is not None:
        if not set(agg_value_names) <= env:
            return None
        return plan

    final_tensor = [f.name for f in final_schema if f.dtype.tensor]
    if not set(final_tensor) <= env:
        return None
    if plan.has_filter:
        # a filter permutes every live column: all survivors come out of
        # the program
        plan.out_names = tuple(n for n in order if n in set(final_tensor))
        plan.passthrough = ()
    else:
        plan.out_names = tuple(n for n in order
                               if n in produced and n in set(final_tensor))
        plan.passthrough = tuple(n for n in final_tensor
                                 if n not in produced)
    if not any(m[0] in ("map", "filter") for m in plan.members):
        return None  # select-only: no program needed
    return plan


# ---------------------------------------------------------------------------
# the fused program (per-op fragments composed inside ONE jit)
# ---------------------------------------------------------------------------

_fused_cache: "OrderedDict[tuple, tuple]" = OrderedDict()
_FUSED_CACHE_CAP = 64
_fused_lock = threading.Lock()


def _member_key(m) -> tuple:
    if m[0] == "map":
        return ("map", id(m[1]))
    if m[0] == "filter":
        return ("filter", id(m[1]), m[2])
    return m


def _filter_fragment(comp, alive: Tuple[str, ...], mesh, cnt, env):
    """The per-op ``_dfilter`` shard program, embedded: mask, per-shard
    stable compaction, survivor counts — counts stay traced."""
    axis = mesh.data_axis
    arrs = [env[n] for n in alive]
    in_specs = (P(axis),) + tuple(
        P(axis, *([None] * (a.ndim - 1))) for a in arrs)
    out_specs = tuple(
        P(axis, *([None] * (a.ndim - 1))) for a in arrs
    ) + (P(axis), P(axis))
    in_names = comp.input_names
    pname = comp.output_names[0]

    def filter_shard(cnt_l, *cols_l):
        local = dict(zip(alive, cols_l))
        m = comp.fn({n: local[n] for n in in_names})[pname]
        rows = cols_l[0].shape[0]
        rowid = jnp.arange(rows)
        keep = (m != 0) & (rowid < cnt_l[0])
        order = jnp.argsort((~keep).astype(jnp.int8), stable=True)
        permuted = tuple(jnp.take(c, order, axis=0) for c in cols_l)
        return permuted + (jnp.sum(keep, dtype=jnp.int32)[None], keep)

    outs = shard_map(filter_shard, mesh=mesh.mesh, in_specs=in_specs,
                     out_specs=out_specs)(cnt, *arrs)
    new_env = dict(zip(alive, outs[:len(alive)]))
    return new_env, outs[len(alive)], outs[len(alive) + 1]


def _agg_shard_fn(fetch_names, col_combiners, axis, prog_groups: int):
    """The per-shard monoid segment-reduce + collective — literally
    ``_daggregate``'s own fragment (``_monoid_agg_shard_fn``, one
    definition for the eager, native, fused, and streaming routes)."""
    return _dist()._monoid_agg_shard_fn(fetch_names, dict(col_combiners),
                                        axis, prog_groups)


def _build_fused_fn(plan: _DPlan, mesh, want_keeps: bool,
                    agg_groups: Optional[int] = None):
    """The whole chain as one function of ``(cnt[, ids], *cols)`` —
    map fragments on the global sharded arrays (per-op jit semantics),
    filter/reduce fragments as embedded ``shard_map`` regions."""
    from ..parallel.distributed import _collective_shard_fn

    axis = mesh.data_axis
    members = tuple(plan.members)
    in_names = plan.in_names
    out_names = plan.out_names
    has_filter = plan.has_filter
    reduce_names = plan.reduce_names
    reduce_combs = plan.reduce_combs
    agg = plan.agg_combiners

    def fused(cnt, *arrs):
        if agg_groups is not None:
            ids, cols = arrs[0], arrs[1:]
        else:
            ids, cols = None, arrs
        env = dict(zip(in_names, cols))
        keeps = []
        for m in members:
            if m[0] == "map":
                comp = m[1]
                out = comp.fn({n: env[n] for n in comp.input_names})
                env.update(out)
            elif m[0] == "sel":
                keep = set(m[1])
                env = {n: v for n, v in env.items() if n in keep}
            else:
                env, cnt, kp = _filter_fragment(m[1], m[2], mesh, cnt, env)
                keeps.append(kp)
        if reduce_names is not None:
            rarrs = [env[n] for n in reduce_names]
            in_specs = (P(axis),) + tuple(
                P(axis, *([None] * (a.ndim - 1))) for a in rarrs)
            out_specs = tuple(P() for _ in rarrs)
            red = shard_map(
                _collective_shard_fn(reduce_names, reduce_combs, axis),
                mesh=mesh.mesh, in_specs=in_specs,
                out_specs=out_specs)(cnt, *rarrs)
            return tuple(red) + ((cnt,) if has_filter else ())
        if agg is not None:
            fetch_names = sorted(agg)
            aarrs = [env[n] for n in fetch_names]
            in_specs = (P(axis),) + tuple(
                P(axis, *([None] * (a.ndim - 1))) for a in aarrs)
            out_specs = tuple(P() for _ in fetch_names)
            tables = shard_map(
                _agg_shard_fn(fetch_names, agg, axis, agg_groups),
                mesh=mesh.mesh, in_specs=in_specs,
                out_specs=out_specs)(ids, *aarrs)
            return tuple(tables)
        res = tuple(env[n] for n in out_names)
        if has_filter:
            res = res + (cnt,)
        if want_keeps:
            res = res + tuple(keeps)
        return res

    return fused


def _fused_program(plan: _DPlan, d, want_keeps: bool,
                   agg_groups: Optional[int] = None):
    """The cached jitted program for ``plan`` over ``d``'s mesh/shapes
    (a shrink/reshard changes both and rebuilds; comps are held strongly
    by the entry so their ids stay valid for the key's lifetime)."""
    mesh = d.mesh
    arrays = [d.columns[n] for n in plan.in_names]
    key = (mesh.mesh, mesh.data_axis,
           tuple(_member_key(m) for m in plan.members),
           tuple((n, a.shape, str(a.dtype))
                 for n, a in zip(plan.in_names, arrays)),
           plan.out_names, want_keeps, agg_groups,
           tuple(sorted(plan.reduce_combs))
           if plan.reduce_combs is not None else None,
           tuple(sorted(plan.agg_combiners.items()))
           if plan.agg_combiners else None)
    with _fused_lock:
        hit = _fused_cache.get(key)
        if hit is not None:
            _fused_cache.move_to_end(key)
            return hit[0], arrays
    fn = jax.jit(_build_fused_fn(plan, mesh, want_keeps, agg_groups))
    strong = [m[1] for m in plan.members if m[0] in ("map", "filter")]
    with _fused_lock:
        hit = _fused_cache.setdefault(key, (fn, strong))
        _fused_cache.move_to_end(key)
        while len(_fused_cache) > _FUSED_CACHE_CAP:
            _fused_cache.popitem(last=False)
    counters.inc("dplan.fused_programs")
    return hit[0], arrays


# ---------------------------------------------------------------------------
# execution
# ---------------------------------------------------------------------------

def _cnt_dev(d):
    mesh = d.mesh
    S = mesh.num_data_shards
    counts = d.per_shard_valid().astype(np.int32)
    return jax.make_array_from_callback(
        (S,), mesh.row_sharding(1), lambda idx: counts[idx])


def _admit(plan: _DPlan, d) -> None:
    """Ledger admission for the fused dispatch: spill colder residents
    before the program's outputs land (the per-op ``distribute`` /
    executor admission pattern). The plan-derived estimate
    (``memory.estimate.dist_frame_estimate`` — observed filter
    selectivities included) prices the result when available; the raw
    per-output sum is the fallback."""
    from .. import memory as _memory
    mgr = _memory.active()
    if mgr is None:
        return
    est = plan.est_bytes
    if est is None:
        rows = float(d.padded_rows)
        est = 0
        for o in plan.ops:
            if o.kind == "dmap":
                for s in o.comp.outputs:
                    est += int(rows * _cell_bytes(s.dtype,
                                                  s.shape.dims[1:]))
    if est:
        mgr.make_room(int(est))


def _register_result(cols: Dict, mesh_tag: str):
    """Resident shard edges join the memory LRU: the forced chain's
    columns spill to pinned host under ledger pressure and fault back
    on the next access, like any distributed frame."""
    from .. import memory as _memory
    mgr = _memory.active()
    if mgr is not None and mgr.spill_enabled:
        return _memory.spillable_columns(mesh_tag, cols, mgr)
    return cols


def _feedback_key(plan: _DPlan) -> str:
    """The fused stage's identity in the adaptive feedback registry
    (``docs/adaptive.md``): one record per plan shape, accumulated
    across forcings."""
    return (f"dplan[{','.join(o.kind for o in plan.ops)}]"
            f"({plan.final_schema.names})")


def _feedback_lines(plan: _DPlan) -> List[str]:
    """The per-stage shard-time line ``explain()`` renders from the
    feedback registry — the recorded-but-previously-unread half of the
    ROADMAP item 2 follow-on, surfaced so the data is visible before a
    future adaptive pass acts on it."""
    fb = stream_feedback(_feedback_key(plan))
    if fb is None or not fb.forcings:
        return []
    shards = max(fb.blocks // max(fb.forcings, 1), 1)
    return [f"    feedback: {fb.forcings} fused forcing(s) · "
            f"{shards} shard(s)/stage · mean stage wall "
            f"{fb.wall_s / fb.forcings * 1e3:.2f} ms · "
            f"{fb.rows} row(s) total (feedback registry; unused for "
            f"sizing today)"]


def _record_fallback(e: BaseException) -> None:
    """Always-on bookkeeping of a fused-chain fallback to the per-op
    path: the counter pair plus the flight-recorder decision (with the
    classified kind — fallbacks are rare enough to classify)."""
    from ..resilience import error_kind, is_oom
    counters.inc("dplan.fallbacks")
    if is_oom(e):
        counters.inc("dplan.oom_fallbacks")
    _flight.record("dplan.fallback", error=type(e).__name__,
                   error_kind=error_kind(e))


def _dispatch(plan: _DPlan, d, want_keeps: bool,
              agg_groups: Optional[int] = None, ids_dev=None):
    """One fused mesh dispatch over ``d`` through the resilient policy
    (transient retry with an async-failure barrier) + trace plumbing."""
    from ..resilience import default_policy as _default_policy
    from ..resilience import faults as _faults

    D = _dist()
    mesh = d.mesh
    if d.padded_rows % max(mesh.num_data_shards, 1) != 0:
        raise _Unfusable("frame rows do not tile the data axis")
    fn, arrays = _fused_program(plan, d, want_keeps, agg_groups)
    cnt = _cnt_dev(d)
    _admit(plan, d)
    policy = _default_policy()
    ins = (cnt,) + ((ids_dev,) if ids_dev is not None else ()) \
        + tuple(arrays)

    def _go():
        _faults.check("dmap")
        with span("dfused.dispatch"):
            out = fn(*ins)
            if policy.max_attempts > 1:
                jax.block_until_ready(out)
            return out

    trace = current_trace()
    t0 = (D._trace_shards(trace, "dfused", dist=d)
          if trace is not None else 0.0)
    import time as _time
    w0 = _time.perf_counter()
    # the regression drill's deterministic slowdown lands INSIDE the
    # measured stage wall, so the sentinel attributes it to stage_wall_s
    _faults.slowdown("perf")
    outs = policy.call(_go, op="dfused.dispatch")
    wall = _time.perf_counter() - w0
    counters.inc("mesh.dispatches")
    if trace is not None:
        add_event("fused_stage", name="+".join(plan.labels) or "pass",
                  ops=plan.n_ops, filters=plan.n_filters,
                  resident=len(plan.passthrough), wall_s=wall,
                  shards=mesh.num_data_shards)
        D._trace_mesh_done(trace, list(outs), t0, "dfused", mesh=mesh)
    return outs, wall


def _permute_host(a: np.ndarray, keep: np.ndarray, S: int) -> np.ndarray:
    """Replay one filter's per-shard compaction on a host (string)
    ride-along column — the exact ``_dfilter`` host-side rule."""
    rows_per = a.shape[0] // S
    out = np.empty_like(a)
    for s in range(S):
        sl = slice(s * rows_per, (s + 1) * rows_per)
        order = np.argsort(~keep[sl], kind="stable")
        out[sl] = a[sl][order]
    return out


def _meta_dfused(plan=None, source=None, *a, **k):
    source = k.get("source", source)
    plan = k.get("plan", plan)
    if source is None:
        return {}
    D = _dist()
    meta = D._mesh_meta(source)
    if plan is not None:
        meta["fused_ops"] = plan.n_ops
    return meta


@traced_query("dfused", _meta_dfused)
def _run_fused_frame(plan: _DPlan, source):
    from ..parallel import elastic as _elastic

    return _elastic.elastic_call("dfused", source,
                                 lambda d: _exec_frame(plan, d))


def _exec_frame(plan: _DPlan, d):
    D = _dist()
    S = d.mesh.num_data_shards
    want_keeps = plan.has_filter and bool(plan.host_names)
    outs, wall = _dispatch(plan, d, want_keeps)
    cols: Dict[str, object] = {}
    # resident passthrough: untouched source columns chain buffer-to-
    # buffer (matching shardings — no repartition, no program I/O);
    # per-key access through __getitem__ keeps SpillableColumns'
    # fault-back live
    for n in plan.passthrough:
        cols[n] = d.columns[n]
    for n, arr in zip(plan.out_names, outs[:len(plan.out_names)]):
        cols[n] = arr
    idx = len(plan.out_names)
    if plan.has_filter:
        counts = D._read_global(outs[idx]).astype(np.int64)
        idx += 1
        num_rows = int(counts.sum())
        shard_valid = counts
        if plan.n_filters == 1:
            # single-filter chains attribute the observed selectivity
            # to their predicate (row-preserving maps keep the count)
            record_selectivity(plan.filter_nodes[0].comp, d.num_rows,
                               num_rows)
    else:
        num_rows = d.num_rows
        shard_valid = d.shard_valid
    if want_keeps:
        keeps = [D._read_global(k) for k in outs[idx:idx + plan.n_filters]]
        for n in plan.host_names:
            a = np.asarray(d.columns[n], object)
            for keep in keeps:
                a = _permute_host(a, keep, S)
            cols[n] = a
    elif plan.host_names:
        for n in plan.host_names:
            cols[n] = d.columns[n]
    if not plan.passthrough:
        # every column is a FRESH program output: register the result
        # as one LRU spill candidate (the resident shard edge).
        # Passthrough columns are the SOURCE's own device buffers — its
        # registration already accounts them, and a second wrapper over
        # the same buffers would double-count resident bytes and make a
        # spill of either wrapper free nothing.
        cols = _register_result(cols, f"dfused@{id(plan):x}")
    # adaptive feedback (docs/adaptive.md): fused mesh stages record
    # their observed shard-stream shape AND the measured stage wall —
    # unused for sizing today (mesh shards are fixed by the mesh, not
    # the layout pass), but surfaced as the per-stage shard-time line
    # in DistributedFrame.explain()/last_query_report() so the record
    # is visible before a future PR acts on it (ROADMAP 2 follow-on)
    record_stream_feedback(_feedback_key(plan), blocks=S,
                           rows=num_rows, wall_s=wall)
    return D.DistributedFrame(d.mesh, plan.final_schema, cols, num_rows,
                              shard_valid=shard_valid)


def _replay_per_op(source, ops: Sequence[DistNode]):
    """The recorded chain re-run through the UNCHANGED eager d-op
    dispatches — the ``TFT_FUSE=0`` path and the unrecoverable-failure
    fallback, bit-identical to never having recorded at all."""
    D = _dist()
    cur = source
    for o in ops:
        if o.kind == "dmap":
            cur = D.dmap_blocks(o.comp, cur)
        elif o.kind == "dfilter":
            cur = D.dfilter(o.comp, cur)
        else:
            cur = cur.select(list(o.names))
    return cur


def _force_chain(lazy: LazyDistributedFrame):
    source, ops = lazy._source, list(lazy._chain)
    if not ops:
        lazy._dplan_info = ["  dplan    : empty chain (source frame)"]
        return source
    if not fuse_enabled():
        lazy._dplan_info = [
            "  dplan    : TFT_FUSE=0 — recorded chain replayed through "
            "the per-op d-op dispatches"]
        result = _replay_per_op(source, ops)
        result._dplan_info = lazy._dplan_info
        return result
    plan = _plan_chain(source.schema, ops, lazy.schema)
    if plan is None:
        # select-only chains: pure views, no dispatch at all
        cur = source
        for o in ops:
            if o.kind == "dselect":
                cur = cur.select(list(o.names))
            else:  # defensive: unplanned, not failed
                lazy._dplan_info = [
                    "  dplan    : chain not plannable — per-op replay"]
                return _replay_per_op(source, ops)
        lazy._dplan_info = [
            "  dplan    : projection-only chain (0 mesh dispatches)"]
        return cur
    from ..memory.estimate import dist_frame_estimate
    plan.est_bytes = dist_frame_estimate(lazy)[1]
    try:
        result = _run_fused_frame(plan, source)
    except Exception as e:  # noqa: BLE001 - reclassified below
        from ..resilience import is_device_lost
        if is_device_lost(e):
            raise  # elastic recovery exhausted: per-op parity is to raise
        _record_fallback(e)
        _log.warning(
            "fused mesh program failed (%s: %s); re-running the recorded "
            "chain through the per-op d-op dispatches", type(e).__name__,
            e)
        lazy._dplan_info = plan.describe(
            executed=f"FELL BACK per-op: {type(e).__name__}")
        result = _replay_per_op(source, ops)
        result._dplan_info = lazy._dplan_info
        return result
    counters.inc("dplan.fused_forcings")
    lazy._dplan_info = plan.describe(executed="executed") \
        + _feedback_lines(plan)
    # explain() on the FORCED frame renders the same plan section
    result._dplan_info = lazy._dplan_info
    return result


# ---------------------------------------------------------------------------
# folded terminal reductions
# ---------------------------------------------------------------------------

def record_reduce(fetches, lazy: LazyDistributedFrame
                  ) -> Optional[Dict[str, np.ndarray]]:
    """Fold a monoid ``dreduce_blocks`` into the pending chain's fused
    program as the terminal combiner; ``None`` defers to materialize +
    the eager op (generic computations, fusion off)."""
    from ..parallel.collectives import COMBINERS

    if not (isinstance(fetches, Mapping) and fetches and all(
            isinstance(v, str) for v in fetches.values())):
        return None
    if not fuse_enabled() or not lazy._chain:
        return None
    # the eager op's validation errors, raised before any work
    for name, cname in fetches.items():
        if name not in lazy.schema:
            raise KeyError(f"No column {name!r}")
        if cname not in COMBINERS:
            raise KeyError(
                f"Unknown combiner {cname!r}; known: {sorted(COMBINERS)}")
    source, ops = lazy._source, list(lazy._chain)
    plan = _plan_chain(source.schema, ops, lazy.schema,
                       reduce_spec=dict(fetches))
    if plan is None:
        return None
    if not plan.has_filter and source.num_rows == 0:
        raise ValueError("reduce on an empty distributed frame")
    try:
        result = _run_fused_reduce(plan, source)
    except _EmptyReduceError:
        raise  # the empty-after-filter contract (per-op parity)
    except Exception as e:  # noqa: BLE001 - reclassified below
        from ..resilience import is_device_lost
        if is_device_lost(e):
            raise
        _record_fallback(e)
        _log.warning(
            "fused mesh reduce failed (%s: %s); re-running per-op",
            type(e).__name__, e)
        D = _dist()
        return D.dreduce_blocks(fetches, _replay_per_op(source, ops))
    counters.inc("dplan.fused_forcings")
    lazy._dplan_info = plan.describe(executed="executed") \
        + _feedback_lines(plan)
    return result


@traced_query("dfused", _meta_dfused)
def _run_fused_reduce(plan: _DPlan, source):
    from ..parallel import elastic as _elastic

    return _elastic.elastic_call(
        "dfused", source, lambda d: _exec_reduce(plan, d))


def _exec_reduce(plan: _DPlan, d) -> Dict[str, np.ndarray]:
    from .. import dtypes as _dt

    D = _dist()
    outs, wall = _dispatch(plan, d, want_keeps=False)
    record_stream_feedback(_feedback_key(plan),
                           blocks=d.mesh.num_data_shards,
                           rows=d.num_rows, wall_s=wall)
    names = plan.reduce_names
    if plan.has_filter:
        counts = D._read_global(outs[len(names)]).astype(np.int64)
        num_rows = int(counts.sum())
        if plan.n_filters == 1:
            record_selectivity(plan.filter_nodes[0].comp, d.num_rows,
                               num_rows)
        if num_rows == 0:
            # the eager op raises before dispatching; here emptiness is
            # only knowable after — same exception type/text either way
            raise _EmptyReduceError(
                "reduce on an empty distributed frame")
    result = {}
    for name, a in zip(names, outs):
        v = np.asarray(a)
        f = plan.final_schema[name]
        if v.dtype != f.dtype.np_storage and f.dtype is not _dt.bfloat16:
            v = v.astype(f.dtype.np_storage)
        result[name] = v
    return result


def record_aggregate(fetches, lazy: LazyDistributedFrame, keys,
                     max_groups):
    """Fold a monoid host-key ``daggregate`` into the fused program
    (chain values segment-reduce per shard + one collective, DrJAX
    style). ``None`` defers to materialize + the eager op: device-key
    (``max_groups``) aggregations, generic computations, chains with a
    filter (the key→id factorization reads the SOURCE layout, which a
    filter invalidates), or keys produced/renamed by the chain."""
    if not fuse_enabled() or not lazy._chain:
        return None
    if max_groups is not None:
        return None
    if not (isinstance(fetches, Mapping) and fetches and all(
            isinstance(v, str) for v in fetches.values())):
        return None
    source, ops = lazy._source, list(lazy._chain)
    if any(o.kind == "dfilter" for o in ops):
        return None
    for k in keys:
        if k not in lazy.schema or k not in source.schema:
            return None
        if any(o.kind == "dmap" and k in o.comp.output_names for o in ops):
            return None  # a computed key column needs the chain's values
    from ..engine.ops import _validate_monoid_fetches

    value_names = [n for n in lazy.schema.names if n not in keys]
    _validate_monoid_fetches(fetches, value_names, "before distribute()")
    if source.num_rows == 0:
        raise ValueError("aggregate on an empty distributed frame")
    plan = _plan_chain(source.schema, ops, lazy.schema,
                       agg_value_names=sorted(fetches))
    if plan is None:
        return None
    plan.agg_combiners = dict(fetches)
    try:
        result = _run_fused_aggregate(plan, source, list(keys))
    except Exception as e:  # noqa: BLE001 - reclassified below
        from ..resilience import is_device_lost
        if is_device_lost(e):
            raise
        _record_fallback(e)
        _log.warning(
            "fused mesh aggregate failed (%s: %s); re-running per-op",
            type(e).__name__, e)
        D = _dist()
        return D.daggregate(fetches, _replay_per_op(source, ops), keys)
    counters.inc("dplan.fused_forcings")
    lazy._dplan_info = plan.describe(executed="executed") \
        + _feedback_lines(plan)
    return result


@traced_query("dfused", _meta_dfused)
def _run_fused_aggregate(plan: _DPlan, source, keys):
    from ..parallel import elastic as _elastic

    return _elastic.elastic_call(
        "dfused", source, lambda d: _exec_aggregate(plan, d, keys))


def _exec_aggregate(plan: _DPlan, d, keys):
    """Key ids factorize from the SOURCE frame (the chain is filter-free
    and the keys pass through untouched, so the row↔id layout is
    identical) — hot-key salting, the group-ids cache, and the host
    fold-back all ride the eager op's own helpers."""
    D = _dist()
    ids_dev, uniques, num_groups, salt_plan = D._monoid_group_plan(d, keys)
    if salt_plan is not None:
        prog_ids, prog_groups = salt_plan[0], salt_plan[1]
    else:
        prog_ids, prog_groups = ids_dev, num_groups
    fetch_names = sorted(plan.agg_combiners)
    outs, wall = _dispatch(plan, d, want_keeps=False,
                           agg_groups=prog_groups, ids_dev=prog_ids)
    record_stream_feedback(_feedback_key(plan),
                           blocks=d.mesh.num_data_shards,
                           rows=d.num_rows, wall_s=wall)
    tables = list(outs)
    if salt_plan is not None:
        from ..parallel import elastic as _elastic
        tables = [_elastic.fold_salted(t, salt_plan[2],
                                       plan.agg_combiners[f])
                  for f, t in zip(fetch_names, tables)]
    key_cols = {k: u for k, u in zip(keys, uniques)}
    out = D._monoid_agg_result(plan.final_schema, keys, fetch_names,
                               tables, key_cols, num_groups)
    if salt_plan is not None:
        # the fused fold surfaces its hot-key observations like the
        # eager op (frame.hot_keys() / explain() — docs/joins.md)
        D.attach_hot_keys(out, keys, uniques, salt_plan)
    return out


# ---------------------------------------------------------------------------
# streaming: per-batch window folds on the mesh
# ---------------------------------------------------------------------------

_stream_cache: "OrderedDict[tuple, object]" = OrderedDict()
_STREAM_CACHE_CAP = 32
_stream_lock = threading.Lock()


def mesh_segment_partial(mesh, col_combiners: Mapping[str, str],
                         ids: np.ndarray, vals: Mapping[str, np.ndarray],
                         num_groups: int) -> Dict[str, object]:
    """One batch's keyed partial tables computed as ONE fused GSPMD
    program on ``mesh`` — the streaming window fold riding the
    ``daggregate`` path: rows shard over the data axis, each shard
    segment-reduces its local rows, one ``psum``-family collective
    yields the replicated ``[groups, ...]`` tables the window state
    merges. Steady-state batches (same padded size / key cardinality)
    are pure program-cache hits."""
    S = mesh.num_data_shards
    fetch_names = sorted(col_combiners)
    n = int(ids.shape[0])
    padded = max(((n + S - 1) // S) * S, S)
    ids_p = np.full(padded, -1, np.int32)
    ids_p[:n] = ids
    ids_dev = jax.make_array_from_callback(
        (padded,), mesh.row_sharding(1), lambda idx: ids_p[idx])
    arrs = []
    for f in fetch_names:
        v = np.asarray(vals[f])
        if padded != n:
            out = np.zeros((padded,) + v.shape[1:], v.dtype)
            out[:n] = v
            v = out
        arrs.append(jax.device_put(v, mesh.row_sharding(v.ndim)))
    key = (mesh.mesh, mesh.data_axis, padded, num_groups,
           tuple((f, col_combiners[f], a.shape, str(a.dtype))
                 for f, a in zip(fetch_names, arrs)))
    with _stream_lock:
        fn = _stream_cache.get(key)
        if fn is not None:
            _stream_cache.move_to_end(key)
    if fn is None:
        axis = mesh.data_axis
        in_specs = (P(axis),) + tuple(
            P(axis, *([None] * (a.ndim - 1))) for a in arrs)
        out_specs = tuple(P() for _ in fetch_names)
        fn = jax.jit(shard_map(
            _agg_shard_fn(fetch_names, dict(col_combiners), axis,
                          num_groups),
            mesh=mesh.mesh, in_specs=in_specs, out_specs=out_specs))
        with _stream_lock:
            fn = _stream_cache.setdefault(key, fn)
            _stream_cache.move_to_end(key)
            while len(_stream_cache) > _STREAM_CACHE_CAP:
                _stream_cache.popitem(last=False)
        counters.inc("dplan.fused_programs")
    with span("stream.mesh_fold"):
        tables = fn(ids_dev, *arrs)
    counters.inc("mesh.dispatches")
    counters.inc("stream.mesh_folds")
    return dict(zip(fetch_names, tables))
