"""Filter-predicate atom extraction for parquet row-group pushdown.

A filter computation is an opaque traced JAX program; this module
recognizes the narrow, useful shape — conjunctions of single-column
comparisons against literals (``lambda x: x > 3``, ``lambda x, y:
(x > 3) & (y <= 0)``) — by walking the predicate's jaxpr. Anything it
does not PROVE is such a comparison yields no atoms, and the scan reads
everything (pushdown is an optimization, never a semantics change).

Refutation (:func:`refutes`) is evaluated against row-group footer
min/max statistics in the column's DEVICE dtype: casting is monotone
but can round a host value ONTO the literal, so strict and non-strict
comparisons use different boundary rules — a skipped row group must be
one where the predicate is false for EVERY row as the device would
evaluate it. Rows whose value is NaN compare false under every
supported operator, so float stats (which exclude NaN) stay sound.
"""

from __future__ import annotations

from typing import List, NamedTuple, Optional

import numpy as np

from ..utils.logging import get_logger

__all__ = ["Atom", "extract_atoms", "refutes"]

_log = get_logger("plan.predicates")

_CMP = {"gt": "gt", "lt": "lt", "ge": "ge", "le": "le", "eq": "eq"}


class Atom(NamedTuple):
    """One conjunct: ``column <op> value`` (op in gt/lt/ge/le/eq)."""

    column: str
    op: str
    value: float


def _value_preserving(src_dt, dst_dt) -> bool:
    """True when casting ``src_dt -> dst_dt`` provably changes no value
    the refutation could see: bool widening, same-kind int widening,
    f32->f64, small-int->f32, and any-int->f64 (``refutes`` bails
    beyond 2**53 for integer columns, inside which f64 is exact)."""
    try:
        s, d = np.dtype(src_dt), np.dtype(dst_dt)
    except (TypeError, ValueError):
        return False
    if s == d:
        return True
    if s.kind == "b":
        return d.kind in "biuf"
    if s.kind in "iu" and d.kind in "iu":
        return d.kind == s.kind and d.itemsize >= s.itemsize
    if s.kind in "iu" and d.kind == "f":
        if d.itemsize >= 8:
            return True  # exact under the 2**53 bail in refutes()
        return s.itemsize <= 2  # i8/i16/u8/u16 fit f32's mantissa
    if s.kind == "f" and d.kind == "f":
        return d.itemsize >= s.itemsize
    return False


def _literal_scalar(v) -> Optional[float]:
    try:
        a = np.asarray(v)
    except Exception:
        return None
    if a.ndim == 0:
        return float(a)
    return None


def extract_atoms(comp) -> List[Atom]:
    """Conjunctive ``column <op> literal`` atoms of a filter predicate,
    ``[]`` when the shape is not provably that (cached on the comp)."""
    cached = getattr(comp, "_tft_pred_atoms", None)
    if cached is not None:
        return list(cached)
    atoms: List[Atom] = []
    try:
        atoms = _extract(comp)
    except Exception as e:  # noqa: BLE001 - unextractable means unpushed
        _log.debug("predicate extraction failed (%s: %s); no pushdown",
                   type(e).__name__, e)
        atoms = []
    try:
        comp._tft_pred_atoms = tuple(atoms)
    except Exception as e:
        _log.debug("could not cache atoms on %r: %s", comp, e)
    return atoms


def _extract(comp) -> List[Atom]:
    import jax

    from .. import dtypes as _dt

    avals = {s.name: jax.ShapeDtypeStruct(
        tuple(2 if d == -1 else d for d in s.shape.dims),
        _dt.device_dtype(s.dtype)) for s in comp.inputs}
    closed = jax.make_jaxpr(comp.fn)(avals)
    jaxpr = closed.jaxpr
    consts = dict(zip(jaxpr.constvars, closed.consts))
    # var -> source column name (identity-preserving unary ops only)
    src = {}
    flat_in = jaxpr.invars
    # comp.fn takes a dict: jax flattens it sorted by key
    for v, name in zip(flat_in, sorted(avals)):
        src[v] = ("col", name)

    def resolve(v):
        from jax.core import Literal
        if isinstance(v, Literal):
            lit = _literal_scalar(v.val)
            return ("lit", lit) if lit is not None else None
        if v in consts:
            lit = _literal_scalar(consts[v])
            return ("lit", lit) if lit is not None else None
        return src.get(v)

    # var -> list of atoms it PROVABLY equals (a boolean vector)
    bools = {}
    _FLIP = {"gt": "lt", "lt": "gt", "ge": "le", "le": "ge", "eq": "eq"}
    for eqn in jaxpr.eqns:
        prim = eqn.primitive.name
        if prim in ("convert_element_type", "copy"):
            s = resolve(eqn.invars[0])
            if s is not None and (
                    prim == "copy"
                    or _value_preserving(
                        getattr(eqn.invars[0].aval, "dtype", None),
                        getattr(eqn.outvars[0].aval, "dtype", None))):
                # only VALUE-PRESERVING casts keep column identity: a
                # truncating/narrowing cast (float->int, f64->f32)
                # changes what the device compares, so an atom over the
                # raw column would refute groups whose rows match
                src[eqn.outvars[0]] = s
            if eqn.invars[0] in bools:
                bools[eqn.outvars[0]] = bools[eqn.invars[0]]
            continue
        if prim in _CMP:
            a = resolve(eqn.invars[0])
            b = resolve(eqn.invars[1])
            if a and b and a[0] == "col" and b[0] == "lit":
                bools[eqn.outvars[0]] = [Atom(a[1], prim, b[1])]
            elif a and b and a[0] == "lit" and b[0] == "col":
                bools[eqn.outvars[0]] = [Atom(b[1], _FLIP[prim], a[1])]
            continue
        if prim == "and":
            a = bools.get(eqn.invars[0])
            b = bools.get(eqn.invars[1])
            if a is not None and b is not None:
                bools[eqn.outvars[0]] = a + b
            continue
        # any other primitive producing the eventual output breaks the
        # proof chain for its result; harmless intermediates are fine
    out = jaxpr.outvars
    if len(out) != 1:
        return []
    return list(bools.get(out[0], []))


def refutes(atom: Atom, vmin, vmax, device_dtype) -> bool:
    """True when ``column <op> value`` is FALSE for every row of a
    group whose column spans ``[vmin, vmax]`` — as the DEVICE would
    evaluate it. Conservative: unknown stats never refute.

    Integer/bool columns compare in float64: a non-integral literal
    promotes the device comparison to float anyway, and float64 is
    exact for both sides below 2**53 (beyond that, never refute —
    truncating the literal INTO the int dtype would wrongly refute
    groups whose rows match, e.g. ``x < 3.5`` over a group holding 3).
    Float columns compare after the (monotone) cast to the device
    dtype, with strict/non-strict boundary rules that survive a host
    value rounding ONTO the literal."""
    if vmin is None or vmax is None:
        return False
    try:
        dd = np.dtype(device_dtype)
        if dd.kind in "iub":
            exact = float(2 ** 53)
            lo = float(vmin)
            hi = float(vmax)
            v = float(atom.value)
            if abs(lo) > exact or abs(hi) > exact or abs(v) > exact:
                return False
        else:
            lo = np.asarray(vmin, np.float64).astype(dd)
            hi = np.asarray(vmax, np.float64).astype(dd)
            v = np.asarray(atom.value, np.float64).astype(dd)
    except (TypeError, ValueError, OverflowError):
        return False
    # monotone cast: x <= vmax  =>  cast(x) <= hi, etc. Strict device
    # comparisons survive equality at the bound; non-strict need a
    # strict host bound.
    if atom.op == "gt":   # all false iff every cast(x) <= v
        return bool(hi <= v)
    if atom.op == "ge":
        return bool(hi < v)
    if atom.op == "lt":
        return bool(lo >= v)
    if atom.op == "le":
        return bool(lo > v)
    if atom.op == "eq":
        return bool(v < lo or v > hi)
    return False
