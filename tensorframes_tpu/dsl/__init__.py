"""Embedded operator DSL: build computations without writing JAX.

Parity surface with the reference's Scala DSL
(``/root/reference/src/main/scala/org/tensorframes/dsl/package.scala:33-133``):
``placeholder``, ``constant``, ``identity``, ``add``, ``div`` (plus
``sub``/``mul`` sugar), ``fill``, ``zeros``, ``ones``, ``reduce_sum``,
``reduce_min`` (plus ``reduce_max``/``reduce_mean`` extras), operator
overloading on nodes, TF-convention name scoping (``scope``), per-graph
isolation (``with_graph``), and DataFrame-derived placeholders (``block`` /
``row`` live in the package root API).

DSL nodes lower to the same :class:`~..computation.Computation` IR the JAX
front end produces — both front ends meet at StableHLO, the analogue of the
reference's two graph-authoring paths meeting at GraphDef.
"""

from __future__ import annotations

from typing import Optional, Sequence, Union

import jax.numpy as jnp
import numpy as np

from .. import dtypes as _dt
from ..shape import Shape, Unknown
from .graph import Graph, Node, current_graph, scope, with_graph

__all__ = [
    "Node", "Graph", "current_graph", "with_graph", "scope",
    "placeholder", "constant", "identity", "add", "sub", "mul", "div",
    "fill", "zeros", "ones",
    "reduce_sum", "reduce_min", "reduce_max", "reduce_mean",
]


def _as_node(x) -> Node:
    if isinstance(x, Node):
        return x
    return constant(x)


def placeholder(dtype: Union[_dt.DType, str], shape,
                name: Optional[str] = None) -> Node:
    """An input node; its name must match a DataFrame column at execution
    (reference ``dsl/package.scala:48-56``)."""
    if isinstance(dtype, str):
        dtype = _dt.by_name(dtype)
    shape = shape if isinstance(shape, Shape) else Shape(tuple(shape))
    return Node("Placeholder", [], dtype, shape, impl=None, name=name)


def constant(value, dtype: Optional[_dt.DType] = None,
             name: Optional[str] = None) -> Node:
    """A captured constant (scalar / vector / matrix), the DenseTensor
    analogue (reference ``dsl/package.scala:68-76``,
    ``impl/DenseTensor.scala``)."""
    arr = np.asarray(value)
    if dtype is None:
        dtype = _dt.from_numpy(arr.dtype)
    if not dtype.tensor:
        raise ValueError(
            f"constant() requires a numeric tensor dtype, got {dtype.name}")
    arr = arr.astype(dtype.np_storage)
    return Node("Const", [], dtype, Shape(arr.shape),
                impl=None, value=arr, name=name)


def identity(x, name: Optional[str] = None) -> Node:
    x = _as_node(x)
    return Node("Identity", [x], x.dtype, x.shape,
                impl=lambda a: a, name=name)


def _binop(op: str, impl, a, b, name: Optional[str] = None) -> Node:
    a, b = _as_node(a), _as_node(b)
    shape = a.shape.broadcast_with(b.shape)
    dtype = _dt.widen(a.dtype, b.dtype)
    return Node(op, [a, b], dtype, shape, impl=impl, name=name)


def add(a, b, name: Optional[str] = None) -> Node:
    return _binop("Add", lambda x, y: x + y, a, b, name)


def sub(a, b, name: Optional[str] = None) -> Node:
    return _binop("Sub", lambda x, y: x - y, a, b, name)


def mul(a, b, name: Optional[str] = None) -> Node:
    return _binop("Mul", lambda x, y: x * y, a, b, name)


def div(a, b, name: Optional[str] = None) -> Node:
    return _binop("Div", lambda x, y: x / y, a, b, name)


def fill(shape, value, name: Optional[str] = None) -> Node:
    """Tensor of ``shape`` filled with scalar ``value``
    (reference ``dsl/package.scala:93-99``)."""
    sh = shape if isinstance(shape, Shape) else Shape(tuple(shape))
    dims = sh.assert_concrete("fill requires a concrete shape")
    v = _as_node(value)
    if not v.shape.is_scalar:
        raise ValueError("fill value must be scalar")
    return Node("Fill", [v], v.dtype, sh,
                impl=lambda x: jnp.full(dims, x), name=name)


def zeros(shape, dtype: Union[_dt.DType, str] = _dt.double,
          name: Optional[str] = None) -> Node:
    dt = _coerce(dtype)
    return fill(shape, constant(np.zeros((), dt.np_storage), dtype=dt),
                name=name)


def ones(shape, dtype: Union[_dt.DType, str] = _dt.double,
         name: Optional[str] = None) -> Node:
    dt = _coerce(dtype)
    return fill(shape, constant(np.ones((), dt.np_storage), dtype=dt),
                name=name)


def _coerce(dtype) -> _dt.DType:
    return _dt.by_name(dtype) if isinstance(dtype, str) else dtype


def _reduce(op: str, impl, x, axis, name: Optional[str]) -> Node:
    x = _as_node(x)
    if axis is None:
        shape = Shape.empty
    else:
        ax = axis if axis >= 0 else x.shape.ndim + axis
        if not (0 <= ax < x.shape.ndim):
            raise ValueError(f"reduce axis {axis} out of range for "
                             f"{x.shape!r}")
        shape = Shape(tuple(d for i, d in enumerate(x.shape.dims)
                            if i != ax))
    return Node(op, [x], x.dtype, shape,
                impl=lambda a: impl(a, axis), name=name)


def reduce_sum(x, axis: Optional[int] = None,
               name: Optional[str] = None) -> Node:
    """Sum over one axis (or all axes when None), keeping the input dtype
    (reference ``dsl/package.scala:117-123``)."""
    return _reduce("Sum",
                   lambda a, ax: jnp.sum(a, axis=ax).astype(a.dtype),
                   x, axis, name)


def reduce_min(x, axis: Optional[int] = None,
               name: Optional[str] = None) -> Node:
    return _reduce("Min", lambda a, ax: jnp.min(a, axis=ax), x, axis, name)


def reduce_max(x, axis: Optional[int] = None,
               name: Optional[str] = None) -> Node:
    return _reduce("Max", lambda a, ax: jnp.max(a, axis=ax), x, axis, name)


def reduce_mean(x, axis: Optional[int] = None,
                name: Optional[str] = None) -> Node:
    return _reduce("Mean", lambda a, ax: jnp.mean(a, axis=ax), x, axis, name)
