"""Lower DSL node DAGs to the Computation IR.

The analogue of the reference's ``DslImpl.buildGraph`` + ``getClosure``
(``/root/reference/src/main/scala/org/tensorframes/dsl/DslImpl.scala:37-74``):
walk the fetch nodes' transitive closure, turn placeholders into computation
inputs, and emit one pure JAX function evaluating the DAG. Fetch node names
become output column names; placeholder names must match DataFrame columns
(map ops) or follow the reduce naming contracts.
"""

from __future__ import annotations

from typing import Dict, List, Mapping, Sequence

import jax.numpy as jnp

from .. import dtypes as _dt
from ..computation import Computation, TensorSpec
from ..schema import Schema
from ..shape import Shape, Unknown
from .graph import Node

__all__ = ["closure", "lower_nodes", "nodes_to_computation",
           "nodes_to_reduce_computation"]


def _fetch_list(fetches) -> List[Node]:
    if isinstance(fetches, Node):
        return [fetches]
    return list(fetches)


def closure(fetches: Sequence[Node]) -> List[Node]:
    """Transitive parents of the fetches, topologically ordered."""
    seen: Dict[int, Node] = {}
    order: List[Node] = []

    def visit(n: Node):
        if id(n) in seen:
            return
        seen[id(n)] = n
        for p in n.parents:
            visit(p)
        order.append(n)

    for f in fetches:
        visit(f)
    return order


def lower_nodes(fetches: Sequence[Node]):
    """Build ``(placeholders, fn)``: the placeholder nodes and a pure
    dict->dict function evaluating the DAG with jnp."""
    fetches = list(fetches)
    nodes = closure(fetches)
    placeholders = [n for n in nodes if n.op == "Placeholder"]

    def fn(inputs: Mapping[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        vals: Dict[int, jnp.ndarray] = {}
        for n in nodes:
            if n.op == "Placeholder":
                vals[id(n)] = jnp.asarray(inputs[n.name])
            elif n.op == "Const":
                # the numpy value stays raw: jnp ops lift it to a jaxpr
                # literal, whereas jnp.asarray here stamps a device_put
                # into the trace and breaks the prim-for-prim parity with
                # handwritten JAX that the golden DSL tests assert
                vals[id(n)] = n.value
            else:
                vals[id(n)] = n.impl(*[vals[id(p)] for p in n.parents])
        return {f.name: vals[id(f)] for f in fetches}

    return placeholders, fn


def _check_unique_fetches(fetches: Sequence[Node]) -> None:
    names = [f.name for f in fetches]
    if len(set(names)) != len(names):
        raise ValueError(
            f"Could not infer a list of unique names for the output "
            f"columns: {names}")


def nodes_to_computation(fetches, schema: Schema,
                         block_level: bool) -> Computation:
    """DSL fetches -> Computation for the map ops.

    Placeholder shapes declared in the DSL are refined by the frame's
    column metadata when the metadata is more precise (the reference ships
    both and lets the engine reconcile, ``Node.hints`` +
    ``SchemaTransforms``)."""
    fetches = _fetch_list(fetches)
    _check_unique_fetches(fetches)
    placeholders, fn = lower_nodes(fetches)
    specs = []
    for p in placeholders:
        field = schema.get(p.name)
        shape = p.shape
        if field is not None and field.block_shape is not None:
            declared = field.block_shape if block_level \
                else field.block_shape.tail
            if declared.is_more_precise_than(shape):
                shape = declared
        specs.append(TensorSpec(p.name, p.dtype, shape))
    return Computation.trace(fn, specs, takes_dict=True)


def nodes_to_reduce_computation(fetches, schema: Schema,
                                suffixes: Sequence[str],
                                block_level: bool) -> Computation:
    """DSL fetches -> Computation for the reduce ops (the ``z_input`` /
    ``z_1``/``z_2`` contracts are validated by the engine afterwards)."""
    fetches = _fetch_list(fetches)
    _check_unique_fetches(fetches)
    placeholders, fn = lower_nodes(fetches)
    specs = [TensorSpec(p.name, p.dtype, p.shape) for p in placeholders]
    return Computation.trace(fn, specs, takes_dict=True)
