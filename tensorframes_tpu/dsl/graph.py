"""DSL graph structure: nodes, implicit graphs, TF-style name scoping.

TPU-native re-design of the reference's Scala DSL core
(``/root/reference/src/main/scala/org/tensorframes/dsl/Operation.scala``,
``Paths.scala``): operator nodes form a DAG; each node gets a TF-convention
path — scope prefixes joined with ``/``, duplicate base names deduplicated
with ``_1``, ``_2`` suffixes — assigned from the *current graph*'s counters.
Where the reference emits ``NodeDef`` protos consumed by a TF C++ session,
these nodes lower to a JAX function (see :mod:`.lower`) that XLA compiles.

Graphs are implicit and thread-local; ``with_graph()`` opens a fresh graph
(resetting name counters — the test-isolation contract of the reference's
``GraphScoping.testGraph``), ``scope(name)`` opens a name scope.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import dtypes as _dt
from ..shape import Shape, Unknown

__all__ = ["Node", "Graph", "current_graph", "with_graph", "scope"]


class Graph:
    """Holds name-dedup counters and the scope stack for one DSL graph."""

    def __init__(self):
        self._counters: Dict[str, int] = {}
        self._scopes: List[str] = []
        self.nodes: List["Node"] = []

    def assign_name(self, base: str) -> str:
        prefix = "/".join(self._scopes)
        full_base = f"{prefix}/{base}" if prefix else base
        n = self._counters.get(full_base, 0)
        self._counters[full_base] = n + 1
        return full_base if n == 0 else f"{full_base}_{n}"

    def claim_name(self, name: str) -> str:
        """Claim an explicit (user-requested) name, deduplicating like TF."""
        return self.assign_name(name)


class _State(threading.local):
    def __init__(self):
        self.stack: List[Graph] = []
        self.default = Graph()


_state = _State()


def current_graph() -> Graph:
    return _state.stack[-1] if _state.stack else _state.default


@contextmanager
def with_graph(g: Optional[Graph] = None):
    """Run DSL construction in a fresh graph (fresh naming counters)."""
    g = g or Graph()
    _state.stack.append(g)
    try:
        yield g
    finally:
        _state.stack.pop()


@contextmanager
def scope(name: str):
    """TF-style name scope: nested ops get ``name/`` path prefixes."""
    g = current_graph()
    g._scopes.append(name)
    try:
        yield
    finally:
        g._scopes.pop()


class Node:
    """One DSL operation node.

    ``op`` names the abstract operation; ``impl`` is its jnp lowering
    ``(input_arrays...) -> array``; ``parents`` the input nodes; ``value``
    an optional captured constant. Shape/dtype are inferred eagerly at
    construction (the reference's broadcastShape moment,
    ``dsl/DslImpl.scala:115-132``).
    """

    _tft_dsl_node = True  # duck-type marker for the engine

    def __init__(self, op: str, parents: Sequence["Node"],
                 dtype: _dt.DType, shape: Shape,
                 impl: Optional[Callable] = None,
                 value: Optional[np.ndarray] = None,
                 name: Optional[str] = None):
        g = current_graph()
        self.graph = g
        self.op = op
        self.parents = list(parents)
        self.dtype = dtype
        self.shape = shape
        self.impl = impl
        self.value = value
        self.name = g.claim_name(name) if name else g.assign_name(op)
        g.nodes.append(self)

    # -- naming ------------------------------------------------------------
    def named(self, name: str) -> "Node":
        """Rename this node (the reference's ``named`` operator,
        ``dsl/Operation.scala:40-44``)."""
        self.name = self.graph.claim_name(name)
        return self

    # -- operator sugar (reference dsl/Operation.scala:46-56) --------------
    def __add__(self, other):
        from . import add
        return add(self, other)

    def __radd__(self, other):
        from . import add
        return add(other, self)

    def __sub__(self, other):
        from . import sub
        return sub(self, other)

    def __rsub__(self, other):
        from . import sub
        return sub(other, self)

    def __mul__(self, other):
        from . import mul
        return mul(self, other)

    def __rmul__(self, other):
        from . import mul
        return mul(other, self)

    def __truediv__(self, other):
        from . import div
        return div(self, other)

    def __rtruediv__(self, other):
        from . import div
        return div(other, self)

    def __repr__(self):
        return (f"Node({self.name}: {self.op} "
                f"{self.dtype.name}{self.shape!r})")
