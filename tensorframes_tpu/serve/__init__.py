"""Multi-tenant query serving: scheduler, admission control, quotas.

The library's serving front end (ROADMAP item 2): everything a server
needs existed in pieces — correlated query traces, classified errors and
deadlines, HBM watermarks, a Prometheus endpoint, a bounded pipeline
window — and this package composes them:

- :class:`QueryScheduler` (:mod:`.scheduler`) — per-tenant bounded FIFO
  queues with weighted-fair (stride) selection, in-flight slot quotas,
  rows/sec token buckets, per-query deadlines, HBM admission control
  (wait-or-shed, never OOM mid-flight), and a process-wide
  :class:`~..engine.pipeline.SlotPool` bounding cross-query in-flight
  blocks. Rejections are classified resilience errors
  (:class:`~..resilience.QueueFull`, :class:`~..resilience.OverQuota`,
  :class:`~..resilience.AdmissionDeadline`).
- :class:`SharedCompileCache` (:mod:`.cache`) — structural interning of
  Computations at the executor boundary, so identical workloads from
  different tenants (the millionth ``x + 3``) share one compiled
  program.
- :class:`ServerStats` / :func:`serve_report` (:mod:`.stats`) — per-
  tenant outcome totals, live queue/in-flight gauges on the metrics
  endpoint, p99 from ``query_latency_seconds{tenant=...}``.
- :mod:`.quarantine` — poison-query containment: a plan fingerprint
  that keeps failing permanently (``TFT_QUARANTINE_AFTER`` in a row)
  flips to a classified
  :class:`~..resilience.QueryQuarantined` fast-reject with a TTL
  (``TFT_QUARANTINE_TTL_S``) and a manual ``tft.unquarantine()``
  override, so one poison plan cannot starve its tenant's healthy
  queries of slots.
- :class:`ServeFabric` (:mod:`.fabric`) — the multi-host tier: tenants
  sharded across worker processes with heartbeat/lease health, a
  classified ``worker_lost`` failure path (queued queries re-placed,
  running queries resumed from persisted checkpoints on a survivor —
  never wrong, never dropped), SLO-burn-driven re-placement, and
  rolling restarts that come back warm from the durable tier
  (``memory/persist.py``). ``TFT_FABRIC=0`` collapses it to the
  single-process path bit-identically.

Entry points: ``tft.submit(df, tenant=..., deadline=...)`` (the
process-default scheduler) or an explicit ``QueryScheduler`` as a
context manager. See ``docs/serving.md``.
"""

from .cache import SharedCompileCache, computation_signature
from .fabric import (FabricQuery, FabricWorker, ServeFabric,
                     fabric_enabled, live_fabric)
from .quarantine import quarantine_status, unquarantine
from .scheduler import (QueryScheduler, SubmittedQuery, TenantQuota,
                        default_scheduler, live_scheduler,
                        set_default_scheduler, shutdown_default_scheduler)
from .stats import ServerStats, serve_report

__all__ = [
    "QueryScheduler", "SubmittedQuery", "TenantQuota",
    "default_scheduler", "set_default_scheduler",
    "shutdown_default_scheduler", "live_scheduler",
    "SharedCompileCache", "computation_signature",
    "ServerStats", "serve_report",
    "ServeFabric", "FabricQuery", "FabricWorker",
    "live_fabric", "fabric_enabled",
    "unquarantine", "quarantine_status",
]
