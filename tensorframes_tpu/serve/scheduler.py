"""Multi-tenant query scheduling: admission, fairness, quotas, deadlines.

The concurrency story of the library used to end at one forcing thread:
two callers racing into ``frame.blocks()`` contended blindly over the
engine. :class:`QueryScheduler` is the serving front end that composes
the pieces the last four PRs built — correlated query traces
(observability), classified errors and deadlines (resilience), HBM
watermarks (observability.device), and the bounded pipeline window
(engine.pipeline) — into one multiplexing layer:

- **Submission** (:meth:`QueryScheduler.submit`): a query is a lazy
  frame (+ optional fetches), a tenant id, and an optional deadline. It
  lands on the tenant's bounded FIFO queue; a full queue rejects
  immediately with a classified :class:`~..resilience.QueueFull`
  (backpressure, never unbounded buffering), and an exhausted rows/sec
  token bucket rejects with :class:`~..resilience.OverQuota`.
- **Weighted-fair selection** (stride scheduling): each tenant carries a
  virtual pass incremented by ``1/weight`` per served query; workers
  always serve the eligible tenant with the smallest pass, so completion
  shares converge to the weight ratio regardless of arrival order.
  Eligibility = non-empty queue AND in-flight below the tenant's
  ``max_inflight`` slot quota.
- **Admission control**: before a query runs, its estimated block
  footprint is checked against the HBM high-water mark
  (``observability.device.watermark()``; fraction
  ``TFT_SERVE_HBM_FRACTION`` of the allocator limit). A query that would
  cross the mark WAITS (bounded by ``TFT_SERVE_ADMISSION_WAIT_S`` and
  its own deadline); mid-wait the scheduler asks the largest
  checkpointable running query to PARK (preempt-aware admission,
  ``serve.admission_preempts``) so the arrival can fit, and only an
  arrival preemption could not make room for is **shed** with a
  classified :class:`~..resilience.AdmissionDeadline` — a policy
  rejection instead of an OOM mid-flight. Backends that report no
  memory stats (CPU) admit freely.
- **Execution**: workers force the frame inside a
  :func:`~..observability.query_trace` carrying the tenant label (the
  frame's own forcing joins it, so block/retry/compile events correlate
  to the serving query) and inside a resilience
  :func:`~..resilience.deadline` scope, so the engine's retry loops and
  the pipeline's slot waits honor the query deadline. Total in-flight
  block concurrency across all queries is bounded by the
  :class:`~..engine.pipeline.SlotPool` the scheduler installs (workers x
  pipeline depth by default, ``TFT_SERVE_SLOTS`` overrides).
- **Shared compile cache**: while a scheduler is live, the engine's
  executors intern every Computation through a
  :class:`~.cache.SharedCompileCache`, so identical workloads from
  different tenants share one compiled program
  (``serve.compile_cache.hits``).

- **Preemption & cancellation** (``docs/serving.md``):
  :meth:`QueryScheduler.cancel` stops a queued query immediately and a
  running one at its next block boundary (classified
  :class:`~..resilience.QueryCancelled` on the future). When a
  higher-weight tenant submits while every execution slot is busy, the
  lowest-weight running query that has run for at least
  ``TFT_PREEMPT_AFTER_MS`` parks at its next block boundary — its
  completed block outputs checkpoint off-device through the memory
  ledger (``memory/checkpoint.py``) and it re-queues at the FRONT of
  its tenant's queue; resume re-dispatches only the remaining blocks,
  bit-identical to an uninterrupted run. ``TFT_FAULTS=preempt:N``
  drives the park/resume path deterministically.

``workers=0`` builds a *manually driven* scheduler — no threads;
:meth:`QueryScheduler.step` executes exactly one scheduling decision
synchronously. Tests and benchmarks use it for deterministic ordering.

Env knobs (all ``TFT_SERVE_*``; see ``docs/serving.md``):
``TFT_SERVE_WORKERS`` (2), ``TFT_SERVE_QUEUE_DEPTH`` (64 per tenant),
``TFT_SERVE_INFLIGHT`` (2 per tenant), ``TFT_SERVE_SLOTS``,
``TFT_SERVE_HBM_FRACTION`` (0.9), ``TFT_SERVE_HBM_LIMIT_BYTES``,
``TFT_SERVE_ADMISSION_WAIT_S`` (5), ``TFT_SERVE_ADMISSION_POLL_S``
(0.02), ``TFT_SERVE_SHARED_CACHE`` (1), ``TFT_SERVE_DEADLINE_S``,
``TFT_SERVE_COMPILE_CACHE`` (512), ``TFT_SERVE_PREEMPT`` (1),
``TFT_PREEMPT_AFTER_MS`` (100).
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Any, Callable, Dict, List, Mapping, Optional, Tuple

from ..engine import executor as _executor
from ..engine import pipeline as _pipeline
from ..engine import preempt as _preempt
from ..observability import baseline as _baseline
from ..observability import device as _obs_device
from ..observability import events as _obs
from ..observability import flight as _flight
from ..observability import history as _history
from ..observability import slo as _slo
from ..resilience import (AdmissionDeadline, DeadlineExceeded, OverQuota,
                          QueryCancelled, QueryPreempted, QueueFull,
                          ServeRejected, deadline as _deadline,
                          env_bool, env_float, env_int, error_kind)
from ..resilience import invariants as _invariants
from ..resilience.classify import InvariantViolation
from ..utils.logging import get_logger
from ..utils.tracing import counters, gauge, histograms
from .cache import SharedCompileCache
from . import quarantine as _quarantine

__all__ = ["TenantQuota", "SubmittedQuery", "QueryScheduler",
           "default_scheduler", "set_default_scheduler",
           "shutdown_default_scheduler"]

_log = get_logger("serve.scheduler")

_OUTCOMES = ("submitted", "admitted", "rejected", "over_quota", "shed",
             "quarantined", "completed", "failed", "preempted",
             "cancelled")


@dataclasses.dataclass(frozen=True)
class TenantQuota:
    """Per-tenant limits; ``None`` fields defer to the ``TFT_SERVE_*``
    process defaults at registration time.

    ``weight`` shapes the fair share (a weight-2 tenant completes ~2x
    the queries of a weight-1 tenant under contention); ``max_queue``
    bounds queued submissions (reject beyond); ``max_inflight`` bounds
    concurrently running queries; ``rows_per_sec`` is a token bucket
    over *estimated* rows (burst = one second of budget; a query whose
    estimate exceeds the burst can never pass and is always rejected
    over-quota); ``deadline_s`` is the default per-query deadline.
    """

    weight: float = 1.0
    max_queue: Optional[int] = None
    max_inflight: Optional[int] = None
    rows_per_sec: Optional[float] = None
    deadline_s: Optional[float] = None

    def __post_init__(self):
        if self.weight <= 0:
            raise ValueError(f"weight must be > 0, got {self.weight}")
        if self.max_queue is not None and self.max_queue < 1:
            raise ValueError(
                f"max_queue must be >= 1, got {self.max_queue}")
        if self.max_inflight is not None and self.max_inflight < 1:
            # 0 would accept submissions that no worker may ever pick:
            # an unclassified forever-hang, the exact thing this layer
            # exists to prevent (pause a tenant by closing its client
            # path or rejecting at submit, not by wedging its queue)
            raise ValueError(
                f"max_inflight must be >= 1, got {self.max_inflight}")
        if self.rows_per_sec is not None and self.rows_per_sec <= 0:
            raise ValueError(
                f"rows_per_sec must be > 0 (omit it for unlimited), "
                f"got {self.rows_per_sec}")
        if self.deadline_s is not None and self.deadline_s <= 0:
            raise ValueError(
                f"deadline_s must be > 0, got {self.deadline_s}")


class _TokenBucket:
    """Rows/sec budget: refills continuously, burst = 1s of rate."""

    __slots__ = ("rate", "burst", "tokens", "_t")

    def __init__(self, rate: float):
        self.rate = float(rate)
        self.burst = float(rate)
        self.tokens = self.burst
        self._t = time.monotonic()

    def try_take(self, n: float) -> bool:
        now = time.monotonic()
        self.tokens = min(self.burst, self.tokens +
                          (now - self._t) * self.rate)
        self._t = now
        if n <= self.tokens:
            self.tokens -= n
            return True
        return False


class SubmittedQuery:
    """A query accepted onto a tenant queue: a future over its forcing.

    ``result(timeout)`` blocks until the scheduler completes the query,
    returning the forced frame — or raising the classified error
    (``DeadlineExceeded``, ``AdmissionDeadline``, ``QueryCancelled``,
    or whatever the execution raised). ``state`` is one of ``queued`` /
    ``running`` / ``done`` / ``failed`` / ``shed`` (admission) /
    ``rejected`` (never ran: scheduler shut down) / ``cancelled``.
    A preempted query goes back to ``queued`` with its checkpoint
    (``preemptions`` counts how often) — preemption is not a terminal
    state; the future resolves when the resumed run finishes.
    """

    __slots__ = ("query_id", "tenant", "est_rows", "est_bytes",
                 "est_stream_bytes", "deadline_at", "submitted_at",
                 "started_at", "finished_at", "state", "preemptions",
                 "fingerprint",
                 "_thunk", "_event", "_result", "_error", "_scope",
                 "_checkpoint", "_cancel_requested")

    def __init__(self, query_id: str, tenant: str, thunk: Callable[[], Any],
                 est_rows: Optional[float], est_bytes: Optional[int],
                 deadline_at: Optional[float],
                 est_stream_bytes: Optional[int] = None):
        self.query_id = query_id
        self.tenant = tenant
        self.est_rows = est_rows
        self.est_bytes = est_bytes
        # the streaming working set (~one block of the frame): what the
        # spill-capable ledger actually has to hold at once
        self.est_stream_bytes = est_stream_bytes
        self.deadline_at = deadline_at  # monotonic, or None
        self.submitted_at = time.monotonic()
        self.started_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.state = "queued"
        self.preemptions = 0
        # plan-fingerprint of the FULL query (frame + fetches), set at
        # submit: the poison-query quarantine's streak key
        self.fingerprint: Optional[str] = None
        self._thunk = thunk
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        # preemption plumbing: the live scope while running, the parked
        # checkpoint between a preempt and its resume, and the
        # cancel-before-start flag (docs/serving.md)
        self._scope = None
        self._checkpoint = None
        self._cancel_requested = False

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    def result(self, timeout: Optional[float] = None):
        if not self._event.wait(timeout):
            raise TimeoutError(
                f"query {self.query_id} not finished within {timeout}s "
                f"(state={self.state})")
        if self._error is not None:
            raise self._error
        return self._result

    def _complete(self, result: Any = None,
                  error: Optional[BaseException] = None) -> None:
        if self._event.is_set():
            return  # exactly one terminal state, even under races
        cp, self._checkpoint = self._checkpoint, None
        if cp is not None:
            cp.free()  # no terminal state keeps parked buffers alive
        self._scope = None
        self.finished_at = time.monotonic()
        self._result = result
        self._error = error
        if error is None:
            self.state = "done"
        elif isinstance(error, QueryCancelled):
            self.state = "cancelled"
        elif isinstance(error, AdmissionDeadline):
            self.state = "shed"
        elif isinstance(error, ServeRejected):
            self.state = "rejected"
        else:
            self.state = "failed"
        self._event.set()

    def __repr__(self):
        return (f"SubmittedQuery({self.query_id}, tenant={self.tenant!r}, "
                f"state={self.state})")


class _Tenant:
    __slots__ = ("name", "weight", "max_queue", "max_inflight", "bucket",
                 "deadline_s", "queue", "inflight", "vpass", "counts")

    def __init__(self, name: str, quota: TenantQuota):
        self.name = name
        self.weight = quota.weight
        self.max_queue = (quota.max_queue if quota.max_queue is not None
                          else env_int("TFT_SERVE_QUEUE_DEPTH", 64))
        self.max_inflight = (quota.max_inflight
                             if quota.max_inflight is not None
                             else env_int("TFT_SERVE_INFLIGHT", 2))
        self.bucket = (_TokenBucket(quota.rows_per_sec)
                       if quota.rows_per_sec is not None else None)
        self.deadline_s = (quota.deadline_s if quota.deadline_s is not None
                           else env_float("TFT_SERVE_DEADLINE_S", None))
        self.queue: "deque[SubmittedQuery]" = deque()
        self.inflight = 0
        self.vpass = 0.0
        self.counts: Dict[str, int] = {k: 0 for k in _OUTCOMES}


def _estimate(frame) -> Tuple[Optional[float], Optional[int]]:
    """Best-effort (rows, bytes) of a frame through the memory
    manager's estimator (``docs/memory.md``): exact when already forced
    (cached blocks), the plan-derived hint for UNFORCED frames — source
    constructors record their actual bytes and ops scale them — and
    ``(None, None)`` only when neither exists. Admission and quotas
    enforce what they can measure; before the memory subsystem that
    meant forced frames only (the PR 5 follow-on this closes)."""
    from .. import memory as _memory
    return _memory.frame_estimate(frame)


# live schedulers, newest last (serve_report() and the metrics provider
# read the most recent; entries remove themselves on close)
_live_lock = threading.Lock()
_live: List["QueryScheduler"] = []


def live_schedulers() -> List["QueryScheduler"]:
    """Every not-yet-closed scheduler, oldest first (the invariant
    auditors walk all of them — overlapping schedulers each keep their
    own books)."""
    with _live_lock:
        return list(_live)


def live_scheduler() -> Optional["QueryScheduler"]:
    with _live_lock:
        return _live[-1] if _live else None


class QueryScheduler:
    """See the module docstring. Use as a context manager or call
    :meth:`close` — the scheduler installs process-wide hooks (slot
    pool, computation interner, metrics provider) that must be
    uninstalled."""

    def __init__(self, quotas: Optional[Mapping[str, TenantQuota]] = None,
                 workers: Optional[int] = None,
                 slots: Optional[int] = None,
                 admission: bool = True,
                 shared_cache: Optional[bool] = None,
                 preemption: Optional[bool] = None,
                 name: str = "serve"):
        self.name = name
        self._cond = threading.Condition()
        self._tenants: Dict[str, _Tenant] = {}
        # every live (queued or running) query by id: cancel() and the
        # priority preemptor need to find them; entries leave on any
        # terminal state
        self._queries: Dict[str, SubmittedQuery] = {}
        self._vtime = 0.0
        self._qid = itertools.count(1)
        self._open = True
        # a lost worker's scheduler: stops picking work and orphans
        # parked queries, but close() (full teardown, thread joins)
        # still runs later from another thread — see mark_lost()
        self._dying = False
        self._admission = admission
        self._preemption = (preemption if preemption is not None
                            else env_bool("TFT_SERVE_PREEMPT", True))
        self.workers = (workers if workers is not None
                        else env_int("TFT_SERVE_WORKERS", 2))
        if self.workers < 0:
            raise ValueError(f"workers must be >= 0, got {self.workers}")
        n_slots = (slots if slots is not None
                   else env_int("TFT_SERVE_SLOTS",
                                max(1, self.workers)
                                * _pipeline.pipeline_depth()))
        self.slot_pool = _pipeline.SlotPool(max(1, n_slots))
        if isinstance(shared_cache, SharedCompileCache):
            # an explicit cache INSTANCE: the serving fabric hands every
            # worker the same one, so structurally-identical computations
            # compile once per fleet, not once per worker
            self.compile_cache = shared_cache
        else:
            use_cache = (shared_cache if shared_cache is not None
                         else env_bool("TFT_SERVE_SHARED_CACHE", True))
            self.compile_cache = SharedCompileCache() if use_cache else None
        # set by the serving fabric: worker_id tags this scheduler's
        # flight records; on_worker_fault(self) fires when a running
        # query's park was caused by the `worker` fault site
        self.worker_id: Optional[str] = None
        self.on_worker_fault = None
        for tname, quota in (quotas or {}).items():
            self._tenants[tname] = _Tenant(tname, quota)
        self._threads: List[threading.Thread] = []
        self._install()
        for i in range(self.workers):
            t = threading.Thread(target=self._worker,
                                 name=f"tft-{name}-worker-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        _log.info("QueryScheduler %r: %d worker(s), %d pipeline slot(s), "
                  "shared compile cache %s", name, self.workers,
                  self.slot_pool.slots,
                  "on" if self.compile_cache else "off")

    # -- lifecycle ---------------------------------------------------------
    def _install(self) -> None:
        self._prev_pool = _pipeline.install_slot_pool(self.slot_pool)
        # pin the exact bound method installed: close() restores the
        # previous hook only if it still owns the slot (overlapping
        # schedulers closed out of LIFO order must not resurrect a dead
        # scheduler's pool/interner over a live one's)
        self._interner_fn = None
        self._prev_interner = None
        if self.compile_cache is not None:
            self._interner_fn = self.compile_cache.intern
            self._prev_interner = _executor.set_computation_interner(
                self._interner_fn)
        from . import stats as _stats
        _stats.register_scheduler_metrics(self)
        with _live_lock:
            _live.append(self)

    def __enter__(self) -> "QueryScheduler":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def mark_lost(self) -> None:
        """Flag this scheduler as dying WITHOUT joining its threads.

        The serving fabric's worker-fault hook runs on the victim's own
        worker thread — a full :meth:`close` there would self-join.
        This flips the kill switch synchronously instead: workers stop
        picking queries, new submits are refused, and a parked query's
        requeue takes the orphan path (a classified rejection the
        fabric reads as *migrating*, not failed). A later :meth:`close`
        from another thread still runs the full teardown."""
        with self._cond:
            self._dying = True
            self._cond.notify_all()

    def close(self, timeout: float = 30.0) -> None:
        """Stop accepting, fail still-queued queries with a classified
        rejection, wait for running queries, uninstall the hooks.
        Idempotent."""
        with self._cond:
            if not self._open:
                return
            self._open = False
            orphans: List[SubmittedQuery] = []
            for t in self._tenants.values():
                while t.queue:
                    q = t.queue.popleft()
                    self._queries.pop(q.query_id, None)
                    t.counts["rejected"] += 1
                    counters.inc("serve.rejected")
                    orphans.append(q)
            self._cond.notify_all()
        for q in orphans:
            q._complete(error=ServeRejected(
                f"scheduler {self.name!r} shut down before query "
                f"{q.query_id} ran"))
        for t in self._threads:
            t.join(timeout=timeout)
        # quiesce-point audit while the hooks are still installed: every
        # query accounted for, every slot lease returned. Guarded to
        # our own pool — an out-of-order close under a NEWER scheduler
        # must not read that scheduler's live leases as our leak.
        if _invariants.enabled() and \
                _pipeline.current_slot_pool() is self.slot_pool:
            _invariants.audit("scheduler.close")
        # hook teardown, out-of-order safe: restore the previous hook
        # only while still the installed owner; otherwise unlink this
        # scheduler from the restore chain (any live scheduler whose
        # "previous" is ours must now skip to OUR previous), so a dead
        # scheduler's pool/interner can never be resurrected later
        with _live_lock:
            others = [s for s in _live if s is not self]
        for s in others:
            if s._prev_pool is self.slot_pool:
                s._prev_pool = self._prev_pool
            if self._interner_fn is not None and \
                    s._prev_interner is self._interner_fn:
                s._prev_interner = self._prev_interner
        if _pipeline.current_slot_pool() is self.slot_pool:
            _pipeline.install_slot_pool(self._prev_pool)
        else:
            _log.warning(
                "scheduler %r closed out of order: a newer scheduler "
                "owns the engine hooks; unlinked this one from its "
                "restore chain", self.name)
        if self._interner_fn is not None and \
                _executor.current_computation_interner() \
                is self._interner_fn:
            _executor.set_computation_interner(self._prev_interner)
        from . import stats as _stats
        _stats.unregister_scheduler_metrics(self)
        with _live_lock:
            if self in _live:
                _live.remove(self)
        _log.info("QueryScheduler %r closed", self.name)

    # -- tenants -----------------------------------------------------------
    def register_tenant(self, name: str,
                        quota: Optional[TenantQuota] = None) -> None:
        """Register (or re-quota) a tenant explicitly; submitting to an
        unknown tenant auto-registers it with default quotas.
        Re-quotaing an ACTIVE tenant keeps its queue, in-flight
        accounting, fairness pass, and stats — only the limits change."""
        with self._cond:
            fresh = _Tenant(name, quota or TenantQuota())
            t = self._tenants.get(name)
            if t is None:
                self._tenants[name] = fresh
            else:
                t.weight = fresh.weight
                t.max_queue = fresh.max_queue
                t.max_inflight = fresh.max_inflight
                t.deadline_s = fresh.deadline_s
                # an idempotent re-quota must not refill the rows/sec
                # budget: keep the live bucket at an unchanged rate,
                # and carry spent tokens into a changed one
                if fresh.bucket is None:
                    t.bucket = None
                elif t.bucket is None or \
                        t.bucket.rate != fresh.bucket.rate:
                    if t.bucket is not None:
                        t.bucket.try_take(0.0)  # apply the lazy refill
                        fresh.bucket.tokens = min(t.bucket.tokens,
                                                  fresh.bucket.burst)
                    t.bucket = fresh.bucket
            self._cond.notify_all()  # eligibility may have widened

    def _tenant(self, name: str) -> _Tenant:
        t = self._tenants.get(name)
        if t is None:
            t = self._tenants[name] = _Tenant(name, TenantQuota())
        return t

    def tenants(self) -> List[str]:
        with self._cond:
            return sorted(self._tenants)

    # -- submission --------------------------------------------------------
    def submit(self, frame, fetches=None, *, tenant: str = "default",
               deadline: Optional[float] = None,
               est_rows: Optional[float] = None,
               est_bytes: Optional[int] = None,
               query_id: Optional[str] = None,
               _checkpoint=None) -> SubmittedQuery:
        """Queue one query: force ``frame`` (after applying ``fetches``
        via ``map_blocks`` when given) under the tenant's quotas.

        Raises :class:`~..resilience.QueueFull` (bounded queue) or
        :class:`~..resilience.OverQuota` (rows/sec budget) — both
        classified, both *before* any work happens. Returns a
        :class:`SubmittedQuery` future otherwise.

        ``query_id`` / ``_checkpoint`` are the serving fabric's
        re-dispatch hooks (``serve/fabric.py``): a query migrated off a
        lost worker re-submits under its ORIGINAL id carrying its
        persisted checkpoint, so ``tft.why(query_id)`` shows one causal
        chain across workers and the resume re-dispatches only the
        blocks the dead worker never finished.
        """
        if fetches is None:
            def thunk(frame=frame):
                frame.blocks()
                return frame
        else:
            def thunk(frame=frame, fetches=fetches):
                out = frame.map_blocks(fetches)
                out.blocks()
                return out
        if est_rows is None or est_bytes is None:
            rows_guess, bytes_guess = _estimate(frame)
            est_rows = est_rows if est_rows is not None else rows_guess
            est_bytes = est_bytes if est_bytes is not None else bytes_guess
        # fingerprint the FULL query (frame + fetches) for the poison
        # quarantine's streak key; a chain with no usable identity
        # (fp None) is simply never quarantined
        fp: Optional[str] = None
        try:
            from ..plan import adaptive as _adaptive
            fp_frame = frame if fetches is None else \
                frame.map_blocks(fetches)
            got = _adaptive.query_fingerprint(fp_frame)
            if got is not None:
                fp = got[0]
        except Exception as e:
            _log.debug("query fingerprint failed at submit: %s", e)
        with self._cond:
            if not self._open or self._dying:
                raise RuntimeError(
                    f"scheduler {self.name!r} is closed")
            t = self._tenant(tenant)
            if query_id is None and _checkpoint is None:
                # a fabric re-dispatch (original id / checkpoint in
                # hand) is a MIGRATION, not a fresh submission: it must
                # not fast-reject mid-flight
                try:
                    _quarantine.check(fp)
                except _quarantine.QueryQuarantined:
                    t.counts["quarantined"] += 1
                    gauge("serve.queue_depth", self._queued_locked())
                    raise
            if len(t.queue) >= t.max_queue:
                t.counts["rejected"] += 1
                counters.inc("serve.rejected")
                _flight.record("serve.reject", tenant=tenant,
                               queued=len(t.queue),
                               max_queue=t.max_queue)
                raise QueueFull(
                    f"tenant {tenant!r} queue is full "
                    f"({t.max_queue} queued); retry later (classified "
                    f"'rejected', transient)")
            if t.bucket is not None and est_rows:
                if not t.bucket.try_take(est_rows):
                    t.counts["over_quota"] += 1
                    counters.inc("serve.over_quota")
                    _flight.record("serve.over_quota", tenant=tenant,
                                   est_rows=est_rows,
                                   rate=t.bucket.rate,
                                   tokens=t.bucket.tokens)
                    raise OverQuota(
                        f"tenant {tenant!r} rows/sec budget exhausted "
                        f"({t.bucket.rate:g} rows/s, query estimated "
                        f"{est_rows:g} rows); retry later (classified "
                        f"'over_quota', transient)")
            dl = deadline if deadline is not None else t.deadline_s
            est_stream = None
            if est_bytes:
                parts = max(1, getattr(frame, "num_partitions", 1) or 1)
                est_stream = max(1, int(est_bytes / parts))
            q = SubmittedQuery(
                query_id or f"{self.name}-q{next(self._qid)}", tenant,
                thunk, est_rows, est_bytes,
                time.monotonic() + dl if dl is not None else None,
                est_stream_bytes=est_stream)
            q.fingerprint = fp
            if _checkpoint is not None:
                q._checkpoint = _checkpoint
            was_empty = not t.queue
            t.queue.append(q)
            self._queries[q.query_id] = q
            if was_empty:
                # re-activation: an idle tenant must not cash in the
                # passes it never used (stride scheduling)
                t.vpass = max(t.vpass, self._vtime)
            t.counts["submitted"] += 1
            counters.inc("serve.submitted")
            gauge("serve.queue_depth", self._queued_locked())
            self._maybe_preempt_locked(t, arriving_query=q.query_id)
            self._cond.notify()
        return q

    # -- preemption & cancellation -----------------------------------------
    def _maybe_preempt_locked(self, arriving: _Tenant,
                              arriving_query: Optional[str] = None
                              ) -> None:
        """Priority preemption on arrival (``docs/serving.md``): when a
        higher-weight tenant submits and every execution slot is busy,
        the lowest-weight running query that has run for at least
        ``TFT_PREEMPT_AFTER_MS`` is asked to park at its next block
        boundary. Called with the scheduler lock held."""
        if not self._preemption or not self._open:
            return
        # busy-ness is the INFLIGHT count, not the scoped-running list
        # below: a worker stuck in the HBM admission wait has no scope
        # yet but is every bit as busy — and this early return keeps
        # the uncontended submit path O(tenants), not O(live queries)
        if self._inflight_locked() < max(1, self.workers):
            return  # a free worker will pick the arrival up anyway
        # capture (query, scope) pairs: _complete/_requeue null
        # q._scope outside this lock, and dereferencing it twice could
        # hit None mid-way — requesting preempt on a captured scope
        # whose query just finished is a harmless no-op instead
        running = [(q, sc) for q in self._queries.values()
                   for sc in (q._scope,)
                   if q.state == "running" and sc is not None
                   and not sc.preempt_requested
                   and not sc.cancel_requested]
        after_s = env_float("TFT_PREEMPT_AFTER_MS", 100.0) / 1000.0
        now = time.monotonic()
        victims = [(q, sc) for q, sc in running
                   if self._tenants[q.tenant].weight < arriving.weight
                   and q.started_at is not None
                   and now - q.started_at >= max(after_s, 0.0)]
        if not victims:
            return
        victim, vscope = min(victims, key=lambda p: (
            self._tenants[p[0].tenant].weight, p[0].started_at))
        vscope.request_preempt(
            f"preempted by tenant {arriving.name!r} "
            f"(weight {arriving.weight:g} > "
            f"{self._tenants[victim.tenant].weight:g})")
        counters.inc("serve.preempt_requests")
        _flight.record("serve.preempt", query=victim.query_id,
                       victim_tenant=victim.tenant,
                       victim_weight=self._tenants[victim.tenant].weight,
                       arriving=arriving.name,
                       arriving_query=arriving_query,
                       arriving_weight=arriving.weight,
                       workers=max(1, self.workers),
                       after_ms=env_float("TFT_PREEMPT_AFTER_MS", 100.0))
        # no add_event here: this runs on the SUBMITTER's thread, whose
        # active trace (if any) is not the victim's — the victim-side
        # park records the request (with its reason naming the
        # preemptor) into the right query's trace at the boundary
        _log.info("query %s (tenant %r, weight %g) asked to preempt for "
                  "arriving tenant %r (weight %g)", victim.query_id,
                  victim.tenant, self._tenants[victim.tenant].weight,
                  arriving.name, arriving.weight)

    def cancel(self, query_id: str) -> bool:
        """Cancel a query by id. A queued query never runs (its future
        fails with a classified :class:`~..resilience.QueryCancelled`
        immediately); a running one stops at its next block boundary
        and frees any checkpoint. Returns False when the query is
        unknown or already terminal — a second ``cancel`` of the same
        query is a no-op, not an error."""
        with self._cond:
            q = self._queries.get(query_id)
            if q is None or q.done():
                return False
            t = self._tenants.get(q.tenant)
            queued = t is not None and q in t.queue
            if queued:
                t.queue.remove(q)
                self._queries.pop(query_id, None)
                t.counts["cancelled"] += 1
                gauge("serve.queue_depth", self._queued_locked())
            else:
                # between queue-pop and run, or running: the flag stops
                # it before the thunk / at the next block boundary
                q._cancel_requested = True
                sc = q._scope
                if sc is not None:
                    sc.request_cancel(f"cancel({query_id})")
            self._cond.notify_all()
        counters.inc("serve.cancel_requests")
        _flight.record("serve.cancel", query=query_id, tenant=q.tenant,
                       state="queued" if queued else "running")
        # like the preempt request above, the victim-side boundary
        # records the `cancel` event into the victim's own trace
        if queued:
            counters.inc("serve.cancelled")
            q._complete(error=QueryCancelled(
                f"query {query_id} (tenant {q.tenant!r}) cancelled "
                f"while queued; it never ran"))
        return True

    def query(self, query_id: str) -> Optional[SubmittedQuery]:
        """The live (queued or running) query with this id, else None."""
        with self._cond:
            return self._queries.get(query_id)

    # -- selection ---------------------------------------------------------
    def _queued_locked(self) -> int:
        return sum(len(t.queue) for t in self._tenants.values())

    def _inflight_locked(self) -> int:
        return sum(t.inflight for t in self._tenants.values())

    def _pick_locked(self) -> Optional[_Tenant]:
        best = None
        for t in self._tenants.values():
            if not t.queue or t.inflight >= t.max_inflight:
                continue
            if best is None or t.vpass < best.vpass:
                best = t
        return best

    def _next(self, block: bool) -> Optional[SubmittedQuery]:
        with self._cond:
            while True:
                if not self._open or self._dying:
                    return None
                t = self._pick_locked()
                if t is not None:
                    q = t.queue.popleft()
                    self._vtime = t.vpass
                    t.vpass += 1.0 / t.weight
                    t.inflight += 1
                    gauge("serve.queue_depth", self._queued_locked())
                    gauge("serve.inflight", self._inflight_locked())
                    return q
                if not block:
                    return None
                self._cond.wait(timeout=0.1)

    # -- execution ---------------------------------------------------------
    def _worker(self) -> None:
        while True:
            q = self._next(block=True)
            if q is None:
                return
            self._execute(q)

    def step(self) -> bool:
        """Manually execute ONE scheduling decision (pick the fairest
        eligible query and run it to completion on the calling thread).
        Returns False when nothing is eligible. The deterministic drive
        for ``workers=0`` schedulers (tests, benchmarks, embedding)."""
        q = self._next(block=False)
        if q is None:
            return False
        self._execute(q)
        return True

    def _execute(self, q: SubmittedQuery) -> None:
        # the cost capture rides INSIDE the flight scope: the sentinel's
        # regression record correlates to the same query id, and a
        # preempted run that requeues discards its partial capture at
        # this context exit (partial runs must not calibrate baselines)
        with _flight.scope(q.query_id, worker=self.worker_id):
            with _baseline.capture(q.query_id, tenant=q.tenant):
                self._execute_scoped(q)

    def _execute_scoped(self, q: SubmittedQuery) -> None:
        # everything inside runs under the flight-recorder correlation
        # scope: decisions made deep in the forcing (a mesh shrink, a
        # re-plan, a ledger spill) land in the ring tagged with this
        # query id — with TFT_TRACE off (docs/observability.md)
        t = self._tenants[q.tenant]
        q.started_at = time.monotonic()
        q.state = "running"
        queue_wait = q.started_at - q.submitted_at
        try:
            if q._cancel_requested:
                # cancelled in the gap between queue-pop and run: it
                # must not execute (the caller was told it would not)
                raise QueryCancelled(
                    f"query {q.query_id} (tenant {q.tenant!r}) "
                    f"cancelled before it started")
            # shed what already missed its deadline while queued: running
            # it would spend capacity on a result nobody can use
            if q.deadline_at is not None and \
                    time.monotonic() >= q.deadline_at:
                raise DeadlineExceeded(
                    f"query {q.query_id} (tenant {q.tenant!r}) spent "
                    f"{queue_wait:.3f}s queued and missed its deadline "
                    f"before starting")
            self._admit(q)
            with self._cond:
                t.counts["admitted"] += 1
            counters.inc("serve.admitted")
            _flight.record("serve.start", tenant=q.tenant,
                           queue_wait_s=round(queue_wait, 6),
                           est_bytes=q.est_bytes,
                           resumed=q.preemptions > 0)
            remaining = None
            if q.deadline_at is not None:
                remaining = max(q.deadline_at - time.monotonic(), 1e-3)
            # the preemption token: cancel()/priority arrivals flip it;
            # the pipelined engine polls it at block boundaries. A
            # resumed query carries its parked checkpoint back in.
            # Publication and the cancel-flag seed happen under the
            # scheduler lock: a cancel() that landed during the
            # admission wait (flag set, no scope yet) must reach this
            # scope, and one arriving after sees q._scope non-None —
            # no window where a cancel can vanish.
            scope = _preempt.PreemptionScope(q.query_id,
                                             checkpoint=q._checkpoint)
            with self._cond:
                q._scope = scope
                if q._cancel_requested:
                    scope.request_cancel(f"cancel({q.query_id})")
            with _obs.query_trace("serve", tenant=q.tenant,
                                  query=q.query_id) as tr:
                if tr is not None:
                    tr.add("sched_start", name=q.query_id,
                           tenant=q.tenant, queue_wait_s=queue_wait,
                           resumed=q.preemptions > 0)
                with _deadline(remaining), _preempt.activate(scope):
                    try:
                        result = q._thunk()
                    except Exception as e:
                        # a device_lost error means the mesh shrank
                        # underneath the query (parallel/elastic.py):
                        # one re-attempt on the surviving devices
                        # instead of failing the future
                        if error_kind(e) != "device_lost":
                            raise
                        counters.inc("serve.device_lost_retries")
                        _obs.add_event("device_lost_retry",
                                       name=q.query_id, tenant=q.tenant)
                        _log.warning(
                            "query %s (tenant %r) hit a device loss "
                            "(%s); retrying once on the shrunken mesh",
                            q.query_id, q.tenant, e)
                        result = q._thunk()
        except QueryPreempted:
            self._requeue_preempted(q, t)
            return
        except BaseException as e:
            if isinstance(e, (KeyboardInterrupt, SystemExit)):
                self._finish(q, t, error=e)
                raise
            self._finish(q, t, error=e)
            return
        # fingerprint the result chain while the frame is in hand — the
        # sentinel keys this completion's cost vector by it in _finish
        _baseline.note_result_frame(result)
        self._finish(q, t, result=result)

    def _requeue_preempted(self, q: SubmittedQuery, t: _Tenant) -> None:
        """A preempted query parks, it does not fail: carry the
        checkpoint, put it back at the FRONT of its tenant's queue (it
        already waited its turn), and let the fair scheduler resume it.
        Its deadline keeps running while parked."""
        scope = q._scope
        if scope is not None and scope.checkpoint is not None:
            q._checkpoint = scope.checkpoint
        worker_fault = scope is not None and \
            getattr(scope, "worker_fault", False)
        q._scope = None
        if worker_fault and self.on_worker_fault is not None:
            # the `worker` fault site fired during this query: tell the
            # fabric BEFORE taking our lock (its handler may close this
            # scheduler, which takes _cond — holding it here would
            # deadlock); the query still requeues below so the fabric
            # finds it in the dead worker's queue and re-places it
            try:
                self.on_worker_fault(self)
            except Exception as e:
                _log.warning("on_worker_fault hook failed: %s", e)
        with self._cond:
            if not self._open or self._dying:
                # lost the race with close()/mark_lost(): fail like any
                # orphan — the fabric reads this rejection from a dead
                # worker as "migrating" and re-dispatches elsewhere
                self._queries.pop(q.query_id, None)
                t.inflight -= 1
                t.counts["rejected"] += 1
                gauge("serve.inflight", self._inflight_locked())
                self._cond.notify_all()
                q._complete(error=ServeRejected(
                    f"scheduler {self.name!r} shut down while query "
                    f"{q.query_id} was parked"))
                counters.inc("serve.rejected")
                return
            q.preemptions += 1
            q.state = "queued"
            q.started_at = None
            t.inflight -= 1
            t.counts["preempted"] += 1
            t.queue.appendleft(q)
            gauge("serve.queue_depth", self._queued_locked())
            gauge("serve.inflight", self._inflight_locked())
            self._cond.notify_all()
        counters.inc("serve.preemptions")
        cp = q._checkpoint
        _flight.record("serve.requeue", query=q.query_id,
                       tenant=q.tenant, preemptions=q.preemptions,
                       parked_blocks=cp.parked_blocks
                       if cp is not None else 0)
        _log.info("query %s (tenant %r) parked (%d block(s) "
                  "checkpointed); re-queued at the front", q.query_id,
                  q.tenant, cp.parked_blocks if cp is not None else 0)

    def _admit(self, q: SubmittedQuery) -> None:
        """HBM admission: wait (bounded) for headroom, preempting a
        checkpointable whale to clear it, else shed.

        Against a real backend watermark the whole-frame estimate is
        the enforceable footprint (pre-spill semantics). When the
        headroom comes from the spill-capable memory ledger instead
        (``docs/memory.md`` — no backend stats, ``TFT_MEM_LIMIT_BYTES``
        set), admission is **spill-aware**: the engine streams the
        frame block-by-block and the ledger spills or splits the rest,
        so the footprint compared is the streaming working set
        (~one block) — a larger-than-budget query is executable
        out-of-core and must not be shed for its total size.

        Preempt-aware (the PR 13 follow-on, ``docs/serving.md``):
        before falling through to shed, the wait asks the
        largest-footprint running query to PARK at its next block
        boundary — its checkpoint moves completed block outputs
        off-device through the memory ledger, clearing headroom the
        arrival can use, and the whale resumes later from where it
        parked. An arrival is rejected only when preemption could not
        free enough within the wait budget.
        """
        if not self._admission or not q.est_bytes:
            return
        need = q.est_bytes
        if q.est_stream_bytes is not None \
                and _obs_device.watermark() is None:
            from .. import memory as _memory
            mgr = _memory.active()
            if mgr is not None and mgr.spill_enabled:
                need = min(need, q.est_stream_bytes)
        budget = env_float("TFT_SERVE_ADMISSION_WAIT_S", 5.0)
        poll = env_float("TFT_SERVE_ADMISSION_POLL_S", 0.02)
        give_up_at = time.monotonic() + max(budget, 0.0)
        if q.deadline_at is not None:
            give_up_at = min(give_up_at, q.deadline_at)
        waited_since: Optional[float] = None
        waited = False
        preempt_tried = False
        while True:
            if q._cancel_requested:
                # don't spend the admission-wait budget on a query
                # whose caller was already told it will not run
                raise QueryCancelled(
                    f"query {q.query_id} (tenant {q.tenant!r}) "
                    f"cancelled while waiting for admission")
            headroom = self._hbm_headroom()
            if headroom is None or need <= headroom:
                if waited:
                    counters.inc("serve.admission_waits")
                _flight.record(
                    "serve.admit", tenant=q.tenant, est_bytes=need,
                    headroom=headroom,
                    waited_s=round(time.monotonic() - waited_since, 6)
                    if waited_since is not None else 0.0)
                return
            if not preempt_tried:
                # one preemption attempt per admission: ask the whale
                # to park, then keep polling while it checkpoints
                preempt_tried = True
                self._preempt_for_admission(q, need,
                                            shortfall=need - headroom)
            if time.monotonic() >= give_up_at:
                _flight.record("serve.shed", tenant=q.tenant,
                               est_bytes=need, headroom=headroom,
                               budget_s=budget,
                               preempt_tried=preempt_tried)
                raise AdmissionDeadline(
                    f"query {q.query_id} (tenant {q.tenant!r}) shed: "
                    f"estimated footprint {need} B exceeds HBM "
                    f"headroom {headroom} B and admission could not "
                    f"clear within its budget — preemption could not "
                    f"free enough (classified 'deadline_admission')")
            if not waited:
                waited = True
                waited_since = time.monotonic()
                _obs.add_event("sched_admission_wait", name=q.query_id,
                               tenant=q.tenant, est_bytes=need)
            time.sleep(max(poll, 0.001))

    def _preempt_for_admission(self, q: SubmittedQuery, need: int,
                               shortfall: int) -> bool:
        """Ask the largest-footprint checkpointable running query to
        park so ``q`` can admit (``docs/serving.md``). Returns whether
        a preempt was requested; the park itself happens at the
        victim's next block boundary. A victim whose known footprint
        cannot plausibly cover ``shortfall`` is left alone — parking
        it would cost a checkpoint + resume for zero headroom gain."""
        if not self._preemption:
            return False
        with self._cond:
            victims = [(v, sc) for v in self._queries.values()
                       for sc in (v._scope,)
                       if v is not q and v.state == "running"
                       and sc is not None
                       and not sc.preempt_requested
                       and not sc.cancel_requested]
        if not victims:
            return False
        victim, vscope = max(
            victims, key=lambda p: (p[0].est_bytes or 0,
                                    p[0].started_at or 0.0))
        if victim.est_bytes is not None \
                and victim.est_bytes < max(shortfall, 0):
            _log.info(
                "admission for query %s: not preempting — the largest "
                "running query %s (est %d B) cannot cover the %d B "
                "shortfall; the arrival will shed at its wait budget",
                q.query_id, victim.query_id, victim.est_bytes,
                shortfall)
            return False
        vscope.request_preempt(
            f"parked to clear {need} B of admission headroom for "
            f"query {q.query_id} (tenant {q.tenant!r})")
        counters.inc("serve.admission_preempts")
        _obs.add_event("sched_admission_preempt", name=q.query_id,
                       tenant=q.tenant, victim=victim.query_id,
                       victim_bytes=victim.est_bytes or 0)
        _flight.record("serve.admission_preempt", query=q.query_id,
                       tenant=q.tenant, victim=victim.query_id,
                       victim_bytes=victim.est_bytes or 0, need=need,
                       shortfall=shortfall)
        _log.info("admission for query %s (tenant %r, %d B) preempting "
                  "query %s (est %s B): parking the whale instead of "
                  "shedding the arrival", q.query_id, q.tenant, need,
                  victim.query_id, victim.est_bytes)
        return True

    def _hbm_headroom(self) -> Optional[int]:
        """Bytes below the high-water mark, or None when unenforceable.

        The backend watermark (live allocator stats) is authoritative
        when the backend reports one; otherwise the memory manager's
        ledger stands in (``docs/memory.md``) — its budget minus
        in-flight reservations, with spillable resident bytes counted
        as reclaimable — which makes admission enforceable even on
        backends without memory stats (``TFT_MEM_LIMIT_BYTES`` on CPU).
        None only when neither exists."""
        wm = _obs_device.watermark()
        frac = env_float("TFT_SERVE_HBM_FRACTION", 0.9)
        if wm is None:
            from .. import memory as _memory
            mgr = _memory.active()
            if mgr is not None:
                return mgr.headroom(frac)
            return None
        limit = env_int("TFT_SERVE_HBM_LIMIT_BYTES", 0) \
            or wm.get("limit_bytes") or 0
        if limit <= 0:
            return None
        return int(limit * frac) - int(wm["live_bytes"])

    def _finish(self, q: SubmittedQuery, t: _Tenant,
                result: Any = None,
                error: Optional[BaseException] = None) -> None:
        # cross-cutting audit at the query-finish quiesce point
        # (resilience/invariants.py): in strict (chaos/test) mode a
        # violation fails THIS query, classified 'invariant', instead
        # of resolving its future green over books just proven wrong
        if _invariants.enabled():
            try:
                _invariants.audit("serve.finish")
            except InvariantViolation as iv:
                if error is None:
                    result, error = None, iv
        q._complete(result=result, error=error)
        from ..memory import persist as _persist
        if _persist.enabled():
            # a TERMINAL state is the only point the durable checkpoint
            # dies: close()'s orphan path keeps the file so the fabric
            # can resume the query in another worker (serve/fabric.py)
            _persist.discard_checkpoint(q.query_id)
        dur = q.finished_at - q.submitted_at  # end-to-end serving latency
        if error is None:
            outcome = "ok"
            key = "completed"
        else:
            outcome = error_kind(error)
            if isinstance(error, QueryCancelled):
                key = "cancelled"
            elif isinstance(error, AdmissionDeadline):
                key = "shed"
            elif isinstance(error, ServeRejected):
                key = "rejected"
            else:
                key = "failed"
        # tenant bookkeeping BEFORE the observability tail: the future
        # resolved at q._complete above, so a caller holding result()
        # may read snapshot() at any moment — the counts must already
        # reflect this completion (the baseline finalize below walks
        # counter registries and can take milliseconds under load)
        with self._cond:
            self._queries.pop(q.query_id, None)
            t.inflight -= 1
            t.counts[key] += 1
            gauge("serve.inflight", self._inflight_locked())
            self._cond.notify_all()
        # poison-query streaks: only PERMANENT failures count — the
        # resilience layer's own outcomes (transient retries, OOM
        # splits, preempts, sheds) are not evidence the plan is poison
        if key == "completed":
            _quarantine.note_success(q.fingerprint)
        elif key == "failed" and outcome == "permanent":
            _quarantine.note_failure(q.fingerprint, error)
        histograms.observe("query_latency_seconds", dur, op="serve",
                           tenant=t.name, outcome=outcome)
        counters.inc(f"serve.{key}")
        # close out the cost capture: fold the vector into the plan
        # fingerprint's baseline and run the regression check (only
        # "completed" calibrates; the capture contextvar is still live
        # because _finish runs inside _execute's capture scope). The
        # baseline gets EXECUTION latency, not end-to-end: queue wait
        # under a burst is a scheduling condition the SLO layer already
        # watches — folding it in makes every congested query look like
        # a plan regression
        run_s = dur if q.started_at is None \
            else q.finished_at - q.started_at
        vec = _baseline.finalize(latency_s=run_s, outcome=key)
        _flight.record("serve.finish", query=q.query_id, tenant=t.name,
                       outcome=key, latency_s=round(dur, 6))
        # durable query history: fold this completion — cost vector,
        # flight-decision digest, worker stamp — into the on-disk
        # archive, AFTER the serve.finish record so the digest carries
        # the terminal decision too (best-effort; never raises)
        _history.record_finish(
            q.query_id, tenant=t.name, fingerprint=q.fingerprint,
            outcome=key,
            error=(f"{type(error).__name__}: {error}"
                   if error is not None else None),
            error_kind=outcome if error is not None else None,
            worker=self.worker_id, cost=vec,
            queued_s=(q.started_at - q.submitted_at
                      if q.started_at is not None else None),
            run_s=run_s, total_s=dur,
            est_rows=q.est_rows, est_bytes=q.est_bytes,
            preemptions=q.preemptions, source="serve",
            decisions=_flight.for_query(q.query_id))
        # SLO burn-rate callbacks evaluate off the completion path
        # (throttled per tenant; docs/observability.md)
        _slo.note_completion(t.name)

    def request_park_all(self, reason: str = "drain") -> int:
        """Ask every RUNNING query to park at its next block boundary
        (their checkpoints write through to the durable tier when it is
        on). The fabric's crash/drain primitive: called before
        :meth:`close` so a simulated worker death leaves resumable
        checkpoints instead of completed queries. Returns the number of
        queries asked."""
        with self._cond:
            scopes = [sc for q in self._queries.values()
                      for sc in (q._scope,)
                      if q.state == "running" and sc is not None]
        for sc in scopes:
            sc.request_preempt(reason)
        if scopes:
            _log.info("scheduler %r: park requested for %d running "
                      "query(ies) (%s)", self.name, len(scopes), reason)
        return len(scopes)

    # -- introspection -----------------------------------------------------
    def audit_invariants(self, point: str = "inline") -> List[str]:
        """No-orphan accounting, one consistent read (the built-in
        scheduler auditor, ``resilience/invariants.py``): every live
        query is queued, running, or mid-``_finish``; queue lengths,
        inflight counts, and the live-query table all agree; nothing
        has gone negative. At a ``*.close`` point the table must be
        EMPTY — anything left is an orphan whose future never
        resolves."""
        out: List[str] = []
        with self._cond:
            queued = running = finishing = 0
            for q in self._queries.values():
                if q.state == "queued":
                    queued += 1
                elif q.state == "running":
                    running += 1
                else:
                    # terminal state, not yet popped: the short window
                    # inside a concurrent _finish — balanced below, an
                    # orphan only at close
                    finishing += 1
            in_queues = sum(len(t.queue) for t in self._tenants.values())
            inflight = sum(t.inflight for t in self._tenants.values())
            if queued != in_queues:
                out.append(
                    f"scheduler {self.name!r}: {queued} queued query(ies)"
                    f" vs {in_queues} queue entries")
            if inflight != running + finishing:
                out.append(
                    f"scheduler {self.name!r}: inflight accounting "
                    f"{inflight} != {running} running + {finishing} "
                    f"finishing")
            for t in self._tenants.values():
                if t.inflight < 0:
                    out.append(f"tenant {t.name!r}: negative inflight "
                               f"({t.inflight})")
                for k, v in t.counts.items():
                    if v < 0:
                        out.append(f"tenant {t.name!r}: negative "
                                   f"{k!r} count ({v})")
            if point.endswith(".close") and not self._open \
                    and self._queries:
                out.append(
                    f"scheduler {self.name!r}: {len(self._queries)} "
                    f"query(ies) orphaned at {point}: "
                    f"{sorted(self._queries)[:5]}")
        return out

    def snapshot(self) -> Dict[str, Dict[str, Any]]:
        """Per-tenant live state + outcome totals (one consistent read)."""
        with self._cond:
            out: Dict[str, Dict[str, Any]] = {}
            for name, t in sorted(self._tenants.items()):
                out[name] = {"weight": t.weight,
                             "queued": len(t.queue),
                             "inflight": t.inflight,
                             "max_queue": t.max_queue,
                             "max_inflight": t.max_inflight,
                             **t.counts}
            return out

    def __repr__(self):
        state = "open" if self._open else "closed"
        return (f"QueryScheduler({self.name!r}, {state}, "
                f"workers={self.workers}, tenants={len(self._tenants)})")


# ---------------------------------------------------------------------------
# process-default scheduler (the `tft.submit()` backend)
# ---------------------------------------------------------------------------

_default_lock = threading.Lock()
_default: Optional[QueryScheduler] = None


def default_scheduler() -> QueryScheduler:
    """The lazily-created process default (env-configured); created on
    first :func:`~..api.submit`."""
    global _default
    if _default is None or not _default._open:
        with _default_lock:
            if _default is None or not _default._open:
                _default = QueryScheduler(name="serve")
    return _default


def set_default_scheduler(s: Optional[QueryScheduler]
                          ) -> Optional[QueryScheduler]:
    """Swap the process default (does NOT close the old one); returns
    the previous."""
    global _default
    with _default_lock:
        prev, _default = _default, s
    return prev


def shutdown_default_scheduler() -> None:
    global _default
    with _default_lock:
        s, _default = _default, None
    if s is not None:
        s.close()
