"""Server-side observability: ``ServerStats`` / ``serve_report()`` and
the per-tenant Prometheus series.

Three surfaces over one source of truth (the scheduler's per-tenant
snapshot plus the always-on ``query_latency_seconds`` histogram, which
the scheduler observes with a ``tenant`` label at every completion):

- :class:`ServerStats` — programmatic: per-tenant outcome totals, live
  queue depth / in-flight, shared-compile-cache totals, and per-tenant
  latency percentiles read back out of the histogram buckets;
- :func:`serve_report` — the human-readable table (the serving twin of
  ``frame.explain()``);
- a metrics provider registered with
  :func:`~..observability.metrics.register_metrics_provider` while a
  scheduler is live, so ``GET /metrics`` (``TFT_METRICS_PORT``) exposes
  ``tft_serve_queue_depth`` / ``tft_serve_inflight`` gauges and
  ``tft_serve_queries_total{tenant=...,outcome=...}`` counters that are
  read LIVE at scrape time (queue depth between scrapes is invisible to
  the flat counter registry). Per-tenant p99 comes from the
  ``tft_query_latency_seconds{op="serve",tenant="..."}`` histogram the
  endpoint already renders.
"""

from __future__ import annotations

from typing import Dict, List, Optional

from ..observability import baseline as _baseline
from ..observability import slo as _slo
from ..observability.metrics import (_escape_label as _escape,
                                     register_metrics_provider,
                                     unregister_metrics_provider)
from ..utils import tracing
from ..utils.logging import get_logger

__all__ = ["ServerStats", "serve_report"]

_log = get_logger("serve.stats")


def _latency_series(tenant: Optional[str] = None) -> List[dict]:
    """The ``query_latency_seconds`` histogram snapshots for op=serve
    (optionally one tenant), any outcome."""
    out = []
    for (family, labels), h in tracing.histograms.snapshot().items():
        if family != "query_latency_seconds":
            continue
        lab = dict(labels)
        if lab.get("op") != "serve":
            continue
        if tenant is not None and lab.get("tenant") != tenant:
            continue
        out.append(h)
    return out


def latency_quantile(q: float, tenant: Optional[str] = None
                     ) -> Optional[float]:
    """Estimate of the ``q`` quantile (e.g. 0.99) of serving latency
    from the histogram buckets: the bucket edge at/above the quantile
    rank — the standard Prometheus ``histogram_quantile``
    discretization, which also means a quantile landing in the ``+Inf``
    bucket CLAMPS to the largest finite bucket edge (the true tail is
    at least that; the buckets cannot say how much more). None before
    any observation."""
    series = _latency_series(tenant)
    total = sum(h["count"] for h in series)
    if total == 0:
        return None
    # merge the (identically-bucketed) series
    les = series[0]["les"]
    counts = [0] * len(les)
    for h in series:
        for i, c in enumerate(h["counts"]):
            counts[i] += c
    finite = [le for le in les if le != float("inf")]
    rank = q * total
    cum = 0
    for le, c in zip(les, counts):
        cum += c
        if cum >= rank:
            return le if le != float("inf") else (
                finite[-1] if finite else None)
    return None


class ServerStats:
    """A read-only view over a :class:`~.scheduler.QueryScheduler`.

    Note on latency: ``p50``/``p99`` read the PROCESS-GLOBAL
    ``query_latency_seconds`` histogram (filtered to ``op="serve"`` and
    the tenant label) — Prometheus-style cumulative series that are
    never reset, so they cover every scheduler this process has run,
    not only this one. For a fresh window, reset the registry
    (``utils.tracing.histograms.reset()``) or, on a real deployment,
    compute windowed quantiles from the scraped series (``rate()`` over
    buckets), which is the intended path.
    """

    def __init__(self, scheduler):
        self._scheduler = scheduler

    def snapshot(self) -> Dict[str, dict]:
        return self._scheduler.snapshot()

    def compile_cache(self) -> Optional[dict]:
        cc = self._scheduler.compile_cache
        return cc.stats() if cc is not None else None

    def p99(self, tenant: Optional[str] = None) -> Optional[float]:
        return latency_quantile(0.99, tenant)

    def p50(self, tenant: Optional[str] = None) -> Optional[float]:
        return latency_quantile(0.50, tenant)

    def render(self) -> str:
        snap = self.snapshot()
        sched = self._scheduler
        lines = [
            f"serve {sched.name!r} · {len(snap)} tenant(s) · "
            f"{sched.workers} worker(s) · "
            f"{sched.slot_pool.slots} pipeline slot(s)",
        ]
        if not snap:
            lines.append("  (no tenants yet — nothing submitted)")
        for name, s in snap.items():
            p99 = self.p99(name)
            p99_s = f"{p99 * 1000:.1f} ms" if p99 is not None else "n/a"
            lines.append(
                f"  tenant {name!r}: weight {s['weight']:g} · "
                f"{s['queued']} queued / {s['inflight']} in flight "
                f"(caps {s['max_queue']}/{s['max_inflight']})")
            lines.append(
                f"    {s['submitted']} submitted · {s['admitted']} "
                f"admitted · {s['completed']} completed · "
                f"{s['failed']} failed · p99 {p99_s}")
            lines.append(
                f"    rejected {s['rejected']} (queue full) · "
                f"over_quota {s['over_quota']} · shed {s['shed']} "
                f"(admission)")
            if s.get("quarantined"):
                lines.append(
                    f"    quarantined {s['quarantined']} (poison-plan "
                    f"fast-reject; tft.unquarantine() lifts)")
            slo = _slo.slo_status(name).get(name)
            if slo is not None and slo["total"]:
                lines.append(
                    f"    SLO {slo['objective_ms']:g} ms @ "
                    f"{slo['target']:.4g}: compliance "
                    f"{slo['compliance']:.4%} · burn "
                    f"{slo['burn_rate']:.2f}x · budget left "
                    f"{slo['budget_remaining']:.1%} "
                    f"({slo['good']} good / {slo['bad']} bad)")
            if s.get("preempted") or s.get("cancelled"):
                lines.append(
                    f"    preempted {s.get('preempted', 0)} "
                    f"(checkpointed + resumed) · "
                    f"cancelled {s.get('cancelled', 0)}")
            regs = [r for r in _baseline.regressions()
                    if r.get("tenant") == name]
            if regs:
                last = regs[-1]
                lines.append(
                    f"    PERF: {len(regs)} regression(s) flagged · "
                    f"last: plan {last['fingerprint'][:16]}… "
                    f"{last['component']} {last['baseline']:g} -> "
                    f"{last['observed']:g} ({last['sigma']:g} sigma; "
                    f"tft.regressions())")
        cc = self.compile_cache()
        if cc is not None:
            lines.append(
                f"  shared compile cache: {cc['entries']} entries · "
                f"{cc['hits']} hit(s) / {cc['misses']} miss(es) · "
                f"{cc['uncacheable']} uncacheable")
        try:
            from . import quarantine as _quarantine
            q = _quarantine.status()
        except Exception:  # noqa: BLE001 - report must render regardless
            q = {"active": {}}
        for fp, info in sorted((q.get("active") or {}).items()):
            lines.append(
                f"  QUARANTINE: plan {fp[:20]}… — {info['failures']} "
                f"permanent failure(s), lifts in "
                f"{info['ttl_remaining_s']:.0f}s "
                f"(tft.unquarantine() lifts now)")
        return "\n".join(lines)


def serve_report(scheduler=None) -> str:
    """The serving layer's ``explain()``: per-tenant queues, in-flight,
    outcome totals, p99, and shared-compile-cache behavior. Uses the
    most recently created live scheduler when none is given. When a
    :class:`~.fabric.ServeFabric` is live, its placement table
    (worker epochs, lease state, tenant placement, durable-tier
    footprint) is appended."""
    if scheduler is None:
        from .scheduler import live_scheduler
        scheduler = live_scheduler()
    if scheduler is None:
        return ("(no scheduler running — create a serve.QueryScheduler "
                "or submit a query through tft.submit())")
    out = ServerStats(scheduler).render()
    try:
        from ..observability import history as _history
        hs = _history.stats()
    except Exception:  # noqa: BLE001 - report must render regardless
        hs = {"enabled": False}
    if hs.get("enabled"):
        out += (f"\n  history: {hs['segments']} segment(s) "
                f"({hs['bytes']} B) at {hs['dir']} · "
                f"{hs['records_written']} record(s) this process · "
                f"tft.history() / tft.why(qid)")
        if hs.get("unclean"):
            out += ("\n  UNCLEAN SHUTDOWN detected on startup — "
                    "tft.postmortem() has the triage report")
    try:
        from .fabric import live_fabric
        fab = live_fabric()
    except Exception:  # noqa: BLE001 - report must render regardless
        fab = None
    if fab is not None:
        out = out + "\n\n" + fab.placement_report()
    return out


# ---------------------------------------------------------------------------
# Prometheus provider (live gauges; registered per live scheduler)
# ---------------------------------------------------------------------------

def _provider_lines(scheduler) -> List[str]:
    snap = scheduler.snapshot()
    lines = [
        "# HELP tft_serve_queue_depth Queries queued per tenant "
        "(live at scrape time).",
        "# TYPE tft_serve_queue_depth gauge",
    ]
    for name, s in snap.items():
        lines.append(f'tft_serve_queue_depth{{tenant="{_escape(name)}"}} '
                     f'{s["queued"]}')
    lines.append("# HELP tft_serve_inflight Queries executing per tenant "
                 "(live at scrape time).")
    lines.append("# TYPE tft_serve_inflight gauge")
    for name, s in snap.items():
        lines.append(f'tft_serve_inflight{{tenant="{_escape(name)}"}} '
                     f'{s["inflight"]}')
    from .scheduler import _OUTCOMES  # single source for outcome keys

    lines.append("# HELP tft_serve_queries_total Scheduler outcomes per "
                 "tenant (submitted/admitted/rejected/over_quota/shed/"
                 "completed/failed/preempted/cancelled).")
    lines.append("# TYPE tft_serve_queries_total counter")
    for name, s in snap.items():
        for key in _OUTCOMES:
            lines.append(
                f'tft_serve_queries_total{{tenant="{_escape(name)}",'
                f'outcome="{key}"}} {s[key]}')
    snap_c = tracing.counters.snapshot()
    for fam, key, help_s in (
            ("tft_serve_preemptions_total", "serve.preemptions",
             "Running queries parked at a block boundary with a "
             "resumable checkpoint (docs/serving.md)."),
            ("tft_serve_cancelled_total", "serve.cancelled",
             "Queries cancelled (queued or at a block boundary)."),
            ("tft_serve_resumed_blocks_total", "pipeline.resumed_blocks",
             "Blocks restored from preemption checkpoints instead of "
             "re-dispatched."),
            ("tft_serve_checkpoint_discards_total",
             "serve.checkpoint_discards",
             "Preemption checkpoints discarded on resume (plan changed "
             "under the query; re-ran from scratch)."),
            ("tft_serve_quarantines_total", "serve.quarantines",
             "Plan fingerprints quarantined after a permanent-failure "
             "streak (poison-query fast-reject)."),
            ("tft_serve_quarantine_rejects_total", "serve.quarantined",
             "Submissions fast-rejected because their plan fingerprint "
             "is quarantined.")):
        lines.append(f"# HELP {fam} {help_s}")
        lines.append(f"# TYPE {fam} counter")
        lines.append(f"{fam} {snap_c.get(key, 0)}")
    cc = scheduler.compile_cache
    if cc is not None:
        st = cc.stats()
        lines.append("# HELP tft_serve_compile_cache_total Shared "
                     "cross-query compile cache interning outcomes.")
        lines.append("# TYPE tft_serve_compile_cache_total counter")
        for key in ("hits", "misses", "uncacheable"):
            lines.append(
                f'tft_serve_compile_cache_total{{result="{key}"}} '
                f'{st[key]}')
        lines.append("# HELP tft_serve_compile_cache_entries Canonical "
                     "computations currently interned.")
        lines.append("# TYPE tft_serve_compile_cache_entries gauge")
        lines.append(f"tft_serve_compile_cache_entries {st['entries']}")
    return lines


def register_scheduler_metrics(scheduler) -> None:
    register_metrics_provider(f"serve:{scheduler.name}:{id(scheduler)}",
                              lambda: _provider_lines(scheduler))


def unregister_scheduler_metrics(scheduler) -> None:
    unregister_metrics_provider(f"serve:{scheduler.name}:{id(scheduler)}")
