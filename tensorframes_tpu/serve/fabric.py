"""Multi-host serving fabric: tenants sharded across worker processes.

Every robustness guarantee the serving stack earned — preempt/resume
checkpoints, the plan-fingerprint result cache, per-tenant SLO burn
rates — lived inside ONE process and died with it. The fabric is the
coordinator that makes the process group itself a managed, failure-prone
resource (the TF-HPC lesson, ``PAPERS.md``): N workers, each a full
:class:`~.scheduler.QueryScheduler`, with the coordinator owning
placement, health, and recovery.

**Workers.** Each :class:`FabricWorker` wraps one scheduler named
``<fabric>-w<i>e<epoch>`` (the epoch increments across restarts). In a
real multi-process deployment each worker is a process bootstrapped by
``parallel/cluster.py`` (:func:`~..parallel.cluster.process_identity`
names it); this module's in-process workers simulate the process
boundary honestly: a "crash" parks running queries (checkpoints write
through to the durable tier — ``memory/persist.py``), closes the
scheduler (queued queries orphan), and invalidates the in-memory result
cache — exactly the state a dead process loses. What survives is
exactly what disk holds. All workers share ONE
:class:`~.cache.SharedCompileCache`: its keys are structural
(process-independent), so the fleet compiles each computation once.

**Placement.** Tenants map to workers least-loaded-first at first
submit (``fabric.place``). The balancing signal is the PR 15 SLO burn
rate: a tenant burning its error budget faster than ``TFT_FABRIC_BURN_FACTOR``
times its hottest peer (and above 1.0 — actually over budget) is
re-placed onto the least-loaded other worker (``fabric.rebalance``,
cooldown-limited). Every placement decision lands in the flight ring
under ``query="tenant:<name>"``, so ``tft.why("tenant:hot")``
reconstructs a tenant's placement history.

**Failure matrix.** Worker health is a heartbeat/lease: every
:meth:`ServeFabric.tick` beats each worker; ``TFT_FABRIC_MISSED_HB``
consecutive misses declare it lost (``fabric.worker_lost``, classified
``worker_lost`` — checked like ``device_lost``: never retried against
the corpse, recovery is structural). Then:

- **queued queries** of the dead worker re-place onto survivors and
  re-run cold — they never started, nothing to resume;
- **running queries** resume from their PERSISTED checkpoint on a
  survivor (``fabric.resume_dispatch`` under the query's ORIGINAL id,
  so ``tft.why(qid)`` is one causal chain across workers). The resume
  re-dispatches only the blocks the dead worker never finished,
  bit-identical; any tag/cursor mismatch discards to a cold re-run —
  never wrong, never dropped;
- **tenants** of the dead worker re-place (``fabric.replace``).

The deterministic ``worker`` fault site (``TFT_FAULTS=worker:1``)
drives this whole path, mirroring ``device:1``: a running query's next
block boundary parks it and flags the crash
(``engine/preempt.py``); an idle worker consumes the fault at its next
heartbeat.

**Rolling restarts.** :meth:`restart_worker` drains (park → persist),
closes, bumps the epoch, starts a fresh scheduler, and health-gates
re-admission with a probe query (the PR 13 ``probe_device`` pattern: a
tiny known-answer query through the worker's own scheduler —
``fabric.admit`` / ``fabric.admit_probe_failed``).
:meth:`rolling_restart` does that worker-by-worker; in-flight queries
migrate, and the result cache comes back warm from the durable tier
(``plan.result_cache_warm_hits`` — zero dispatches).

``TFT_FABRIC=0`` degrades to one worker with pass-through submits —
bit-identical to the single-process path. See ``docs/serving.md``.
"""

from __future__ import annotations

import itertools
import shutil
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ..memory import persist as _persist
from ..observability import flight as _flight
from ..observability import history as _history
from ..resilience import (ServeRejected, WorkerLost, env_bool, env_float,
                          env_int)
from ..resilience import faults as _faults
from ..utils.logging import get_logger
from ..utils.tracing import counters
from .cache import SharedCompileCache
from .scheduler import QueryScheduler, TenantQuota

__all__ = ["ServeFabric", "FabricWorker", "FabricQuery", "live_fabric",
           "fabric_enabled"]

_log = get_logger("serve.fabric")

_live_lock = threading.Lock()
_live: List["ServeFabric"] = []


def fabric_enabled() -> bool:
    """``TFT_FABRIC`` gate (default on). ``TFT_FABRIC=0`` collapses the
    fabric to one pass-through worker — bit-identical to a plain
    :class:`~.scheduler.QueryScheduler`."""
    return env_bool("TFT_FABRIC", True)


def live_fabric() -> Optional["ServeFabric"]:
    """The most recently opened fabric still running, or ``None``
    (``tft.health()``'s fabric section reads this)."""
    with _live_lock:
        for f in reversed(_live):
            if f._open:
                return f
    return None


class FabricWorker:
    """One worker process (simulated in-process; module docstring)."""

    __slots__ = ("index", "epoch", "scheduler", "alive", "lost",
                 "missed", "lease_at", "fault_pending", "started_at")

    def __init__(self, index: int, epoch: int,
                 scheduler: QueryScheduler):
        self.index = index
        self.epoch = epoch
        self.scheduler = scheduler
        self.alive = True
        self.lost = False
        self.missed = 0            # consecutive failed heartbeats
        self.lease_at = time.monotonic()
        self.fault_pending = False  # a crash scheduled for the next tick
        self.started_at = time.monotonic()

    @property
    def worker_id(self) -> str:
        return f"w{self.index}"

    def busy(self) -> bool:
        try:
            snap = self.scheduler.snapshot()
        except Exception:
            return False
        return any(v.get("queued", 0) or v.get("inflight", 0)
                   for v in snap.values())

    def heartbeat(self, allow_fault: bool = True) -> bool:
        """One lease check: True when the worker answered. An idle
        worker consumes a pending ``worker`` fault here — but only
        while the WHOLE fabric is idle (``allow_fault``): when any
        query is running somewhere, its own block boundary consumes
        the fault (``engine/preempt.py``) so ``TFT_FAULTS=worker:1``
        deterministically kills the worker doing the work."""
        if not self.alive or not self.scheduler._open:
            return False
        if allow_fault and _faults.active("worker") \
                and not self.fault_pending and not self.busy():
            try:
                _faults.check("worker")
            except _faults.InjectedFault:
                self.fault_pending = True  # the next tick executes it
        return True

    def __repr__(self):
        state = ("lost" if self.lost
                 else "alive" if self.alive else "down")
        return (f"FabricWorker({self.worker_id}, epoch={self.epoch}, "
                f"{state})")


class FabricQuery:
    """The fabric-level future over a query: survives its worker.

    Wraps the current :class:`~.scheduler.SubmittedQuery` attempt; a
    worker death swaps a new attempt in (same ``query_id``, persisted
    checkpoint carried over) without the caller noticing anything but
    latency. Terminal errors (the query's own failure, a policy
    rejection from a LIVE worker) pass through; a rejection from a dead
    or restarting worker means *migrating*, not failed.
    """

    __slots__ = ("query_id", "tenant", "attempts", "worker_index",
                 "_fabric", "_frame", "_fetches", "_kwargs", "_current",
                 "_event", "_result", "_error", "_lock")

    def __init__(self, fabric: "ServeFabric", query_id: str, tenant: str,
                 frame, fetches, kwargs: Dict[str, Any]):
        self.query_id = query_id
        self.tenant = tenant
        self.attempts = 0
        self.worker_index: Optional[int] = None
        self._fabric = fabric
        self._frame = frame
        self._fetches = fetches
        self._kwargs = kwargs
        self._current = None  # the live SubmittedQuery attempt
        self._event = threading.Event()
        self._result: Any = None
        self._error: Optional[BaseException] = None
        self._lock = threading.Lock()

    def done(self) -> bool:
        return self._event.is_set()

    @property
    def error(self) -> Optional[BaseException]:
        return self._error

    @property
    def state(self) -> str:
        if self._event.is_set():
            return "failed" if self._error is not None else "done"
        sq = self._current
        return sq.state if sq is not None else "placing"

    def _complete(self, result: Any = None,
                  error: Optional[BaseException] = None) -> None:
        with self._lock:
            if self._event.is_set():
                return
            self._result = result
            self._error = error
            self._event.set()

    def result(self, timeout: Optional[float] = None):
        """Block for the query's terminal result across any number of
        worker deaths and migrations. Drives the fabric's tick while
        waiting, so monitorless fabrics (tests) still make progress."""
        deadline = (time.monotonic() + timeout
                    if timeout is not None else None)
        while not self._event.is_set():
            self._fabric.tick()
            if self._event.is_set():
                break
            sq = self._current
            if sq is not None:
                sq._event.wait(0.05)
            else:
                time.sleep(0.01)
            self._fabric._settle(self)
            if deadline is not None and not self._event.is_set() \
                    and time.monotonic() >= deadline:
                raise TimeoutError(
                    f"fabric query {self.query_id} not finished within "
                    f"{timeout}s (state={self.state}, "
                    f"attempts={self.attempts})")
        if self._error is not None:
            raise self._error
        return self._result

    def __repr__(self):
        return (f"FabricQuery({self.query_id}, tenant={self.tenant!r}, "
                f"state={self.state}, attempts={self.attempts})")


class ServeFabric:
    """The coordinator (module docstring). Context-manage or
    :meth:`close`."""

    def __init__(self,
                 workers: Optional[int] = None,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 worker_threads: int = 1,
                 persist_dir: Optional[str] = None,
                 monitor: Optional[bool] = None,
                 probe: bool = True,
                 heartbeat_ms: Optional[float] = None,
                 missed_hb: Optional[int] = None,
                 name: str = "fab"):
        self.name = name
        self.enabled = fabric_enabled()
        n = (workers if workers is not None
             else env_int("TFT_FABRIC_WORKERS", 2))
        if not self.enabled:
            n = 1  # TFT_FABRIC=0: one pass-through worker
        if n < 1:
            raise ValueError(f"workers must be >= 1, got {n}")
        self.heartbeat_ms = (heartbeat_ms if heartbeat_ms is not None
                             else env_float("TFT_HEARTBEAT_MS", 100.0))
        self.missed_hb = (missed_hb if missed_hb is not None
                          else env_int("TFT_FABRIC_MISSED_HB", 3))
        self.rebalance_ticks = env_int("TFT_FABRIC_REBALANCE_TICKS", 5)
        self.burn_factor = env_float("TFT_FABRIC_BURN_FACTOR", 2.0)
        self.burn_min_queries = env_int("TFT_FABRIC_BURN_MIN_QUERIES", 3)
        self.max_redispatch = env_int("TFT_FABRIC_MAX_REDISPATCH", 3)
        self._quotas = dict(quotas or {})
        self._worker_threads = max(1, int(worker_threads))
        self._lock = threading.RLock()
        self._open = True
        self._qn = itertools.count(1)
        self._tick_no = 0
        self._queries: Dict[str, FabricQuery] = {}
        self._placement: Dict[str, int] = {}
        # tenant -> (tick of last burn-move, query total at that move)
        self._last_rebalance: Dict[str, Tuple[int, int]] = {}
        # the fleet-level compile cache: one instance, every worker —
        # structural keys make it safe across (simulated) processes
        self.compile_cache = SharedCompileCache()
        # durable tier: an explicit dir, the ambient TFT_PERSIST_DIR,
        # or a private tmpdir the fabric owns and removes on close
        self._persist_prev: Any = False  # False = never configured
        self._own_persist_dir: Optional[str] = None
        if persist_dir is not None:
            self._persist_prev = _persist.configure(persist_dir)
        elif not _persist.enabled():
            d = tempfile.mkdtemp(prefix=f"tft-{name}-persist-")
            self._own_persist_dir = d
            self._persist_prev = _persist.configure(d)
        self._workers: List[FabricWorker] = []
        for i in range(n):
            w = FabricWorker(i, 0, self._new_scheduler(i, 0))
            self._workers.append(w)
        with _live_lock:
            _live.append(self)
        if self.enabled and probe:
            for w in self._workers:
                self._probe_worker(w)
        self._monitor: Optional[threading.Thread] = None
        run_monitor = (monitor if monitor is not None else self.enabled)
        if run_monitor:
            self._monitor = threading.Thread(
                target=self._monitor_loop,
                name=f"tft-{name}-monitor", daemon=True)
            self._monitor.start()
        _log.info("ServeFabric %r: %d worker(s), heartbeat %.0fms, "
                  "lease %d missed beats, persist %s%s", name, n,
                  self.heartbeat_ms, self.missed_hb,
                  _persist.root() or "off",
                  "" if self.enabled else " (TFT_FABRIC=0 pass-through)")

    # -- lifecycle ---------------------------------------------------------
    def _new_scheduler(self, index: int, epoch: int) -> QueryScheduler:
        s = QueryScheduler(quotas=dict(self._quotas),
                           workers=self._worker_threads,
                           shared_cache=self.compile_cache,
                           name=f"{self.name}-w{index}e{epoch}")
        s.worker_id = f"w{index}"
        s.on_worker_fault = self._on_worker_fault
        return s

    def __enter__(self) -> "ServeFabric":
        return self

    def __exit__(self, *exc) -> bool:
        self.close()
        return False

    def close(self, timeout: float = 30.0) -> None:
        """Close every worker, stop the monitor, restore the persist
        override, remove a fabric-owned persistence dir. Idempotent."""
        with self._lock:
            if not self._open:
                return
            self._open = False
            workers = list(self._workers)
        for w in workers:
            w.alive = False
            try:
                w.scheduler.close(timeout=timeout)
            except Exception as e:
                _log.warning("closing worker %s failed: %s",
                             w.worker_id, e)
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
        with _live_lock:
            if self in _live:
                _live.remove(self)
        if self._persist_prev is not False:
            _persist.configure(self._persist_prev)
        if self._own_persist_dir is not None:
            shutil.rmtree(self._own_persist_dir, ignore_errors=True)
        _log.info("ServeFabric %r closed", self.name)

    def _monitor_loop(self) -> None:
        interval = max(self.heartbeat_ms, 1.0) / 1000.0
        while self._open:
            try:
                self.tick()
            except Exception as e:
                _log.error("fabric %r tick failed: %s", self.name, e)
            time.sleep(interval)

    # -- placement ---------------------------------------------------------
    def _live_workers_locked(self,
                             exclude: Optional[int] = None
                             ) -> List[FabricWorker]:
        return [w for w in self._workers
                if w.alive and not w.lost and not w.fault_pending
                and w.scheduler._open and not w.scheduler._dying
                and w.index != exclude]

    def _tenant_count_locked(self, index: int) -> int:
        return sum(1 for i in self._placement.values() if i == index)

    def _pick_worker_locked(self,
                            exclude: Optional[int] = None
                            ) -> Optional[FabricWorker]:
        live = self._live_workers_locked(exclude)
        if not live:
            return None
        return min(live, key=lambda w: (
            self._tenant_count_locked(w.index), w.index))

    def _place_locked(self, tenant: str) -> Optional[int]:
        idx = self._placement.get(tenant)
        if idx is not None:
            w = self._workers[idx]
            if w.alive and not w.lost and not w.fault_pending \
                    and w.scheduler._open and not w.scheduler._dying:
                return idx
        w = self._pick_worker_locked()
        if w is None:
            return None
        self._placement[tenant] = w.index
        _flight.record("fabric.place", query=f"tenant:{tenant}",
                       tenant=tenant, worker=w.worker_id,
                       tenants_on_worker=self._tenant_count_locked(
                           w.index))
        _log.info("fabric %r: tenant %r placed on %s", self.name,
                  tenant, w.worker_id)
        return w.index

    # -- submit ------------------------------------------------------------
    def submit(self, frame, fetches=None, *, tenant: str = "default",
               **kwargs) -> FabricQuery:
        """Queue one query on the tenant's placed worker. Raises the
        scheduler's classified policy rejections (queue full / over
        quota) directly — those are the tenant's quota talking, not a
        worker failure. Returns a :class:`FabricQuery`."""
        with self._lock:
            if not self._open:
                raise RuntimeError(f"fabric {self.name!r} is closed")
            qid = f"{self.name}-q{next(self._qn)}"
            fq = FabricQuery(self, qid, tenant, frame, fetches,
                             dict(kwargs))
            idx = self._place_locked(tenant)
            if idx is None:
                raise WorkerLost(
                    f"fabric {self.name!r} has no live workers to "
                    f"place tenant {tenant!r} on")
            w = self._workers[idx]
            self._queries[qid] = fq
        try:
            sq = w.scheduler.submit(frame, fetches, tenant=tenant,
                                    query_id=qid, **kwargs)
        except Exception:
            with self._lock:
                self._queries.pop(qid, None)
            raise
        with fq._lock:
            fq._current = sq
            fq.worker_index = w.index
            fq.attempts = 1
        counters.inc("fabric.submitted")
        return fq

    # -- failure handling --------------------------------------------------
    def _on_worker_fault(self, scheduler: QueryScheduler) -> None:
        """Scheduler hook: a running query's park was caused by the
        ``worker`` fault site. Kill the scheduler's intake NOW
        (``mark_lost`` — this thread is the victim's own worker
        thread, so a full close() here would self-join) so the parked
        query orphans instead of resuming on the corpse; the next tick
        executes the rest of the crash."""
        scheduler.mark_lost()
        with self._lock:
            for w in self._workers:
                if w.scheduler is scheduler and w.alive:
                    w.fault_pending = True
                    _log.warning("fabric %r: worker %s hit the "
                                 "`worker` fault site; crash scheduled",
                                 self.name, w.worker_id)
                    return

    def _execute_crash(self, w: FabricWorker) -> None:
        """Kill one worker the way a process dies: running queries are
        already parked (or asked to), the scheduler closes (queued
        queries orphan with rejections the fabric treats as
        *migrating*), and the in-memory result cache dies with it.
        Disk keeps what the durable tier wrote."""
        counters.inc("fabric.worker_crashes")
        _flight.record("fabric.worker_crash", worker=w.worker_id,
                       epoch=w.epoch)
        _log.warning("fabric %r: worker %s crashed (epoch %d)",
                     self.name, w.worker_id, w.epoch)
        try:
            w.scheduler.request_park_all("worker crash")
            w.scheduler.close()
        except Exception as e:
            _log.warning("crashing worker %s: close failed: %s",
                         w.worker_id, e)
        from ..plan import adaptive as _adaptive
        _adaptive.invalidate_results()  # process memory is gone

    def _declare_lost(self, w: FabricWorker) -> None:
        """The lease expired: classify, re-place tenants, re-dispatch
        the dead worker's queries (module docstring failure matrix)."""
        if w.lost:
            return
        w.lost = True
        w.alive = False
        counters.inc("fabric.workers_lost")
        _flight.record("fabric.worker_lost", worker=w.worker_id,
                       epoch=w.epoch, missed=w.missed,
                       classified="worker_lost")
        _log.error("fabric %r: worker %s declared lost after %d missed "
                   "heartbeat(s)", self.name, w.worker_id, w.missed)
        if w.scheduler._open:
            try:
                w.scheduler.request_park_all("worker lost")
                w.scheduler.close()
            except Exception as e:
                _log.warning("closing lost worker %s failed: %s",
                             w.worker_id, e)
        with self._lock:
            moved = [t for t, i in self._placement.items()
                     if i == w.index]
            for t in moved:
                nw = self._pick_worker_locked(exclude=w.index)
                if nw is None:
                    continue
                self._placement[t] = nw.index
                _flight.record("fabric.replace", query=f"tenant:{t}",
                               tenant=t, source=w.worker_id,
                               worker=nw.worker_id,
                               reason="worker_lost")
                _log.info("fabric %r: tenant %r re-placed %s -> %s "
                          "(worker lost)", self.name, t, w.worker_id,
                          nw.worker_id)
            victims = [fq for fq in self._queries.values()
                       if fq.worker_index == w.index
                       and not fq.done()]
        for fq in victims:
            self._redispatch(fq, reason="worker_lost")

    def _redispatch(self, fq: FabricQuery, reason: str) -> None:
        """Move one in-flight query to a survivor: resume from its
        persisted checkpoint when one exists (and matches — the PR 13
        contract discards any drift to a cold re-run), cold otherwise.
        Same query id either way: one causal chain in ``tft.why()``."""
        if fq.done():
            return
        with self._lock:
            prev = (self._workers[fq.worker_index].worker_id
                    if fq.worker_index is not None else None)
            idx = self._place_locked(fq.tenant)
            w = self._workers[idx] if idx is not None else None
        if w is None:
            fq._complete(error=WorkerLost(
                f"query {fq.query_id}: no surviving workers to "
                f"re-dispatch onto"))
            return
        if fq.attempts >= 1 + self.max_redispatch:
            fq._complete(error=WorkerLost(
                f"query {fq.query_id} re-dispatched "
                f"{fq.attempts - 1} time(s) without completing "
                f"(TFT_FABRIC_MAX_REDISPATCH={self.max_redispatch})"))
            return
        cp = (_persist.load_checkpoint(fq.query_id)
              if _persist.enabled() else None)
        try:
            sq = w.scheduler.submit(fq._frame, fq._fetches,
                                    tenant=fq.tenant,
                                    query_id=fq.query_id,
                                    _checkpoint=cp, **fq._kwargs)
        except Exception as e:
            fq._complete(error=e)
            return
        with fq._lock:
            fq._current = sq
            fq.worker_index = w.index
            fq.attempts += 1
        counters.inc("fabric.redispatches")
        _flight.record("fabric.resume_dispatch", query=fq.query_id,
                       tenant=fq.tenant, worker=w.worker_id,
                       reason=reason, attempt=fq.attempts,
                       resumed_blocks=(cp.parked_blocks
                                       if cp is not None else 0),
                       from_checkpoint=cp is not None)
        # durable query history: a dead worker never reaches its own
        # _finish fold, so the coordinator stamps the migration here —
        # the survivor's terminal record stitches onto this one (same
        # query id, worker path A->B) in tft.history()
        _history.record_finish(
            fq.query_id, tenant=fq.tenant, outcome="migrated",
            worker=prev, source="fabric",
            summary=f"re-dispatched to {w.worker_id} ({reason}, "
                    f"attempt #{fq.attempts}, "
                    + (f"{cp.parked_blocks} block(s) from checkpoint"
                       if cp is not None else "cold re-run") + ")",
            decisions=_flight.for_query(fq.query_id))
        _log.info("fabric %r: query %s re-dispatched to %s (%s, "
                  "%s)", self.name, fq.query_id, w.worker_id, reason,
                  f"{cp.parked_blocks} block(s) from checkpoint"
                  if cp is not None else "cold")

    def _settle(self, fq: FabricQuery) -> bool:
        """Fold one attempt's outcome into the fabric future. A
        rejection from a dead/restarting worker is *migrating* (the
        tick re-dispatches); everything else is terminal."""
        if fq.done():
            return True
        sq = fq._current
        if sq is None or not sq.done():
            return False
        if sq._error is None:
            fq._complete(result=sq._result)
            return True
        err = sq._error
        with self._lock:
            w = (self._workers[fq.worker_index]
                 if fq.worker_index is not None else None)
            worker_dead = (w is None or not w.alive or w.lost
                           or not w.scheduler._open
                           or w.scheduler._dying or w.fault_pending)
        if isinstance(err, ServeRejected) and worker_dead:
            return False  # migrating: the dead worker's orphan rejection
        fq._complete(error=err)
        return True

    # -- the heartbeat loop ------------------------------------------------
    def tick(self) -> None:
        """One coordinator pass: execute scheduled crashes, beat every
        lease, declare the expired lost, settle finished queries,
        maybe rebalance. Thread-safe; the monitor calls it on the
        heartbeat interval and ``FabricQuery.result`` drives it too."""
        if not self._open:
            return
        with self._lock:
            crashing = [w for w in self._workers
                        if w.fault_pending and w.alive]
            for w in crashing:
                w.alive = False
                w.fault_pending = False
        for w in crashing:
            self._execute_crash(w)
        lost_now: List[FabricWorker] = []
        with self._lock:
            if not self.enabled:
                pass  # one pass-through worker: no lease to manage
            else:
                idle = not any(w.busy() for w in self._workers
                               if w.alive and not w.lost)
                for w in self._workers:
                    if w.lost:
                        continue
                    if w.heartbeat(allow_fault=idle):
                        w.missed = 0
                        w.lease_at = time.monotonic()
                    else:
                        w.missed += 1
                        _flight.record("fabric.heartbeat_miss",
                                       worker=w.worker_id,
                                       missed=w.missed,
                                       limit=self.missed_hb)
                        if w.missed >= self.missed_hb:
                            lost_now.append(w)
            queries = list(self._queries.values())
        for w in lost_now:
            self._declare_lost(w)
        for fq in queries:
            self._settle(fq)
        with self._lock:
            self._tick_no += 1
            do_rebalance = (self.enabled
                            and self.rebalance_ticks > 0
                            and self._tick_no % self.rebalance_ticks
                            == 0)
        if do_rebalance:
            self._rebalance()

    # -- SLO-burn rebalance ------------------------------------------------
    def _rebalance(self) -> None:
        """Act on the PR 15 burn rates: a tenant over budget AND
        burning ``TFT_FABRIC_BURN_FACTOR``x its hottest peer moves to
        the least-loaded other worker. Edge-triggered per tenant with a
        cooldown so one hot window cannot thrash placement."""
        try:
            from ..observability.slo import slo_status
            statuses = slo_status()
        except Exception as e:
            _log.debug("fabric rebalance: slo_status failed: %s", e)
            return
        with self._lock:
            placed = dict(self._placement)
        burns: Dict[str, float] = {}
        for t, idx in placed.items():
            st = statuses.get(t)
            if not st or st.get("burn_rate") is None:
                continue
            if st.get("total", 0) < self.burn_min_queries:
                continue
            burns[t] = float(st["burn_rate"])
        for t, burn in sorted(burns.items(), key=lambda kv: -kv[1]):
            if burn <= 1.0:
                break  # inside budget: nothing to act on
            peers = [b for pt, b in burns.items() if pt != t]
            peer_max = max(peers) if peers else 0.0
            if peers and burn <= self.burn_factor * peer_max:
                continue
            total = int(statuses[t].get("total", 0))
            with self._lock:
                cooldown = max(2 * self.rebalance_ticks, 1)
                last = self._last_rebalance.get(t)
                if last is not None and (
                        self._tick_no - last[0] < cooldown
                        or total <= last[1]):
                    # burn is a trailing window: without NEW queries
                    # since the last move it is stale evidence, and
                    # acting on it again just ping-pongs the tenant
                    continue
                cur = placed[t]
                nw = self._pick_worker_locked(exclude=cur)
                if nw is None or nw.index == cur:
                    continue
                self._placement[t] = nw.index
                self._last_rebalance[t] = (self._tick_no, total)
                src = self._workers[cur].worker_id
                counters.inc("fabric.rebalances")
                _flight.record("fabric.rebalance",
                               query=f"tenant:{t}", tenant=t,
                               source=src, worker=nw.worker_id,
                               burn_rate=round(burn, 3),
                               peer_max=round(peer_max, 3),
                               factor=self.burn_factor,
                               reason="slo_burn")
                _log.warning(
                    "fabric %r: tenant %r re-placed %s -> %s (burn "
                    "%.2f vs hottest peer %.2f)", self.name, t, src,
                    nw.worker_id, burn, peer_max)
            break  # at most one move per pass: observe, then re-judge

    # -- health-gated admission (the PR 13 probe pattern) ------------------
    def _probe_worker(self, w: FabricWorker,
                      timeout: float = 30.0) -> bool:
        """A tiny known-answer query through the worker's OWN scheduler
        gates admission: a worker that cannot add 1.0 to four floats
        must not be handed tenants."""
        from ..api import frame as _frame
        try:
            f = _frame({"x": np.arange(4.0)}, num_partitions=1)
            sq = w.scheduler.submit(f, lambda x: {"y": x + 1.0},
                                    tenant="_probe")
            out = sq.result(timeout=timeout)
            got = np.asarray(out.blocks()[0].columns["y"])
            if not np.array_equal(got, np.arange(4.0) + 1.0):
                raise RuntimeError(f"probe returned {got!r}")
        except Exception as e:
            counters.inc("fabric.admit_probe_failures")
            _flight.record("fabric.admit_probe_failed",
                           worker=w.worker_id, epoch=w.epoch,
                           error=str(e)[:200])
            _log.error("fabric %r: worker %s failed its admission "
                       "probe: %s", self.name, w.worker_id, e)
            w.alive = False
            return False
        _flight.record("fabric.admit", worker=w.worker_id,
                       epoch=w.epoch)
        return True

    # -- rolling restarts --------------------------------------------------
    def restart_worker(self, index: int, timeout: float = 30.0) -> bool:
        """Drain, kill, and re-admit one worker at the next epoch.
        Running queries park (checkpoints persist) and migrate; the
        in-memory result cache dies with the process and comes back
        warm from the durable tier. Returns True when the fresh worker
        passed its admission probe."""
        with self._lock:
            if not self._open:
                raise RuntimeError(f"fabric {self.name!r} is closed")
            w = self._workers[index]
            w.alive = False
        counters.inc("fabric.worker_restarts")
        _flight.record("fabric.worker_restart", worker=w.worker_id,
                       epoch=w.epoch, next_epoch=w.epoch + 1)
        _log.info("fabric %r: rolling restart of %s (epoch %d -> %d)",
                  self.name, w.worker_id, w.epoch, w.epoch + 1)
        try:
            w.scheduler.request_park_all("rolling restart")
            w.scheduler.close(timeout=timeout)
        except Exception as e:
            _log.warning("restart of %s: close failed: %s",
                         w.worker_id, e)
        from ..plan import adaptive as _adaptive
        _adaptive.invalidate_results()  # the old process's memory
        with self._lock:
            victims = [fq for fq in self._queries.values()
                       if fq.worker_index == index and not fq.done()]
        for fq in victims:
            self._redispatch(fq, reason="restart")
        w.epoch += 1
        w.scheduler = self._new_scheduler(index, w.epoch)
        w.alive = True
        w.lost = False
        w.missed = 0
        w.fault_pending = False
        w.lease_at = time.monotonic()
        w.started_at = time.monotonic()
        ok = self._probe_worker(w, timeout=timeout) \
            if self.enabled else True
        return ok

    def rolling_restart(self, timeout: float = 30.0) -> int:
        """Restart every worker in sequence (the fleet never empties
        with >= 2 workers). Returns how many came back healthy."""
        with self._lock:
            indices = [w.index for w in self._workers if not w.lost]
        ok = 0
        for i in indices:
            if self.restart_worker(i, timeout=timeout):
                ok += 1
            self.tick()
        return ok

    # -- introspection -----------------------------------------------------
    def health_snapshot(self) -> Dict[str, Any]:
        """The ``tft.health()`` fabric section: workers live/lost,
        leases, per-worker tenant counts, durable-tier bytes."""
        now = time.monotonic()
        with self._lock:
            per_worker = []
            for w in self._workers:
                try:
                    snap = w.scheduler.snapshot() \
                        if w.scheduler._open else {}
                except Exception:
                    snap = {}
                per_worker.append({
                    "worker": w.worker_id,
                    "epoch": w.epoch,
                    "alive": w.alive,
                    "lost": w.lost,
                    "missed_heartbeats": w.missed,
                    "lease_age_s": round(now - w.lease_at, 3),
                    "tenants": self._tenant_count_locked(w.index),
                    "queued": sum(v.get("queued", 0)
                                  for v in snap.values()),
                    "inflight": sum(v.get("inflight", 0)
                                    for v in snap.values()),
                })
            placement = {t: f"w{i}"
                         for t, i in sorted(self._placement.items())}
            queries = len(self._queries)
            done = sum(1 for fq in self._queries.values()
                       if fq.done())
        return {
            "running": self._open,
            "enabled": self.enabled,
            "name": self.name,
            "workers": len(per_worker),
            "live": sum(1 for p in per_worker
                        if p["alive"] and not p["lost"]),
            "lost": sum(1 for p in per_worker if p["lost"]),
            "heartbeat_ms": self.heartbeat_ms,
            "missed_hb_limit": self.missed_hb,
            "per_worker": per_worker,
            "placement": placement,
            "queries": {"total": queries, "done": done,
                        "inflight": queries - done},
            "persist": _persist.stats(),
            "history": _history.stats(),
        }

    def audit_invariants(self, point: str = "inline") -> List[str]:
        """Fabric no-orphan accounting (the built-in fabric auditor,
        ``resilience/invariants.py``): every live fabric query is
        placing, attached to a real worker, or done; placements point
        at real workers; nothing is left unresolved once the fabric
        closes (a non-done query after close is a future no tick will
        ever settle)."""
        out: List[str] = []
        with self._lock:
            n = len(self._workers)
            for fq in self._queries.values():
                wi = fq.worker_index
                if wi is not None and not 0 <= wi < n:
                    out.append(f"fabric {self.name!r}: query "
                               f"{fq.query_id} placed on worker index "
                               f"{wi} of {n}")
                if not fq.done() and not self._open:
                    out.append(f"fabric {self.name!r}: query "
                               f"{fq.query_id} ({fq.state}) orphaned "
                               f"at {point} — no tick will settle it")
            for tenant, wi in self._placement.items():
                if not 0 <= wi < n:
                    out.append(f"fabric {self.name!r}: tenant "
                               f"{tenant!r} placed on worker index "
                               f"{wi} of {n}")
        return out

    def placement_report(self) -> str:
        """The ``serve_report()`` placement table."""
        snap = self.health_snapshot()
        lines = [f"fabric {self.name!r}: {snap['live']}/{snap['workers']}"
                 f" worker(s) live, {snap['lost']} lost",
                 f"{'worker':<8} {'epoch':>5} {'state':<6} "
                 f"{'tenants':>7} {'queued':>6} {'inflight':>8}"]
        for p in snap["per_worker"]:
            state = ("lost" if p["lost"]
                     else "live" if p["alive"] else "down")
            lines.append(f"{p['worker']:<8} {p['epoch']:>5} "
                         f"{state:<6} {p['tenants']:>7} "
                         f"{p['queued']:>6} {p['inflight']:>8}")
        if snap["placement"]:
            lines.append("placement: " + ", ".join(
                f"{t}->{w}" for t, w in snap["placement"].items()))
        ps = snap["persist"]
        if ps.get("enabled"):
            lines.append(
                f"persist: {ps['checkpoints']} checkpoint(s) "
                f"({ps['checkpoint_bytes']} B), {ps['results']} "
                f"result(s) ({ps['result_bytes']} B) at {ps['dir']}")
        hs = snap.get("history") or {}
        if hs.get("enabled"):
            lines.append(
                f"history: {hs['segments']} segment(s) "
                f"({hs['bytes']} B) at {hs['dir']}, "
                f"{hs['records_written']} record(s) this process")
        return "\n".join(lines)

    def __repr__(self):
        state = "open" if self._open else "closed"
        return (f"ServeFabric({self.name!r}, {state}, "
                f"workers={len(self._workers)})")
