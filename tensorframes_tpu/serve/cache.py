"""Shared cross-query compile cache: structural interning of Computations.

The engine's jit caches are keyed by the live :class:`~..computation.
Computation` object (weakly, so entries die with the computation). That
is the right bound for one program run — but a server re-traces the same
user workload per submission: the millionth tenant sending ``x + 3``
builds a millionth Computation object, and every one compiles its own
executable. This module closes that gap with *interning*: a Computation
is reduced to a **structural signature** — its input/output specs plus
the jaxpr obtained by tracing with SYMBOLIC leading dimensions (the same
``_sym_avals`` machinery ``Computation.serialize`` uses) and the bytes of
any captured array constants — and the first Computation seen with a
given signature becomes canonical. Later equivalents are swapped for the
canonical object at the executor boundary
(:func:`~..engine.executor.set_computation_interner`), so every
downstream per-Computation cache (jit wrappers, padded variants, native
programs) is shared automatically, with zero changes to the engine's
cache structure.

Symbolic tracing is the correctness load-bearing choice: two programs
that merely coincide at one probe size (``x * x.shape[0]`` at 2 rows vs
``x * 2.0``) produce DIFFERENT jaxprs under a symbolic row count, so they
are never merged; a program that cannot trace symbolically is marked
uncacheable and runs un-interned (counted, never failed).

The cache holds canonical Computations STRONGLY (bounded LRU,
``TFT_SERVE_COMPILE_CACHE`` entries, default 512): keeping the canonical
object alive is exactly what keeps the engine's weak-keyed jit entries
warm across queries.
"""

from __future__ import annotations

import hashlib
import threading
from collections import OrderedDict
from typing import Optional

import numpy as np

from ..observability.events import add_event as _obs_event
from ..resilience import env_int
from ..utils.logging import get_logger
from ..utils.tracing import counters

__all__ = ["SharedCompileCache", "computation_signature"]

_log = get_logger("serve.cache")

_SIG_ATTR = "_tft_serve_sig"
_CANON_ATTR = "_tft_serve_canon"


def computation_signature(comp) -> Optional[str]:
    """The structural signature of a Computation, or ``None`` when it
    cannot be derived safely (then the computation is uncacheable and
    must run un-interned). Cached on the object — one symbolic trace per
    Computation per process."""
    sig = getattr(comp, _SIG_ATTR, False)
    if sig is not False:
        return sig
    try:
        sig = _build_signature(comp)
    except Exception as e:
        _log.debug("computation signature failed (%s: %s); marking "
                   "uncacheable", type(e).__name__, e)
        sig = None
    try:
        setattr(comp, _SIG_ATTR, sig)
    except Exception:
        _log.debug("could not cache signature on %r", comp)
    return sig


def _build_signature(comp) -> str:
    import jax

    from ..computation import _sym_avals

    avals, _ = _sym_avals(comp.inputs, share_lead_symbol=True)
    names = comp.input_names

    def flat(*args):
        return comp.fn(dict(zip(names, args)))

    closed = jax.make_jaxpr(flat)(*avals)
    h = hashlib.sha256()
    for s in comp.inputs:
        h.update(repr((s.name, s.dtype.name, s.shape.dims)).encode())
    for s in comp.outputs:
        h.update(repr((s.name, s.dtype.name, s.shape.dims)).encode())
    h.update(str(closed.jaxpr).encode())
    # captured array constants become constvars whose VALUES are not in
    # the jaxpr text — two programs differing only in a captured table
    # must not merge
    for c in closed.consts:
        a = np.asarray(c)
        h.update(repr((a.shape, str(a.dtype))).encode())
        h.update(a.tobytes())
    return h.hexdigest()


class SharedCompileCache:
    """Signature -> canonical Computation (bounded LRU, thread-safe).

    :meth:`intern` is the executor hook: it returns the canonical
    equivalent of ``comp`` (possibly ``comp`` itself, registering it).
    Hit/miss/uncacheable totals are exported through the always-on
    counters (``serve.compile_cache.*``) and, when a query trace is
    active, as ``shared_compile_cache`` events — compile seconds
    themselves stay where they always were, in the engine's
    ``compile_seconds`` histogram (a shared hit simply never reaches it).
    """

    def __init__(self, capacity: Optional[int] = None):
        self.capacity = max(1, capacity if capacity is not None
                            else env_int("TFT_SERVE_COMPILE_CACHE", 512))
        self._lock = threading.Lock()
        self._map: "OrderedDict[str, object]" = OrderedDict()
        self.hits = 0
        self.misses = 0
        self.uncacheable = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._map)

    def intern(self, comp):
        # resolved once per Computation OBJECT: later blocks of the same
        # query short-circuit here, so hits count avoided COMPILES (one
        # per duplicate computation), not block dispatches — and the
        # per-block cost is one attribute read, no lock
        canon = getattr(comp, _CANON_ATTR, None)
        if canon is not None:
            return canon
        sig = computation_signature(comp)
        if sig is None:
            with self._lock:
                self.uncacheable += 1
            counters.inc("serve.compile_cache.uncacheable")
            return comp
        with self._lock:
            canon = self._map.get(sig)
            if canon is None or canon is comp:
                self._map[sig] = comp
                hit = canon is comp  # re-registering canonical: no count
                if not hit:
                    self.misses += 1
                self._map.move_to_end(sig)
                while len(self._map) > self.capacity:
                    self._map.popitem(last=False)
                canon = comp
                count_miss = not hit
                hit = False
            else:
                self._map.move_to_end(sig)
                self.hits += 1
                hit = True
                count_miss = False
        try:
            # the duplicate holds its canonical strongly: even after an
            # LRU eviction the engine's weak-keyed jit entries stay alive
            # as long as any equivalent computation does
            setattr(comp, _CANON_ATTR, canon)
        except Exception as e:
            _log.debug("could not cache canonical on %r: %s", comp, e)
        if hit:
            counters.inc("serve.compile_cache.hits")
            _obs_event("shared_compile_cache", hit=True)
        elif count_miss:
            counters.inc("serve.compile_cache.misses")
            _obs_event("shared_compile_cache", hit=False)
        return canon

    def stats(self) -> dict:
        with self._lock:
            return {"entries": len(self._map), "hits": self.hits,
                    "misses": self.misses,
                    "uncacheable": self.uncacheable,
                    "capacity": self.capacity}

    def clear(self) -> None:
        with self._lock:
            self._map.clear()
