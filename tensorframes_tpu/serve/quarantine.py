"""Poison-query quarantine: per-fingerprint permanent-failure streaks.

A deterministically-crashing query is worse than a slow one: every
submission eats its full retry budget, parks checkpoints, survives
worker restarts (the fabric dutifully resumes it on a survivor), and
does it all again — across every tenant that submits the same shape.
This registry tracks a **permanent-failure streak per plan
fingerprint** (PR 18's portable fingerprints,
:func:`~..plan.adaptive.query_fingerprint` — the same identity the
performance sentinel and the durable result tier key on). After
``TFT_QUARANTINE_AFTER`` consecutive permanent failures (default 3; 0
disables) the fingerprint flips to quarantined: the scheduler
fast-rejects it at submit with a classified
:class:`~..resilience.QueryQuarantined` before it touches a queue,
quota, or worker.

Only **permanent** classifications count (``resilience.classify``):
transient faults, OOM splits, preemptions, cancellations, and load
rejections are the resilience layer doing its job, not evidence the
plan is poison. Any success resets the streak.

Release paths: the TTL (``TFT_QUARANTINE_TTL_S``, default 300s)
expires a quarantine into ONE probe admission — the streak restarts at
``threshold - 1``, so a still-poisonous plan re-quarantines on the
probe's failure while a fixed one walks free — and
``tft.unquarantine()`` lifts it manually (one fingerprint or all).
Surfaced in ``tft.doctor()`` / ``health()`` / ``serve_report()``;
every transition is flight-recorded.

The registry is process-global on purpose: the in-process serving
fabric's workers share it, so a plan quarantined on one worker is
quarantined across the fabric.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from ..resilience import env_float, env_int
from ..resilience.classify import QueryQuarantined
from ..utils.logging import get_logger
from ..utils.tracing import counters

__all__ = ["check", "note_failure", "note_success", "unquarantine",
           "status", "quarantine_status", "reset", "QueryQuarantined"]

_log = get_logger("serve.quarantine")

_lock = threading.Lock()
_streaks: Dict[str, int] = {}
# fp -> {"until": monotonic, "failures": n, "error": str}
_quarantined: Dict[str, dict] = {}


def _threshold() -> int:
    return env_int("TFT_QUARANTINE_AFTER", 3)


def _ttl() -> float:
    return env_float("TFT_QUARANTINE_TTL_S", 300.0)


def check(fp: Optional[str]) -> None:
    """Submit-time gate: raise :class:`QueryQuarantined` while ``fp``
    is quarantined; expire an aged quarantine into one probe admission
    (streak restarts at ``threshold - 1``)."""
    if fp is None or _threshold() <= 0:
        return
    with _lock:
        entry = _quarantined.get(fp)
        if entry is None:
            return
        remaining = entry["until"] - time.monotonic()
        if remaining <= 0:
            # TTL expired: this submission is the probe
            del _quarantined[fp]
            _streaks[fp] = max(_threshold() - 1, 0)
            failures = entry["failures"]
        else:
            failures = entry["failures"]
            error = entry["error"]
    from ..observability import flight as _flight
    if remaining <= 0:
        counters.inc("serve.quarantine_expired")
        _flight.record("serve.quarantine_expire", fingerprint=fp,
                       failures=failures)
        _log.info("quarantine on %s expired; admitting one probe", fp)
        return
    counters.inc("serve.quarantined")
    _flight.record("serve.quarantine_reject", fingerprint=fp,
                   failures=failures, ttl_remaining_s=round(remaining, 1))
    raise QueryQuarantined(
        f"plan fingerprint {fp} is quarantined: {failures} consecutive "
        f"permanent failures (last: {error}); expires in "
        f"{remaining:.0f}s, or lift it with tft.unquarantine({fp!r})")


def note_failure(fp: Optional[str], error: BaseException) -> None:
    """Count one PERMANENT failure of ``fp``; quarantine at the
    threshold. The caller has already classified — transient/OOM/
    preempt/rejection outcomes must never reach here."""
    threshold = _threshold()
    if fp is None or threshold <= 0:
        return
    with _lock:
        if fp in _quarantined:
            return  # already quarantined (e.g. a racing in-flight run)
        streak = _streaks.get(fp, 0) + 1
        _streaks[fp] = streak
        if streak < threshold:
            quarantine = False
        else:
            quarantine = True
            del _streaks[fp]
            _quarantined[fp] = {"until": time.monotonic() + _ttl(),
                                "failures": streak,
                                "error": f"{type(error).__name__}: {error}"}
    if not quarantine:
        return
    counters.inc("serve.quarantines")
    from ..observability import flight as _flight
    _flight.record("serve.quarantine", fingerprint=fp, failures=streak,
                   ttl_s=_ttl(), error=str(error)[:200])
    _log.warning(
        "QUARANTINED plan fingerprint %s after %d consecutive permanent "
        "failures (%s: %s); submissions fast-reject for %.0fs "
        "(tft.unquarantine() lifts it)", fp, streak,
        type(error).__name__, error, _ttl())


def note_success(fp: Optional[str]) -> None:
    """A completed run clears the fingerprint's streak."""
    if fp is None:
        return
    with _lock:
        _streaks.pop(fp, None)


def unquarantine(fp: Optional[str] = None) -> int:
    """Lift quarantines (and their streaks): one fingerprint, or every
    one when ``fp`` is ``None``. Returns how many were active. Exported
    as ``tft.unquarantine``."""
    with _lock:
        if fp is None:
            lifted = list(_quarantined)
            _quarantined.clear()
            _streaks.clear()
        else:
            lifted = [fp] if _quarantined.pop(fp, None) is not None else []
            _streaks.pop(fp, None)
    if lifted:
        counters.inc("serve.unquarantined", len(lifted))
        from ..observability import flight as _flight
        for f in lifted:
            _flight.record("serve.unquarantine", fingerprint=f)
            _log.info("quarantine on %s lifted manually", f)
    return len(lifted)


def status() -> dict:
    """Registry snapshot for ``health()`` / ``doctor()`` /
    ``serve_report()``."""
    now = time.monotonic()
    with _lock:
        active = {fp: {"failures": e["failures"],
                       "error": e["error"],
                       "ttl_remaining_s": round(max(e["until"] - now, 0.0),
                                                1)}
                  for fp, e in _quarantined.items()}
        streaks = dict(_streaks)
    return {"threshold": _threshold(), "ttl_s": _ttl(),
            "active": active, "streaks": streaks}


def reset() -> None:
    """Drop every streak and quarantine (tests)."""
    with _lock:
        _streaks.clear()
        _quarantined.clear()


# re-exported spelling for the package surface (``serve.quarantine_status``
# / ``tft.quarantine_status`` — ``status`` alone is too generic there)
quarantine_status = status
