"""Durable state tier: checkpoints and result-cache entries on disk.

Every robustness guarantee earned above this line — preempt/resume
(:mod:`.checkpoint`), the plan-fingerprint result cache
(``plan/adaptive.py``) — lives in process memory and dies with the
process. This module is the disk tier UNDER those LRUs that makes them
survive process death, which is what the serving fabric
(``serve/fabric.py``) needs to keep a promise no single process can:
a worker crash resumes its running queries elsewhere, and a rolling
restart comes back warm.

Two artifact families, one directory (``TFT_PERSIST_DIR`` or
:func:`configure`):

- **checkpoints** (``<dir>/checkpoints/<query>.ckpt``): the parked form
  of a :class:`~.checkpoint.QueryCheckpoint`, written through on every
  park. Device shardings are stripped before pickling — a sharding is a
  live-process handle and the restoring process re-plans placement
  anyway (``spill._device_put(host, None)`` takes the default). The
  stream ``tag`` + ``total`` cursor ride along verbatim, so a resume on
  a DIFFERENT host hits exactly the PR 13 mismatch contract: any drift
  discards to a cold re-run, never restores wrong data.
- **results** (``<dir>/results/<fp>.res``): interned result blocks keyed
  by their *portable* plan fingerprint (footer identity + structural
  computation signatures — see ``plan/adaptive.py``), so a restarted
  worker can serve a zero-dispatch warm hit for a plan it has never
  executed. The result dir is byte-budgeted (``TFT_PERSIST_RESULT_BYTES``)
  and swept oldest-first.
- **baselines** (``<dir>/baselines/<fp>.perf``): the performance
  sentinel's rolling per-fingerprint cost baselines
  (``observability/baseline.py``), keyed by the same portable
  fingerprints as results — a restarted worker's regression detector
  stays calibrated instead of re-warming from zero. Tiny (a few
  hundred bytes each), so no sweep; they age out with the directory.

Durability here is best-effort by design: every write/read failure is
logged and counted, never raised — a broken disk must degrade the
serving tier to cold re-runs, not crash the query that was being
checkpointed. Corrupt or truncated files load as ``None`` (cold path).

Every artifact is framed ``magic + sha256(payload) + payload`` and the
digest is verified on load. Truncation usually breaks the pickle on
its own, but single-bit rot inside a numpy buffer does NOT — the file
still unpickles and silently restores WRONG data, which the serving
tier would then hand out as a warm hit. The checksum closes that hole:
a mismatch goes cold (counted ``memory.persist_corrupt``,
flight-recorded, file removed), never wrong. The ``disk`` fault site
(``resilience/faults.py``) injects both failure shapes here: a plain
disk fault takes the read-failure path, one whose message mentions
``corrupt`` flips a payload byte so the checksum path is exercised.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import re
import tempfile
import threading
from typing import Any, List, Optional, Tuple

from ..utils.logging import get_logger
from ..utils.tracing import counters

__all__ = ["configure", "root", "enabled", "save_checkpoint",
           "load_checkpoint", "discard_checkpoint", "save_result",
           "load_result", "save_baseline", "load_baseline", "stats"]

_log = get_logger("memory.persist")

_lock = threading.Lock()
_override: Optional[str] = None  # configure() beats the env knob

_CKPT_DIR = "checkpoints"
_RES_DIR = "results"
_BL_DIR = "baselines"

# result-dir byte budget before the oldest-first sweep (default 512 MiB)
_DEFAULT_RESULT_BYTES = 512 * 1024 * 1024

# artifact framing: magic + sha256(payload) + payload. The magic keys
# the container format (bump on layout change); the digest makes
# bit-rot detectable before pickle can silently restore wrong data.
_MAGIC = b"TFTP\x01"
_DIGEST_LEN = 32


def _pack(payload: bytes) -> bytes:
    """Frame pickled ``payload`` with the magic + content checksum."""
    return _MAGIC + hashlib.sha256(payload).digest() + payload


def _corrupt(path: str, why: str) -> None:
    """The checksum cold path: count, flight-record, remove, ``None``.
    Distinct from ``persist.read_errors`` (I/O and unpickle failures)
    because a digest mismatch means the bytes CHANGED after a good
    write — the one failure shape that would otherwise restore wrong
    data silently."""
    counters.inc("memory.persist_corrupt")
    from ..observability import flight as _flight
    _flight.record("memory.persist_corrupt", path=os.path.basename(path),
                   why=why)
    _log.warning("persist artifact corrupt (%s): %s — treating as cold",
                 path, why)
    try:
        os.unlink(path)
    except OSError:
        pass
    return None


def configure(path: Optional[str]) -> Optional[str]:
    """Point the tier at ``path`` (``None`` disables unless
    ``TFT_PERSIST_DIR`` is set). Returns the previous override so a
    scoped owner (the fabric) can restore it on close."""
    global _override
    with _lock:
        prev = _override
        _override = path
    return prev


def root() -> Optional[str]:
    """The active persistence root, or ``None`` when the tier is off."""
    with _lock:
        if _override is not None:
            return _override
    return os.environ.get("TFT_PERSIST_DIR") or None


def enabled() -> bool:
    return root() is not None


def _safe_name(key: str) -> str:
    """A filesystem-safe, collision-free filename for ``key``: the
    sanitized key for greppability plus a short hash for identity."""
    tail = hashlib.sha256(key.encode()).hexdigest()[:12]
    stem = re.sub(r"[^A-Za-z0-9_.-]", "_", key)[:80]
    return f"{stem}-{tail}"


def _subdir(kind: str) -> Optional[str]:
    base = root()
    if base is None:
        return None
    path = os.path.join(base, kind)
    try:
        os.makedirs(path, exist_ok=True)
    except OSError as e:
        _log.warning("persist tier unavailable (%s): %s", path, e)
        return None
    return path


def _atomic_write(path: str, payload: bytes) -> bool:
    """Write-then-rename so readers never see a torn file (a crash
    mid-write leaves the previous version or nothing, both safe)."""
    d = os.path.dirname(path)
    try:
        fd, tmp = tempfile.mkstemp(dir=d, suffix=".tmp")
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(payload)
            os.replace(tmp, path)
        except BaseException:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            raise
        return True
    except Exception as e:
        counters.inc("persist.write_errors")
        _log.warning("persist write failed (%s): %s", path, e)
        return False


def _read(path: str) -> Optional[Any]:
    from ..resilience import faults as _faults
    data: Optional[bytes] = None
    try:
        try:
            _faults.check("disk")
        except _faults.InjectedFault as e:
            if "corrupt" not in str(e):
                raise
            # corruption-shaped injection: read the real bytes, then
            # flip one payload bit — the artifact still "reads fine"
            # and must be caught by the checksum, not by luck
            with open(path, "rb") as f:
                buf = bytearray(f.read())
            if buf:
                buf[-1] ^= 0x01
            data = bytes(buf)
        if data is None:
            with open(path, "rb") as f:
                data = f.read()
    except FileNotFoundError:
        return None
    except Exception as e:
        # I/O failure (including injected disk faults): cold path
        counters.inc("persist.read_errors")
        _log.warning("persist read failed (%s): %s — treating as cold",
                     path, e)
        try:
            os.unlink(path)
        except OSError:
            pass
        return None
    if (not data.startswith(_MAGIC)
            or len(data) < len(_MAGIC) + _DIGEST_LEN):
        return _corrupt(path, "missing or truncated artifact header")
    digest = data[len(_MAGIC):len(_MAGIC) + _DIGEST_LEN]
    payload = data[len(_MAGIC) + _DIGEST_LEN:]
    if hashlib.sha256(payload).digest() != digest:
        return _corrupt(path, "sha256 content checksum mismatch")
    try:
        return pickle.loads(payload)
    except Exception as e:
        # checksum held but the pickle didn't: version/environment skew
        counters.inc("persist.read_errors")
        _log.warning("persist unpickle failed (%s): %s — treating as "
                     "cold", path, e)
        try:
            os.unlink(path)
        except OSError:
            pass
        return None


def _strip_shardings(t: Tuple) -> Tuple:
    """The parked form's ``("dev", host, sharding)`` tuples carry a live
    sharding handle that neither pickles portably nor means anything in
    another process; ``None`` makes the restore take the default
    placement (bit-identical values either way)."""
    kind = t[0]
    if kind == "dev":
        return ("dev", t[1], None)
    if kind in ("block", "dict"):
        mapped = {k: _strip_shardings(c) for k, c in t[1].items()}
        return (kind, mapped) + tuple(t[2:])
    return t


# -- checkpoints ----------------------------------------------------------

def save_checkpoint(query_id: str, parked: Tuple[List[Tuple], int, str],
                    parked_blocks: int, moved_bytes: int) -> bool:
    """Write-through one parked stream (called from
    :meth:`~.checkpoint.QueryCheckpoint.park_stream`). Best-effort:
    a failure degrades THAT query's cross-process resume to a cold
    re-run and nothing else."""
    d = _subdir(_CKPT_DIR)
    if d is None:
        return False
    vals, total, tag = parked
    try:
        payload = pickle.dumps(
            {"version": 1, "query_id": query_id, "tag": tag,
             "total": int(total),
             "vals": [_strip_shardings(v) for v in vals],
             "parked_blocks": int(parked_blocks),
             "moved_bytes": int(moved_bytes)},
            protocol=pickle.HIGHEST_PROTOCOL)
    except Exception as e:
        counters.inc("persist.write_errors")
        _log.warning("checkpoint of %s not picklable: %s", query_id, e)
        return False
    path = os.path.join(d, _safe_name(query_id) + ".ckpt")
    if not _atomic_write(path, _pack(payload)):
        return False
    counters.inc("persist.checkpoint_writes")
    _log.debug("persisted checkpoint of %s: %d block(s), %d B -> %s",
               query_id, parked_blocks, len(payload), path)
    return True


def load_checkpoint(query_id: str):
    """The persisted :class:`~.checkpoint.QueryCheckpoint` of
    ``query_id``, or ``None`` (cold). The returned checkpoint still
    enforces the tag+total mismatch contract on resume."""
    d = _subdir(_CKPT_DIR)
    if d is None:
        return None
    rec = _read(os.path.join(d, _safe_name(query_id) + ".ckpt"))
    if not isinstance(rec, dict) or rec.get("version") != 1:
        return None
    from .checkpoint import QueryCheckpoint
    cp = QueryCheckpoint(query_id)
    cp._parked = (rec["vals"], int(rec["total"]), str(rec["tag"]))
    cp.parked_blocks = int(rec.get("parked_blocks", len(rec["vals"])))
    cp.moved_bytes = int(rec.get("moved_bytes", 0))
    counters.inc("persist.checkpoint_loads")
    return cp


def discard_checkpoint(query_id: str) -> None:
    """Drop the persisted checkpoint (terminal completion — the query
    finished for real, nothing left to resume)."""
    base = root()
    if base is None:
        return
    path = os.path.join(base, _CKPT_DIR, _safe_name(query_id) + ".ckpt")
    try:
        os.unlink(path)
        counters.inc("persist.checkpoint_discards")
    except FileNotFoundError:
        pass
    except OSError as e:
        _log.debug("checkpoint discard of %s failed: %s", query_id, e)


# -- result-cache entries -------------------------------------------------

def _result_budget() -> int:
    try:
        return int(os.environ.get("TFT_PERSIST_RESULT_BYTES",
                                  _DEFAULT_RESULT_BYTES))
    except ValueError:
        return _DEFAULT_RESULT_BYTES


def _sweep_results(d: str) -> None:
    """Oldest-first eviction when the result dir crosses its byte
    budget — mirrors the in-memory LRU's discipline on disk."""
    budget = _result_budget()
    try:
        entries = []
        total = 0
        with os.scandir(d) as it:
            for e in it:
                if not e.name.endswith(".res"):
                    continue
                st = e.stat()
                entries.append((st.st_mtime, st.st_size, e.path))
                total += st.st_size
        if total <= budget:
            return
        entries.sort()
        for _, size, path in entries:
            try:
                os.unlink(path)
                counters.inc("persist.result_evictions")
                total -= size
            except OSError:
                continue
            if total <= budget:
                break
    except OSError as e:
        _log.debug("result sweep failed: %s", e)


def save_result(fingerprint: str, blocks: List[Any]) -> bool:
    """Persist one interned result (the host-converted parked forms of
    its blocks) under its portable plan fingerprint."""
    d = _subdir(_RES_DIR)
    if d is None:
        return False
    from .checkpoint import _park
    try:
        stats = {"moved": 0}
        parked = [_strip_shardings(_park(b, stats)) for b in blocks]
        payload = pickle.dumps({"version": 1, "blocks": parked},
                               protocol=pickle.HIGHEST_PROTOCOL)
    except Exception as e:
        counters.inc("persist.write_errors")
        _log.warning("result %s not picklable: %s", fingerprint[:16], e)
        return False
    path = os.path.join(d, _safe_name(fingerprint) + ".res")
    if not _atomic_write(path, _pack(payload)):
        return False
    counters.inc("persist.result_writes")
    _sweep_results(d)
    return True


def load_result(fingerprint: str) -> Optional[List[Any]]:
    """The persisted blocks for ``fingerprint``, or ``None`` (cold)."""
    d = _subdir(_RES_DIR)
    if d is None:
        return None
    rec = _read(os.path.join(d, _safe_name(fingerprint) + ".res"))
    if not isinstance(rec, dict) or rec.get("version") != 1:
        return None
    from .checkpoint import _restore
    try:
        blocks = [_restore(b) for b in rec["blocks"]]
    except Exception as e:
        counters.inc("persist.read_errors")
        _log.warning("result %s restore failed: %s — treating as cold",
                     fingerprint[:16], e)
        return None
    counters.inc("persist.result_loads")
    return blocks


# -- performance-sentinel baselines ---------------------------------------

def save_baseline(fingerprint: str, payload: dict) -> bool:
    """Persist one plan fingerprint's rolling cost baseline
    (``observability/baseline.py`` owns the payload shape). Best-effort
    like everything here: a failure degrades that fingerprint's
    regression detector to an in-memory re-warm after restart."""
    d = _subdir(_BL_DIR)
    if d is None:
        return False
    try:
        blob = pickle.dumps({"version": 1, "baseline": payload},
                            protocol=pickle.HIGHEST_PROTOCOL)
    except Exception as e:
        counters.inc("persist.write_errors")
        _log.warning("baseline %s not picklable: %s", fingerprint[:16], e)
        return False
    path = os.path.join(d, _safe_name(fingerprint) + ".perf")
    if not _atomic_write(path, _pack(blob)):
        return False
    counters.inc("persist.baseline_writes")
    return True


def load_baseline(fingerprint: str) -> Optional[dict]:
    """The persisted baseline payload for ``fingerprint``, or ``None``
    (cold — the detector re-warms from live completions)."""
    d = _subdir(_BL_DIR)
    if d is None:
        return None
    rec = _read(os.path.join(d, _safe_name(fingerprint) + ".perf"))
    if not isinstance(rec, dict) or rec.get("version") != 1:
        return None
    payload = rec.get("baseline")
    if not isinstance(payload, dict):
        return None
    counters.inc("persist.baseline_loads")
    return payload


# -- introspection --------------------------------------------------------

def _dir_stats(kind: str, suffix: str) -> Tuple[int, int]:
    base = root()
    if base is None:
        return (0, 0)
    d = os.path.join(base, kind)
    n = total = 0
    try:
        with os.scandir(d) as it:
            for e in it:
                if e.name.endswith(suffix):
                    n += 1
                    total += e.stat().st_size
    except OSError:
        return (0, 0)
    return (n, total)


def stats() -> dict:
    """Tier snapshot for ``tft.health()``: what is on disk right now."""
    ckpt_n, ckpt_b = _dir_stats(_CKPT_DIR, ".ckpt")
    res_n, res_b = _dir_stats(_RES_DIR, ".res")
    bl_n, bl_b = _dir_stats(_BL_DIR, ".perf")
    return {
        "enabled": enabled(),
        "dir": root(),
        "checkpoints": ckpt_n,
        "checkpoint_bytes": ckpt_b,
        "results": res_n,
        "result_bytes": res_b,
        "baselines": bl_n,
        "baseline_bytes": bl_b,
    }
