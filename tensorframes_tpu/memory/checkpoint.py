"""Preemption checkpoints: parked block outputs, spilled off-device.

When the serving layer preempts a running query at a block boundary
(``docs/serving.md``), the pipelined stream has already drained some
blocks and is about to discard the rest of its window. Throwing the
drained work away would make preemption cost a full re-run; keeping it
on device would defeat the point of preempting (the preemptor needs the
HBM). A :class:`QueryCheckpoint` is the middle path:

- **completed block outputs are parked**: containers (``Block`` /
  ``dict``) are walked and every device-resident array moves to a
  pinned host buffer through the spill machinery
  (:func:`~.spill.to_pinned_host` — bit-identical per dtype, recorded
  sharding), counted through the active ledger's spill accounting
  (``memory.spills`` / ``checkpoint:<query>`` events). Host numpy and
  ride-along values are kept by reference — they were never device
  bytes.
- **a cursor into the plan's block sequence**: the parked output count
  IS the cursor; on resume the stream restores the parked outputs
  (fault-back with the recorded sharding, counted as ledger faults) and
  re-dispatches only the remaining blocks — bit-identical to an
  uninterrupted run because each block's computation is deterministic
  and the restored outputs round-tripped bit-for-bit.

A checkpoint holds at most ONE parked stream: forcing is sequential
(nested streams complete before their consumer starts), so the
preempted query has exactly one stream in flight, and every upstream
stream's results are already cached on their frames. On resume the
first stream whose block count matches restores; a mismatch (the plan
changed under the query) discards the checkpoint and re-runs from
scratch — never wrong, at worst cold (``serve.checkpoint_discards``).

Cancellation (:meth:`QueryCheckpoint.free`) drops the parked buffers.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from ..resilience import invariants as _invariants
from ..utils.logging import get_logger
from ..utils.tracing import counters
from . import spill as _spill

__all__ = ["QueryCheckpoint"]

_log = get_logger("memory.checkpoint")


def _park(v: Any, stats: dict) -> Tuple:
    """One output value -> a host-only parked form. Tags keep the
    structure reconstructible without constructing Blocks over
    placeholder values."""
    from ..frame import Block
    if isinstance(v, Block):
        return ("block", {k: _park(c, stats)
                          for k, c in v.columns.items()}, v.num_rows)
    if isinstance(v, dict):
        return ("dict", {k: _park(c, stats) for k, c in v.items()})
    if _spill.is_device_value(v):
        host = _spill.to_pinned_host(v)
        stats["moved"] += _spill.array_nbytes(v)
        return ("dev", host, getattr(v, "sharding", None))
    return ("raw", v)  # host numpy / lists / scalars: kept by reference


def _restore(t: Tuple) -> Any:
    kind = t[0]
    if kind == "block":
        from ..frame import Block
        return Block({k: _restore(c) for k, c in t[1].items()}, t[2])
    if kind == "dict":
        return {k: _restore(c) for k, c in t[1].items()}
    if kind == "dev":
        return _spill._device_put(t[1], t[2])
    return t[1]


class QueryCheckpoint:
    """Parked outputs + cursor of one preempted query (module docstring).

    Created lazily by the preemption scope on the first park; carried on
    the scheduler's :class:`~..serve.scheduler.SubmittedQuery` between
    the preempt and the resume; freed on any terminal state.
    """

    __slots__ = ("query_id", "parked_blocks", "moved_bytes", "_parked")

    def __init__(self, query_id: str):
        self.query_id = query_id
        # (values, total blocks, stream tag)
        self._parked: Optional[Tuple[List[Tuple], int, str]] = None
        self.parked_blocks = 0
        self.moved_bytes = 0

    @property
    def empty(self) -> bool:
        return self._parked is None

    def park_stream(self, outputs: Sequence[Any], total: int,
                    tag: str = "stream") -> int:
        """Park ``outputs`` (the stream's first ``len(outputs)`` drained
        results, FIFO order) with cursor ``total`` blocks under stream
        identity ``tag``. Returns the device bytes moved to host."""
        stats = {"moved": 0}
        vals = [_park(v, stats) for v in outputs]
        # cursor consistency: the parked prefix can never exceed the
        # stream it came from — a longer one would resume duplicate
        # rows (strict mode raises; always-on counts + flight-records)
        _invariants.check(
            len(vals) <= int(total), "checkpoint",
            f"query {self.query_id}: parked {len(vals)} block(s) of a "
            f"{total}-block stream {tag!r}", point="checkpoint.park")
        self._parked = (vals, int(total), str(tag))
        self.parked_blocks = len(vals)
        self.moved_bytes = int(stats["moved"])
        if self.moved_bytes:
            from . import active as _active
            m = _active()
            if m is not None:
                m.note_spill(self.moved_bytes,
                             f"checkpoint:{self.query_id}")
        counters.inc("pipeline.parked_blocks", len(vals))
        from . import persist as _persist
        if _persist.enabled():
            # write-through to the durable tier: a crash of THIS process
            # can now resume the query in another one (serve/fabric.py);
            # best-effort — a failed write degrades to a cold re-run
            _persist.save_checkpoint(self.query_id, self._parked,
                                     self.parked_blocks,
                                     self.moved_bytes)
        return self.moved_bytes

    def resume_stream(self, total: int,
                      tag: str = "stream") -> Optional[List[Any]]:
        """The parked outputs when ``total`` AND the stream ``tag``
        match the parked record, else ``None`` (and the checkpoint is
        discarded — a mismatched stream means the execution path
        changed under the query, e.g. a fused plan falling back
        per-op; re-running from scratch is correct, resuming a
        different stream's outputs would not be)."""
        if self._parked is None:
            return None
        vals, t, parked_tag = self._parked
        self._parked = None
        if t != int(total) or parked_tag != str(tag):
            counters.inc("serve.checkpoint_discards")
            _log.warning(
                "checkpoint of query %s parked %d/%d block(s) of "
                "stream %r but the resumed stream is %r over %d "
                "block(s); discarding and re-running from scratch",
                self.query_id, len(vals), t, parked_tag, tag, total)
            self.parked_blocks = 0
            self.moved_bytes = 0
            return None
        if not _invariants.check(
                len(vals) <= t, "checkpoint",
                f"query {self.query_id}: checkpoint cursor {len(vals)} "
                f"past the {t}-block stream {tag!r}; discarding",
                point="checkpoint.resume"):
            # always-on mode: cold-path the inconsistent checkpoint
            # rather than resume duplicate rows
            counters.inc("serve.checkpoint_discards")
            self.parked_blocks = 0
            self.moved_bytes = 0
            return None
        restored = [_restore(v) for v in vals]
        if self.moved_bytes:
            from . import active as _active
            m = _active()
            if m is not None:
                m.note_fault(self.moved_bytes,
                             f"checkpoint:{self.query_id}")
        self.parked_blocks = 0
        self.moved_bytes = 0
        return restored

    def free(self) -> None:
        """Drop the parked buffers (cancellation, terminal states)."""
        self._parked = None
        self.parked_blocks = 0
        self.moved_bytes = 0

    def __repr__(self):
        state = (f"{self.parked_blocks} block(s) parked"
                 if self._parked is not None else "empty")
        return f"QueryCheckpoint({self.query_id!r}, {state})"
