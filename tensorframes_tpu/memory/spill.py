"""Spillable device buffers: pinned-host round trips, bit-identical.

The mechanics half of the memory subsystem: wrappers that move
device-resident arrays to **pinned host buffers**
(``native.empty_aligned`` — page-aligned allocations the DMA engines
can address directly) and restore them on next touch with the original
placement (``jax.device_put`` with the recorded sharding). The round
trip is bit-identical for every device dtype — including ``bfloat16``,
which travels as its ``ml_dtypes`` host view, never through a float32
widening — and host-side ride-along columns (strings) pass through
untouched: they were never device bytes to begin with.

Two stock spillables implement the ledger's duck-typed entry protocol
(:class:`~.manager.MemoryManager`):

- :class:`SpillableBuffer` — a named set of arrays (tests, ad-hoc
  intermediates);
- :class:`SpillableColumns` — a ``dict`` drop-in for a
  ``DistributedFrame``'s column mapping whose device values spill as a
  unit and fault back **transparently on any access** (``__getitem__``
  / ``values`` / ``items``), so the 2000 lines of mesh ops need no
  spill awareness at all.
"""

from __future__ import annotations

from typing import Any, Dict, Mapping, Optional, Tuple

import numpy as np

from ..utils.logging import get_logger

__all__ = ["array_nbytes", "is_device_value", "to_pinned_host",
           "SpillableBuffer", "SpillableColumns", "host_value",
           "value_nbytes"]

_log = get_logger("memory.spill")


def is_device_value(a: Any) -> bool:
    """True for device (jax) arrays; host numpy / lists / scalars are
    already host bytes and never spill."""
    return (not isinstance(a, (np.ndarray, list, tuple))
            and hasattr(a, "shape") and hasattr(a, "dtype"))


def array_nbytes(a: Any) -> int:
    """Byte size of an array (host or device)."""
    nb = getattr(a, "nbytes", None)
    if nb is not None:
        return int(nb)
    try:
        return int(np.prod(a.shape)) * np.dtype(a.dtype).itemsize
    except Exception:
        return 0


def to_pinned_host(a: Any) -> np.ndarray:
    """D2H copy into a pinned (page-aligned) host buffer, preserving the
    device dtype bit-for-bit (bfloat16 stays ``ml_dtypes.bfloat16``)."""
    host = np.asarray(a)
    try:
        from .. import native as _native
        dst = _native.empty_aligned(host.shape, host.dtype)
        np.copyto(dst, host)
        return dst
    except Exception as e:  # aligned pool unavailable: plain host numpy
        _log.debug("pinned allocation failed (%s); spilling to plain "
                   "host memory", e)
        return host


def _device_put(host: np.ndarray, sharding) -> Any:
    import jax

    if sharding is not None:
        try:
            return jax.device_put(host, sharding)
        except Exception as e:  # a dead mesh: restore unplaced
            _log.debug("fault-back with recorded sharding failed (%s); "
                       "restoring with default placement", e)
    return jax.device_put(host)


def host_value(columns: Mapping[str, Any], name: str) -> np.ndarray:
    """A column's value as host numpy WITHOUT faulting a spilled mapping
    back to the device (the external sort reads runs this way)."""
    if isinstance(columns, SpillableColumns):
        return columns.host_value(name)
    return np.asarray(columns[name]) if is_device_value(columns[name]) \
        else columns[name]


def value_nbytes(columns: Mapping[str, Any], name: str) -> int:
    """A column's byte size, spilled or resident, without faulting."""
    if isinstance(columns, SpillableColumns):
        return columns.value_nbytes(name)
    return array_nbytes(columns[name])


class SpillableBuffer:
    """A named set of device arrays that round-trips to pinned host
    buffers. Standalone use (no ledger)::

        buf = SpillableBuffer("sorted-run-3", {"x": dev_x, "k": dev_k})
        buf.spill()            # device -> pinned host, bit-identical
        a = buf.get("x")       # faults the whole buffer back

    Registered with a :class:`~.manager.MemoryManager` it becomes an LRU
    spill candidate; host-side values (numpy/object arrays) ride along
    uncounted and unconverted.
    """

    __slots__ = ("_name", "_values", "_host", "__weakref__")

    def __init__(self, name: str, arrays: Mapping[str, Any]):
        self._name = name
        self._values: Dict[str, Any] = dict(arrays)
        # spilled store: name -> (pinned host array, recorded sharding)
        self._host: Optional[Dict[str, Tuple[np.ndarray, Any]]] = None

    # -- ledger entry protocol --------------------------------------------
    def mem_name(self) -> str:
        return self._name

    def mem_is_spilled(self) -> bool:
        return self._host is not None

    def mem_device_bytes(self) -> int:
        if self._host is not None:
            return 0
        return sum(array_nbytes(v) for v in self._values.values()
                   if is_device_value(v))

    def mem_host_bytes(self) -> int:
        if self._host is None:
            return 0
        return sum(array_nbytes(h) for h, _ in self._host.values())

    def mem_spill(self) -> int:
        if self._host is not None:
            return 0
        host: Dict[str, Tuple[np.ndarray, Any]] = {}
        freed = 0
        for n, v in self._values.items():
            if is_device_value(v):
                host[n] = (to_pinned_host(v), getattr(v, "sharding", None))
                freed += array_nbytes(v)
                self._values[n] = None  # drop the device reference
        self._host = host
        return freed

    def mem_fault(self) -> int:
        if self._host is None:
            return 0
        restored = 0
        for n, (h, sh) in self._host.items():
            a = _device_put(h, sh)
            self._values[n] = a
            restored += array_nbytes(a)
        self._host = None
        return restored

    # -- convenience -------------------------------------------------------
    spill = mem_spill
    fault = mem_fault

    @property
    def spilled(self) -> bool:
        return self.mem_is_spilled()

    def get(self, name: str) -> Any:
        if self._host is not None:
            self.mem_fault()
        return self._values[name]

    def arrays(self) -> Dict[str, Any]:
        if self._host is not None:
            self.mem_fault()
        return dict(self._values)

    def __repr__(self):
        state = "spilled" if self.mem_is_spilled() else "resident"
        return f"SpillableBuffer({self._name!r}, {state})"


class SpillableColumns(dict):
    """A ``DistributedFrame.columns`` mapping whose device values can
    spill to pinned host buffers as a unit and fault back transparently
    on the next access.

    Every read path (``[]`` / ``get`` / ``values`` / ``items``) touches
    the owning :class:`~.manager.MemoryManager` first — refreshing LRU
    recency and faulting the columns back when spilled — so mesh ops
    stay spill-oblivious. Host ride-along columns (strings) are plain
    values: never counted, never converted. While spilled, the device
    slots hold ``None``; only the overridden accessors are public API.
    """

    def __init__(self, name: str, cols: Mapping[str, Any], manager):
        super().__init__(cols)
        self._name = name
        self._mgr = manager
        self._host: Optional[Dict[str, Tuple[np.ndarray, Any]]] = None

    # -- ledger entry protocol --------------------------------------------
    def mem_name(self) -> str:
        return self._name

    def mem_is_spilled(self) -> bool:
        return self._host is not None

    def mem_device_bytes(self) -> int:
        if self._host is not None:
            return 0
        return sum(array_nbytes(v) for v in dict.values(self)
                   if is_device_value(v))

    def mem_host_bytes(self) -> int:
        if self._host is None:
            return 0
        return sum(array_nbytes(h) for h, _ in self._host.values())

    def mem_spill(self) -> int:
        if self._host is not None:
            return 0
        host: Dict[str, Tuple[np.ndarray, Any]] = {}
        freed = 0
        for n in list(dict.keys(self)):
            v = dict.__getitem__(self, n)
            if is_device_value(v):
                host[n] = (to_pinned_host(v), getattr(v, "sharding", None))
                freed += array_nbytes(v)
                dict.__setitem__(self, n, None)
        self._host = host
        return freed

    def mem_fault(self) -> int:
        if self._host is None:
            return 0
        restored = 0
        for n, (h, sh) in self._host.items():
            a = _device_put(h, sh)
            dict.__setitem__(self, n, a)
            restored += array_nbytes(a)
        self._host = None
        return restored

    # -- transparent access ------------------------------------------------
    def _touch(self) -> None:
        m = self._mgr
        if m is not None:
            m.touch(self)  # faults back under the ledger lock if spilled
        elif self._host is not None:
            self.mem_fault()

    def __getitem__(self, key):
        self._touch()
        return dict.__getitem__(self, key)

    def get(self, key, default=None):
        self._touch()
        return dict.get(self, key, default)

    def values(self):
        self._touch()
        return dict.values(self)

    def items(self):
        self._touch()
        return dict.items(self)

    # -- spill-free reads (external sort, estimates, shape metadata) -------
    def leading_rows(self) -> int:
        """Leading row count of the first column WITHOUT faulting a
        spilled mapping back to the device (``DistributedFrame.
        padded_rows`` routes here: shape metadata must never cost a
        device_put of a larger-than-budget frame)."""
        if self._host:
            for n in dict.keys(self):
                entry = self._host.get(n)
                if entry is not None:
                    return int(entry[0].shape[0])
        for v in dict.values(self):
            if v is not None and hasattr(v, "shape"):
                return int(v.shape[0])
        raise ValueError("no shaped columns to read a row count from")

    def host_value(self, name: str) -> np.ndarray:
        if self._host is not None and name in self._host:
            return self._host[name][0]
        v = dict.__getitem__(self, name)
        return np.asarray(v) if is_device_value(v) else v

    def value_nbytes(self, name: str) -> int:
        if self._host is not None and name in self._host:
            return array_nbytes(self._host[name][0])
        return array_nbytes(dict.__getitem__(self, name))

    def __repr__(self):
        state = "spilled" if self.mem_is_spilled() else "resident"
        return (f"SpillableColumns({self._name!r}, "
                f"{list(dict.keys(self))}, {state})")
