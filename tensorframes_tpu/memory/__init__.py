"""Out-of-core frames: the device-memory manager (``docs/memory.md``).

Public surface:

- :func:`manager` — the process :class:`~.manager.MemoryManager`
  (created on first use; budget from ``TFT_MEM_LIMIT_BYTES`` or the
  backend allocator limit x ``TFT_MEM_FRACTION``);
- :func:`active` — the manager IF it has a budget, else ``None``: the
  hot-path gate every integration point checks first, so an unlimited
  process pays one global read per dispatch and nothing else;
- :func:`configure` / :func:`bypass` / :func:`_reset` — explicit
  control for tests and benchmarks;
- :class:`SpillableBuffer` / :class:`SpillableColumns` /
  :func:`external_sort` — the spill mechanics and the out-of-core sort
  (``dsort`` routes here when a frame outgrows the budget);
- :mod:`.persist` — the durable disk tier under the in-memory state:
  preemption checkpoints and result-cache entries written through so
  they survive process death (``TFT_PERSIST_DIR``, ``serve/fabric.py``).

Integration map: the block executor admits every dispatch
(``engine/executor.py``: reserve at submit, release at drain, proactive
pre-dispatch split on predicted overflow); pipelined pending blocks
register as spill candidates (their device output can drain to host
early); ``distribute`` registers mesh frames' columns; the serve
scheduler estimates unforced frames through :func:`frame_estimate` and
reads :meth:`~.manager.MemoryManager.headroom`; streaming window state
spills instead of force-evicting. ``tft_memory_*`` gauges join the
metrics endpoint; ``spill`` / ``fault`` / ``proactive_split`` events
join query traces and ``explain()``.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Any, Mapping, Optional

from .checkpoint import QueryCheckpoint
from . import persist
from .estimate import (blocks_estimate, frame_estimate, propagate_hints,
                       schema_row_bytes)
from .external_sort import external_sort
from .manager import MemoryManager
from .spill import (SpillableBuffer, SpillableColumns, array_nbytes,
                    host_value, is_device_value, to_pinned_host,
                    value_nbytes)

__all__ = [
    "MemoryManager", "manager", "active", "configure", "bypass",
    "SpillableBuffer", "SpillableColumns", "spillable_columns",
    "external_sort", "frame_estimate", "propagate_hints",
    "blocks_estimate", "schema_row_bytes", "array_nbytes",
    "host_value", "value_nbytes", "is_device_value", "to_pinned_host",
    "note_frame_cache", "forget_frame_cache", "QueryCheckpoint",
    "persist",
]

_lock = threading.Lock()
_manager: Optional[MemoryManager] = None
_active: Optional[MemoryManager] = None
_resolved = False
_provider_registered = False


def _register_provider() -> None:
    global _provider_registered
    if _provider_registered:
        return
    try:
        from ..observability.metrics import register_metrics_provider
        register_metrics_provider("memory", _metrics_lines)
        _provider_registered = True
    except Exception as e:  # metrics are decoration, never a gate
        from ..utils.logging import get_logger
        get_logger("memory").warning(
            "could not register the tft_memory_* metrics provider: %s", e)


def _resolve() -> None:
    global _manager, _active, _resolved
    with _lock:
        if _resolved:
            return
        _manager = MemoryManager()
        _active = _manager if _manager.limited else None
        _resolved = True
    _register_provider()


def manager() -> MemoryManager:
    """The process memory manager (created on first use)."""
    if not _resolved:
        _resolve()
    return _manager


def active() -> Optional[MemoryManager]:
    """The manager when it has a budget, else ``None`` — the zero-cost
    gate: unlimited processes take one global read per call."""
    if not _resolved:
        _resolve()
    return _active


def configure(limit_bytes: Optional[int] = None,
              spill: Optional[bool] = None) -> MemoryManager:
    """Install a fresh manager with an explicit budget (tests and
    benchmarks; production uses the env knobs). ``limit_bytes=None``
    re-reads ``TFT_MEM_LIMIT_BYTES`` / the device budget; ``0`` means
    explicitly unlimited. Returns the new manager."""
    global _manager, _active, _resolved
    with _lock:
        if limit_bytes == 0:
            m = MemoryManager(limit_bytes=-1, spill=spill)
        else:
            m = MemoryManager(limit_bytes=limit_bytes, spill=spill)
        _manager = m
        _active = m if m.limited else None
        _resolved = True
    _register_provider()
    return m


def _reset() -> None:
    """Drop the singleton so the next use re-reads the environment
    (tests monkeypatching ``TFT_MEM_LIMIT_BYTES`` call this)."""
    global _manager, _active, _resolved
    with _lock:
        _manager = None
        _active = None
        _resolved = False


@contextlib.contextmanager
def bypass():
    """Temporarily disable the memory manager entirely (benchmarks
    measuring the ledger's own overhead)."""
    global _active
    if not _resolved:
        _resolve()
    with _lock:
        prev, _active = _active, None
    try:
        yield
    finally:
        with _lock:
            _active = prev


def spillable_columns(name: str, cols: Mapping[str, Any],
                      mgr: Optional[MemoryManager] = None):
    """Wrap a column mapping as a registered LRU spill candidate when a
    budget is active; returns the mapping unchanged otherwise."""
    m = mgr if mgr is not None else active()
    if m is None or not m.spill_enabled:
        return cols if isinstance(cols, dict) else dict(cols)
    wrapped = SpillableColumns(name, cols, m)
    m.register(wrapped)
    return wrapped


def note_frame_cache(frame) -> None:
    """Record a frame's forced block cache for the host-side gauge."""
    m = active()
    if m is not None:
        m.note_frame_cache(frame)


def forget_frame_cache(frame) -> None:
    m = _active
    if m is not None:
        m.forget_frame_cache(frame)


def _metrics_lines() -> list:
    """``tft_memory_*`` exposition lines for the metrics endpoint."""
    from ..utils.tracing import counters as _counters
    m = manager()
    snap = m.snapshot()
    lines = []
    gauges = (
        ("tft_memory_budget_bytes",
         "Configured device budget (0 = unlimited).",
         snap["limit_bytes"]),
        ("tft_memory_inflight_bytes",
         "Bytes reserved by in-flight block dispatches.",
         snap["inflight_bytes"]),
        ("tft_memory_resident_bytes",
         "Device bytes held by registered spillable buffers.",
         snap["resident_bytes"]),
        ("tft_memory_spilled_bytes",
         "Host bytes held by spilled buffers awaiting fault-back.",
         snap["spilled_bytes"]),
        ("tft_memory_resident_buffers",
         "Registered spillable buffers (spilled or resident).",
         snap["resident_buffers"]),
        ("tft_memory_frame_cache_bytes",
         "Host bytes held by forced TensorFrame block caches.",
         m.frame_cache_bytes()),
    )
    for name, help_s, value in gauges:
        lines.append(f"# HELP {name} {help_s}")
        lines.append(f"# TYPE {name} gauge")
        lines.append(f"{name} {int(value)}")
    for name, counter in (
            ("tft_memory_spills_total", "memory.spills"),
            ("tft_memory_spill_bytes_total", "memory.spill_bytes"),
            ("tft_memory_faults_total", "memory.faults"),
            ("tft_memory_fault_bytes_total", "memory.fault_bytes"),
            ("tft_memory_proactive_splits_total",
             "memory.proactive_splits"),
            ("tft_memory_admission_waits_total",
             "memory.admission_waits"),
            ("tft_memory_overflow_admissions_total",
             "memory.overflow_admissions")):
        lines.append(f"# TYPE {name} counter")
        lines.append(f"{name} {_counters.get(counter)}")
    return lines
