"""External-memory sort: budget-sized device runs + host k-way merge.

``dsort``'s columnsort assumes the whole frame is device-resident; a
frame larger than the device budget cannot take that path at all. This
module is the out-of-core alternative (the classic external merge
sort, device-flavored):

1. the input rows split into contiguous **runs**, each sized to fit the
   budget (``TFT_MEM_LIMIT_BYTES`` / the derived device budget, with a
   4x headroom factor for input + output + staging);
2. each run sorts **on the device** in one compiled program — the same
   stable ``lax.sort`` chain as ``dsort``'s single-shard fallback:
   order-transformed keys (float negation / bitwise-not for
   ``descending``) with the run-local row position as the
   least-significant key — admitted against the ledger like any block
   dispatch;
3. the sorted run moves to pinned host buffers (each move is a
   ``memory.spill``: a device-resident intermediate leaving for host);
4. the runs **k-way merge on the host**: adjacent pairs merge per
   round (log2(k) rounds). Single-key numeric runs without NaNs merge
   in O(n) with a vectorized two-pointer (``np.searchsorted``
   interleave); multi-key or NaN-bearing keys fall back to a stable
   ``np.lexsort`` over the concatenated pair — both keep earlier-run
   rows first on ties, so the final order is IDENTICAL to the
   in-memory sort's (stable by original row position).

Host memory is the destination anyway — a larger-than-budget sorted
frame can only live spilled — so the merge's host footprint (two runs
per merge plus the output) is the natural cost, not a regression.
"""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Any, Dict, List, Mapping, Optional, Tuple

import numpy as np

from ..utils.logging import get_logger
from ..utils.tracing import counters, span
from .spill import array_nbytes, to_pinned_host

__all__ = ["external_sort"]

_log = get_logger("memory.external_sort")

# compiled run-sort programs keyed by (key sig, column sig); LRU-capped
_sort_cache: "OrderedDict[Tuple, Any]" = OrderedDict()
_sort_cache_lock = threading.Lock()
_SORT_CACHE_CAP = 32


def _transform_key(k: np.ndarray, descending: bool) -> np.ndarray:
    """Order-reversing host transform matching ``dsort``'s device one
    (``parallel.distributed._key_transform``): float negation, and
    bitwise-not for ints (never overflows). bfloat16 (numpy kind 'V')
    widens to float32 first — exact, order-preserving."""
    if np.dtype(k.dtype).kind == "V":  # ml_dtypes bfloat16
        k = k.astype(np.float32)
    if not descending:
        return k
    return -k if np.dtype(k.dtype).kind == "f" else ~k


def _merge_key(k: np.ndarray) -> np.ndarray:
    """A merge-comparable host view of a transformed key (bfloat16 is
    already widened by :func:`_transform_key`)."""
    return np.ascontiguousarray(k)


def _run_sort_fn(key_sig: Tuple, col_sig: Tuple, n_cols: int):
    """Cached jitted stable run sort: ascending over the transformed
    keys with the run-local position as the final tiebreak."""
    import jax
    import jax.numpy as jnp

    key = (key_sig, col_sig)
    with _sort_cache_lock:
        fn = _sort_cache.get(key)
        if fn is not None:
            _sort_cache.move_to_end(key)
            return fn

    def program(keys, cols):
        n = keys[0].shape[0]
        pos = jnp.arange(n)
        sorted_ops = jax.lax.sort(tuple(keys) + (pos,),
                                  num_keys=len(keys) + 1)
        order = sorted_ops[-1]
        outs = tuple(jnp.take(c, order, axis=0) for c in cols)
        return sorted_ops[:-1], outs, order

    fn = jax.jit(program)
    with _sort_cache_lock:
        fn = _sort_cache.setdefault(key, fn)
        _sort_cache.move_to_end(key)
        while len(_sort_cache) > _SORT_CACHE_CAP:
            _sort_cache.popitem(last=False)
    return fn


def _sort_one_run(keys_t: List[np.ndarray], cols: Dict[str, np.ndarray],
                  names: List[str], start: int, manager
                  ) -> Dict[str, Any]:
    """Sort one run on the device within budget; returns the run record
    spilled to pinned host buffers."""
    key_sig = tuple((a.shape, str(a.dtype)) for a in keys_t)
    col_sig = tuple((n, cols[n].shape, str(cols[n].dtype)) for n in names)
    fn = _run_sort_fn(key_sig, col_sig, len(names))
    run_bytes = (sum(a.nbytes for a in keys_t)
                 + sum(cols[n].nbytes for n in names))
    tok = 0
    if manager is not None:
        tok = manager.reserve(2 * run_bytes, op="memory.external_sort")
    try:
        with span("memory.run_sort"):
            s_keys, s_cols, order = fn(tuple(keys_t),
                                       tuple(cols[n] for n in names))
            # D2H into pinned buffers: the run leaves the device — this
            # IS the spill the external path exists to make
            rec = {
                "mk": [_merge_key(np.asarray(k)) for k in s_keys],
                "cols": {n: to_pinned_host(c)
                         for n, c in zip(names, s_cols)},
                "ids": np.asarray(order).astype(np.int64) + start,
            }
    finally:
        if manager is not None:
            manager.release(tok)
    if manager is not None:
        manager.note_spill(run_bytes, name=f"sort-run@{start}")
    return rec


def _merge_two(a: Dict[str, Any], b: Dict[str, Any],
               fast: bool) -> Dict[str, Any]:
    """Stable merge of two sorted runs; run ``a``'s rows (earlier
    original positions) come first on equal keys."""
    na = len(a["ids"])
    nb = len(b["ids"])
    if fast:
        ka, kb = a["mk"][0], b["mk"][0]
        pos_a = np.arange(na) + np.searchsorted(kb, ka, side="left")
        pos_b = np.arange(nb) + np.searchsorted(ka, kb, side="right")

        def interleave(x, y):
            out = np.empty((na + nb,) + x.shape[1:], x.dtype)
            out[pos_a] = x
            out[pos_b] = y
            return out
    else:
        cat = [np.concatenate([x, y]) for x, y in zip(a["mk"], b["mk"])]
        # np.lexsort is stable and the last key is primary; runs
        # concatenate a-first, so ties keep original order
        order = np.lexsort(tuple(reversed(cat)))

        def interleave(x, y):
            return np.concatenate([x, y])[order]

    return {
        "mk": [interleave(x, y) for x, y in zip(a["mk"], b["mk"])],
        "cols": {n: interleave(a["cols"][n], b["cols"][n])
                 for n in a["cols"]},
        "ids": interleave(a["ids"], b["ids"]),
    }


def external_sort(columns: Mapping[str, np.ndarray], keys: List[str],
                  descending: bool = False, manager=None,
                  run_bytes: Optional[int] = None
                  ) -> Tuple[Dict[str, np.ndarray], np.ndarray,
                             Dict[str, int]]:
    """Sort host ``columns`` by ``keys`` out-of-core (module docstring).

    Returns ``(sorted_columns, order, stats)`` where ``order`` maps each
    output row to its input row (host ride-along columns permute with
    it) and ``stats`` carries ``{"runs", "rows", "bytes"}``. The result
    order is bit-identical to a stable in-memory sort by the transformed
    keys — i.e. to ``dsort`` over the same rows.
    """
    names = sorted(columns)
    for k in keys:
        if k not in columns:
            raise KeyError(f"No sort key column {k!r}; columns: {names}")
    n = int(next(iter(columns.values())).shape[0]) if columns else 0
    total = sum(array_nbytes(columns[c]) for c in names)
    if run_bytes is None:
        budget = getattr(manager, "limit", None)
        run_bytes = max(budget // 4, 1) if budget else max(total, 1)
    row_bytes = max(total // max(n, 1), 1)
    run_rows = max(int(run_bytes) // row_bytes, 1)
    stats = {"runs": 0, "rows": n, "bytes": total}
    if n == 0:
        return ({c: np.asarray(columns[c]) for c in names},
                np.empty(0, np.int64), stats)

    keys_t = [_transform_key(np.asarray(columns[k]), descending)
              for k in keys]
    # the O(n) searchsorted merge needs a single totally-ordered key:
    # NaNs break the comparator, multi-key needs lexicographic ties
    fast = (len(keys) == 1
            and not (np.dtype(keys_t[0].dtype).kind == "f"
                     and bool(np.isnan(keys_t[0]).any())))

    runs: List[Dict[str, Any]] = []
    with span("memory.external_sort"):
        for start in range(0, n, run_rows):
            end = min(start + run_rows, n)
            run_cols = {c: np.ascontiguousarray(columns[c][start:end])
                        for c in names}
            run_keys = [k[start:end] for k in keys_t]
            runs.append(_sort_one_run(run_keys, run_cols, names, start,
                                      manager))
        stats["runs"] = len(runs)
        counters.inc("memory.external_sorts")
        counters.inc("memory.external_sort_runs", len(runs))
        _log.debug("external sort: %d rows (%d B) in %d run(s) of "
                   "<=%d rows", n, total, len(runs), run_rows)
        with span("memory.kway_merge"):
            while len(runs) > 1:
                nxt = []
                for i in range(0, len(runs) - 1, 2):
                    nxt.append(_merge_two(runs[i], runs[i + 1], fast))
                if len(runs) % 2:
                    nxt.append(runs[-1])
                runs = nxt
    merged = runs[0]
    return dict(merged["cols"]), merged["ids"], stats
