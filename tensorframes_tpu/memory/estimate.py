"""Byte/row estimates for frames and dispatches.

The admission side of the memory subsystem needs numbers *before* work
runs: how many bytes will this block dispatch touch, how big is this
frame likely to be once forced. Forced frames are exact (their cached
blocks are counted); lazy frames carry **hints** threaded through the
plan at construction time — source constructors record their actual
bytes, and every op scales its input's hint by the schema row-byte
ratio (an upper bound for ``filter``, exact for ``select``). The serve
scheduler's admission control consumes these through
:func:`frame_estimate`, which is what finally gives UNFORCED frames a
real admission estimate (the PR 5 follow-on).
"""

from __future__ import annotations

from typing import Any, Optional, Sequence, Tuple

import numpy as np

__all__ = ["array_nbytes", "column_nbytes", "block_nbytes",
           "blocks_estimate", "schema_row_bytes", "frame_estimate",
           "dist_frame_estimate", "exchange_buffer_bytes",
           "propagate_hints"]

from .spill import array_nbytes


def column_nbytes(col) -> int:
    """Host bytes of one column (ragged list columns sum their cells).
    The single definition the plan cost model and block accounting
    share."""
    if isinstance(col, np.ndarray):
        return int(col.nbytes)
    total = 0
    for cell in col:  # ragged / list-backed: per-cell arrays (or strings)
        total += array_nbytes(cell) or 8
    return total


def block_nbytes(block) -> int:
    """Host bytes of one block."""
    return sum(column_nbytes(col) for col in block.columns.values())


def blocks_estimate(blocks: Sequence) -> Tuple[int, int]:
    """Exact ``(rows, bytes)`` of a materialized block list."""
    rows = 0
    nbytes = 0
    for b in blocks:
        rows += int(b.num_rows)
        nbytes += block_nbytes(b)
    return rows, nbytes


def schema_row_bytes(schema) -> int:
    """Declared bytes per row of a schema: storage itemsize times the
    known cell size (Unknown dims count 1 — a deliberate floor);
    non-tensor (string) columns count a pointer."""
    total = 0
    for f in schema:
        if not f.dtype.tensor:
            total += 8
            continue
        cells = 1
        cell = f.cell_shape
        if cell is not None:
            for d in cell.dims:
                if isinstance(d, int) and d > 0:
                    cells *= d
        total += cells * int(np.dtype(f.dtype.np_storage).itemsize)
    return max(total, 1)


def frame_estimate(frame) -> Tuple[Optional[float], Optional[int]]:
    """Best-effort ``(rows, bytes)`` of a frame: exact when already
    forced (cached blocks); for UNFORCED frames with a logical-plan
    node, the plan's per-column cost model (measured leaf bytes
    propagated column-by-column through the chain — ``docs/plan.md``);
    else the construction-time scalar hint; ``(None, None)`` when
    nothing exists — admission and quotas only enforce what they can
    measure."""
    blocks = getattr(frame, "_cache", None)
    if blocks:
        rows, nbytes = blocks_estimate(blocks)
        return float(rows), nbytes
    node = getattr(frame, "_plan_node", None)
    if node is not None:
        try:
            rows, col_bytes = node.estimate()
        except Exception as e:
            from ..utils.logging import get_logger
            get_logger("memory.estimate").debug(
                "plan-node estimate failed (%s); falling back to the "
                "scalar hints", e)
            rows, col_bytes = None, None
        if col_bytes is not None:
            return (float(rows) if rows is not None else None,
                    int(sum(col_bytes.values())))
    rows = getattr(frame, "_rows_hint", None)
    nbytes = getattr(frame, "_bytes_hint", None)
    return (float(rows) if rows is not None else None,
            int(nbytes) if nbytes is not None else None)


def dist_frame_estimate(frame) -> Tuple[Optional[float], Optional[int]]:
    """Best-effort ``(rows, device_bytes)`` of a (possibly lazy)
    :class:`~..parallel.distributed.DistributedFrame`.

    A LAZY frame (``frame.lazy()`` chains, ``docs/plan.md``) answers
    from its distributed plan node WITHOUT forcing — source column
    bytes propagated op by op, filters priced at their observed
    selectivity once any forcing of the same predicate recorded one
    (the keeps-everything upper bound before that). Materialized frames
    count their columns exactly.
    """
    node = getattr(frame, "_dplan_node", None)
    forced = getattr(frame, "_forced", None)
    if node is not None and forced is None:
        try:
            rows, cols = node.estimate()
        except Exception as e:
            from ..utils.logging import get_logger
            get_logger("memory.estimate").debug(
                "distributed plan estimate failed (%s); counting the "
                "source instead", e)
            rows, cols = None, None
        if cols is not None:
            return (float(rows) if rows is not None else None,
                    int(sum(cols.values())))
        frame = getattr(frame, "_source", frame)
    elif forced is not None:
        frame = forced
    try:
        # value_nbytes reads sizes WITHOUT faulting spilled columns
        # back to the device — pricing a frame must never re-resident
        # it (the PR 8 fault-free-metadata rule)
        from .spill import value_nbytes
        total = 0
        for name in frame.schema.names:
            total += int(value_nbytes(frame.columns, name) or 0)
        return float(frame.num_rows), total
    except Exception:
        return None, None


def exchange_buffer_bytes(cell_specs: Sequence[Tuple[Tuple[int, ...], Any]],
                          shards: int, cap: int,
                          rowid_bytes: int = 0) -> int:
    """Device bytes a ``dexchange`` dispatch admits against the ledger:
    every shard scatters into ``shards`` static buckets of ``cap`` rows
    per column, and the ``all_to_all`` holds send + receive sides at
    once — ``shards * shards * cap`` rows of every exchanged column
    (plus the optional carried row-id lane), times two.

    ``cell_specs`` is ``[(cell_shape, dtype), ...]`` for the tensor
    columns riding the exchange.
    """
    per_row = int(rowid_bytes)
    for cell, dt in cell_specs:
        n = 1
        for d in cell:
            n *= int(d)
        per_row += n * int(np.dtype(dt).itemsize)
    return 2 * shards * shards * cap * per_row


def propagate_hints(src_frame, out_schema
                    ) -> Tuple[Optional[int], Optional[int]]:
    """``(rows_hint, bytes_hint)`` for an op's result frame: rows carry
    over; bytes scale by the schema row-byte ratio. An upper bound for
    row-dropping ops (filter), exact for column projections."""
    rows, nbytes = frame_estimate(src_frame)
    if nbytes is not None:
        src_schema = getattr(src_frame, "_schema", None)
        if src_schema is not None and src_schema is not out_schema:
            nbytes = int(nbytes * schema_row_bytes(out_schema)
                         / schema_row_bytes(src_schema))
    return (int(rows) if rows is not None else None, nbytes)
