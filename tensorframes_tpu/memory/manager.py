"""The device-memory budget ledger: admission, LRU spill, fault-back.

The engine used to find out about device-memory pressure the hard way:
the allocator failed, the error classified as OOM, and the block was
re-dispatched as two halves (``engine/executor.py`` — the *reactive*
``oom_split`` path). This module is the subsystem that acts **before**
the allocator fails:

- a **budget**: ``TFT_MEM_LIMIT_BYTES`` when set (the deterministic
  CPU-testing knob), else the backend's reported allocator limit
  (``observability.device.watermark()['limit_bytes']``) scaled by
  ``TFT_MEM_FRACTION``; neither known means *unlimited* and every entry
  point collapses to one attribute check;
- a **ledger** of device-resident bytes: transient dispatch
  reservations (reserved at executor submit, released at drain) plus
  registered *resident* spillables (a distributed frame's columns, a
  pipelined block's not-yet-drained device output) in LRU order;
- **admission**: every block dispatch reserves its estimated footprint
  against the budget; under pressure the ledger spills the coldest
  resident entries to pinned host buffers first (``memory.spills``),
  then waits (bounded) for in-flight reservations to drain, and only
  then — loudly — overshoots (``memory.overflow_admissions``), because
  a soft ledger must degrade to the pre-ledger behavior rather than
  fail work the allocator might still manage;
- **fault-back**: touching a spilled resident restores it to the
  device bit-identically (``memory.faults``) after making room.

The *proactive* split lives in the executor: when an admission estimate
alone exceeds the whole budget and the computation is row-local, the
block splits **before** dispatch (``memory.proactive_splits``) —
counted separately from the reactive ``oom_split`` path it replaces.

Thread model: one re-entrant lock guards the ledger; spill and fault
run under it (a spill performs a device-to-host read, so a concurrent
admission waits — latency, never a cycle: the device work it waits on
completes independently). See ``docs/memory.md``.
"""

from __future__ import annotations

import threading
import time
import weakref
from collections import OrderedDict
from typing import Any, Dict, Iterator, Optional

from ..observability import flight as _flight
from ..observability.events import add_event as _obs_event
from ..resilience import check_deadline, env_bool, env_float, env_int
from ..utils.logging import get_logger
from ..utils.tracing import counters

__all__ = ["MemoryManager", "DEFAULT_FRACTION", "DEFAULT_SORT_FRACTION"]

_log = get_logger("memory.manager")

# fraction of the backend-reported allocator limit the ledger budgets
# when TFT_MEM_LIMIT_BYTES is not set (headroom for XLA scratch)
DEFAULT_FRACTION = 0.85
# fraction of the budget above which dsort takes the external-memory
# path (runs + host k-way merge) instead of the in-device columnsort
DEFAULT_SORT_FRACTION = 0.5


class MemoryManager:
    """Budget ledger over device-resident bytes (module docstring).

    Resident entries are duck-typed spillables implementing
    ``mem_name() / mem_device_bytes() / mem_host_bytes() /
    mem_is_spilled() / mem_spill() -> freed / mem_fault() -> restored``
    (:mod:`~.spill` provides the stock implementations). The ledger
    holds them **weakly**: an entry dies with its owner (a collected
    frame releases its bytes with no unregister call), and the spilled
    host copy lives on the entry itself, so dropping the owner drops
    the host copy too.
    """

    def __init__(self, limit_bytes: Optional[int] = None,
                 spill: Optional[bool] = None):
        if limit_bytes is None:
            limit_bytes = env_int("TFT_MEM_LIMIT_BYTES", 0)
            if limit_bytes <= 0:
                limit_bytes = self._device_budget() or 0
        self.limit: Optional[int] = (int(limit_bytes)
                                     if limit_bytes and limit_bytes > 0
                                     else None)
        self.spill_enabled = (bool(spill) if spill is not None
                              else env_bool("TFT_MEM_SPILL", True))
        self._lock = threading.RLock()
        self._inflight = 0  # reserved transient dispatch bytes
        # LRU of resident spillables: id(obj) -> weakref (oldest first)
        self._resident: "OrderedDict[int, weakref.ref]" = OrderedDict()
        # host-side bookkeeping: frames whose forced block cache is live
        self._frame_caches: "weakref.WeakSet" = weakref.WeakSet()

    # -- budget ------------------------------------------------------------
    @staticmethod
    def _device_budget() -> Optional[int]:
        """Backend allocator limit x ``TFT_MEM_FRACTION``, or None when
        the backend reports no memory stats (CPU)."""
        try:
            from ..observability import device as _obs_device
            wm = _obs_device.watermark()
        except Exception as e:  # a failed probe means no enforceable budget
            _log.debug("device budget probe failed: %s", e)
            return None
        if not wm or not wm.get("limit_bytes"):
            return None
        frac = env_float("TFT_MEM_FRACTION", DEFAULT_FRACTION)
        return int(wm["limit_bytes"] * frac)

    @property
    def limited(self) -> bool:
        return self.limit is not None

    def would_overflow(self, nbytes: int) -> bool:
        """True when ``nbytes`` cannot fit even with everything else
        spilled and drained — the caller should split before dispatch."""
        return self.limit is not None and nbytes > self.limit

    def external_sort_threshold(self) -> Optional[int]:
        """Frame size above which dsort goes external (None = never)."""
        if self.limit is None:
            return None
        frac = env_float("TFT_MEM_SORT_FRACTION", DEFAULT_SORT_FRACTION)
        return int(self.limit * frac)

    # -- resident spillables ----------------------------------------------
    def _live_locked(self) -> Iterator[Any]:
        """Live resident entries, LRU first; prunes dead weakrefs."""
        dead = []
        for key, ref in self._resident.items():
            obj = ref()
            if obj is None:
                dead.append(key)
            else:
                yield obj
        for key in dead:
            self._resident.pop(key, None)

    def _device_in_use_locked(self) -> int:
        used = self._inflight
        for obj in list(self._live_locked()):
            used += int(obj.mem_device_bytes())
        return used

    def register(self, obj) -> None:
        """Add a resident spillable (MRU); registering over-budget
        content immediately spills the coldest entries to fit."""
        if self.limit is None:
            return
        with self._lock:
            self._resident[id(obj)] = weakref.ref(obj)
            self._make_room_locked(0)

    def touch(self, obj) -> None:
        """Mark ``obj`` most-recently-used; fault it back if spilled."""
        if self.limit is None:
            return
        with self._lock:
            key = id(obj)
            if key in self._resident:
                self._resident.move_to_end(key)
            if obj.mem_is_spilled():
                self._fault_locked(obj)

    def drop(self, obj) -> None:
        """Forget a resident entry (its bytes are the owner's problem
        again — e.g. a drained pipeline block)."""
        if self.limit is None:
            return
        with self._lock:
            self._resident.pop(id(obj), None)

    def _spill_locked(self, obj) -> int:
        name = obj.mem_name()
        try:
            freed = int(obj.mem_spill())
        except Exception as e:
            # a spillable that cannot spill must not wedge admission:
            # unregister it and move on (its bytes stay counted against
            # nothing — the owner still holds them)
            _log.warning("spill of %s failed (%s); dropping it from the "
                         "ledger", name, e)
            self._resident.pop(id(obj), None)
            return 0
        if freed:
            counters.inc("memory.spills")
            counters.inc("memory.spill_bytes", freed)
            _obs_event("spill", name=name, bytes=freed)
            _flight.record("memory.spill", name=name, bytes=freed,
                           limit=self.limit)
            _log.debug("spilled %s (%d B) to host", name, freed)
        return freed

    def _fault_locked(self, obj) -> int:
        self._make_room_locked(int(obj.mem_host_bytes()), exclude=obj)
        restored = int(obj.mem_fault())
        if restored:
            counters.inc("memory.faults")
            counters.inc("memory.fault_bytes", restored)
            _obs_event("fault", name=obj.mem_name(), bytes=restored)
            _flight.record("memory.fault", name=obj.mem_name(),
                           bytes=restored)
            _log.debug("faulted %s (%d B) back to device",
                       obj.mem_name(), restored)
        return restored

    def _make_room_locked(self, extra: int, exclude=None) -> bool:
        if self.limit is None:
            return True
        while self._device_in_use_locked() + extra > self.limit:
            victim = None
            if self.spill_enabled:
                for obj in self._live_locked():
                    if (obj is not exclude and not obj.mem_is_spilled()
                            and obj.mem_device_bytes() > 0):
                        victim = obj
                        break
            if victim is None:
                return False
            self._spill_locked(victim)
        return True

    def make_room(self, nbytes: int, exclude=None) -> bool:
        """Best-effort: spill cold residents until ``nbytes`` of budget
        headroom exists (used before a large ``device_put``)."""
        if self.limit is None:
            return True
        with self._lock:
            return self._make_room_locked(int(nbytes), exclude=exclude)

    # -- out-of-ledger spill accounting (external sort, stream state) ------
    def note_spill(self, nbytes: int, name: str) -> None:
        counters.inc("memory.spills")
        counters.inc("memory.spill_bytes", int(nbytes))
        _obs_event("spill", name=name, bytes=int(nbytes))
        _flight.record("memory.spill", name=name, bytes=int(nbytes),
                       limit=self.limit)

    def note_fault(self, nbytes: int, name: str) -> None:
        counters.inc("memory.faults")
        counters.inc("memory.fault_bytes", int(nbytes))
        _obs_event("fault", name=name, bytes=int(nbytes))
        _flight.record("memory.fault", name=name, bytes=int(nbytes))

    # -- admission ---------------------------------------------------------
    def try_reserve(self, nbytes: int, op: str = "dispatch"
                    ) -> Optional[int]:
        """Non-blocking admission: spill cold residents to make room and
        reserve, or return ``None`` under pressure (the async submit
        path then falls back to the synchronous admitted run)."""
        if self.limit is None:
            return 0
        nbytes = int(nbytes)
        with self._lock:
            if self._make_room_locked(nbytes):
                self._inflight += nbytes
                return nbytes
        return None

    def reserve(self, nbytes: int, op: str = "dispatch") -> int:
        """Blocking-but-bounded admission; never fails.

        Spills cold residents first; waits up to ``TFT_MEM_ADMIT_WAIT_S``
        (honoring the ambient resilience deadline) for in-flight
        reservations to drain; then admits OVER budget with a warning
        (``memory.overflow_admissions``) — a soft ledger must degrade to
        the pre-ledger behavior, not fail work the allocator might still
        manage. Returns the token to pass to :meth:`release`."""
        if self.limit is None:
            return 0
        nbytes = int(nbytes)
        if self.would_overflow(nbytes):
            # mathematically unable to fit: waiting for drains cannot
            # help — spill what we can for the allocator's sake and
            # overflow-admit immediately instead of stalling the full
            # wait budget on every such dispatch
            with self._lock:
                self._make_room_locked(0)
                self._inflight += nbytes
            counters.inc("memory.overflow_admissions")
            _flight.record("memory.overflow_admit", op=op, bytes=nbytes,
                           limit=self.limit, cause="request > budget")
            _log.warning(
                "admitting %d B for %s OVER the %d B device budget (the "
                "request alone exceeds it); split the input into "
                "smaller blocks to stay within budget", nbytes, op,
                self.limit)
            return nbytes
        tok = self.try_reserve(nbytes, op)
        if tok is not None:
            return tok
        counters.inc("memory.admission_waits")
        _obs_event("mem_wait", name=op, bytes=nbytes)
        _flight.record("memory.wait", op=op, bytes=nbytes,
                       limit=self.limit)
        budget = env_float("TFT_MEM_ADMIT_WAIT_S", 5.0)
        give_up = time.monotonic() + max(budget, 0.0)
        while time.monotonic() < give_up:
            check_deadline("memory.admit")
            time.sleep(0.002)
            tok = self.try_reserve(nbytes, op)
            if tok is not None:
                return tok
        counters.inc("memory.overflow_admissions")
        _flight.record("memory.overflow_admit", op=op, bytes=nbytes,
                       limit=self.limit,
                       cause=f"wait budget {budget:g}s exhausted")
        _log.warning(
            "admitting %d B for %s OVER the %d B device budget (nothing "
            "left to spill and in-flight work did not drain within "
            "%.1fs); the allocator may still manage — split the input "
            "into smaller blocks to stay within budget", nbytes, op,
            self.limit, budget)
        with self._lock:
            self._inflight += nbytes
        return nbytes

    def release(self, token: int) -> None:
        if token:
            with self._lock:
                self._inflight -= token

    def convert_reservation(self, token: int, obj) -> None:
        """Turn a dispatch reservation into a resident entry: the
        pipelined submit path registers its pending block as a spill
        candidate (its device output can be drained to host early)."""
        with self._lock:
            self._inflight -= token
            self._resident[id(obj)] = weakref.ref(obj)

    # -- introspection -----------------------------------------------------
    def headroom(self, fraction: float = 1.0) -> Optional[int]:
        """Bytes below ``limit * fraction``; spillable resident bytes
        count as reclaimable (admission can spill them). ``None`` when
        unlimited."""
        if self.limit is None:
            return None
        with self._lock:
            used = self._inflight
            if not self.spill_enabled:
                for obj in list(self._live_locked()):
                    used += int(obj.mem_device_bytes())
            return int(self.limit * fraction) - used

    def note_frame_cache(self, frame) -> None:
        self._frame_caches.add(frame)

    def forget_frame_cache(self, frame) -> None:
        self._frame_caches.discard(frame)

    def frame_cache_bytes(self) -> int:
        from .estimate import blocks_estimate
        total = 0
        for f in list(self._frame_caches):
            blocks = getattr(f, "_cache", None)
            if blocks:
                total += blocks_estimate(blocks)[1]
        return total

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            resident = spilled = resident_n = spilled_n = 0
            for obj in list(self._live_locked()):
                resident_n += 1
                resident += int(obj.mem_device_bytes())
                if obj.mem_is_spilled():
                    spilled_n += 1
                    spilled += int(obj.mem_host_bytes())
            return {"limit_bytes": self.limit or 0,
                    "inflight_bytes": self._inflight,
                    "resident_bytes": resident,
                    "resident_buffers": resident_n,
                    "spilled_bytes": spilled,
                    "spilled_buffers": spilled_n}

    def audit(self) -> list:
        """Ledger-balance + spillable-registry consistency check (the
        built-in memory auditor, ``resilience/invariants.py``). Returns
        violation messages: a negative in-flight reservation balance
        means a double release; a resident entry whose spilled flag
        disagrees with its byte accounting (device bytes while spilled,
        host bytes while resident) means the registry and the spillable
        have diverged — the fault-back path would restore from the
        wrong side."""
        out = []
        with self._lock:
            if self._inflight < 0:
                out.append(f"memory ledger in-flight reservations went "
                           f"negative ({self._inflight} B): a "
                           f"reservation released twice")
            for obj in list(self._live_locked()):
                name = obj.mem_name()
                spilled = obj.mem_is_spilled()
                dev = int(obj.mem_device_bytes())
                host = int(obj.mem_host_bytes())
                if spilled and dev > 0:
                    out.append(f"spillable {name!r} is marked spilled "
                               f"but still counts {dev} device bytes")
                if not spilled and host > 0:
                    out.append(f"spillable {name!r} is marked resident "
                               f"but still counts {host} host bytes")
        return out

    def __repr__(self):
        lim = "unlimited" if self.limit is None else f"{self.limit} B"
        return (f"MemoryManager(limit={lim}, "
                f"spill={'on' if self.spill_enabled else 'off'})")
