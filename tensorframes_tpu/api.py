"""Public user-facing API — reference parity surface.

Signature-level parity with the reference's Python module
(``/root/reference/src/main/python/tensorframes/core.py``): ``map_blocks``,
``map_rows``, ``reduce_blocks``, ``reduce_rows``, ``aggregate``, ``analyze``,
``print_schema``, ``block``, ``row``. Differences are deliberate TPU-native
redesigns:

- *fetches* are JAX-traceable callables, :class:`Computation` objects, or DSL
  nodes (``tensorframes_tpu.dsl``) — instead of TF graph elements;
- *dframe* is a :class:`~.frame.TensorFrame` — instead of a Spark DataFrame;
- reduce results unpack to numpy exactly like the reference's
  ``_unpack_row`` (``core.py:78-92``): one array for a single fetch, a list
  for several.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from .analysis import analyze, print_schema, explain
from .engine import ops as _ops
from .engine.compaction import DEFAULT_BUFFER_SIZE
from .frame import GroupedFrame, TensorFrame, frame

__all__ = [
    "map_blocks", "map_rows", "reduce_blocks", "reduce_rows", "aggregate",
    "filter_rows", "analyze", "print_schema", "explain", "block", "row",
    "frame", "submit",
]


def submit(dframe: TensorFrame, fetches=None, *, tenant: str = "default",
           deadline: Optional[float] = None,
           est_rows: Optional[float] = None,
           est_bytes: Optional[int] = None,
           scheduler=None):
    """Defer a frame's forcing to the multi-tenant query scheduler.

    Instead of forcing inline (``df.blocks()``), the query — ``dframe``
    with ``fetches`` applied via ``map_blocks`` when given — joins
    ``tenant``'s bounded FIFO queue on the process-default
    :class:`~.serve.QueryScheduler` (or an explicit ``scheduler``) and
    runs under its weighted-fair selection, HBM admission control, and
    quotas. Returns a :class:`~.serve.SubmittedQuery` future; a full
    queue or exhausted rows/sec budget raises a classified
    :class:`~.resilience.QueueFull` / :class:`~.resilience.OverQuota`
    immediately. ``deadline`` (seconds) bounds queue wait + execution.
    See ``docs/serving.md``.
    """
    from . import serve as _serve
    sched = scheduler if scheduler is not None \
        else _serve.default_scheduler()
    return sched.submit(dframe, fetches, tenant=tenant, deadline=deadline,
                        est_rows=est_rows, est_bytes=est_bytes)


def map_blocks(fetches, dframe: TensorFrame, trim: bool = False,
               executor=None) -> TensorFrame:
    """Transforms a DataFrame into another DataFrame block by block.

    Appends new columns (trim=False) or discards the inputs and returns only
    the computation's outputs (trim=True), in which case the number of rows
    may differ from the input block's. Lazy. Reference: ``core.py:172-218``.
    ``executor`` overrides the process-default :class:`BlockExecutor`.
    """
    return _ops.map_blocks(fetches, dframe, trim=trim, executor=executor)


def map_rows(fetches, dframe: TensorFrame, executor=None) -> TensorFrame:
    """Transforms a DataFrame row by row, adding one column per fetch.

    Works on cells (no leading block dimension); the only op that accepts
    rows whose vector cells vary in size. Lazy. Reference: ``core.py:132-170``.
    ``executor`` overrides the process-default padding executor.
    """
    return _ops.map_rows(fetches, dframe, executor=executor)


def _unpack(result: Dict[str, np.ndarray], names: Sequence[str]):
    vals = []
    for n in names:
        v = result[n]
        vals.append(v.item() if v.ndim == 0 else v)
    return vals[0] if len(vals) == 1 else vals


def reduce_blocks(fetches, dframe: TensorFrame, executor=None):
    """Reduces the frame to one row, block-at-a-time then across partials.

    Naming contract: each fetch ``z`` requires an input ``z_input`` of one
    rank higher. Eager; combine order unspecified. Returns a numpy value per
    fetch (a list if several). Reference: ``core.py:220-256``.
    ``executor`` overrides the process-default :class:`BlockExecutor`.
    """
    comp = _ops._reduce_computation(fetches, dframe.schema, ("_input",),
                                    block_level=True)
    out = _ops.reduce_blocks(comp, dframe, executor=executor)
    return _unpack(out, comp.output_names)


def reduce_rows(fetches, dframe: TensorFrame, executor=None):
    """Reduces the frame to one row, pairwise.

    Naming contract: each fetch ``z`` requires inputs ``z_1`` and ``z_2`` of
    z's own shape/dtype. Eager; order unspecified.
    Reference: ``core.py:95-130``.
    """
    comp = _ops._reduce_computation(fetches, dframe.schema, ("_1", "_2"),
                                    block_level=False)
    out = _ops.reduce_rows(comp, dframe, executor=executor)
    return _unpack(out, comp.output_names)


def filter_rows(predicate, dframe: TensorFrame,
                executor=None) -> TensorFrame:
    """Keeps the rows where ``predicate`` is true (nonzero). Lazy.

    ``predicate`` follows the map conventions (named args select columns)
    and must produce one boolean/integer vector of block length. Beyond
    the reference's own surface — its users filtered through Spark's
    relational API, which a standalone frame library must supply itself.
    """
    return _ops.filter_rows(predicate, dframe, executor=executor)


def aggregate(fetches, grouped_data: GroupedFrame,
              buffer_size: int = DEFAULT_BUFFER_SIZE,
              executor=None) -> TensorFrame:
    """Algebraic aggregation of the grouped data: one output row per key,
    fetch columns appended to the key columns.
    Reference: ``core.py:284-300``.
    """
    return _ops.aggregate(fetches, grouped_data, buffer_size=buffer_size,
                          executor=executor)


def block(df: TensorFrame, col_name: str, tf_name: Optional[str] = None):
    """DSL placeholder automatically shaped like **blocks** of a column.

    The leading dimension is always unknown — a block's row count varies and
    may be zero on empty partitions (reference ``core.py:302-315, 350-355``).
    """
    from . import dsl as _dsl
    field = df.schema.get(col_name)
    if field is None:
        raise ValueError(f"Could not find column with name {col_name!r}")
    shape = _ops._field_spec(field, True, "block placeholder").with_lead(-1)
    return _dsl.placeholder(field.dtype, shape, name=tf_name or col_name)


def row(df: TensorFrame, col_name: str, tf_name: Optional[str] = None):
    """DSL placeholder shaped like **one row** of a column
    (reference ``core.py:317-330``)."""
    from . import dsl as _dsl
    field = df.schema.get(col_name)
    if field is None:
        raise ValueError(f"Could not find column with name {col_name!r}")
    shape = _ops._field_spec(field, False, "row placeholder")
    return _dsl.placeholder(field.dtype, shape, name=tf_name or col_name)
