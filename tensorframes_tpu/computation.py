"""Computation IR: capture, validate, and serialize tensor programs.

This is the TPU-native replacement for the reference's GraphDef pipeline
(``/root/reference/src/main/scala/org/tensorframes/impl/TensorFlowOps.scala``):
where the reference serializes a TF ``GraphDef`` protobuf on the driver,
broadcasts the bytes, and parses them into a C++ session per executor, here a
user computation is a **pure JAX function over named arrays**, captured once
with shape polymorphism (``jax.export.symbolic_shape`` stands in for TF's
``None`` placeholder dims) and serialized as **StableHLO** bytes
(:meth:`Computation.serialize`), which any host can deserialize and compile
with XLA — no graph-parsing session required.

``analyze_graph`` is the analogue of ``TensorFlowOps.analyzeGraph``
(``TensorFlowOps.scala:84-161``): it validates a computation against shape
hints and reports input/output summaries *without executing it*, via
``jax.eval_shape`` (abstract interpretation replaces loading the graph into a
throwaway C++ session).
"""

from __future__ import annotations

import inspect
import json
import struct
from dataclasses import dataclass
from typing import Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import export as jax_export

from . import dtypes as _dt
from .shape import Shape, Unknown
from .utils.logging import get_logger

_log = get_logger("computation")

__all__ = [
    "TensorSpec",
    "GraphNodeSummary",
    "Computation",
    "analyze_graph",
]

_MAGIC = b"TFTPU1\x00"


@dataclass(frozen=True)
class TensorSpec:
    """Name + dtype + (possibly unknown) shape of a computation input/output."""

    name: str
    dtype: _dt.DType
    shape: Shape

    def __repr__(self):
        return f"{self.name}:{self.dtype.name}{self.shape!r}"

    def to_json(self) -> dict:
        return {"name": self.name, "dtype": self.dtype.name,
                "shape": list(self.shape.dims)}

    @staticmethod
    def from_json(d: dict) -> "TensorSpec":
        return TensorSpec(d["name"], _dt.by_name(d["dtype"]),
                          Shape(tuple(d["shape"])))


@dataclass(frozen=True)
class GraphNodeSummary:
    """Summary of one computation endpoint — the ``GraphNodeSummary``
    analogue (reference ``TensorFlowOps.scala:183-189``)."""

    name: str
    is_input: bool
    is_output: bool
    dtype: _dt.DType
    shape: Shape

    def __repr__(self):
        kind = "input" if self.is_input else "output"
        return f"[{kind}] {self.name} {self.dtype.name}{self.shape!r}"


def _sym_avals(inputs: Sequence[TensorSpec], share_lead_symbol: bool):
    """Build (possibly symbolic) ShapeDtypeStructs for the input specs.

    All inputs with an Unknown *leading* dim share one symbol when
    ``share_lead_symbol`` — the "rows in this block" dimension is one
    quantity across every column of a block. Other Unknown dims each get a
    fresh symbol.
    """
    scope = jax_export.SymbolicScope()
    lead = None
    fresh = 0
    avals = []
    any_symbolic = False
    for spec in inputs:
        dims = []
        for i, d in enumerate(spec.shape.dims):
            if d == Unknown:
                any_symbolic = True
                if i == 0 and share_lead_symbol:
                    if lead is None:
                        (lead,) = jax_export.symbolic_shape("_n", scope=scope)
                    dims.append(lead)
                else:
                    (s,) = jax_export.symbolic_shape(f"_d{fresh}", scope=scope)
                    fresh += 1
                    dims.append(s)
            else:
                dims.append(d)
        avals.append(jax.ShapeDtypeStruct(
            tuple(dims), _dt.device_dtype(spec.dtype)))
    return avals, any_symbolic


def _shape_from_aval(dims) -> Shape:
    return Shape(tuple(d if isinstance(d, int) else Unknown for d in dims))


def _dtype_from_np(np_dtype) -> _dt.DType:
    s = str(np.dtype(np_dtype)) if str(np_dtype) != "bfloat16" else "bfloat16"
    if s == "bfloat16":
        return _dt.bfloat16
    dt = _dt.from_numpy(np_dtype)
    if not dt.tensor:
        raise ValueError(
            f"Computation outputs must be numeric tensors, got {dt.name}")
    return dt


def _output_framework_dtype(np_dtype, input_specs: Sequence[TensorSpec]) -> _dt.DType:
    """Map an output's device dtype back to a framework dtype.

    On TPU, ``double`` columns compute in f32 (dtypes.device_dtype policy);
    an f32 output must then still be a ``double`` column, or the
    fetch/input same-dtype contract would break on TPU only. Rule: if some
    input's device dtype equals the output's device dtype, the output
    inherits the widest such input's framework dtype; otherwise the direct
    numpy mapping applies.
    """
    np_dtype = np.dtype(np_dtype) if str(np_dtype) != "bfloat16" else np_dtype
    cand = None
    for s in input_specs:
        if _dt.device_dtype(s.dtype) == np_dtype:
            if cand is None or s.dtype.priority > cand.priority:
                cand = s.dtype
    return cand if cand is not None else _dtype_from_np(np_dtype)


class Computation:
    """A captured tensor program: ordered named inputs -> named outputs.

    Outputs are canonically **sorted by name**, matching the reference
    engine's output-column ordering contract (``DebugRowOps.scala:344-355``).
    """

    def __init__(self, fn: Callable, inputs: Sequence[TensorSpec],
                 outputs: Sequence[TensorSpec]):
        self._fn = fn  # dict[str, Array] -> dict[str, Array]
        self.inputs: Tuple[TensorSpec, ...] = tuple(inputs)
        self.outputs: Tuple[TensorSpec, ...] = tuple(
            sorted(outputs, key=lambda s: s.name))
        self._input_index = {s.name: s for s in self.inputs}
        self._output_index = {s.name: s for s in self.outputs}

    # -- access ------------------------------------------------------------
    @property
    def input_names(self) -> List[str]:
        return [s.name for s in self.inputs]

    @property
    def output_names(self) -> List[str]:
        return [s.name for s in self.outputs]

    def input(self, name: str) -> TensorSpec:
        return self._input_index[name]

    def output(self, name: str) -> TensorSpec:
        return self._output_index[name]

    def __call__(self, arrays: Mapping[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
        missing = [n for n in self.input_names if n not in arrays]
        if missing:
            raise ValueError(f"Missing computation inputs: {missing}")
        return dict(self._fn({n: arrays[n] for n in self.input_names}))

    @property
    def fn(self) -> Callable:
        """The raw dict->dict JAX-traceable callable (for jit/shard_map)."""
        return self._fn

    def __repr__(self):
        ins = ", ".join(map(repr, self.inputs))
        outs = ", ".join(map(repr, self.outputs))
        return f"Computation({ins} -> {outs})"

    # -- construction ------------------------------------------------------
    @staticmethod
    def trace(fn: Callable,
              input_specs: Mapping[str, Tuple[_dt.DType, Shape]] | Sequence[TensorSpec],
              output_shapes: Optional[Mapping[str, Shape]] = None,
              share_lead_symbol: bool = True,
              takes_dict: Optional[bool] = None) -> "Computation":
        """Capture a Python function as a Computation.

        ``fn`` takes named arrays (one kw/positional arg per input, in
        signature order, or a single dict argument) and returns a dict of
        named outputs (a single array return is named after the function).
        Output shapes are inferred abstractly; ``output_shapes`` are optional
        driver-provided hints (the ``ShapeDescription`` analogue, reference
        ``ShapeDescription.scala:12-17``) used when symbolic inference cannot
        determine a shape.
        """
        if isinstance(input_specs, Mapping):
            specs = [TensorSpec(n, dt, sh) for n, (dt, sh) in input_specs.items()]
        else:
            specs = list(input_specs)

        if takes_dict is None:
            takes_dict = _fn_takes_dict(fn, len(specs))
        kw_only = _keyword_only_names(fn)

        def dict_fn(d: Mapping[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
            if takes_dict:
                out = fn(dict(d))
            else:
                args = [d[s.name] for s in specs if s.name not in kw_only]
                kwargs = {s.name: d[s.name] for s in specs
                          if s.name in kw_only}
                out = fn(*args, **kwargs)
            if not isinstance(out, Mapping):
                name = getattr(fn, "__name__", "output")
                if name == "<lambda>":
                    name = "output"
                out = {name: out}
            return {k: jnp.asarray(v) for k, v in out.items()}

        out_specs = _infer_outputs(dict_fn, specs, share_lead_symbol,
                                   output_shapes)
        return Computation(dict_fn, specs, out_specs)

    # -- serialization (StableHLO via jax.export) --------------------------
    def serialize(self) -> bytes:
        """Serialize to portable bytes: a JSON header (names/dtypes/shapes
        + native-execution metadata) + the raw StableHLO module (symbolic
        dims for Unknowns) + the full ``jax.export`` blob. The analogue of
        ``GraphDef.SerializeToString`` + ``ShapeDescription`` travelling
        together.

        The raw module section is what a jax-free executor host needs: the
        native core refines its symbolic dims at concrete shapes and
        compiles it without re-entering jax
        (``native/pjrt_core.cpp:refine_to_hlo_proto``; the reference's
        executors likewise ran shipped GraphDef bytes with no Python
        graph-authoring stack, ``TensorFlowOps.scala:46-52``). Lowered for
        both cpu and tpu so one blob runs on either host kind.
        """
        avals, _ = _sym_avals(self.inputs, share_lead_symbol=True)
        names = self.input_names

        def flat_fn(*args):
            return self._fn(dict(zip(names, args)))

        jitted = jax.jit(flat_fn)
        try:
            exported = jax_export.export(
                jitted, platforms=("cpu", "tpu"))(*avals)
        except Exception as e:
            # a computation that cannot lower for one of the platforms
            # still serializes for the local one (jax-path only); leave a
            # breadcrumb — the executor-side error ("lowered for (...)")
            # is far from this root cause otherwise
            _log.warning(
                "dual-platform (cpu,tpu) export failed (%s: %s); "
                "serializing for the local platform only", type(e).__name__,
                e)
            exported = jax_export.export(jitted)(*avals)
        module = exported.mlir_module_serialized
        blob = exported.serialize()
        header = json.dumps({
            "inputs": [s.to_json() for s in self.inputs],
            "outputs": [s.to_json() for s in self.outputs],
            "native": {
                "cc_version": exported.calling_convention_version,
                "platforms": list(exported.platforms),
                "module_len": len(module),
                # the TRACED argument dtypes (x64-policy-dependent): what
                # the module's parameters actually are, for jax-free hosts
                "arg_dtypes": [str(np.dtype(a.dtype)) for a in avals],
            },
        }).encode("utf-8")
        return (_MAGIC + struct.pack("<I", len(header)) + header
                + module + blob)

    @staticmethod
    def from_stablehlo(module, inputs: Sequence[TensorSpec],
                       outputs: Optional[Sequence[TensorSpec]] = None,
                       platforms: Optional[Sequence[str]] = None
                       ) -> "Computation":
        """Import a BARE StableHLO/MLIR module as a Computation.

        The foreign-graph entry: the reference accepted computations
        authored by an alien stack — real TF Python serialized a
        ``GraphDef`` and the engine ran it (reference ``core.py:37-40``,
        ``TensorFlowOps.scala:46-52``). Here any exporter that can produce
        StableHLO qualifies: ``module`` is MLIR text (``str``/``bytes``,
        e.g. ``jax.jit(fn).lower(...).as_text()`` from a DIFFERENT
        library/process) or a StableHLO portable-bytecode artifact. No
        ``TFTPU1`` header is involved; the signature comes from the
        explicit ``inputs`` specs (the ShapeDescription side-channel
        role). Shapes must be concrete — a bare module is a static graph;
        for symbolic row dims use this library's ``serialize`` format.

        ``outputs``: explicit specs, or ``None`` to infer shapes/dtypes
        abstractly (named ``out_0``, ``out_1``, ... in module result
        order). ``platforms`` defaults to the current backend; it must
        name the platform(s) the module was lowered for.

        The imported computation runs on BOTH executors: the jax path
        calls it through ``jax.export``'s calling convention, and the
        native C++ core compiles the same bytecode via its jax-free
        refine+compile pipeline (``_native_dynamic``).
        """
        for s in inputs:
            if any(d is None or d < 0 for d in s.shape.dims):
                raise ValueError(
                    f"from_stablehlo input {s.name!r} has unknown dims "
                    f"({s.shape}); bare modules are static graphs")
        if isinstance(module, str):
            module = module.encode()
        if not module.startswith(b"ML\xefR"):  # MLIR text -> bytecode
            try:
                from jaxlib.mlir.dialects import stablehlo as _sh
                version = _sh.get_minimum_version()
            except Exception:
                version = "0.9.0"
            from .utils.compat import serialize_stablehlo_artifact
            module = serialize_stablehlo_artifact(module, version)
        if platforms is None:
            platforms = (jax.default_backend(),)
        platforms = tuple("tpu" if p == "axon" else p for p in platforms)
        import jax.tree_util as jtu

        names = [s.name for s in inputs]
        in_avals = tuple(
            jax.core.ShapedArray(tuple(s.shape.dims),
                                 _dt.device_dtype(s.dtype))
            for s in inputs)
        n = len(inputs)

        def build_exported(out_avals):
            import dataclasses as _dc

            kwargs = dict(
                fun_name="foreign_stablehlo",
                in_tree=jtu.tree_structure((tuple(in_avals), {})),
                in_avals=in_avals,
                out_tree=jtu.tree_structure(tuple(out_avals)),
                out_avals=tuple(out_avals),
                in_shardings_hlo=(None,) * n,
                out_shardings_hlo=(None,) * len(out_avals),
                _has_named_shardings=False,
                _in_named_shardings=None,
                _out_named_shardings=None,
                nr_devices=1,
                platforms=tuple(platforms),
                ordered_effects=(),
                unordered_effects=(),
                disabled_safety_checks=(),
                mlir_module_serialized=module,
                calling_convention_version=(
                    jax_export.maximum_supported_calling_convention_version),
                module_kept_var_idx=tuple(range(n)),
                uses_global_constants=False,
                _get_vjp=None,
            )
            # the named-shardings triple is newer than some supported jax
            # builds; construct with whatever fields this Exported declares
            fields = {f.name for f in _dc.fields(jax_export.Exported)}
            return jax_export.Exported(
                **{k: v for k, v in kwargs.items() if k in fields})

        if outputs is None:
            # the module knows its results; discover them abstractly by
            # declaring one output and reading the real structure from
            # the deserialized module's main signature via eval_shape on
            # a permissive Exported is not possible — instead parse the
            # result count/types from the portable artifact's text form
            out_specs_raw = _module_result_avals(module)
            outputs = [
                TensorSpec(f"out_{i}", _dt.from_numpy(np.dtype(dt)),
                           Shape(*shape))
                for i, (shape, dt) in enumerate(out_specs_raw)]
        out_names = [s.name for s in outputs]
        out_avals = tuple(
            jax.core.ShapedArray(tuple(s.shape.dims),
                                 _dt.device_dtype(s.dtype))
            for s in outputs)
        exported = build_exported(out_avals)

        def dict_fn(d: Mapping[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
            res = exported.call(*[d[nm] for nm in names])
            if isinstance(res, (list, tuple)):
                return dict(zip(out_names, res))
            return {out_names[0]: res}

        comp = Computation(dict_fn, list(inputs), list(outputs))
        comp._native_dynamic = {
            "module": module,
            "cc_version":
                jax_export.maximum_supported_calling_convention_version,
            "platforms": tuple(platforms),
            "arg_dtypes": [str(np.dtype(_dt.device_dtype(s.dtype)))
                           for s in inputs],
        }
        return comp

    @staticmethod
    def deserialize(data: bytes) -> "Computation":
        if not data.startswith(_MAGIC):
            raise ValueError("Not a serialized tensorframes-tpu computation")
        off = len(_MAGIC)
        (hlen,) = struct.unpack_from("<I", data, off)
        off += 4
        header = json.loads(data[off:off + hlen].decode("utf-8"))
        payload = data[off + hlen:]
        native = header.get("native")
        native_dynamic = None
        if native:
            mlen = native["module_len"]
            native_dynamic = {
                "module": payload[:mlen],
                "cc_version": native["cc_version"],
                "platforms": tuple(native["platforms"]),
                "arg_dtypes": native.get("arg_dtypes"),
            }
            blob = payload[mlen:]
        else:  # pre-native blobs: jax.export payload only
            blob = payload
        exported = jax_export.deserialize(blob)
        inputs = [TensorSpec.from_json(d) for d in header["inputs"]]
        outputs = [TensorSpec.from_json(d) for d in header["outputs"]]
        names = [s.name for s in inputs]
        out_names = [s.name for s in outputs]

        def dict_fn(d: Mapping[str, jnp.ndarray]) -> Dict[str, jnp.ndarray]:
            res = exported.call(*[d[n] for n in names])
            # exported.call returns the original dict pytree when possible;
            # normalize both dict and flat-sequence forms.
            if isinstance(res, Mapping):
                return dict(res)
            if isinstance(res, (list, tuple)):
                return dict(zip(out_names, res))
            return {out_names[0]: res}

        comp = Computation(dict_fn, inputs, outputs)
        # the raw dynamic module lets the native core compile this
        # computation per signature without re-entering jax
        comp._native_dynamic = native_dynamic
        return comp


def _module_result_avals(bytecode: bytes):
    """(shape tuple, numpy dtype) per result of the module's @main, read
    from the portable artifact's text form — used when
    :meth:`Computation.from_stablehlo` is given no output specs."""
    import re

    from .utils.compat import deserialize_stablehlo_artifact

    text = deserialize_stablehlo_artifact(bytecode)
    if isinstance(text, bytes):
        text = text.decode("utf-8", errors="replace")
    m = re.search(
        r"@main\s*\((?:[^()]|\([^()]*\))*\)\s*->\s*"
        r"(\((?P<multi>.*?)\)|(?P<single>tensor<[^>]*>))\s*(\{|attributes)",
        text, re.S)
    if m is None:
        raise ValueError(
            "could not parse the module's @main result signature; pass "
            "explicit output specs to from_stablehlo")
    res = m.group("multi") if m.group("multi") is not None \
        else m.group("single")
    dt_map = {"f32": np.float32, "f64": np.float64, "i32": np.int32,
              "i64": np.int64, "i1": np.bool_, "ui32": np.uint32,
              "ui64": np.uint64, "bf16": "bfloat16"}
    declared = re.findall(r"tensor<[^>]*>", res)
    out = []
    for tm in re.finditer(r"tensor<([0-9x]*?)(" + "|".join(dt_map) + r")>",
                          res):
        dims_s, dt = tm.group(1), tm.group(2)
        dims = tuple(int(d) for d in dims_s.split("x") if d) \
            if dims_s else ()
        np_dt = dt_map[dt]
        if np_dt == "bfloat16":
            import ml_dtypes

            np_dt = ml_dtypes.bfloat16
        out.append((dims, np.dtype(np_dt)))
    if not out or len(out) != len(declared):
        # a result type this importer cannot map (i8/f16/complex/dynamic
        # dims...) must not silently drop outputs
        raise ValueError(
            f"module's @main declares {len(declared)} tensor result(s) "
            f"but only {len(out)} have element types this importer "
            f"understands; pass explicit output specs to from_stablehlo")
    return out


def _keyword_only_names(fn: Callable) -> frozenset:
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return frozenset()
    return frozenset(p.name for p in sig.parameters.values()
                     if p.kind == p.KEYWORD_ONLY)


def _fn_takes_dict(fn: Callable, n_inputs: int) -> bool:
    try:
        sig = inspect.signature(fn)
    except (TypeError, ValueError):
        return False
    params = [p for p in sig.parameters.values()
              if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD)]
    has_varargs = any(p.kind == p.VAR_POSITIONAL
                      for p in sig.parameters.values())
    if has_varargs:
        return False
    return len(params) == 1 and n_inputs != 1


def _infer_outputs(dict_fn: Callable, specs: Sequence[TensorSpec],
                   share_lead_symbol: bool,
                   output_shapes: Optional[Mapping[str, Shape]]) -> List[TensorSpec]:
    """Abstractly evaluate the computation to get output specs.

    Strategy 1: symbolic dims (exact propagation of the unknown row dim).
    Strategy 2 (fallback, when an op rejects symbolic dims): substitute a
    distinctive concrete size for each Unknown and mark output dims that
    equal it as Unknown — with driver hints taking precedence (the
    reference's hint mechanism existed for exactly this reason).
    """
    avals, any_symbolic = _sym_avals(specs, share_lead_symbol)
    out = None
    try:
        out = jax.eval_shape(dict_fn, dict(zip([s.name for s in specs], avals)))
    except Exception:
        # Only symbolic-dim-hostile computations may fall back; a failure on
        # fully-concrete avals is a real error in the user computation.
        if not any_symbolic:
            raise
    if out is None:
        # Fallback: probe with a sentinel size per unknown dim.
        SENTINEL = 61  # prime, unlikely to appear as a real static dim
        conc = []
        for spec, aval in zip(specs, avals):
            dims = tuple(SENTINEL if not isinstance(d, int) else d
                         for d in aval.shape)
            conc.append(jax.ShapeDtypeStruct(dims, aval.dtype))
        out = jax.eval_shape(dict_fn, {s.name: a for s, a in zip(specs, conc)})
        inferred = {name: Shape(tuple(Unknown if d == SENTINEL else d
                                      for d in out[name].shape))
                    for name in out}
    else:
        inferred = {name: _shape_from_aval(out[name].shape) for name in out}
    out_specs = []
    for name in sorted(out):
        sh = inferred[name]
        if output_shapes and name in output_shapes:
            hinted = output_shapes[name]
            if not sh.is_more_precise_than(hinted) and \
                    not hinted.is_more_precise_than(sh):
                raise ValueError(
                    f"Output {name!r}: hint {hinted} incompatible with "
                    f"inferred shape {sh}")
            sh = hinted if hinted.is_more_precise_than(sh) else sh
        out_specs.append(TensorSpec(
            name, _output_framework_dtype(out[name].dtype, specs), sh))
    return out_specs


def analyze_graph(comp: Computation,
                  shape_hints: Optional[Mapping[str, Shape]] = None,
                  fetches: Optional[Sequence[str]] = None) -> List[GraphNodeSummary]:
    """Validate a computation and summarize its endpoints without running it.

    The ``analyzeGraph`` analogue (reference ``TensorFlowOps.scala:84-161``):
    inputs are the computation's placeholders; outputs are the requested
    fetches (default: all outputs). Shape hints must be consistent with the
    captured specs; fetches must exist.
    """
    shape_hints = dict(shape_hints or {})
    fetch_names = list(fetches) if fetches is not None else comp.output_names
    summaries: List[GraphNodeSummary] = []
    for spec in comp.inputs:
        sh = spec.shape
        hint = shape_hints.get(spec.name)
        if hint is not None:
            if not hint.is_more_precise_than(sh) and \
                    not sh.is_more_precise_than(hint):
                raise ValueError(
                    f"Input {spec.name!r}: hint {hint} incompatible with "
                    f"declared shape {sh}")
            sh = hint if hint.is_more_precise_than(sh) else sh
        summaries.append(GraphNodeSummary(spec.name, True, False,
                                          spec.dtype, sh))
    for name in fetch_names:
        if name not in comp.output_names:
            raise ValueError(
                f"Fetch {name!r} not produced by computation; outputs: "
                f"{comp.output_names}")
        spec = comp.output(name)
        sh = spec.shape
        hint = shape_hints.get(name)
        if hint is not None and hint.is_more_precise_than(sh):
            sh = hint
        summaries.append(GraphNodeSummary(name, False, True, spec.dtype, sh))
    return summaries
