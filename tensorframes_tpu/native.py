"""ctypes binding to the C++ native runtime core (``native/libtfruntime.so``).

The reference's execution path is Scala over a **C++** runtime reached
through JNI (``TensorFlowOps.scala:46-64``, javacpp buffers in
``datatypes.scala:267``). Here XLA is the compute engine and this module
binds the native side of everything around it: threaded dtype-conversion
kernels (the hot ``astype`` in every host↔device marshal), row gather (the
aggregate shuffle), ragged-cell packing (CSR + pad-to-dense), and a pooled
aligned host allocator for staging buffers.

Everything degrades gracefully: if the library is not built (``make -C
native``) or ``TFT_DISABLE_NATIVE=1``, every function falls back to its
numpy equivalent — the same design as the reference's ``fastPath`` switch
(``DataOps.scala:40``).
"""

from __future__ import annotations

import ctypes
import os
from typing import List, Optional, Sequence, Tuple

import numpy as np

__all__ = [
    "available", "lib_version", "set_threads", "convert", "gather_rows",
    "pack_ragged", "pad_ragged", "empty_aligned", "pool_bytes", "pool_trim",
]

_DTYPE_CODES = {
    np.dtype(np.float32): 0,
    np.dtype(np.float64): 1,
    np.dtype(np.int32): 2,
    np.dtype(np.int64): 3,
    np.dtype(np.uint8): 4,
}

# below this many bytes the ctypes call overhead beats any threading win
_MIN_NATIVE_BYTES = 1 << 16

_lib: Optional[ctypes.CDLL] = None
_load_attempted = False


def _find_library() -> Optional[str]:
    cand = os.environ.get("TFT_NATIVE_LIB")
    if cand and os.path.exists(cand):
        return cand
    here = os.path.dirname(os.path.abspath(__file__))
    for rel in (os.path.join(here, "..", "native", "libtfruntime.so"),
                os.path.join(here, "libtfruntime.so")):
        p = os.path.abspath(rel)
        if os.path.exists(p):
            return p
    return None


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _load_attempted
    if _load_attempted:
        return _lib
    _load_attempted = True
    if os.environ.get("TFT_DISABLE_NATIVE"):
        return None
    path = _find_library()
    if path is None:
        return None
    try:
        lib = ctypes.CDLL(path)
    except OSError:
        return None
    c64 = ctypes.c_int64
    vp = ctypes.c_void_p
    lib.tfr_version.restype = ctypes.c_char_p
    lib.tfr_set_threads.argtypes = [ctypes.c_int]
    lib.tfr_get_threads.restype = ctypes.c_int
    lib.tfr_convert.argtypes = [vp, ctypes.c_int, vp, ctypes.c_int, c64]
    lib.tfr_convert.restype = ctypes.c_int
    lib.tfr_gather_rows.argtypes = [vp, c64, vp, c64, c64, vp]
    lib.tfr_gather_rows.restype = ctypes.c_int
    lib.tfr_pack_ragged.argtypes = [vp, vp, c64, vp, vp]
    lib.tfr_pack_ragged.restype = c64
    lib.tfr_pad_ragged.argtypes = [vp, vp, c64, c64, c64, vp, vp]
    lib.tfr_pad_ragged.restype = ctypes.c_int
    lib.tfr_alloc.argtypes = [c64]
    lib.tfr_alloc.restype = vp
    lib.tfr_free.argtypes = [vp, c64]
    lib.tfr_pool_bytes.restype = c64
    _lib = lib
    return _lib


def available() -> bool:
    return _load() is not None


def lib_version() -> Optional[str]:
    lib = _load()
    return lib.tfr_version().decode() if lib else None


def set_threads(n: int) -> None:
    lib = _load()
    if lib:
        lib.tfr_set_threads(int(n))


def _ptr(a: np.ndarray):
    return ctypes.c_void_p(a.ctypes.data)


def convert(src: np.ndarray, dst_dtype) -> np.ndarray:
    """dtype-convert an array (threaded native kernel for large buffers;
    numpy ``astype`` otherwise). Returns ``src`` unchanged if already right."""
    dst_dtype = np.dtype(dst_dtype)
    if src.dtype == dst_dtype:
        return src
    lib = _load()
    if (lib is None or src.nbytes < _MIN_NATIVE_BYTES
            or src.dtype not in _DTYPE_CODES
            or dst_dtype not in _DTYPE_CODES
            or not src.flags.c_contiguous):
        return src.astype(dst_dtype)
    dst = np.empty(src.shape, dst_dtype)
    rc = lib.tfr_convert(_ptr(src), _DTYPE_CODES[src.dtype], _ptr(dst),
                         _DTYPE_CODES[dst_dtype], src.size)
    if rc != 0:  # pragma: no cover — only on dtype-table drift
        return src.astype(dst_dtype)
    return dst


def gather_rows(src: np.ndarray, idx: np.ndarray) -> np.ndarray:
    """``src[idx]`` along axis 0 (threaded native row-gather for large
    blocks; numpy fancy-indexing fallback)."""
    lib = _load()
    idx = np.ascontiguousarray(idx, np.int64)
    if (lib is None or src.nbytes < _MIN_NATIVE_BYTES
            or not src.flags.c_contiguous or src.ndim < 1):
        # match the native kernel's contract exactly: no negative-wrapping
        if idx.size and (idx.min() < 0 or idx.max() >= src.shape[0]):
            raise IndexError("gather_rows: index out of bounds")
        return src[idx]
    row_bytes = src.dtype.itemsize * int(np.prod(src.shape[1:], dtype=np.int64))
    if row_bytes == 0:
        return src[idx]
    dst = np.empty((len(idx),) + src.shape[1:], src.dtype)
    rc = lib.tfr_gather_rows(_ptr(src), src.shape[0], _ptr(idx), len(idx),
                             row_bytes, _ptr(dst))
    if rc != 0:
        raise IndexError("gather_rows: index out of bounds")
    return dst


def _as_cell_list(cells: Sequence[np.ndarray], dtype) -> List[np.ndarray]:
    return [np.ascontiguousarray(c, dtype) for c in cells]


def pack_ragged(cells: Sequence[np.ndarray], dtype=None
                ) -> Tuple[np.ndarray, np.ndarray]:
    """Concatenate variable-length cells into (values, element_offsets) —
    the CSR layout for ragged columns."""
    if dtype is None:
        dtype = cells[0].dtype if len(cells) else np.float64
    dtype = np.dtype(dtype)
    arrs = _as_cell_list(cells, dtype)
    n = len(arrs)
    lib = _load()
    total_bytes = sum(a.nbytes for a in arrs)
    if lib is None or total_bytes < _MIN_NATIVE_BYTES:
        offsets = np.zeros(n + 1, np.int64)
        for i, a in enumerate(arrs):
            offsets[i + 1] = offsets[i] + a.size
        values = (np.concatenate([a.reshape(-1) for a in arrs])
                  if arrs else np.empty(0, dtype))
        return values.astype(dtype, copy=False), offsets
    ptrs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrs])
    nbytes = np.array([a.nbytes for a in arrs], np.int64)
    values = np.empty(total_bytes // dtype.itemsize, dtype)
    byte_offsets = np.empty(n + 1, np.int64)
    lib.tfr_pack_ragged(ctypes.cast(ptrs, ctypes.c_void_p), _ptr(nbytes), n,
                        _ptr(values), _ptr(byte_offsets))
    return values, byte_offsets // dtype.itemsize


def pad_ragged(cells: Sequence[np.ndarray], max_len: Optional[int] = None,
               dtype=None, with_mask: bool = True
               ) -> Tuple[np.ndarray, Optional[np.ndarray]]:
    """Pad 1-d variable-length cells to a dense ``[n, max_len]`` block plus
    a validity mask — the static-shape form XLA wants (SURVEY.md §7 hard
    part #1)."""
    if dtype is None:
        dtype = cells[0].dtype if len(cells) else np.float64
    dtype = np.dtype(dtype)
    arrs = _as_cell_list(cells, dtype)
    n = len(arrs)
    lens = np.array([a.size for a in arrs], np.int64)
    if max_len is None:
        max_len = int(lens.max()) if n else 0
    lib = _load()
    if lib is None or int(lens.sum()) * dtype.itemsize < _MIN_NATIVE_BYTES:
        dense = np.zeros((n, max_len), dtype)
        mask = np.zeros((n, max_len), np.uint8) if with_mask else None
        for i, a in enumerate(arrs):
            dense[i, :a.size] = a.reshape(-1)
            if mask is not None:
                mask[i, :a.size] = 1
        return dense, mask
    ptrs = (ctypes.c_void_p * n)(*[a.ctypes.data for a in arrs])
    dense = np.empty((n, max_len), dtype)
    mask = np.empty((n, max_len), np.uint8) if with_mask else None
    rc = lib.tfr_pad_ragged(
        ctypes.cast(ptrs, ctypes.c_void_p), _ptr(lens), n, max_len,
        dtype.itemsize, _ptr(dense),
        _ptr(mask) if mask is not None else None)
    if rc != 0:
        raise ValueError(f"pad_ragged: a cell exceeds max_len={max_len}")
    return dense, mask


def empty_aligned(shape, dtype) -> np.ndarray:
    """64-byte-aligned array from the native buffer pool (falls back to
    ``np.empty``). Reuse of hot staging sizes skips page-faulting fresh
    allocations on every block; the storage returns to the pool when the
    array is garbage-collected."""
    import weakref

    dtype = np.dtype(dtype)
    nbytes = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
    lib = _load()
    if lib is None or nbytes < _MIN_NATIVE_BYTES:
        return np.empty(shape, dtype)
    ptr = lib.tfr_alloc(nbytes)
    if not ptr:  # pragma: no cover — OOM
        return np.empty(shape, dtype)
    buf = (ctypes.c_char * nbytes).from_address(ptr)
    base = np.frombuffer(buf, dtype=dtype, count=nbytes // dtype.itemsize)
    weakref.finalize(base, lib.tfr_free, ptr, nbytes)
    return base.reshape(shape)


def pool_bytes() -> int:
    lib = _load()
    return int(lib.tfr_pool_bytes()) if lib else 0


def pool_trim() -> None:
    lib = _load()
    if lib:
        lib.tfr_pool_trim()
