"""Schema layer: fields, tensor metadata, and schema pretty-printing.

TPU-native re-design of the reference's column-metadata layer
(``/root/reference/src/main/scala/org/tensorframes/ColumnInformation.scala``,
``MetadataConstants.scala``, ``DataFrameInfo.scala``). The reference smuggles
tensor info (scalar type + block shape) through Spark ``StructField.metadata``
under the keys ``org.spartf.shape`` / ``org.sparktf.type``; here the DataFrame
is ours, so tensor info is a first-class part of :class:`Field`, with a
dict codec (:meth:`Field.to_meta` / :meth:`Field.from_meta`) preserved for
serialization and for parity with the metadata round-trip semantics.

Conventions carried over from the reference:

- the recorded shape of a column is the **block** shape: leading dim is the
  number of rows in a block (``Unknown`` in general), remaining dims are the
  cell shape (``ColumnInformation.scala:76-80``);
- a scalar column's block shape is ``[?]`` and can be inferred without a data
  scan; array columns have unknown cell shape until ``analyze`` stamps it;
- merging column info refines unknown dims with concrete ones
  (``ColumnInformation.merged``).
"""

from __future__ import annotations

from dataclasses import dataclass, replace
from typing import Dict, List, Optional, Sequence, Union

import numpy as np

from . import dtypes as _dt
from .shape import Shape, Unknown

__all__ = ["Field", "Schema", "SHAPE_KEY", "TYPE_KEY"]

# Metadata keys, kept wire-compatible in spirit with the reference
# (``MetadataConstants.scala:19,27`` — including its historical 'spartf' typo,
# which we do not reproduce; our keys are namespaced fresh).
SHAPE_KEY = "tensorframes.shape"
TYPE_KEY = "tensorframes.dtype"


@dataclass(frozen=True)
class Field:
    """One column: name, scalar dtype, and (optionally) its tensor structure.

    ``block_shape`` is the shape of a block of cells from this column — lead
    dim is the (usually unknown) row count. ``None`` means the tensor
    structure has not been determined (non-scalar column before ``analyze``).
    """

    name: str
    dtype: _dt.DType
    block_shape: Optional[Shape] = None
    nullable: bool = False
    # rank of the *SQL-level* value (0 scalar, 1 array, 2 array-of-array);
    # retained so un-analyzed array columns still print sensibly.
    sql_rank: int = 0

    # -- derived -----------------------------------------------------------
    @property
    def has_tensor_info(self) -> bool:
        return self.block_shape is not None

    @property
    def cell_shape(self) -> Optional[Shape]:
        if self.block_shape is None:
            return None
        return self.block_shape.tail

    def with_block_shape(self, shape: Shape) -> "Field":
        return replace(self, block_shape=shape, sql_rank=max(0, shape.ndim - 1))

    def merged(self, other: "Field") -> "Field":
        """Refine this field's info with another's (unknowns filled in).

        Conflicting concrete dims or dtypes raise rather than silently
        propagating one side into compiled-program shapes.
        """
        if other.block_shape is not None and self.dtype is not other.dtype:
            raise ValueError(
                f"Cannot merge field {self.name}: dtypes differ "
                f"({self.dtype} vs {other.dtype})"
            )
        if other.block_shape is None:
            return self
        if self.block_shape is None:
            return replace(self, block_shape=other.block_shape,
                           sql_rank=other.sql_rank)
        if self.block_shape.ndim != other.block_shape.ndim:
            raise ValueError(
                f"Cannot merge field {self.name}: ranks differ "
                f"({self.block_shape} vs {other.block_shape})"
            )
        dims = []
        for a, b in zip(self.block_shape.dims, other.block_shape.dims):
            if a != Unknown and b != Unknown and a != b:
                raise ValueError(
                    f"Cannot merge field {self.name}: dims conflict "
                    f"({self.block_shape} vs {other.block_shape})"
                )
            dims.append(b if a == Unknown else a)
        return replace(self, block_shape=Shape(tuple(dims)))

    # -- metadata codec ----------------------------------------------------
    def to_meta(self) -> Dict[str, object]:
        meta: Dict[str, object] = {}
        if self.block_shape is not None:
            meta[SHAPE_KEY] = list(self.block_shape.dims)
            meta[TYPE_KEY] = self.dtype.name
        return meta

    @staticmethod
    def from_meta(name: str, dtype: _dt.DType, meta: Dict[str, object],
                  sql_rank: int = 0, nullable: bool = False) -> "Field":
        shape = None
        if SHAPE_KEY in meta:
            shape = Shape(tuple(int(d) for d in meta[SHAPE_KEY]))
            tname = meta.get(TYPE_KEY)
            if tname is not None:
                dtype = _dt.by_name(str(tname))
            sql_rank = max(0, shape.ndim - 1)
        f = Field(name=name, dtype=dtype, block_shape=shape, nullable=nullable,
                  sql_rank=sql_rank)
        if shape is None and sql_rank == 0:
            # scalar columns always have derivable block shape [?]
            f = f.with_block_shape(Shape(Unknown))
        return f

    # -- display -----------------------------------------------------------
    def type_string(self) -> str:
        base = self.dtype.name
        for _ in range(self.sql_rank):
            base = f"array<{base}>"
        return base

    def describe(self) -> str:
        if self.block_shape is not None:
            return (f"{self.name}: {self.type_string()} "
                    f"(shape={self.block_shape})")
        return f"{self.name}: {self.type_string()} (no tensor info)"


def _field_for_scalar(name: str, dtype: _dt.DType) -> Field:
    return Field(name, dtype, block_shape=Shape(Unknown), sql_rank=0)


class Schema:
    """An ordered collection of fields."""

    __slots__ = ("_fields", "_index")

    def __init__(self, fields: Sequence[Field]):
        self._fields: List[Field] = list(fields)
        self._index = {f.name: i for i, f in enumerate(self._fields)}
        if len(self._index) != len(self._fields):
            seen, dup = set(), None
            for f in self._fields:
                if f.name in seen:
                    dup = f.name
                    break
                seen.add(f.name)
            raise ValueError(f"Duplicate column name {dup!r} in schema")

    # -- container protocol ------------------------------------------------
    def __iter__(self):
        return iter(self._fields)

    def __len__(self):
        return len(self._fields)

    def __contains__(self, name: str) -> bool:
        return name in self._index

    def __getitem__(self, key: Union[int, str]) -> Field:
        if isinstance(key, str):
            try:
                return self._fields[self._index[key]]
            except KeyError:
                raise KeyError(
                    f"No column {key!r}; columns: {self.names}"
                ) from None
        return self._fields[key]

    def __eq__(self, other):
        if isinstance(other, Schema):
            return self._fields == other._fields
        return NotImplemented

    def __repr__(self):
        return "Schema(" + ", ".join(f.describe() for f in self._fields) + ")"

    # -- accessors ---------------------------------------------------------
    @property
    def fields(self) -> List[Field]:
        return list(self._fields)

    @property
    def names(self) -> List[str]:
        return [f.name for f in self._fields]

    def get(self, name: str) -> Optional[Field]:
        i = self._index.get(name)
        return None if i is None else self._fields[i]

    def index_of(self, name: str) -> int:
        return self._index[name]

    # -- derivations -------------------------------------------------------
    def select(self, names: Sequence[str]) -> "Schema":
        return Schema([self[n] for n in names])

    def append(self, fields: Sequence[Field]) -> "Schema":
        return Schema(self._fields + list(fields))

    def replace_field(self, field: Field) -> "Schema":
        out = list(self._fields)
        out[self._index[field.name]] = field
        return Schema(out)

    def merged(self, other: "Schema") -> "Schema":
        """Refine tensor info field-by-field (names/positions must match)."""
        if self.names != other.names:
            raise ValueError(
                f"Schema mismatch: {self.names} vs {other.names}"
            )
        return Schema([a.merged(b) for a, b in zip(self._fields, other)])

    # -- display (the `explain` / print_schema analogue) -------------------
    def tree_string(self) -> str:
        lines = ["root"]
        for f in self._fields:
            extra = ""
            if f.block_shape is not None:
                extra = f" {f.dtype.name}{f.block_shape!r}"
            lines.append(
                f" |-- {f.name}: {f.type_string()} (nullable = "
                f"{str(f.nullable).lower()}){extra}"
            )
        return "\n".join(lines)

    # -- constructors ------------------------------------------------------
    @staticmethod
    def of(**cols: Union[str, _dt.DType]) -> "Schema":
        """Quick scalar-column schema: ``Schema.of(x='double', n='int')``."""
        fields = []
        for name, dt in cols.items():
            if isinstance(dt, str):
                dt = _dt.by_name(dt)
            fields.append(_field_for_scalar(name, dt))
        return Schema(fields)

    @staticmethod
    def from_numpy_columns(cols: Dict[str, np.ndarray]) -> "Schema":
        fields = []
        for name, arr in cols.items():
            arr = np.asarray(arr)
            if arr.dtype.kind == "O":
                # only string cells qualify; arbitrary objects are rejected
                # here, at construction, not deep in the engine
                if not all(isinstance(c, (str, bytes)) for c in arr.flat):
                    raise ValueError(
                        f"Column {name!r} holds non-string Python objects; "
                        f"supported: numeric tensors and strings")
                dt = _dt.string
            else:
                dt = _dt.from_numpy(arr.dtype)
            if not dt.tensor and arr.ndim != 1:
                raise ValueError(
                    f"Column {name!r}: string columns must be scalar "
                    f"(1-D), got array of rank {arr.ndim}")
            if not dt.tensor:
                fields.append(Field(name, dt, sql_rank=0))
                continue
            shape = Shape((Unknown,) + arr.shape[1:])
            fields.append(Field(name, dt, block_shape=shape,
                                sql_rank=arr.ndim - 1))
        return Schema(fields)
