"""tensorframes-tpu: manipulate columnar DataFrames with compiled tensor
programs on TPU.

A brand-new TPU-native framework with the capabilities of TensorFrames (the
reference at shobhit-agarwal/tensorframes): ``map_blocks``, ``map_rows``,
``reduce_blocks``, ``reduce_rows`` and keyed ``aggregate`` over blocks of
DataFrame rows, plus shape analysis (``analyze``, ``print_schema``) and an
embedded operator DSL. Computations are captured as JAX programs (serialized
as StableHLO), compiled by XLA, and executed on TPU; distribution rides a
``jax.sharding.Mesh`` with ICI collectives instead of a Spark reduce-tree.

Core API (parity with reference ``__init__.py:15-27``):

 - map_rows: adds extra columns one row at a time
 - map_blocks: adds extra columns block by block
 - reduce_rows: applies a transform on pairs of rows until one row is left
 - reduce_blocks: applies a transform on blocks of rows until one row is left
 - aggregate: algebraic aggregation of blocks of rows grouped by key
 - analyze: shape analysis of all numerical data in a dataframe
 - print_schema: prints the schema with tensor metadata

Auto-placeholder helpers (``block``, ``row``) build DSL placeholders shaped
from a DataFrame column, mirroring reference ``core.py:302-355``.
"""

from __future__ import annotations

__version__ = "0.1.0"

from .shape import Shape, Unknown
from . import dtypes
from . import utils
from .utils.logging import initialize_logging
from .utils.tracing import dump_stats
from .schema import Field, Schema
from .frame import Block, GroupedFrame, Row, TensorFrame
from . import observability
from .observability import doctor, health, last_query_report, regressions, why
from .observability.history import history, postmortem
from .observability.timeline import timeline
from .computation import Computation, TensorSpec, analyze_graph
from .api import (
    aggregate, analyze, block, explain, filter_rows, frame, map_blocks,
    map_rows, print_schema, reduce_blocks, reduce_rows, row, submit,
)
from . import builder
from . import io
from . import memory
from . import relational
from . import serve
from . import stream
from .relational import (approx_distinct, approx_quantile, approx_top_k,
                         join)
from .serve import quarantine_status, serve_report, unquarantine

__all__ = [
    "io",
    "Shape",
    "Unknown",
    "Field",
    "Schema",
    "dtypes",
    "Block",
    "GroupedFrame",
    "Row",
    "TensorFrame",
    "Computation",
    "TensorSpec",
    "analyze_graph",
    "map_rows",
    "map_blocks",
    "reduce_rows",
    "reduce_blocks",
    "filter_rows",
    "aggregate",
    "analyze",
    "print_schema",
    "explain",
    "block",
    "row",
    "frame",
    "utils",
    "builder",
    "initialize_logging",
    "observability",
    "last_query_report",
    "why",
    "health",
    "doctor",
    "timeline",
    "history",
    "postmortem",
    "regressions",
    "dump_stats",
    "memory",
    "relational",
    "join",
    "approx_distinct",
    "approx_quantile",
    "approx_top_k",
    "serve",
    "submit",
    "serve_report",
    "unquarantine",
    "quarantine_status",
    "stream",
    "__version__",
]
