"""ResNet-50 batch inference over an image-tensor column (BASELINE config 4).

The reference's north star ("ResNet-50 frozen-graph batch inference over
image-tensor DataFrame column", ``BASELINE.json``) maps a frozen network over
blocks of rows — exactly ``map_blocks(trim=True)`` with the network's
parameters closed over as constants, the way the reference would broadcast a
frozen ``GraphDef``.

Pure-JAX implementation, NHWC layout (TPU-native: channels-last feeds the
MXU's 128-lane minor dimension), inference-mode batch norm folded to a
scale/bias affine at parameter-preparation time so each residual branch is
conv → affine → relu — a chain XLA fuses into the convolution.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["ResNet50"]

Params = Dict[str, Any]

# Stage specification for ResNet-50: (blocks, bottleneck width)
_STAGES: Tuple[Tuple[int, int], ...] = ((3, 64), (4, 128), (6, 256), (3, 512))
_EXPANSION = 4


def _conv(x, w, stride=1):
    return jax.lax.conv_general_dilated(
        x, w, window_strides=(stride, stride), padding="SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _affine(x, p):
    # inference-mode batch norm, pre-folded to y = x*scale + bias
    return x * p["scale"] + p["bias"]


class ResNet50:
    """Frozen ResNet-50 classifier, ``[N, H, W, 3] -> [N, num_classes]``.

    ``init`` builds a randomly-initialized frozen parameter pytree (He-normal
    convs, identity affines); real weights can be loaded into the same tree
    layout. ``apply`` is a pure jit-friendly function.
    """

    def __init__(self, num_classes: int = 1000,
                 dtype: jnp.dtype = jnp.float32):
        self.num_classes = int(num_classes)
        self.dtype = dtype

    # -- parameters ---------------------------------------------------------
    def init(self, rng: Optional[jax.Array] = None) -> Params:
        if rng is None:
            rng = jax.random.PRNGKey(0)
        keys = iter(jax.random.split(rng, 64))

        def conv_p(kh, kw, cin, cout):
            fan_in = kh * kw * cin
            w = jax.random.normal(next(keys), (kh, kw, cin, cout),
                                  self.dtype)
            return w * np.sqrt(2.0 / fan_in).astype(np.float32)

        def affine_p(c):
            return {"scale": jnp.ones((c,), self.dtype),
                    "bias": jnp.zeros((c,), self.dtype)}

        params: Params = {
            "stem": {"conv": conv_p(7, 7, 3, 64), "bn": affine_p(64)},
            "stages": [],
        }
        cin = 64
        for stage_i, (blocks, width) in enumerate(_STAGES):
            stage: List[Params] = []
            cout = width * _EXPANSION
            for block_i in range(blocks):
                stride = 2 if (block_i == 0 and stage_i > 0) else 1
                blk: Params = {
                    "conv1": conv_p(1, 1, cin, width), "bn1": affine_p(width),
                    "conv2": conv_p(3, 3, width, width),
                    "bn2": affine_p(width),
                    "conv3": conv_p(1, 1, width, cout), "bn3": affine_p(cout),
                }
                if block_i == 0:
                    blk["proj"] = conv_p(1, 1, cin, cout)
                    blk["proj_bn"] = affine_p(cout)
                stage.append(blk)
                cin = cout
            params["stages"].append(stage)
        params["head"] = {
            "w": jax.random.normal(next(keys),
                                   (cin, self.num_classes),
                                   self.dtype) * 0.01,
            "b": jnp.zeros((self.num_classes,), self.dtype),
        }
        return params

    # -- forward ------------------------------------------------------------
    def _bottleneck(self, x, blk, stride):
        y = jax.nn.relu(_affine(_conv(x, blk["conv1"]), blk["bn1"]))
        y = jax.nn.relu(_affine(_conv(y, blk["conv2"], stride), blk["bn2"]))
        y = _affine(_conv(y, blk["conv3"]), blk["bn3"])
        if "proj" in blk:
            x = _affine(_conv(x, blk["proj"], stride), blk["proj_bn"])
        return jax.nn.relu(x + y)

    def apply(self, params: Params, images: jax.Array) -> jax.Array:
        """images: [N, H, W, 3] (NHWC) -> logits [N, num_classes].

        Defined as the composition of :meth:`stage_fns`, so the staged
        (per-stage-compiled) path can never diverge from this one."""
        x = images
        for f in self.stage_fns():
            x = f(params, x)
        return x

    def stage_fns(self):
        """The forward pass as a chain of per-stage callables
        ``f(params, x) -> x`` whose composition equals :meth:`apply`.

        Staged compilation exists for relay-fragile transports: shipping
        ResNet-50 as ONE StableHLO module has broken this environment's
        tunnelled `remote_compile` mid-response (BASELINE.md config 4,
        r3); six ~5x-smaller payloads survive where one large one dies,
        and with the persistent compilation cache a dropped attempt
        resumes from the stages already compiled instead of from zero.
        """
        def stem(params, x):
            x = x.astype(self.dtype)
            x = jax.nn.relu(_affine(_conv(x, params["stem"]["conv"], 2),
                                    params["stem"]["bn"]))
            return jax.lax.reduce_window(
                x, -jnp.inf, jax.lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                "SAME")

        def make_stage(stage_i):
            def stage(params, x):
                for block_i, blk in enumerate(params["stages"][stage_i]):
                    stride = 2 if (block_i == 0 and stage_i > 0) else 1
                    x = self._bottleneck(x, blk, stride)
                return x
            return stage

        def head(params, x):
            x = jnp.mean(x, axis=(1, 2))
            return x @ params["head"]["w"] + params["head"]["b"]

        return [stem] + [make_stage(i) for i in range(len(_STAGES))] \
            + [head]

    # -- DataFrame formulation (the BASELINE workload) ----------------------
    def infer_via_frame(self, params: Params, df, image_col: str = "image",
                        trim: bool = True):
        """Batch inference through ``map_blocks``: the frozen parameters
        ride into the computation as closed-over constants (the broadcast-
        the-frozen-graph pattern). Returns a lazy frame with a ``logits``
        column."""
        apply = self.apply

        def fn_impl(**cols):
            return {"logits": apply(params, cols[image_col])}

        from .logreg import _named_args_fn
        return df.map_blocks(_named_args_fn(fn_impl, [image_col]), trim=trim)
