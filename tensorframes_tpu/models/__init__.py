"""Model zoo for the framework's acceptance workloads.

The reference has no model zoo (SURVEY.md: "What the reference is NOT"), but
its BASELINE configs define model workloads the TPU build must run through
the DataFrame ops:

- config 4: ResNet-50 frozen-graph batch inference over an image-tensor
  column (:mod:`.resnet`);
- config 5: logistic-regression gradient step via ``map_blocks`` +
  ``reduce_blocks`` allreduce on a v5e-8 (:mod:`.logreg`).

:mod:`.transformer` is the framework's flagship long-context model: a
decoder-only LM whose attention can run as ring attention over a mesh
``seq`` axis (sequence parallelism) with tensor-parallel weights over a
``model`` axis and data-parallel batch — exercising every mesh axis the
parallel layer provides.

Models are pure-JAX: parameters are nested-dict pytrees, forward passes are
jit-friendly pure functions. This keeps sharding fully explicit
(``NamedSharding`` per leaf) instead of hiding it behind a module library.
"""

from .logreg import LogisticRegression
from .resnet import ResNet50
from .transformer import TransformerLM, TransformerConfig

__all__ = ["LogisticRegression", "ResNet50", "TransformerLM",
           "TransformerConfig"]
