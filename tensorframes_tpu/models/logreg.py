"""Logistic regression as a DataFrame workload (BASELINE config 5).

The training step is expressed through the framework's own ops, the way the
reference's k-means demo drives Spark (``kmeans_demo.py:47-148``): the model
is broadcast into the computation as constants, ``map_blocks`` scores blocks
of rows, and the gradient is a ``reduce_blocks`` — which on a mesh becomes a
``psum`` allreduce over the data axis (the reference's Spark tree-reduce,
re-expressed as an ICI collective; SURVEY.md §2.3).
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..frame import TensorFrame
from ..parallel.mesh import DeviceMesh

__all__ = ["LogisticRegression"]


class LogisticRegression:
    """Binary logistic regression over a feature-vector column.

    Parameters are a ``{"w": [d], "b": []}`` pytree. All methods are pure;
    the instance only carries hyperparameters.
    """

    def __init__(self, num_features: int, l2: float = 0.0):
        self.num_features = int(num_features)
        self.l2 = float(l2)

    def init(self, rng: Optional[jax.Array] = None) -> Dict[str, jax.Array]:
        if rng is None:
            rng = jax.random.PRNGKey(0)
        w = jax.random.normal(rng, (self.num_features,), jnp.float32) * 0.01
        return {"w": w, "b": jnp.zeros((), jnp.float32)}

    # -- pure model math ----------------------------------------------------
    def logits(self, params, x: jax.Array) -> jax.Array:
        return x @ params["w"] + params["b"]

    def loss(self, params, x: jax.Array, y: jax.Array) -> jax.Array:
        """Mean sigmoid cross-entropy over the batch (+ L2)."""
        z = self.logits(params, x)
        # log(1+e^z) - y*z, numerically stable
        nll = jnp.maximum(z, 0.0) - z * y + jnp.log1p(jnp.exp(-jnp.abs(z)))
        reg = 0.5 * self.l2 * jnp.sum(params["w"] ** 2)
        return jnp.mean(nll) + reg

    def grads(self, params, x: jax.Array, y: jax.Array):
        return jax.grad(self.loss)(params, x, y)

    def sgd_step(self, params, x, y, lr: float = 0.1):
        g = self.grads(params, x, y)
        return jax.tree_util.tree_map(lambda p, gi: p - lr * gi, params, g)

    # -- DataFrame-op formulation (the BASELINE workload) -------------------
    def gradient_via_frame(self, params, df: TensorFrame,
                           features: str = "features", label: str = "label",
                           ) -> Tuple[Dict[str, np.ndarray], float]:
        """One gradient evaluation driven entirely through the six-op API.

        ``map_blocks`` computes per-row gradient contributions (the model's
        parameters ride into the jitted computation as closed-over
        constants — the reference's broadcast-the-graph step), then
        ``reduce_blocks`` sums them across partitions. Returns
        ``({'w': gw, 'b': gb}, loss)``.
        """
        w = np.asarray(params["w"])
        b = np.asarray(params["b"])
        n_total = df.count()

        def per_row(**cols):
            x, y = cols[features], cols[label]
            z = x @ w + b
            p = jax.nn.sigmoid(z)
            err = (p - y)[:, None]
            gw = err * x                       # [n, d] per-row grad
            gb = err[:, 0]
            nll = (jnp.maximum(z, 0.0) - z * y
                   + jnp.log1p(jnp.exp(-jnp.abs(z))))
            return {"gw": gw, "gb": gb, "nll": nll}

        fn = _named_args_fn(per_row, [features, label])
        scored = df.map_blocks(fn, trim=True)
        sums = scored.reduce_blocks(
            lambda gw_input, gb_input, nll_input: {
                "gw": gw_input.sum(axis=0),
                "gb": gb_input.sum(axis=0),
                "nll": nll_input.sum(axis=0)})
        gb_s, gw_s, nll_s = sums  # fetches come back sorted by name
        grad = {"w": gw_s / n_total + self.l2 * w,
                "b": gb_s / n_total}
        loss = float(nll_s / n_total + 0.5 * self.l2 * np.sum(w ** 2))
        return grad, loss

    def fit_via_frame(self, df: TensorFrame, steps: int = 10,
                      lr: float = 0.5, features: str = "features",
                      label: str = "label", params=None):
        """Driver-side iteration loop, k-means-demo style: state lives on
        the host between rounds, re-embedded as constants each round."""
        params = params if params is not None else self.init()
        params = {k: np.asarray(v) for k, v in params.items()}
        losses = []
        for _ in range(steps):
            grad, loss = self.gradient_via_frame(
                params, df, features=features, label=label)
            params = {"w": params["w"] - lr * grad["w"],
                      "b": params["b"] - lr * grad["b"]}
            losses.append(loss)
        return params, losses

    # -- mesh-parallel single-program step (the v5e-8 path) -----------------
    def make_sharded_train_step(self, mesh: DeviceMesh, lr: float = 0.1):
        """Data-parallel train step as ONE compiled program over the mesh.

        Batch enters row-sharded over the data axis; the gradient allreduce
        is the ``jnp.mean`` XLA lowers to a ``psum`` across shards — the
        reference's Spark tree-reduce as an ICI collective.
        """
        from jax.sharding import NamedSharding, PartitionSpec as P

        data_sharding = NamedSharding(mesh.mesh, P(mesh.data_axis))
        repl = NamedSharding(mesh.mesh, P())

        def step(params, x, y):
            g = self.grads(params, x, y)
            new = jax.tree_util.tree_map(lambda p, gi: p - lr * gi,
                                         params, g)
            return new, self.loss(params, x, y)

        return jax.jit(
            step,
            in_shardings=(jax.tree_util.tree_map(lambda _: repl,
                                                 {"w": 0, "b": 0}),
                          data_sharding, data_sharding),
            out_shardings=(jax.tree_util.tree_map(lambda _: repl,
                                                  {"w": 0, "b": 0}), repl))


def _named_args_fn(kw_fn, names):
    """Build a positional function whose parameter names are ``names`` —
    the engine derives computation inputs from parameter names
    (``engine/ops.py:_callable_input_names``)."""
    args = ", ".join(names)
    kwargs = ", ".join(f"{n!r}: {n}" for n in names)
    ns = {"_kw_fn": kw_fn}
    exec(f"def _f({args}):\n    return _kw_fn(**{{{kwargs}}})\n", ns)
    return ns["_f"]
