"""Decoder-only transformer LM — the framework's flagship long-context model.

The reference predates attention entirely (SURVEY.md §5: "no attention, no
sequences"), but long-context and distributed execution are first-class in
this framework, so the flagship model exercises every mesh axis the parallel
layer provides in ONE compiled training step:

- **data parallelism**: batch row-sharded over the ``data`` axis (the
  reference's partition parallelism);
- **tensor parallelism**: attention heads and MLP hidden dim sharded over
  the ``model`` axis, Megatron-style — XLA inserts the two allreduces per
  layer from the ``NamedSharding`` annotations alone;
- **sequence parallelism**: activations sequence-sharded over the ``seq``
  axis with :func:`~tensorframes_tpu.parallel.ring.ring_attention` rotating
  k/v blocks around the ICI ring (peak per-chip memory O(S/n)).

Pure JAX: params are nested-dict pytrees, rotary positions (no position
table — computed from global indices, so sequence sharding needs no
parameter surgery), pre-LN blocks, bf16-friendly.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ..parallel.mesh import DeviceMesh
from ..parallel.ring import ring_attention

__all__ = ["TransformerConfig", "TransformerLM"]

Params = Dict[str, Any]


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int = 32000
    d_model: int = 512
    n_heads: int = 8
    n_layers: int = 6
    d_ff: int = 2048
    rope_base: float = 10000.0
    dtype: Any = jnp.float32
    # MoE: >0 replaces every layer's dense FFN with a Switch top-1 MoE of
    # this many experts (expert-parallel over a mesh axis when given)
    num_experts: int = 0
    expert_capacity_factor: float = 1.25
    aux_loss_weight: float = 0.01

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


def _rope(x: jax.Array, positions: jax.Array, base: float) -> jax.Array:
    """Rotary position embedding. x: [..., S, H, D], positions: [S]."""
    d = x.shape[-1]
    half = d // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    angles = positions[:, None].astype(jnp.float32) * freqs  # [S, half]
    cos = jnp.cos(angles)[None, :, None, :].astype(x.dtype)
    sin = jnp.sin(angles)[None, :, None, :].astype(x.dtype)
    x1, x2 = x[..., :half], x[..., half:]
    return jnp.concatenate([x1 * cos - x2 * sin,
                            x1 * sin + x2 * cos], axis=-1)


def _rms_norm(x: jax.Array, scale: jax.Array, eps: float = 1e-6) -> jax.Array:
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), axis=-1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + eps)
            ).astype(x.dtype) * scale


class TransformerLM:
    """Causal LM: tokens [B, S] (int32) -> logits [B, S, vocab]."""

    def __init__(self, config: TransformerConfig):
        self.config = config

    # -- parameters ---------------------------------------------------------
    def init(self, rng: Optional[jax.Array] = None) -> Params:
        c = self.config
        if rng is None:
            rng = jax.random.PRNGKey(0)
        n_keys = 2 + 6 * c.n_layers
        keys = iter(jax.random.split(rng, n_keys))

        def dense(shape, fan_in):
            return (jax.random.normal(next(keys), shape, c.dtype)
                    * np.sqrt(1.0 / fan_in).astype(np.float32))

        H, D, Dh, F = c.n_heads, c.d_model, c.head_dim, c.d_ff
        layers = []
        for _ in range(c.n_layers):
            lp = {
                "ln1": jnp.ones((D,), c.dtype),
                "wq": dense((D, H, Dh), D),
                "wk": dense((D, H, Dh), D),
                "wv": dense((D, H, Dh), D),
                "wo": dense((H, Dh, D), D),
                "ln2": jnp.ones((D,), c.dtype),
            }
            if c.num_experts > 0:
                from ..parallel.moe import init_switch_ffn
                lp["moe"] = init_switch_ffn(next(keys), D, F,
                                            c.num_experts, c.dtype)
            else:
                lp["w1"] = dense((D, F), D)
                lp["w2"] = dense((F, D), F)
            layers.append(lp)
        return {
            "embed": dense((c.vocab_size, D), D) * np.float32(np.sqrt(D)),
            "layers": layers,
            "ln_f": jnp.ones((D,), c.dtype),
            "head": dense((D, c.vocab_size), D),
        }

    # -- forward ------------------------------------------------------------
    def _attention(self, q, k, v, *, mesh: Optional[DeviceMesh],
                   seq_axis: Optional[str], data_axis: Optional[str],
                   model_axis: Optional[str]):
        if mesh is not None and seq_axis is not None:
            return ring_attention(q, k, v, mesh, seq_axis=seq_axis,
                                  causal=True, batch_axis=data_axis,
                                  head_axis=model_axis)
        # single-device path: the Pallas flash kernel on TPU (blockwise,
        # scores never leave VMEM), plain-XLA softmax attention elsewhere
        from ..ops import flash_attention
        return flash_attention(q, k, v, causal=True)

    def _block(self, lp, x, positions, *, mesh, seq_axis, data_axis,
               model_axis, expert_axis):
        """One transformer block: attention + (dense | MoE) FFN.
        Returns (x, aux) — aux is the MoE load-balance term (0 for dense)."""
        c = self.config
        h = _rms_norm(x, lp["ln1"])
        q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"])
        k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"])
        v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
        q = _rope(q, positions, c.rope_base)
        k = _rope(k, positions, c.rope_base)
        attn = self._attention(q, k, v, mesh=mesh, seq_axis=seq_axis,
                               data_axis=data_axis, model_axis=model_axis)
        x = x + jnp.einsum("bshk,hkd->bsd", attn, lp["wo"])
        h = _rms_norm(x, lp["ln2"])
        if "moe" in lp:
            from ..parallel.moe import switch_ffn
            B, S, D = h.shape
            y, aux = switch_ffn(h.reshape(B * S, D), lp["moe"],
                                capacity_factor=c.expert_capacity_factor,
                                mesh=mesh, expert_axis=expert_axis)
            return x + y.reshape(B, S, D), aux
        return x + jax.nn.gelu(h @ lp["w1"]) @ lp["w2"], jnp.float32(0.0)

    def apply_with_aux(self, params: Params, tokens: jax.Array,
                       mesh: Optional[DeviceMesh] = None,
                       seq_axis: Optional[str] = None,
                       data_axis: Optional[str] = None,
                       model_axis: Optional[str] = None,
                       expert_axis: Optional[str] = None,
                       ) -> Tuple[jax.Array, jax.Array]:
        """Forward pass -> (logits, moe_aux_loss). With ``mesh`` +
        ``seq_axis``, attention runs as a sequence-parallel ring; positions
        are global, so rotary phases are correct on every shard."""
        S = tokens.shape[1]
        x = params["embed"][tokens]  # [B, S, D]
        positions = jnp.arange(S)
        aux_total = jnp.float32(0.0)
        for lp in params["layers"]:
            x, aux = self._block(lp, x, positions, mesh=mesh,
                                 seq_axis=seq_axis, data_axis=data_axis,
                                 model_axis=model_axis,
                                 expert_axis=expert_axis)
            aux_total = aux_total + aux
        x = _rms_norm(x, params["ln_f"])
        return x @ params["head"], aux_total

    def apply(self, params: Params, tokens: jax.Array, **kw) -> jax.Array:
        return self.apply_with_aux(params, tokens, **kw)[0]

    # -- autoregressive decoding (KV cache) ---------------------------------
    def init_cache(self, batch: int, max_len: int) -> Params:
        """Static-shape KV cache: per layer ``k``/``v`` of
        ``[B, max_len, H, Dh]`` — XLA-friendly decoding writes into fixed
        buffers with ``dynamic_update_slice`` instead of growing arrays."""
        c = self.config
        zeros = lambda: jnp.zeros(  # noqa: E731
            (batch, max_len, c.n_heads, c.head_dim), c.dtype)
        return {"layers": [{"k": zeros(), "v": zeros()}
                           for _ in range(c.n_layers)]}

    def _block_cached(self, lp, ck, x, start, positions, key_positions):
        """One block over ``x`` (``[B, S, D]`` at global ``positions``),
        reading/writing the KV cache at offset ``start``. Attention sees
        every cached key with ``key_positions <= position`` (causal within
        the new tokens, everything before them unconditionally). Returns
        ``(x, new_cache_entry)``."""
        c = self.config
        h = _rms_norm(x, lp["ln1"])
        q = jnp.einsum("bsd,dhk->bshk", h, lp["wq"])
        k = jnp.einsum("bsd,dhk->bshk", h, lp["wk"])
        v = jnp.einsum("bsd,dhk->bshk", h, lp["wv"])
        q = _rope(q, positions, c.rope_base)
        k = _rope(k, positions, c.rope_base)
        kc = jax.lax.dynamic_update_slice(ck["k"], k, (0, start, 0, 0))
        vc = jax.lax.dynamic_update_slice(ck["v"], v, (0, start, 0, 0))
        scores = jnp.einsum("bqhk,bthk->bhqt", q, kc,
                            preferred_element_type=jnp.float32)
        scores = scores * (1.0 / np.sqrt(c.head_dim))
        mask = key_positions[None, :] <= positions[:, None]  # [S, T]
        scores = jnp.where(mask[None, None], scores, -jnp.inf)
        p = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhqt,bthk->bqhk", p, vc.astype(p.dtype))
        x = x + jnp.einsum("bshk,hkd->bsd", attn.astype(x.dtype), lp["wo"])
        h = _rms_norm(x, lp["ln2"])
        if "moe" in lp:
            from ..parallel.moe import switch_ffn
            B, S, D = h.shape
            y, _ = switch_ffn(h.reshape(B * S, D), lp["moe"],
                              capacity_factor=c.expert_capacity_factor)
            ff = y.reshape(B, S, D)
        else:
            ff = jax.nn.gelu(h @ lp["w1"]) @ lp["w2"]
        return x + ff, {"k": kc, "v": vc}

    def _forward_cached(self, params, cache, tokens, start, max_len):
        """Cached forward over ``tokens`` (``[B, S]``) written at cache
        offset ``start``; serves both prefill (S = prompt) and decode
        (S = 1). Returns ``(logits [B, S, V], new_cache)``."""
        S = tokens.shape[1]
        x = params["embed"][tokens]
        positions = start + jnp.arange(S)
        key_positions = jnp.arange(max_len)
        new_layers = []
        for lp, ck in zip(params["layers"], cache["layers"]):
            x, nck = self._block_cached(lp, ck, x, start, positions,
                                        key_positions)
            new_layers.append(nck)
        x = _rms_norm(x, params["ln_f"])
        return x @ params["head"], {"layers": new_layers}

    def generate(self, params: Params, prompt: jax.Array,
                 max_new_tokens: int, temperature: float = 0.0,
                 rng: Optional[jax.Array] = None) -> jax.Array:
        """Autoregressive decode: ``prompt`` ``[B, S0]`` int32 ->
        ``[B, S0 + max_new_tokens]``.

        One prefill pass fills the KV cache for the whole prompt, then a
        ``lax.scan`` emits one token per step against the static-shape
        cache — the whole loop is one compiled XLA program (no Python in
        the decode path, the TPU-idiomatic replacement for a host loop).
        ``temperature=0`` is greedy; otherwise softmax sampling with
        ``rng``.
        """
        if temperature > 0 and rng is None:
            raise ValueError("temperature > 0 sampling needs rng")
        if rng is None:
            rng = jax.random.PRNGKey(0)
        prompt = jnp.asarray(prompt, jnp.int32)
        # one compiled program for prefill + decode scan + glue (cached per
        # static (max_new_tokens, temperature); prompt shape changes
        # retrace as usual) — an un-jitted prefill would dispatch op by op,
        # which through a ~0.5 s/RTT relay costs seconds per call
        if not hasattr(self, "_generate_jit"):
            self._generate_jit = jax.jit(self._generate_impl,
                                         static_argnums=(3, 4))
        return self._generate_jit(params, prompt, rng, max_new_tokens,
                                  temperature)

    def _generate_impl(self, params, prompt, rng, max_new_tokens,
                       temperature):
        B, S0 = prompt.shape
        T = S0 + max_new_tokens
        cache = self.init_cache(B, T)
        logits, cache = self._forward_cached(params, cache, prompt, 0, T)

        def pick(lg, key):
            if temperature > 0:
                return jax.random.categorical(key, lg / temperature, axis=-1)
            return jnp.argmax(lg, axis=-1)

        first_key, scan_key = jax.random.split(rng)
        first = pick(logits[:, -1].astype(jnp.float32), first_key)

        def step(carry, key):
            cache, tok, pos = carry
            lg, cache = self._forward_cached(
                params, cache, tok[:, None], pos, T)
            nxt = pick(lg[:, -1].astype(jnp.float32), key)
            return (cache, nxt.astype(jnp.int32), pos + 1), tok

        # each step emits the token it was CARRIED (first, then each
        # sampled successor), so max_new_tokens steps yield exactly
        # max_new_tokens tokens; the last step's sampled successor is
        # discarded (one spare decode forward keeps the loop uniform)
        keys = jax.random.split(scan_key, max_new_tokens)
        _, toks = jax.lax.scan(
            step, (cache, first.astype(jnp.int32), S0), keys)
        return jnp.concatenate([prompt, toks.transpose(1, 0)], axis=1)

    def generate_via_frame(self, params: Params, df,
                           max_new_tokens: int,
                           prompt_col: str = "prompt",
                           temperature: float = 0.0,
                           rng: Optional[jax.Array] = None,
                           trim: bool = True):
        """Batch decoding through ``map_blocks``: prompts live in a frame
        column (``[S0]`` int cells), completions come back as a
        ``completion`` column (``[S0 + max_new_tokens]``) — the
        broadcast-the-frozen-graph pattern the other zoo models use for
        inference, here driving the KV-cache decode loop per block.

        Sampling (``temperature > 0``) folds the block's token content
        into ``rng`` so different blocks draw independent streams; blocks
        with byte-identical prompts reproduce the same completion
        (deterministic by content — re-running the frame gives the same
        result, the laziness contract's requirement)."""
        def fn_impl(**cols):
            toks = cols[prompt_col].astype(jnp.int32)
            key = rng
            if key is not None:
                mix = jnp.sum(
                    toks.astype(jnp.uint32)
                    * (jnp.arange(toks.size, dtype=jnp.uint32)
                       .reshape(toks.shape)
                       * np.uint32(2654435761) + np.uint32(1)))
                key = jax.random.fold_in(key, mix.astype(jnp.uint32))
            out = self.generate(params, toks, max_new_tokens,
                                temperature=temperature, rng=key)
            return {"completion": out}

        from .logreg import _named_args_fn
        return df.map_blocks(_named_args_fn(fn_impl, [prompt_col]),
                             trim=trim)

    @staticmethod
    def _xent(logits: jax.Array, targets: jax.Array) -> jax.Array:
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
        ll = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
        return -jnp.mean(ll)

    def loss(self, params: Params, tokens: jax.Array, targets: jax.Array,
             **apply_kw) -> jax.Array:
        """Mean next-token cross-entropy (+ weighted MoE aux when
        experts are on); ``targets[b, s]`` is the label for position ``s``
        (caller pre-shifts)."""
        logits, aux = self.apply_with_aux(params, tokens, **apply_kw)
        return self._xent(logits, targets) \
            + self.config.aux_loss_weight * aux

    # -- sharding -----------------------------------------------------------
    def param_shardings(self, mesh: DeviceMesh, model_axis: str = "model",
                        expert_axis: Optional[str] = None) -> Params:
        """Megatron-style tensor-parallel placement over ``model_axis``;
        expert weights sharded over ``expert_axis`` when MoE is on."""
        m = mesh.mesh

        def s(*spec):
            return NamedSharding(m, P(*spec))

        layer = {
            "ln1": s(), "ln2": s(),
            "wq": s(None, model_axis, None),
            "wk": s(None, model_axis, None),
            "wv": s(None, model_axis, None),
            "wo": s(model_axis, None, None),
        }
        if self.config.num_experts > 0:
            layer["moe"] = {
                "router": s(),
                "w1": s(expert_axis, None, model_axis),
                "w2": s(expert_axis, model_axis, None),
            }
        else:
            layer["w1"] = s(None, model_axis)
            layer["w2"] = s(model_axis, None)
        return {
            "embed": s(None, None),
            "layers": [jax.tree_util.tree_map(
                lambda x: x, layer,
                is_leaf=lambda l: isinstance(l, NamedSharding))
                for _ in range(self.config.n_layers)],
            "ln_f": s(),
            "head": s(None, model_axis),
        }

    def make_sharded_train_step(self, mesh: DeviceMesh,
                                data_axis: str = "data",
                                model_axis: Optional[str] = "model",
                                seq_axis: Optional[str] = None,
                                expert_axis: Optional[str] = None,
                                learning_rate: float = 1e-3):
        """One compiled SPMD training step (adam) over the mesh.

        Returns ``(step, init_state)`` factories: ``state = init_state(rng)``
        then ``state, loss = step(state, tokens, targets)``. Shardings:
        params tensor-parallel over ``model_axis`` (replicated if the axis is
        absent/None), batch over ``data_axis``, activations sequence-sharded
        with ring attention when ``seq_axis`` is given, and — with MoE on —
        expert weights and the dispatched token buffer over ``expert_axis``
        (the all_to_all pair is XLA-inserted).
        """
        import optax

        axes = mesh.axis_names
        ma = model_axis if model_axis in axes else None
        sa = seq_axis if seq_axis in axes else None
        ea = expert_axis if expert_axis in axes else None
        p_shard = (self.param_shardings(mesh, ma, ea) if (ma or ea)
                   else jax.tree_util.tree_map(
                       lambda _: NamedSharding(mesh.mesh, P()),
                       jax.eval_shape(self.init)))
        tok_shard = NamedSharding(mesh.mesh, P(data_axis, sa))
        opt = optax.adam(learning_rate)

        def init_state(rng=None):
            params = jax.device_put(self.init(rng), p_shard)
            # adam moments inherit each param's sharding (jit propagates
            # input shardings to the zeros_like outputs), but scalar leaves
            # (adam's step count) come back with an uncommitted
            # single-device placement. That mixes fine with mesh-committed
            # params only because jax relocates uncommitted arrays — a
            # checkpoint restore commits every leaf, so resume would fail
            # with "incompatible devices". Commit every non-mesh leaf to a
            # replicated mesh sharding up front.
            opt_state = jax.jit(opt.init)(params)
            opt_state = jax.tree_util.tree_map(
                lambda l: l if isinstance(l.sharding, NamedSharding)
                else jax.device_put(l, NamedSharding(mesh.mesh, P())),
                opt_state)
            return {"params": params, "opt": opt_state}

        def step(state, tokens, targets):
            def loss_fn(p):
                return self.loss(p, tokens, targets, mesh=mesh,
                                 seq_axis=sa, data_axis=data_axis,
                                 model_axis=ma, expert_axis=ea)

            loss, grads = jax.value_and_grad(loss_fn)(state["params"])
            updates, new_opt = opt.update(grads, state["opt"],
                                          state["params"])
            new_params = optax.apply_updates(state["params"], updates)
            return {"params": new_params, "opt": new_opt}, loss

        jstep = jax.jit(step,
                        in_shardings=(None, tok_shard, tok_shard),
                        donate_argnums=(0,))
        return jstep, init_state

    # -- pipeline parallelism ------------------------------------------------
    def stacked_layer_params(self, params: Params):
        """Stack the per-layer pytrees into leading-dim-``L`` leaves (the
        layout :func:`~tensorframes_tpu.parallel.pipeline.pipeline_apply`
        wants, with L = stages when one layer per stage)."""
        layers = params["layers"]
        return jax.tree_util.tree_map(
            lambda *ls: jnp.stack(ls), *layers)

    def make_pipelined_train_step(self, mesh: DeviceMesh,
                                  pipe_axis: str = "pipe",
                                  data_axis: str = "data",
                                  num_microbatches: Optional[int] = None,
                                  learning_rate: float = 1e-3):
        """Training step with the layer stack run as a GPipe pipeline over
        ``pipe_axis`` (one or more layers per stage; ``n_layers`` must be a
        multiple of the axis size). Embed/head/final-norm are replicated and
        run outside the pipeline; batch rows are sharded over ``data_axis``
        and split into microbatches inside the pipeline schedule.

        The train state keeps the layer stack in stage-major layout
        ``[P, per_stage, ...]`` sharded over ``pipe_axis`` — each device
        holds (and adam tracks) only its own stage's parameters, the O(L/P)
        memory scaling pipelining exists for. Dense models only: the MoE
        aux loss cannot cross the pipeline boundary (use
        ``make_sharded_train_step`` with ``expert_axis`` for MoE).
        """
        import optax
        from ..parallel.pipeline import pipeline_apply

        c = self.config
        if c.num_experts > 0:
            raise ValueError(
                "make_pipelined_train_step supports dense FFN models only: "
                "the MoE load-balance aux loss would be silently dropped "
                "across the pipeline; use make_sharded_train_step with "
                "expert_axis for MoE")
        pipe_size = mesh.mesh.shape[pipe_axis]
        if c.n_layers % pipe_size:
            raise ValueError(
                f"n_layers={c.n_layers} not divisible by pipe={pipe_size}")
        per_stage = c.n_layers // pipe_size

        def stage_fn(stage_params, act):
            # act: [mb, S, D]; rope positions are just arange(S) — S is
            # static, so each stage recomputes them (nothing to smuggle)
            positions = jnp.arange(act.shape[1])
            x = act
            for i in range(per_stage):
                lp = jax.tree_util.tree_map(lambda a: a[i], stage_params)
                x, _ = self._block(lp, x, positions, mesh=None,
                                   seq_axis=None, data_axis=None,
                                   model_axis=None, expert_axis=None)
            return x

        def forward(params, tokens):
            x = params["outer"]["embed"][tokens]
            out = pipeline_apply(stage_fn, params["stages"], x, mesh,
                                 pipe_axis=pipe_axis,
                                 num_microbatches=num_microbatches,
                                 data_axis=data_axis)
            x = _rms_norm(out, params["outer"]["ln_f"])
            return x @ params["outer"]["head"]

        stage_shard = NamedSharding(mesh.mesh, P(pipe_axis))
        repl = NamedSharding(mesh.mesh, P())
        tok_shard = NamedSharding(mesh.mesh, P(data_axis, None))
        opt = optax.adam(learning_rate)

        def init_state(rng=None):
            flat = self.init(rng)
            # stage-major [P, per, ...] leaves, each sharded over the pipe
            # axis: device p holds exactly its own stage's slice
            stages = jax.tree_util.tree_map(
                lambda a: a.reshape((pipe_size, per_stage) + a.shape[1:]),
                self.stacked_layer_params(flat))
            params = {
                "outer": jax.device_put(
                    {"embed": flat["embed"], "ln_f": flat["ln_f"],
                     "head": flat["head"]}, repl),
                "stages": jax.tree_util.tree_map(
                    lambda a: jax.device_put(a, stage_shard), stages),
            }
            # adam moments inherit each leaf's sharding through jit;
            # commit scalar leaves (adam count) to the mesh so a
            # checkpoint-restored state matches (see make_sharded_train_step)
            opt_state = jax.jit(opt.init)(params)
            opt_state = jax.tree_util.tree_map(
                lambda l: l if isinstance(l.sharding, NamedSharding)
                else jax.device_put(l, repl),
                opt_state)
            return {"params": params, "opt": opt_state}

        def step(state, tokens, targets):
            def loss_fn(p):
                return self._xent(forward(p, tokens), targets)

            loss, grads = jax.value_and_grad(loss_fn)(state["params"])
            updates, new_opt = opt.update(grads, state["opt"],
                                          state["params"])
            new_params = optax.apply_updates(state["params"], updates)
            return {"params": new_params, "opt": new_opt}, loss

        jstep = jax.jit(step,
                        in_shardings=(None, tok_shard, tok_shard),
                        donate_argnums=(0,))
        return jstep, init_state
