"""Row <-> columnar-block marshalling.

The TPU-native analogue of the reference's ``DataOps``
(``/root/reference/src/main/scala/org/tensorframes/impl/DataOps.scala``):
where the reference copies Spark ``Row`` objects cell-by-cell into C++
``jtf.Tensor`` NIO buffers (``convert``) and back (``convertBack``), here
blocks are **columnar numpy arrays** that feed the TPU through
``jax.device_put`` zero-copy-on-host; rows only materialize at the user
boundary (``collect``). Both a fast vectorized path and a slow validating
reference path are kept, like the reference's ``fastPath`` switch
(``DataOps.scala:40, 162``). When the C++ runtime library is built, the fast
paths below dispatch to native packing kernels (see ``native/``).

``infer_physical_shape`` mirrors ``DataOps.inferPhysicalShape``
(``DataOps.scala:307-346``): resolve at most one unknown dim of a declared
shape from a flat buffer's element count.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from . import dtypes as _dt
from .schema import Field, Schema
from .shape import Shape, Unknown

__all__ = [
    "rows_to_columns",
    "columns_to_rows",
    "infer_physical_shape",
    "validate_block_column",
]

Column = Union[np.ndarray, List[np.ndarray]]  # dense | ragged


def infer_physical_shape(num_elements: int, declared: Shape,
                         context: str = "") -> Tuple[int, ...]:
    """Resolve the dims of a flat buffer of ``num_elements`` against a
    declared shape with at most one Unknown dim."""
    unknowns = [i for i, d in enumerate(declared.dims) if d == Unknown]
    if len(unknowns) > 1:
        raise ValueError(
            f"Shape {declared} has multiple unknown dims; cannot infer "
            f"physical shape{': ' + context if context else ''}")
    known = math.prod(d for d in declared.dims if d != Unknown)
    if not unknowns:
        if known != num_elements:
            raise ValueError(
                f"Buffer of {num_elements} elements does not match shape "
                f"{declared}{': ' + context if context else ''}")
        return declared.dims
    if known == 0 or num_elements % known != 0:
        raise ValueError(
            f"Buffer of {num_elements} elements cannot fill shape "
            f"{declared}{': ' + context if context else ''}")
    dims = list(declared.dims)
    dims[unknowns[0]] = num_elements // known
    return tuple(dims)


def _cell_to_array(cell, dtype: np.dtype) -> np.ndarray:
    if cell is None:
        raise ValueError("Null cell encountered; nullable fields are not "
                         "accepted (analyze/ops reject them)")
    return np.asarray(cell, dtype=dtype)


def rows_to_columns(rows: Sequence[Sequence], schema: Schema,
                    fast: bool = True) -> Dict[str, Column]:
    """Convert a sequence of row tuples into columnar arrays.

    Fast path: one vectorized ``np.asarray`` per column (dense data).
    Slow path (and fallback): per-cell conversion with shape validation;
    ragged columns come back as a list of per-row arrays.
    """
    ncols = len(schema)
    out: Dict[str, Column] = {}
    # one C-level transpose instead of re-indexing [r[j] for r in rows]
    # per column (O(rows*cols) Python indexing on the ingest path);
    # len(), not truthiness: rows may be a 2-D ndarray
    transposed = tuple(zip(*rows)) if len(rows) else ((),) * ncols
    for j, field in enumerate(schema):
        np_dt = field.dtype.np_storage
        cells = transposed[j]
        if fast:
            try:
                arr = np.asarray(cells, dtype=np_dt)
                if arr.dtype == object:
                    raise ValueError("ragged")
                out[field.name] = arr
                continue
            except (ValueError, TypeError):
                pass  # fall through to slow path
        arrays = [_cell_to_array(c, np_dt) for c in cells]
        shapes = {a.shape for a in arrays}
        if len(shapes) <= 1:
            out[field.name] = (np.stack(arrays) if arrays
                               else np.empty((0,) + _concrete_cell(field),
                                             np_dt))
        else:
            out[field.name] = arrays  # ragged
    # sanity: all columns agree on row count
    for name, col in out.items():
        n = len(col)
        if n != len(rows):
            raise AssertionError(
                f"Column {name} has {n} rows, expected {len(rows)}")
    assert len(out) == ncols
    return out


def _concrete_cell(field: Field) -> Tuple[int, ...]:
    cs = field.cell_shape
    if cs is None or cs.has_unknown:
        return ()
    return cs.dims


def columns_to_rows(columns: Dict[str, Column], schema: Schema,
                    fast: bool = True) -> List[tuple]:
    """Convert columnar arrays back into row tuples.

    Scalar cells come back as Python scalars, tensor cells as numpy arrays —
    the shape users see from ``collect`` (reference returns Spark Rows whose
    array cells the Python layer re-wraps as numpy, ``core.py:78-92``).
    """
    names = schema.names
    cols = [columns[n] for n in names]
    if not cols:
        return []
    lens = {len(c) for c in cols}
    if len(lens) > 1:
        raise ValueError(
            "columns disagree on row count: "
            + ", ".join(f"{n}={len(c)}" for n, c in zip(names, cols)))
    if fast:
        # column-at-a-time: ndarray.tolist() unboxes a whole scalar column
        # to Python values in C, list(arr) splits a tensor column into row
        # views in C, and zip reassembles tuples — ~10x the per-cell loop
        # below (the reference's fastPath/slow-path split, DataOps.scala:40)
        seqs = [c.tolist() if isinstance(c, np.ndarray) and c.ndim == 1
                else list(c) for c in cols]
        return list(zip(*seqs))
    n = len(cols[0])
    scalar = [isinstance(c, np.ndarray) and c.ndim == 1 for c in cols]
    rows = []
    for i in range(n):
        row = []
        for c, is_scalar in zip(cols, scalar):
            v = c[i]
            if is_scalar:
                # object columns (string) index straight to python values
                v = v.item() if isinstance(v, np.generic) else v
            elif isinstance(v, np.ndarray):
                v = np.asarray(v)
            row.append(v)
        rows.append(tuple(row))
    return rows


def validate_block_column(name: str, col: Column, field: Field) -> None:
    """Check a materialized column against its declared field info."""
    if isinstance(col, np.ndarray):
        declared = field.block_shape
        if declared is not None and not declared.matches_concrete(col.shape):
            raise ValueError(
                f"Column {name!r}: block of shape {tuple(col.shape)} does "
                f"not conform to declared shape {declared}")
    else:
        for i, cell in enumerate(col):
            if field.cell_shape is not None and \
                    field.cell_shape.ndim != cell.ndim:
                raise ValueError(
                    f"Column {name!r} row {i}: cell rank {cell.ndim} does "
                    f"not match declared cell shape {field.cell_shape}")
