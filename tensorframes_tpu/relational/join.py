"""Device-side joins: broadcast hash join + mesh sort-merge join.

**Broadcast hash join** (small build side): the right frame factorizes
ONCE into a :class:`BuildTable` — sorted unique keys, group offsets,
and its value columns re-ordered by key and placed on the device as a
broadcast table (admitted through the memory ledger and registered as
spillable). Each probe block then costs one host key-match (a
vectorized ``searchsorted`` into the sorted key table — the same
"host keys, device values" split ``aggregate``/``daggregate`` use) and
ONE fused device gather program for all build value columns, dispatched
through the resilient :class:`~..engine.executor.BlockExecutor` (retry,
OOM handling, memory admission, compile caches, serve interner). A
build side the ledger refuses to hold resident (over
``TFT_MEM_SORT_FRACTION`` of the budget) probes in budget-sized
contiguous-group CHUNKS instead — each chunk admitted per dispatch,
results combined exactly (a key lives in exactly one chunk), bounded
device memory, bit-identical output (``relational.build_chunks``).

Output order: probe (left) row order, block boundaries preserved;
within a probe row, build matches in build-row order. ``how`` is
``"inner"`` or ``"left"`` (unmatched left rows keep fill values:
NaN for floats, 0 for ints/bools, ``""`` for strings — pass
``indicator=`` for an explicit int32 matched column).

**Sort-merge join** (large-large): both sides sort by key through
``dsort`` on the mesh — columnsort's all_to_all exchanges,
``elastic_call`` device-loss recovery, and the external-memory sort
when the ledger demands it (``mesh=None`` uses the host ``order_by``,
same stable order) — then the two key-sorted streams merge on the
host with a fully vectorized group-cartesian expansion. Output order:
key-ascending, stable by original row order within ties.

Both strategies are LAZY and record a :class:`~..plan.nodes.JoinNode`,
so downstream chains fuse over the join result, column pruning reaches
INTO the join (un-needed build columns are never gathered), and the
per-column cost model prices join results for serve admission.
"""

from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from .. import dtypes as _dt
from ..frame import Block, TensorFrame, _split_even
from ..schema import Field, Schema
from ..shape import Shape, Unknown
from ..utils.logging import get_logger
from ..utils.tracing import counters, span

__all__ = ["join", "broadcast_join", "sort_merge_join",
           "partitioned_hash_join", "BuildTable", "approx_key_distinct"]

_log = get_logger("relational.join")

# broadcast-vs-sort-merge auto routing: a build side estimated above
# this many bytes prefers the mesh sort-merge join when a mesh is given
_DEFAULT_BROADCAST_LIMIT = 64 << 20


def _fill_value(field):
    kind = np.dtype(field.dtype.np_storage).kind
    if kind in "fV":  # 'V' = ml_dtypes bfloat16: a float, fills NaN
        return np.nan
    if kind == "b":
        return False
    if kind in "iu":
        return 0
    return ""  # strings / objects


def _validate_on(left_schema: Schema, right_schema: Schema,
                 on: Sequence[str]) -> List[str]:
    from ..engine.ops import InputNotFoundError, InvalidTypeError
    on = [on] if isinstance(on, str) else list(on)
    if not on:
        raise ValueError("join needs at least one key column (on=)")
    for side, schema in (("left", left_schema), ("right", right_schema)):
        for k in on:
            f = schema.get(k)
            if f is None:
                raise InputNotFoundError(
                    f"join key {k!r} not in the {side} frame; columns: "
                    f"{schema.names}")
            if f.sql_rank != 0:
                raise InvalidTypeError(
                    f"join key {k!r} must be a scalar column")
    for k in on:
        lt = left_schema[k].dtype.tensor
        rt = right_schema[k].dtype.tensor
        if lt != rt:
            raise InvalidTypeError(
                f"join key {k!r} is numeric on one side and string on "
                f"the other; cast one side first")
    return on


def join_schema(left_schema: Schema, right_schema: Schema,
                on: Sequence[str], how: str,
                indicator: Optional[str]) -> Schema:
    """The join output schema: left fields, then the right VALUE fields
    (right order, key columns dropped — they equal the left copy), then
    the optional int32 indicator."""
    if how not in ("inner", "left"):
        raise ValueError(f"how must be 'inner' or 'left', got {how!r}")
    on = set(on)
    fields = list(left_schema)
    for f in right_schema:
        if f.name in on:
            continue
        if f.name in left_schema:
            raise ValueError(
                f"join would duplicate column {f.name!r}; select() or "
                f"rename one side first")
        fields.append(f)
    if indicator:
        if indicator in left_schema or indicator in right_schema:
            raise ValueError(
                f"indicator column {indicator!r} already exists")
        fields.append(Field(indicator, _dt.int32,
                            block_shape=Shape(Unknown), sql_rank=0))
    return Schema(fields)


def approx_key_distinct(frame, on: Sequence[str],
                        bits: int = 12) -> Optional[float]:
    """HLL distinct-count estimate of a FORCED frame's key column(s)
    (``docs/adaptive.md``): one pass over the cached blocks, ~1.6%
    relative error at the default 4096 registers, cached on the frame
    per (keys, version). ``None`` when the frame is unforced (no data
    to sketch without forcing — estimates must never force), a key is
    non-numeric, or any key column is ragged. Feeds
    ``JoinNode.estimate()``'s output-cardinality pricing and, through
    it, the re-planner's broadcast-vs-chunked decision."""
    on = [on] if isinstance(on, str) else list(on)
    blocks = getattr(frame, "_cache", None)
    if not blocks:
        return None
    key = (tuple(on), getattr(frame, "_version", 0), int(bits))
    cache = getattr(frame, "_tft_key_distinct", None)
    if cache is not None and cache.get(key) is not None:
        return cache[key]
    for b in blocks:
        for k in on:
            if k not in b.columns:
                return None
            if b.num_rows and (b.is_ragged(k)
                               or not isinstance(b.columns[k],
                                                 np.ndarray)
                               or b.dense(k).dtype.kind not in "biuf"):
                return None
    from .sketch import HllSketch, _hash64, _splitmix64
    sk = HllSketch(bits=bits)
    table = None
    for b in blocks:
        if b.num_rows == 0:
            continue
        h = _hash64(b.dense(on[0]))
        for k in on[1:]:
            h = _splitmix64(h ^ _hash64(b.dense(k)))
        part = sk.block_partial(h, np.zeros(b.num_rows, np.int64), 1)
        table = part if table is None else sk.combine_np(table, part)
    if table is None:
        return 0.0
    est = float(sk.finalize("d", table)["d"][0])
    counters.inc("relational.key_distinct_probes")
    try:
        if cache is None:
            cache = frame._tft_key_distinct = {}
        cache[key] = est
    except Exception as e:  # noqa: BLE001 - the probe is advisory
        _log.debug("could not cache key-distinct probe: %s", e)
    return est


# ---------------------------------------------------------------------------
# the broadcast build table
# ---------------------------------------------------------------------------

class BuildTable:
    """The factorized, key-sorted, device-resident build side.

    Built ONCE (eagerly — the build frame forces here) and probed many
    times: by every block of a batch join, and by every batch of a
    ``StreamingFrame.join`` enrichment. Value columns are stored in
    key-sorted row order, so each key group's rows are a contiguous
    span ``[starts[g], starts[g] + counts[g])`` — the unique-key fast
    path gathers row ``g`` directly, and the duplicate-key expansion
    gathers contiguous runs.
    """

    def __init__(self, frame: TensorFrame, on: Sequence[str]):
        from ..engine.ops import _factorize_keys
        from .. import memory as _memory

        self.on = [on] if isinstance(on, str) else list(on)
        self.schema = frame.schema
        _validate_on(frame.schema, frame.schema, self.on)
        merged = Block.concat(frame.blocks(), frame.schema)
        self.build_rows = merged.num_rows
        self.value_fields = [f for f in frame.schema
                             if f.name not in self.on]
        if merged.num_rows:
            fact = _factorize_keys(
                [np.asarray(merged.columns[k]) for k in self.on])
            self.uniques = [np.asarray(u) for u in fact.uniques]
            self.num_groups = fact.num_groups
            self.starts = np.asarray(fact.seg_starts, np.int64)
            self.counts = np.diff(
                np.append(self.starts, merged.num_rows)).astype(np.int64)
            order = fact.order
        else:
            self.uniques = [np.empty(0, np.asarray(
                merged.columns[k]).dtype if merged.columns[k] is not None
                else np.float64) for k in self.on]
            self.num_groups = 0
            self.starts = np.empty(0, np.int64)
            self.counts = np.empty(0, np.int64)
            order = np.empty(0, np.int64)
        self.unique_keys = bool(self.num_groups == self.build_rows)

        # key-sorted value columns; tensor columns are device-gather
        # candidates, strings/ragged stay host ride-alongs
        self.host_cols: Dict[str, object] = {}
        self.tensor_names: List[str] = []
        sorted_tensor: Dict[str, np.ndarray] = {}
        for f in self.value_fields:
            col = merged.columns[f.name]
            if f.dtype.tensor and isinstance(col, np.ndarray):
                sorted_tensor[f.name] = col[order]
                self.tensor_names.append(f.name)
            elif isinstance(col, np.ndarray):
                self.host_cols[f.name] = col[order]
            else:  # ragged list column
                self.host_cols[f.name] = [col[i] for i in order]

        # ledger admission: hold the build table device-resident when
        # it fits, otherwise keep it host-side and probe in
        # budget-sized contiguous-group chunks (docs/joins.md)
        self._sorted_host = sorted_tensor
        self.dev_bytes = sum(int(a.nbytes)
                             for a in sorted_tensor.values())
        mgr = _memory.active()
        self.chunks: Optional[List[Tuple[int, int]]] = None  # row spans
        self.dev_cols = None
        threshold = (mgr.external_sort_threshold()
                     if mgr is not None and mgr.spill_enabled else None)
        if threshold is not None and self.dev_bytes > threshold \
                and self.build_rows:
            # size chunks so the executor's ~2x dispatch estimate still
            # admits under the threshold (no overflow admissions on the
            # steady path)
            n_chunks = int(np.ceil(self.dev_bytes
                                   / max(1, threshold // 2)))
            self.chunks = self._chunk_spans(n_chunks)
            counters.inc("relational.build_chunks", len(self.chunks))
            _log.info(
                "join build side (%d B) exceeds the ledger's resident "
                "threshold (%d B); probing in %d contiguous-group "
                "chunk(s) instead of broadcasting it resident",
                self.dev_bytes, threshold, len(self.chunks))
        else:
            dev = {}
            from .. import native as _native
            import jax
            if mgr is not None and self.dev_bytes:
                mgr.make_room(self.dev_bytes)
            for name, a in sorted_tensor.items():
                dd = _dt.device_dtype(self.schema[name].dtype)
                if a.dtype != dd:
                    a = _native.convert(a, dd)
                dev[name] = jax.device_put(a)
            self.dev_cols = (_memory.spillable_columns(
                f"join.build@{id(self):x}", dev, mgr)
                if mgr is not None and dev else dev)
        # cached probe computations: (names, rows) -> Computation
        self._comps: Dict[Tuple, object] = {}
        self._comp_lock = threading.Lock()

    def _chunk_spans(self, n_chunks: int) -> List[Tuple[int, int]]:
        """Contiguous-GROUP row spans of roughly equal rows — a key
        lives in exactly one chunk, so per-chunk probe results combine
        exactly."""
        n_chunks = max(1, min(n_chunks, self.num_groups or 1))
        bounds = np.linspace(0, self.build_rows, n_chunks + 1)
        gbounds = np.searchsorted(self.starts, bounds[1:-1], side="left")
        row_bounds = [0] + [int(self.starts[g]) if g < self.num_groups
                            else self.build_rows for g in gbounds] \
            + [self.build_rows]
        spans = []
        for a, b in zip(row_bounds[:-1], row_bounds[1:]):
            if b > a:
                spans.append((a, b))
        return spans or [(0, self.build_rows)]

    # -- key matching ------------------------------------------------------
    def match(self, key_arrays: List[np.ndarray]
              ) -> Tuple[np.ndarray, np.ndarray]:
        """``(group_id int64 with -1 for no match, matched bool)`` per
        probe row."""
        n = len(key_arrays[0])
        if self.num_groups == 0 or n == 0:
            return np.full(n, -1, np.int64), np.zeros(n, bool)
        if len(self.on) == 1:
            uniq = self.uniques[0]
            probe = np.asarray(key_arrays[0])
            idx = np.searchsorted(uniq, probe)
            idxc = np.minimum(idx, len(uniq) - 1)
            matched = uniq[idxc] == probe
            gid = np.where(matched, idxc, -1).astype(np.int64)
            return gid, np.asarray(matched, bool)
        # composite keys: factorize the (small) unique table together
        # with the probe keys; probe groups landing on a build group id
        # are matches (exact for every dtype incl. strings)
        from ..engine.ops import _factorize_keys
        g = self.num_groups
        cat = [np.concatenate([u, np.asarray(p)])
               for u, p in zip(self.uniques, key_arrays)]
        gf = _factorize_keys(cat)
        inv = np.full(gf.num_groups, -1, np.int64)
        inv[gf.ids[:g]] = np.arange(g)
        gid = inv[gf.ids[g:]]
        return gid, gid >= 0

    # -- the fused device gather ------------------------------------------
    def _probe_comp(self, names: Tuple[str, ...], rows: int):
        key = (names, rows)
        with self._comp_lock:
            comp = self._comps.get(key)
        if comp is not None:
            return comp
        from ..computation import Computation, TensorSpec

        def fn(d):
            import jax.numpy as jnp
            idx = d["_tft_idx"]
            return {n: jnp.take(d[f"_tft_t_{n}"], idx, axis=0)
                    for n in names}

        in_specs = [TensorSpec("_tft_idx", _dt.int32, Shape(Unknown))]
        out_specs = []
        for n in names:
            f = self.schema[n]
            cell = self._sorted_host[n].shape[1:]
            in_specs.append(TensorSpec(f"_tft_t_{n}", f.dtype,
                                       Shape((rows,) + cell)))
            out_specs.append(TensorSpec(n, f.dtype,
                                        Shape((Unknown,) + cell)))
        comp = Computation(fn, in_specs, out_specs)
        with self._comp_lock:
            comp = self._comps.setdefault(key, comp)
        return comp

    def gather_device(self, names: Sequence[str], idx: np.ndarray,
                      gid: np.ndarray, executor=None
                      ) -> Dict[str, np.ndarray]:
        """Gather the named build columns at ``idx`` (int64 build-row
        per output row) — ONE fused dispatch through the resilient
        executor per chunk (one total on the resident fast path)."""
        from ..engine.executor import default_executor
        names = tuple(n for n in names if n in self._sorted_host)
        if not names:
            return {}
        ex = executor or default_executor()
        if not len(idx):
            return {n: self._sorted_host[n][:0].copy() for n in names}
        if self.chunks is None:
            arrays = {"_tft_idx": idx.astype(np.int32)}
            for n in names:
                arrays[f"_tft_t_{n}"] = self.dev_cols[n]
            comp = self._probe_comp(names, self.build_rows)
            counters.inc("relational.probe_dispatches")
            with span("join.probe_gather"):
                return ex.run(comp, arrays, pad_ok=False)
        # chunked probe: each chunk's rows transfer for this dispatch
        # only (admitted by the executor's own reservation), results
        # select by span membership — a build row is in exactly one span
        out = {n: None for n in names}
        for a, b in self.chunks:
            sel = (idx >= a) & (idx < b)
            local = np.where(sel, idx - a, 0).astype(np.int32)
            arrays = {"_tft_idx": local}
            for n in names:
                arrays[f"_tft_t_{n}"] = self._sorted_host[n][a:b]
            comp = self._probe_comp(names, b - a)
            counters.inc("relational.probe_dispatches")
            with span("join.probe_gather_chunk"):
                part = ex.run(comp, arrays, pad_ok=False)
            for n in names:
                if out[n] is None:
                    out[n] = part[n].copy()
                else:
                    out[n][sel] = part[n][sel]
        return out


# ---------------------------------------------------------------------------
# per-block probe
# ---------------------------------------------------------------------------

def _gather_host(col, idx: np.ndarray):
    if isinstance(col, np.ndarray):
        return col[idx]
    return [col[i] for i in idx]


def _mask_host(col, mask: np.ndarray):
    if isinstance(col, np.ndarray):
        return col[mask]
    return [col[i] for i in np.flatnonzero(mask)]


def _fill_unmatched(arr, field, valid: np.ndarray):
    fill = _fill_value(field)
    if isinstance(arr, np.ndarray):
        out = arr.copy() if not arr.flags.writeable else arr
        out[~valid] = fill
        return out
    return [a if v else fill for a, v in zip(arr, valid)]


def _empty_build_cols(build: BuildTable, names: Sequence[str],
                      n: int, how: str) -> Dict[str, object]:
    cols: Dict[str, object] = {}
    for f in build.value_fields:
        if f.name not in names:
            continue
        if f.name in build.tensor_names:
            cell = build._sorted_host[f.name].shape[1:]
            a = np.full((n,) + cell, _fill_value(f),
                        build._sorted_host[f.name].dtype)
            cols[f.name] = a
        else:
            src = build.host_cols[f.name]
            if isinstance(src, np.ndarray):
                cols[f.name] = np.full((n,) + src.shape[1:],
                                       _fill_value(f), src.dtype)
            else:
                cols[f.name] = [_fill_value(f)] * n
    return cols


def probe_block(build: BuildTable, block: Block, how: str,
                out_names: Sequence[str],
                indicator: Optional[str] = None,
                executor=None) -> Block:
    """Join one probe block against the build table; returns the output
    block restricted to ``out_names`` (the pruning surface)."""
    out_set = set(out_names)
    left_names = [n for n in block.columns if n in out_set]
    build_names = [f.name for f in build.value_fields
                   if f.name in out_set]
    n = block.num_rows
    if n == 0:
        cols: Dict[str, object] = {m: block.columns[m][:0]
                                   if isinstance(block.columns[m],
                                                 np.ndarray)
                                   else [] for m in left_names}
        cols.update(_empty_build_cols(build, build_names, 0, how))
        if indicator and indicator in out_set:
            cols[indicator] = np.empty(0, np.int32)
        return Block(cols, 0)

    keys = [np.asarray(block.columns[k]) for k in build.on]
    gid, matched = build.match(keys)

    if build.unique_keys or build.num_groups == 0:
        # 1:1 (or 1:0) — no expansion
        if how == "inner":
            keep = matched
            n_out = int(keep.sum())
            sel_gid = gid[keep]
            idx = (build.starts[sel_gid] if n_out else
                   np.empty(0, np.int64))
            valid = np.ones(n_out, bool)
            cols = {m: _mask_host(block.columns[m], keep)
                    for m in left_names}
        else:
            n_out = n
            idx = np.where(matched, build.starts[np.maximum(gid, 0)]
                           if build.num_groups else 0, 0)
            valid = matched
            cols = {m: block.columns[m] for m in left_names}
    else:
        # duplicate build keys: expand each probe row by its group size
        cnt = np.where(matched,
                       build.counts[np.maximum(gid, 0)], 0)
        out_cnt = np.maximum(cnt, 1) if how == "left" else cnt
        total = int(out_cnt.sum())
        rep = np.repeat(np.arange(n), out_cnt)
        offsets = np.concatenate([[0], np.cumsum(out_cnt)[:-1]])
        within = np.arange(total) - offsets[rep]
        m_rep = matched[rep]
        idx = np.where(
            m_rep,
            build.starts[np.maximum(gid[rep], 0)] + within, 0)
        valid = m_rep
        n_out = total
        cols = {m: _gather_host(block.columns[m], rep)
                for m in left_names}

    if n_out and build.num_groups:
        dev_names = [m for m in build_names if m in build.tensor_names]
        gathered = build.gather_device(dev_names, idx, gid,
                                       executor=executor)
        for m in dev_names:
            a = gathered[m]
            if how == "left" and not valid.all():
                a = _fill_unmatched(np.array(a, copy=True),
                                    build.schema[m], valid)
            cols[m] = a
        for m in build_names:
            if m in build.tensor_names:
                continue
            a = _gather_host(build.host_cols[m], idx)
            if how == "left" and not valid.all():
                a = _fill_unmatched(
                    a if not isinstance(a, np.ndarray) else a.copy(),
                    build.schema[m], valid)
            cols[m] = a
    else:
        cols.update(_empty_build_cols(build, build_names, n_out, how))
    if indicator and indicator in out_set:
        cols[indicator] = valid.astype(np.int32) if n_out else \
            np.empty(0, np.int32)
    counters.inc("relational.rows_joined", n_out)
    return Block(cols, n_out)


# ---------------------------------------------------------------------------
# the lazy join frames
# ---------------------------------------------------------------------------

def _attach_join_node(out: TensorFrame, left: TensorFrame,
                      right: Optional[TensorFrame], on, how: str,
                      strategy: str, materialize) -> None:
    from ..plan.nodes import JoinNode, attach, node_for
    attach(out, JoinNode(
        node_for(left), node_for(right) if right is not None else None,
        out.schema, on, how, strategy, materialize))


def broadcast_join(left: TensorFrame, right=None, on=None,
                   how: str = "inner", indicator: Optional[str] = None,
                   build: Optional[BuildTable] = None,
                   executor=None) -> TensorFrame:
    """Broadcast hash join: build the right side once, probe ``left``
    block by block (lazy). Pass ``build=`` to reuse a prebuilt
    :class:`BuildTable` — the streaming enrichment path does."""
    if build is None:
        if right is None:
            raise ValueError("broadcast_join needs right= or build=")
        build = BuildTable(right, on)
    on = build.on
    out_schema = join_schema(left.schema, build.schema, on, how,
                             indicator)
    _validate_on(left.schema, build.schema, on)
    counters.inc("relational.broadcast_joins")

    def materialize(names: Sequence[str]) -> List[Block]:
        return [probe_block(build, b, how, list(names),
                            indicator=indicator, executor=executor)
                for b in left.blocks()]

    rows_h, _ = _left_rows_hint(left)
    out = TensorFrame(
        out_schema, lambda: materialize(out_schema.names),
        left.num_partitions,
        plan=f"join[broadcast,{how}]({left._plan})",
        rows_hint=rows_h if how == "left" or build.unique_keys else None)
    _attach_join_node(out, left, None, on, how, "broadcast", materialize)
    # the node prices build columns from the BuildTable directly
    out._plan_node.build = build
    return out


def _left_rows_hint(left: TensorFrame):
    from ..memory.estimate import frame_estimate
    rows, nbytes = frame_estimate(left)
    return (int(rows) if rows is not None else None,
            nbytes)


def _sorted_merged(df: TensorFrame, on: List[str], mesh) -> Block:
    """The frame's rows as ONE block, key-sorted ascending, stable by
    original row order — through the mesh ``dsort`` (elastic recovery +
    external-sort routing) when a mesh is given, the host ``order_by``
    otherwise. Both are stable, so both yield the identical order."""
    if mesh is not None and sum(b.num_rows for b in df.blocks()) > 0:
        from ..parallel.distributed import distribute, dsort
        dist = distribute(df, mesh)
        sorted_dist = dsort(on, dist)
        sorted_df = sorted_dist.collect_frame()
        return Block.concat(sorted_df.blocks(), df.schema)
    return Block.concat(df.order_by(*on).blocks(), df.schema)


def _group_spans(key_arrays: List[np.ndarray]
                 ) -> Tuple[List[np.ndarray], np.ndarray, np.ndarray]:
    """``(uniques, starts, counts)`` of already-sorted key columns."""
    n = len(key_arrays[0])
    if n == 0:
        return [a[:0] for a in key_arrays], np.empty(0, np.int64), \
            np.empty(0, np.int64)
    changed = np.zeros(n, bool)
    changed[0] = True
    for a in key_arrays:
        changed[1:] |= a[1:] != a[:-1]
    starts = np.flatnonzero(changed).astype(np.int64)
    counts = np.diff(np.append(starts, n)).astype(np.int64)
    return [a[starts] for a in key_arrays], starts, counts


def sort_merge_join(left: TensorFrame, right: TensorFrame, on,
                    how: str = "inner", mesh=None,
                    indicator: Optional[str] = None) -> TensorFrame:
    """Mesh sort-merge join for large-large sides (lazy).

    Keys must be numeric scalars (the ``dsort`` contract); string
    columns ride along. Output is key-sorted, stable by original row
    order within equal keys; result re-partitioned to the left frame's
    partition count.
    """
    on = _validate_on(left.schema, right.schema,
                      [on] if isinstance(on, str) else list(on))
    from ..engine.ops import InvalidTypeError
    for k in on:
        if not left.schema[k].dtype.tensor:
            raise InvalidTypeError(
                f"sort_merge_join key {k!r} must be numeric (the dsort "
                f"contract); use the partitioned strategy for string "
                f"keys")
    out_schema = join_schema(left.schema, right.schema, on, how,
                             indicator)
    counters.inc("relational.sort_merge_joins")
    right_values = [f for f in right.schema if f.name not in on]

    def materialize(names: Sequence[str]) -> List[Block]:
        out_set = set(names)
        with span("join.sort_merge"):
            lm = _sorted_merged(left, on, mesh)
            rm = _sorted_merged(right, on, mesh)
            lkeys = [np.asarray(lm.columns[k]) for k in on]
            rkeys = [np.asarray(rm.columns[k]) for k in on]
            lu, lstarts, lcounts = _group_spans(lkeys)
            ru, rstarts, rcounts = _group_spans(rkeys)
            # map each left group to its right group (both unique
            # tables are sorted; reuse the composite matcher)
            if len(lu[0]) and len(ru[0]):
                if len(on) == 1:
                    pos = np.searchsorted(ru[0], lu[0])
                    posc = np.minimum(pos, len(ru[0]) - 1)
                    lmatch = ru[0][posc] == lu[0]
                    rgrp = np.where(lmatch, posc, 0)
                else:
                    from ..engine.ops import _factorize_keys
                    g = len(ru[0])
                    cat = [np.concatenate([u, v])
                           for u, v in zip(ru, lu)]
                    gf = _factorize_keys(cat)
                    inv = np.full(gf.num_groups, -1, np.int64)
                    inv[gf.ids[:g]] = np.arange(g)
                    mapped = inv[gf.ids[g:]]
                    lmatch = mapped >= 0
                    rgrp = np.maximum(mapped, 0)
            else:
                lmatch = np.zeros(len(lu[0]) if lu else 0, bool)
                rgrp = np.zeros(len(lu[0]) if lu else 0, np.int64)
            cb = np.where(lmatch, rcounts[rgrp] if len(rcounts)
                          else 0, 0)
            cb_eff = np.maximum(cb, 1) if how == "left" else cb
            group_rows = lcounts * cb_eff
            total = int(group_rows.sum())
            og = np.repeat(np.arange(len(lcounts)), group_rows)
            shift = np.concatenate([[0], np.cumsum(group_rows)[:-1]])
            pos = np.arange(total) - shift[og]
            denom = cb_eff[og]
            l_idx = lstarts[og] + pos // denom
            r_off = pos % denom
            valid = lmatch[og]
            r_idx = np.where(valid,
                             (rstarts[rgrp[og]] if len(rstarts) else 0)
                             + r_off, 0)
            cols: Dict[str, object] = {}
            for f in left.schema:
                if f.name in out_set:
                    cols[f.name] = _gather_host(lm.columns[f.name],
                                                l_idx)
            for f in right_values:
                if f.name not in out_set:
                    continue
                src = rm.columns[f.name]
                if rm.num_rows == 0:
                    # empty right side: every output row is a fill
                    if isinstance(src, np.ndarray):
                        a = np.full((total,) + src.shape[1:],
                                    _fill_value(f), src.dtype)
                    else:
                        a = [_fill_value(f)] * total
                else:
                    a = _gather_host(src, r_idx)
                    if how == "left" and not valid.all():
                        a = _fill_unmatched(
                            a.copy() if isinstance(a, np.ndarray)
                            else a, f, valid)
                cols[f.name] = a
            if indicator and indicator in out_set:
                cols[indicator] = valid.astype(np.int32)
            counters.inc("relational.rows_joined", total)
            spans = _split_even(total, left.num_partitions)
            return [Block({n_: (c[a:b] if isinstance(c, np.ndarray)
                                else list(c[a:b]))
                           for n_, c in cols.items()}, b - a)
                    for a, b in spans]

    rows_h, _ = _left_rows_hint(left)
    out = TensorFrame(
        out_schema, lambda: materialize(out_schema.names),
        left.num_partitions,
        plan=f"join[sort_merge,{how}]({left._plan})",
        rows_hint=rows_h if how == "left" else None)
    _attach_join_node(out, left, right, on, how, "sort_merge",
                      materialize)
    return out


# ---------------------------------------------------------------------------
# partitioned hash join (shuffle exchange)
# ---------------------------------------------------------------------------

_PROW = "_tft_prow"  # the carried probe row id column (internal)


def _partition_keys_ok(left_schema: Schema, right_schema: Schema,
                       on: Sequence[str]) -> bool:
    """Whether the exchange may hash these keys: both sides present,
    scalar, same tensor-ness, and (for device keys) the same STORAGE
    dtype — the device hash is a bit hash, so int32-vs-int64 key pairs
    would place equal values on different shards."""
    for k in on:
        lf = left_schema.get(k)
        rf = right_schema.get(k)
        if lf is None or rf is None:
            return False
        if lf.sql_rank != 0 or rf.sql_rank != 0:
            return False
        if lf.dtype.tensor != rf.dtype.tensor:
            return False
        if lf.dtype.tensor and (np.dtype(lf.dtype.np_storage)
                                != np.dtype(rf.dtype.np_storage)):
            return False
    return True


def partitioned_hash_join(left: TensorFrame, right: TensorFrame, on,
                          how: str = "inner", mesh=None,
                          indicator: Optional[str] = None) -> TensorFrame:
    """Shuffle-partitioned hash join (lazy): BOTH sides hash-repartition
    by key through :func:`~..parallel.exchange.dexchange`, then every
    shard builds a :class:`BuildTable` over ONLY its own key range and
    probes only its own left rows — per-device build memory O(R/S)
    instead of broadcast's O(R), and the probe side never collects onto
    one device. Equal keys colocate by construction (placement is a pure
    function of key value and shard count), so shard-local probes see
    every match.

    Output is bit-identical to :func:`broadcast_join`: a carried row id
    restores probe order with one stable sort and the original block
    boundaries are re-cut. String keys are supported (host-hashed
    destinations); key STORAGE dtypes must match across sides. A
    single-shard mesh or ``TFT_SHUFFLE=0`` falls back to broadcast —
    bit-identical by the same construction.
    """
    if mesh is None:
        raise ValueError("partitioned_hash_join needs a mesh; use "
                         "broadcast_join for host-only frames")
    on = _validate_on(left.schema, right.schema,
                      [on] if isinstance(on, str) else list(on))
    from ..engine.ops import InvalidTypeError
    if not _partition_keys_ok(left.schema, right.schema, on):
        raise InvalidTypeError(
            f"partitioned_hash_join keys {on!r} have mismatched storage "
            f"dtypes across sides; cast one side first")
    from ..parallel import exchange as _ex
    if not _ex.shuffle_enabled() or mesh.num_data_shards <= 1:
        counters.inc("relational.partitioned_fallbacks")
        return broadcast_join(left, right, on, how=how,
                              indicator=indicator)
    out_schema = join_schema(left.schema, right.schema, on, how,
                             indicator)
    counters.inc("relational.partitioned_joins")

    def materialize(names: Sequence[str]) -> List[Block]:
        out_set = set(names)
        with span("join.partitioned"):
            from ..parallel.distributed import distribute
            lneeded = [f.name for f in left.schema
                       if f.name in out_set or f.name in on]
            rneeded = [f.name for f in right.schema
                       if f.name in out_set or f.name in on]
            lf = left.select(lneeded) \
                if set(lneeded) != set(left.schema.names) else left
            rf = right.select(rneeded) \
                if set(rneeded) != set(right.schema.names) else right
            lm = Block.concat(lf.blocks(), lf.schema)
            block_sizes = [b.num_rows for b in lf.blocks()]
            rm = Block.concat(rf.blocks(), rf.schema)
            if lm.num_rows == 0 or rm.num_rows == 0:
                # a degenerate side: broadcast IS the partitioned plan
                # here (an empty exchange buys nothing) — bit-identical
                counters.inc("relational.partitioned_fallbacks")
                build = BuildTable(rf, on)
                return [probe_block(build, b, how, list(names),
                                    indicator=indicator)
                        for b in lf.blocks()]
            lcols = dict(lm.columns)
            lcols[_PROW] = np.arange(lm.num_rows, dtype=np.int64)
            lschema = Schema(list(lf.schema)
                             + [Field(_PROW, _dt.int64)])
            lex = _ex.dexchange(on, distribute(
                TensorFrame.from_columns(lcols, schema=lschema), mesh))
            rex = _ex.dexchange(on, distribute(
                TensorFrame.from_columns(dict(rm.columns),
                                         schema=rf.schema), mesh))
            # a device lost during one exchange shrinks only that side;
            # re-exchange the wider side at the narrower shard count so
            # key ranges line up again (counts only ever decrease)
            from ..parallel import elastic as _elastic
            while (lex.mesh.num_data_shards
                   != rex.mesh.num_data_shards):
                if (lex.mesh.num_data_shards
                        > rex.mesh.num_data_shards):
                    lex = _ex.dexchange(
                        on, _elastic.reshard(lex, rex.mesh))
                else:
                    rex = _ex.dexchange(
                        on, _elastic.reshard(rex, lex.mesh))
            S = lex.mesh.num_data_shards
            lrp = lex.padded_rows // S
            rrp = rex.padded_rows // S
            lvalid = lex.per_shard_valid()
            rvalid = rex.per_shard_valid()

            def shard_cols(ex, schema, cols, rows_per, s, k):
                out = {}
                for n in cols:
                    a = ex.host_read_padded(n)[s * rows_per:
                                               s * rows_per + k]
                    fld = schema[n]
                    if isinstance(a, np.ndarray) and fld.dtype.tensor \
                            and a.dtype != fld.dtype.np_storage \
                            and fld.dtype is not _dt.bfloat16:
                        a = a.astype(fld.dtype.np_storage)
                    out[n] = a
                return out

            probe_names = list(names) + [_PROW]
            parts: List[Block] = []
            build_bytes: List[int] = []
            for s in range(S):
                lk = int(lvalid[s])
                rk = int(rvalid[s])
                if lk == 0:
                    continue
                rshard = TensorFrame.from_columns(
                    shard_cols(rex, rex.schema, rneeded, rrp, s, rk),
                    schema=rf.schema)
                build = BuildTable(rshard, on)
                build_bytes.append(int(build.dev_bytes))
                lblk = Block(shard_cols(lex, lex.schema,
                                        lneeded + [_PROW], lrp, s, lk),
                             lk)
                parts.append(probe_block(build, lblk, how, probe_names,
                                         indicator=indicator))
            part_schema = Schema([out_schema[n] for n in names
                                  if out_schema.get(n) is not None]
                                 + [Field(_PROW, _dt.int64)])
            if parts:
                cat = Block.concat(parts, part_schema)
            else:
                cat = Block({n: (np.empty(0, np.int64) if n == _PROW
                                 else _empty_like(out_schema[n]))
                             for n in part_schema.names}, 0)
            # matches for one probe row all live on ONE shard (equal
            # keys colocate), so a stable sort by the carried row id
            # restores the exact broadcast probe order
            prow = np.asarray(cat.columns[_PROW])
            perm = np.argsort(prow, kind="stable")
            prow_sorted = prow[perm]
            cols = {n: (cat.columns[n][perm]
                        if isinstance(cat.columns[n], np.ndarray)
                        else [cat.columns[n][i] for i in perm])
                    for n in part_schema.names if n != _PROW}
            counters.inc("relational.partitioned_probe_rows",
                         int(lm.num_rows))
            out._partitioned_info = {
                "shards": S,
                "build_bytes": build_bytes,
                "max_build_bytes": max(build_bytes, default=0),
                "global_build_bytes": int(sum(build_bytes)),
            }
            ex_info = getattr(lex, "_exchange", None)
            if ex_info is not None:
                out._exchange_skew = ex_info
            # re-cut the left frame's block boundaries
            bounds = np.cumsum(np.asarray(block_sizes, np.int64))
            splits = np.searchsorted(prow_sorted, bounds, side="left")
            blocks: List[Block] = []
            a = 0
            for b in splits.tolist():
                blocks.append(Block(
                    {n: (c[a:b] if isinstance(c, np.ndarray)
                         else list(c[a:b])) for n, c in cols.items()},
                    b - a))
                a = b
            return blocks

    rows_h, _ = _left_rows_hint(left)
    out = TensorFrame(
        out_schema, lambda: materialize(out_schema.names),
        left.num_partitions,
        plan=f"join[partitioned,{how}]({left._plan})",
        rows_hint=rows_h if how == "left" else None)
    _attach_join_node(out, left, right, on, how, "partitioned",
                      materialize)
    return out


def _empty_like(field):
    if not field.dtype.tensor:
        return []
    cell = ()
    if field.block_shape is not None:
        cell = tuple(d if isinstance(d, int) and d > 0 else 0
                     for d in field.block_shape.dims[1:])
    return np.empty((0,) + cell, field.dtype.np_storage)


def _broadcast_limit() -> int:
    try:
        return int(os.environ.get("TFT_BROADCAST_LIMIT_BYTES",
                                  _DEFAULT_BROADCAST_LIMIT))
    except ValueError:
        return _DEFAULT_BROADCAST_LIMIT


def _route_join(left: TensorFrame, right: TensorFrame, on_l, mesh,
                how: str) -> Tuple[str, Dict[str, object]]:
    """``join()``'s auto-routing, returned with the decision record the
    flight ring keeps (``tft.why()`` renders it like every other
    autonomous decision): the chosen strategy, the estimated build
    bytes it was judged on, and the limit it was judged against."""
    from ..memory.estimate import frame_estimate
    from ..parallel import exchange as _ex
    limit = _broadcast_limit()
    _, rbytes = frame_estimate(right)
    oversized = mesh is not None and (rbytes is None or rbytes > limit)
    tensor_keys = all(
        left.schema.get(k) is not None and left.schema[k].dtype.tensor
        for k in on_l)
    shuffle_ok = (_ex.shuffle_enabled() and mesh is not None
                  and getattr(mesh, "num_data_shards", 1) > 1
                  and _partition_keys_ok(left.schema, right.schema,
                                         on_l))
    strategy = "broadcast"
    reason = "no mesh" if mesh is None else "build fits"
    if oversized:
        if shuffle_ok:
            # over the broadcast limit with a multi-shard mesh: shuffle
            # both sides; works for string keys too (today's only
            # distributed option for them)
            strategy = "partitioned"
            reason = "build over limit"
        elif tensor_keys:
            strategy = "sort_merge"
            reason = ("build over limit (shuffle off)"
                      if mesh is not None else "build over limit")
        else:
            # string keys without the shuffle path can only broadcast
            reason = "string keys, shuffle off"
    route = {"strategy": strategy, "reason": reason,
             "est_build_bytes": (int(rbytes) if rbytes is not None
                                 else None),
             "limit": limit, "how": how,
             "shuffle": bool(_ex.shuffle_enabled()),
             "keys": list(on_l)}
    return strategy, route


def join(left: TensorFrame, right: TensorFrame, on,
         how: str = "inner", strategy: Optional[str] = None,
         mesh=None, indicator: Optional[str] = None) -> TensorFrame:
    """Join two frames (lazy). ``strategy=None`` auto-routes: broadcast
    for build sides estimated under ``TFT_BROADCAST_LIMIT_BYTES``
    (default 64 MiB) or when no mesh is given; the shuffle-partitioned
    hash join for oversized builds on a multi-shard mesh (string keys
    included); the mesh sort-merge join when the shuffle is off
    (``TFT_SHUFFLE=0``) and keys are numeric. The choice is
    flight-recorded (``tft.why()``) and rendered by ``explain()``. See
    ``docs/joins.md``."""
    on_l = [on] if isinstance(on, str) else list(on)
    route = None
    if strategy is None:
        strategy, route = _route_join(left, right, on_l, mesh, how)
    if strategy == "broadcast":
        out = broadcast_join(left, right, on, how=how,
                             indicator=indicator)
    elif strategy == "sort_merge":
        out = sort_merge_join(left, right, on, how=how, mesh=mesh,
                              indicator=indicator)
    elif strategy == "partitioned":
        out = partitioned_hash_join(left, right, on, how=how, mesh=mesh,
                                    indicator=indicator)
    else:
        raise ValueError(
            f"unknown join strategy {strategy!r}; use 'broadcast', "
            f"'sort_merge', or 'partitioned'")
    if route is not None:
        from ..observability import flight as _flight
        _flight.record("relational.join_route", **route)
        out._join_route = route
    return out


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

_REL_FAMILIES = (
    ("relational.broadcast_joins", "tft_relational_broadcast_joins_total",
     "Broadcast hash joins defined."),
    ("relational.sort_merge_joins",
     "tft_relational_sort_merge_joins_total",
     "Sort-merge joins defined."),
    ("relational.partitioned_joins",
     "tft_relational_partitioned_joins_total",
     "Shuffle-partitioned hash joins defined."),
    ("relational.partitioned_fallbacks",
     "tft_relational_partitioned_fallbacks_total",
     "Partitioned joins that fell back to broadcast (TFT_SHUFFLE=0, "
     "single-shard mesh, or a degenerate empty side)."),
    ("relational.partitioned_probe_rows",
     "tft_relational_partitioned_probe_rows_total",
     "Probe rows routed through the shuffle exchange."),
    ("relational.rows_joined", "tft_relational_rows_joined_total",
     "Join output rows produced."),
    ("relational.probe_dispatches",
     "tft_relational_probe_dispatches_total",
     "Fused build-table gather programs dispatched."),
    ("relational.build_chunks", "tft_relational_build_chunks_total",
     "Build-side chunks created because the ledger refused a resident "
     "broadcast (docs/joins.md)."),
    ("relational.sketch_folds", "tft_relational_sketch_folds_total",
     "Sketch partial tables folded (aggregate/daggregate/stream)."),
    ("relational.key_distinct_probes",
     "tft_relational_key_distinct_probes_total",
     "HLL key-distinct probes run for join cardinality estimates "
     "(docs/adaptive.md)."),
)


def _render_metrics() -> List[str]:
    snap = counters.snapshot()
    lines: List[str] = []
    for key, fam, help_text in _REL_FAMILIES:
        lines.append(f"# HELP {fam} {help_text}")
        lines.append(f"# TYPE {fam} counter")
        lines.append(f"{fam} {snap.get(key, 0)}")
    return lines


from ..observability import metrics as _metrics  # noqa: E402

_metrics.register_metrics_provider("relational", _render_metrics)
