"""Relational half: device-side joins + mergeable sketch aggregates.

The missing relational operators (ROADMAP item 4) — everything the
repo could run before this package was map/filter/sort/groupby on ONE
frame. Two op families, both integrated with the existing subsystems
rather than beside them:

- **Joins** (:mod:`.join`): a broadcast hash join for small build sides
  (the build table factorized once, broadcast device-resident, one
  fused gather program per probe block through the resilient
  :class:`~..engine.executor.BlockExecutor`; a build side the memory
  ledger refuses to hold resident probes in budget-sized CHUNKS
  instead), a mesh sort-merge join for large-large numeric keys (both
  sides through ``dsort`` — columnsort all_to_all exchanges,
  ``elastic_call`` device-loss recovery, and the external-memory sort
  when the ledger demands — then a host merge of the two key-sorted
  streams), and a shuffle-partitioned hash join (both sides
  hash-repartitioned by key through ``parallel/exchange.py`` so every
  shard builds and probes only its own key range — O(R/S) build memory
  per device, string keys included). ``StreamingFrame.join`` enriches
  stream batches against a static build table built ONCE at definition
  time.

- **Sketches** (:mod:`.sketch`): mergeable summaries for aggregates
  where exact answers don't scale — HyperLogLog distinct counts,
  DDSketch-style relative-error quantiles, Misra–Gries top-k heavy
  hitters. Each is a MONOID combiner, so it drops into ``aggregate``,
  ``daggregate``, and windowed stream state through the same
  ``{column: combiner}`` mapping the scalar monoids use; HLL and
  quantile states merge ELEMENTWISE (max / sum), so the streaming
  scatter-merge programs and the cross-block folds run unchanged and
  the three paths return bit-identical sketches.

See ``docs/joins.md``.
"""

from __future__ import annotations

from .join import (BuildTable, broadcast_join, join,
                   partitioned_hash_join, sort_merge_join)
from .sketch import (SketchCombiner, approx_distinct, approx_quantile,
                     approx_top_k, hll_sketch, quantile_sketch,
                     top_k_sketch)

__all__ = [
    "join", "broadcast_join", "sort_merge_join",
    "partitioned_hash_join", "BuildTable",
    "SketchCombiner", "hll_sketch", "quantile_sketch", "top_k_sketch",
    "approx_distinct", "approx_quantile", "approx_top_k",
]
