"""Mergeable sketch aggregates: HLL, relative-error quantiles, top-k.

Each sketch is a :class:`SketchCombiner` — a monoid over fixed-width
per-group state tables — so it drops into the SAME ``{column:
combiner}`` mapping the scalar monoids (sum/min/max/prod) use, across
all three aggregation paths:

- ``aggregate`` (``engine.ops._monoid_aggregate``): per-block partial
  tables folded across blocks with the sketch's combine;
- ``daggregate`` (``parallel.distributed._daggregate``): partials over
  the mesh frame's valid rows, under the op's own ``elastic_call`` (a
  lost device during the column reads recovers like any mesh op);
- windowed streams (``stream.aggregate``): the per-batch partial folds
  into the device-resident window state through the EXISTING
  scatter-merge programs when the sketch merges elementwise
  (``elementwise`` names the scalar combiner — ``max`` for HLL
  registers, ``sum`` for quantile bucket counts), and through a host
  table merge otherwise (top-k).

Determinism: hashing and bucketing run on the host in float64/uint64
(``_hash64`` is a fixed splitmix64 — no process-seed dependence), and
HLL/quantile states merge with elementwise integer monoids, so the
same rows produce BIT-IDENTICAL sketch states through ``aggregate``,
``daggregate``, and a windowed stream. Top-k (Misra–Gries) is
order-dependent in its exact state but keeps its error guarantee under
ANY merge order (mergeable-summaries property): every item with true
frequency above ``n/(k+1)`` survives, with count undercounted by at
most ``n/(k+1)``.

Error bounds (asserted in ``tests/test_relational.py``):

- HLL with ``2**bits`` registers: relative standard error
  ``1.04/sqrt(2**bits)`` (the classic bound; tests assert a 5-sigma
  envelope on fixed datasets);
- quantile: returned values are within relative error
  ``sqrt(gamma) - 1`` (≈ ``alpha``) of the true quantile for values
  inside ``[min_value, max_value]``; out-of-range values clamp to the
  edge buckets (documented degradation);
- top-k: exactness above the ``n/(k+1)`` threshold, counts within
  ``n/(k+1)``.
"""

from __future__ import annotations

import math
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..schema import Field
from ..shape import Shape, Unknown
from ..utils.logging import get_logger

__all__ = ["SketchCombiner", "hll_sketch", "quantile_sketch",
           "top_k_sketch", "approx_distinct", "approx_quantile",
           "approx_top_k"]

_log = get_logger("relational.sketch")


# ---------------------------------------------------------------------------
# deterministic 64-bit hashing (host, vectorized)
# ---------------------------------------------------------------------------

def _splitmix64(x: np.ndarray) -> np.ndarray:
    """The splitmix64 finalizer over uint64 lanes (fixed constants, no
    process seed — the same rows hash the same in every path/process)."""
    with np.errstate(over="ignore"):
        z = x + np.uint64(0x9E3779B97F4A7C15)
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        return z ^ (z >> np.uint64(31))


def _hash64(values: np.ndarray) -> np.ndarray:
    """Deterministic 64-bit hashes of a scalar column (numeric fast
    path over raw bit patterns; strings through blake2b)."""
    a = np.asarray(values)
    if a.dtype == object:
        import hashlib
        out = np.empty(len(a), np.uint64)
        for i, s in enumerate(a):
            h = hashlib.blake2b(str(s).encode("utf-8"),
                                digest_size=8).digest()
            out[i] = np.uint64(int.from_bytes(h, "little"))
        return _splitmix64(out)
    if a.dtype.kind in "fV":
        # kind 'V' is ml_dtypes bfloat16 — a float for hashing purposes
        # (the int fallback would truncate 0.25/0.5/0.75 to one hash);
        # bf16 -> f64 is exact
        x = np.ascontiguousarray(np.asarray(a, np.float64))
        # canonicalize -0.0 == 0.0 and all NaN payloads before hashing
        x = np.where(x == 0.0, 0.0, x)
        x = np.where(np.isnan(x), np.float64(np.nan), x)
        return _splitmix64(x.view(np.uint64))
    if a.dtype.kind == "b":
        return _splitmix64(a.astype(np.uint64))
    return _splitmix64(np.ascontiguousarray(a).astype(np.int64)
                       .view(np.uint64))


def _clz64(w: np.ndarray) -> np.ndarray:
    """Leading-zero count of uint64 lanes (0 -> 64), vectorized
    binary descent (6 steps, no per-row Python)."""
    n = np.zeros(w.shape, np.int64)
    x = np.asarray(w, np.uint64).copy()
    for b in (32, 16, 8, 4, 2, 1):
        top_zero = x < (np.uint64(1) << np.uint64(64 - b))
        n = np.where(top_zero, n + b, n)
        with np.errstate(over="ignore"):
            x = np.where(top_zero, x << np.uint64(b), x)
    return np.where(np.asarray(w) == 0, 64, n).astype(np.int32)


# ---------------------------------------------------------------------------
# the combiner protocol
# ---------------------------------------------------------------------------

class SketchCombiner:
    """A mergeable summary usable wherever a scalar combiner name is.

    State is a ``[groups, state_width]`` array of ``state_dtype``.
    ``elementwise`` names the scalar monoid the state merges with
    (``"max"`` / ``"sum"``) — the streaming scatter-merge programs and
    the device segment kernels reuse it directly; ``None`` means the
    state merges through :meth:`merge_tables` on the host (top-k).
    """

    name = "sketch"
    elementwise: Optional[str] = None
    state_width: int = 0
    state_dtype = np.int32

    # -- validation --------------------------------------------------------
    def validate_input(self, field) -> None:
        """Raise for a column this sketch cannot summarize."""

    # -- state -------------------------------------------------------------
    def neutral_table(self, groups: int) -> np.ndarray:
        return np.zeros((groups, self.state_width), self.state_dtype)

    def block_partial(self, values, ids: np.ndarray,
                      num_groups: int) -> np.ndarray:
        """One block/batch/shard of rows -> a ``[num_groups, S]`` state
        table (host values in their storage dtype; ``ids`` dense group
        ids per row)."""
        raise NotImplementedError

    def combine_np(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        """Elementwise fold of two aligned state tables (host twin of
        the device merge — exact for the integer states)."""
        if self.elementwise == "max":
            return np.maximum(a, b)
        if self.elementwise == "sum":
            return a + b
        raise NotImplementedError

    def merge_tables(self, old: np.ndarray, idx_old: np.ndarray,
                     new: np.ndarray, idx_new: np.ndarray,
                     m: int) -> np.ndarray:
        """Scatter-merge into a ``[m, S]`` union table (the streaming
        state fold for host-merged sketches; elementwise sketches use
        the compiled scatter programs instead)."""
        out = self.neutral_table(m)
        out[idx_old] = old
        out[idx_new] = self.combine_np(out[idx_new], new)
        return out

    # -- output ------------------------------------------------------------
    def out_fields(self, name: str, in_field) -> List[Field]:
        raise NotImplementedError

    def finalize(self, name: str,
                 table: np.ndarray) -> Dict[str, np.ndarray]:
        """State table -> the output column(s) named by
        :meth:`out_fields`."""
        raise NotImplementedError

    def _segment_fold(self, slot: np.ndarray, weight: np.ndarray,
                      ids: np.ndarray, num_groups: int) -> np.ndarray:
        """Shared scatter core: fold per-row ``weight`` into state slot
        ``(group, slot)`` with the sketch's elementwise monoid — ONE
        device segment-reduce dispatch over the combined id space (the
        same kernels the monoid ``aggregate`` path launches), host
        fallback when the rows are tiny (dispatch overhead dominates).
        """
        S = self.state_width
        combined = ids.astype(np.int64) * S + slot.astype(np.int64)
        if len(combined) >= 4096:
            from ..engine.ops import _segment_reduce
            try:
                flat = np.asarray(_segment_reduce(
                    self.elementwise, weight.astype(self.state_dtype),
                    combined, num_groups * S))
                table = flat.reshape(num_groups, S)
                if self.elementwise == "max":
                    # empty (group, slot) cells hold the segment
                    # identity (int min); sketch registers are >= 0
                    table = np.maximum(table, 0)
                return table.astype(self.state_dtype)
            except Exception as e:  # noqa: BLE001 - host twin is exact
                _log.debug("device segment fold unavailable (%s); "
                           "folding on host", e)
        table = self.neutral_table(num_groups)
        flat = table.reshape(-1)
        if self.elementwise == "max":
            np.maximum.at(flat, combined, weight.astype(self.state_dtype))
        else:
            np.add.at(flat, combined, weight.astype(self.state_dtype))
        return table


def _require_scalar_tensor(field, what: str) -> None:
    if field.sql_rank != 0:
        raise ValueError(
            f"{what} expects a scalar column; {field.name!r} holds "
            f"rank-{field.sql_rank} cells")


# ---------------------------------------------------------------------------
# HyperLogLog distinct counts
# ---------------------------------------------------------------------------

class HllSketch(SketchCombiner):
    """HyperLogLog distinct-count sketch: ``2**bits`` int32 registers
    per group, elementwise-max mergeable. Output: one int64 estimated
    distinct count per group; relative standard error
    ``1.04/sqrt(2**bits)``."""

    elementwise = "max"
    state_dtype = np.int32

    def __init__(self, bits: int = 10):
        if not 4 <= int(bits) <= 16:
            raise ValueError(f"hll bits must be in [4, 16], got {bits}")
        self.bits = int(bits)
        self.m = 1 << self.bits
        self.state_width = self.m
        self.name = f"approx_distinct(bits={self.bits})"

    @property
    def relative_error(self) -> float:
        return 1.04 / math.sqrt(self.m)

    def validate_input(self, field) -> None:
        _require_scalar_tensor(field, "approx_distinct")

    def block_partial(self, values, ids, num_groups):
        h = _hash64(values)
        reg = (h >> np.uint64(64 - self.bits)).astype(np.int64)
        w = h << np.uint64(self.bits)
        rho = np.minimum(_clz64(w) + 1, 64 - self.bits + 1)
        return self._segment_fold(reg, rho, ids, num_groups)

    def out_fields(self, name, in_field):
        from .. import dtypes as _dt
        return [Field(name, _dt.int64, block_shape=Shape(Unknown),
                      sql_rank=0)]

    def finalize(self, name, table):
        m = float(self.m)
        alpha = 0.7213 / (1.0 + 1.079 / m)
        est = alpha * m * m / np.sum(
            np.power(2.0, -np.asarray(table, np.float64)), axis=1)
        zeros = np.sum(table == 0, axis=1).astype(np.float64)
        small = (est <= 2.5 * m) & (zeros > 0)
        with np.errstate(divide="ignore"):
            lin = m * np.log(np.where(zeros > 0, m / np.maximum(zeros, 1),
                                      1.0))
        est = np.where(small, lin, est)
        return {name: np.rint(est).astype(np.int64)}


# ---------------------------------------------------------------------------
# DDSketch-style relative-error quantiles
# ---------------------------------------------------------------------------

class QuantileSketch(SketchCombiner):
    """Log-bucketed quantile sketch (DDSketch scheme): int32 bucket
    counts over a fixed gamma-geometric grid, elementwise-sum
    mergeable. For values with ``min_value <= |v| <= max_value`` the
    returned quantile is within relative error ``sqrt(gamma) - 1``
    (~``alpha``); smaller magnitudes collapse into an exact-zero
    bucket, larger ones clamp to the edge bucket (documented
    degradation, not an error). NaN rows are DROPPED (a NaN has no
    quantile rank; the scalar min/max monoids are the ops that
    propagate NaN)."""

    elementwise = "sum"
    state_dtype = np.int32

    def __init__(self, qs=0.5, alpha: float = 0.02,
                 min_value: float = 1e-6, max_value: float = 1e6):
        if not 0.0 < alpha < 0.5:
            raise ValueError(f"alpha must be in (0, 0.5), got {alpha}")
        if not 0.0 < min_value < max_value:
            raise ValueError(
                f"need 0 < min_value < max_value, got "
                f"{min_value}/{max_value}")
        self.qs = tuple(float(q) for q in
                        (qs if isinstance(qs, (tuple, list)) else (qs,)))
        for q in self.qs:
            if not 0.0 <= q <= 1.0:
                raise ValueError(f"quantile {q} not in [0, 1]")
        if not self.qs:
            raise ValueError("need at least one quantile")
        self.alpha = float(alpha)
        self.gamma = (1.0 + alpha) / (1.0 - alpha)
        self._lg = math.log(self.gamma)
        self.min_value = float(min_value)
        self.max_value = float(max_value)
        # per sign: buckets i cover [min * gamma^i, min * gamma^(i+1))
        self.side = int(math.ceil(
            math.log(max_value / min_value) / self._lg)) + 1
        # layout: [neg side (reversed)] [zero] [pos side]
        self.state_width = 2 * self.side + 1
        self.name = (f"approx_quantile(q={list(self.qs)}, "
                     f"alpha={self.alpha})")

    @property
    def relative_error(self) -> float:
        """The guaranteed in-range bound: reps sit at the geometric
        bucket midpoint, so error <= sqrt(gamma) - 1."""
        return math.sqrt(self.gamma) - 1.0

    def validate_input(self, field) -> None:
        _require_scalar_tensor(field, "approx_quantile")
        if not field.dtype.tensor:
            raise ValueError(
                f"approx_quantile needs a numeric column; {field.name!r} "
                f"is {field.dtype.name}")

    def _bucket(self, v: np.ndarray) -> np.ndarray:
        x = np.asarray(v, np.float64)
        mag = np.abs(x)
        with np.errstate(divide="ignore", invalid="ignore"):
            i = np.floor(np.log(np.maximum(mag, self.min_value)
                                / self.min_value) / self._lg)
        i = np.clip(np.nan_to_num(i, nan=0.0), 0,
                    self.side - 1).astype(np.int64)
        zero = mag < self.min_value
        slot = np.where(x >= 0, self.side + 1 + i, self.side - 1 - i)
        return np.where(zero, self.side, slot).astype(np.int64)

    def _rep(self, slot: int) -> float:
        if slot == self.side:
            return 0.0
        if slot > self.side:
            i = slot - self.side - 1
            return self.min_value * self.gamma ** (i + 0.5)
        i = self.side - 1 - slot
        return -self.min_value * self.gamma ** (i + 0.5)

    def block_partial(self, values, ids, num_groups):
        x = np.asarray(values, np.float64)
        keep = ~np.isnan(x)
        if not keep.all():
            # NaN is not a value with a quantile rank: drop it (the
            # scalar min/max monoids propagate NaN; a sketch counting
            # it as data would drag every quantile toward -min_value)
            x = x[keep]
            ids = np.asarray(ids)[keep]
        slot = self._bucket(x)
        ones = np.ones(len(slot), np.int32)
        return self._segment_fold(slot, ones, ids, num_groups)

    def out_fields(self, name, in_field):
        from .. import dtypes as _dt
        if len(self.qs) == 1:
            return [Field(name, _dt.double, block_shape=Shape(Unknown),
                          sql_rank=0)]
        return [Field(name, _dt.double,
                      block_shape=Shape(Unknown, len(self.qs)),
                      sql_rank=1)]

    def finalize(self, name, table):
        t = np.asarray(table, np.int64)
        g = t.shape[0]
        cum = np.cumsum(t, axis=1)
        n = cum[:, -1]
        out = np.zeros((g, len(self.qs)), np.float64)
        reps = np.array([self._rep(s) for s in range(self.state_width)])
        for j, q in enumerate(self.qs):
            r = np.maximum(1, np.ceil(q * n).astype(np.int64))
            # first bucket whose cumulative count reaches rank r —
            # vectorized over groups (cum rows are non-decreasing, so
            # the count of entries below the rank IS the index)
            pos = (cum < r[:, None]).sum(axis=1)
            pos = np.minimum(pos, self.state_width - 1)
            out[:, j] = reps[pos]
            out[n == 0, j] = np.nan
        if len(self.qs) == 1:
            return {name: out[:, 0]}
        return {name: out}


# ---------------------------------------------------------------------------
# Misra–Gries top-k heavy hitters
# ---------------------------------------------------------------------------

class TopKSketch(SketchCombiner):
    """Misra–Gries heavy-hitter summary over an INTEGER column: ``k``
    (item, count) slots per group packed as a ``[G, 2k]`` int64 state
    (items first, counts second; count 0 marks an empty slot).

    The mergeable-summaries guarantee: after summarizing ``n`` rows,
    every item with true frequency > ``n/(k+1)`` is present, and every
    kept count is an UNDER-estimate by at most ``n/(k+1)`` — under any
    merge order (blocks, shards, or stream batches). Merging is a host
    table fold (``elementwise=None``); stream state for top-k columns
    therefore lives host-side, which also means it costs zero device
    bytes. String/float heavy hitters: factorize to integer ids
    upstream (``daggregate`` hot-key salting + ``frame.hot_keys()``
    already names hot STRING keys).
    """

    elementwise = None
    state_dtype = np.int64

    def __init__(self, k: int = 8):
        if int(k) < 1:
            raise ValueError(f"top-k needs k >= 1, got {k}")
        self.k = int(k)
        self.state_width = 2 * self.k
        self.name = f"approx_top_k(k={self.k})"

    def validate_input(self, field) -> None:
        _require_scalar_tensor(field, "approx_top_k")
        if not field.dtype.tensor or \
                np.dtype(field.dtype.np_storage).kind not in "iub":
            raise ValueError(
                f"approx_top_k summarizes integer columns; "
                f"{field.name!r} is {field.dtype.name} (factorize "
                f"strings/floats to ids first)")

    def _compress(self, items: np.ndarray,
                  counts: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Misra–Gries reduction to k slots: subtract the (k+1)-th
        largest count from all, keep the positive ones."""
        if len(items) > self.k:
            dec = np.partition(counts, -(self.k + 1))[-(self.k + 1)]
            counts = counts - dec
            keep = counts > 0
            items, counts = items[keep], counts[keep]
            if len(items) > self.k:  # ties at the cut: deterministic trim
                order = np.lexsort((items, -counts))[: self.k]
                items, counts = items[order], counts[order]
        out_i = np.zeros(self.k, np.int64)
        out_c = np.zeros(self.k, np.int64)
        order = np.lexsort((items, -counts))
        out_i[: len(items)] = items[order]
        out_c[: len(items)] = counts[order]
        return out_i, out_c

    def _rows(self, state_row: np.ndarray
              ) -> Tuple[np.ndarray, np.ndarray]:
        items = state_row[: self.k]
        counts = state_row[self.k:]
        live = counts > 0
        return items[live], counts[live]

    def block_partial(self, values, ids, num_groups):
        v = np.asarray(values).astype(np.int64)
        ids = np.asarray(ids, np.int64)
        order = np.lexsort((v, ids))
        sv, si = v[order], ids[order]
        changed = np.ones(len(sv), bool)
        if len(sv) > 1:
            changed[1:] = (sv[1:] != sv[:-1]) | (si[1:] != si[:-1])
        starts = np.flatnonzero(changed)
        pair_counts = np.diff(np.append(starts, len(sv)))
        pair_items, pair_gids = sv[starts], si[starts]
        table = self.neutral_table(num_groups)
        gchg = np.ones(len(pair_gids), bool)
        if len(pair_gids) > 1:
            gchg[1:] = pair_gids[1:] != pair_gids[:-1]
        gstarts = np.flatnonzero(gchg)
        gends = np.append(gstarts[1:], len(pair_gids))
        for a, b in zip(gstarts, gends):
            g = int(pair_gids[a])
            it, ct = self._compress(pair_items[a:b], pair_counts[a:b])
            table[g, : self.k] = it
            table[g, self.k:] = ct
        return table

    def combine_np(self, a, b):
        a = np.asarray(a)
        b = np.asarray(b)
        out = np.zeros_like(a)
        for g in range(a.shape[0]):
            ia, ca = self._rows(a[g])
            ib, cb = self._rows(b[g])
            items = np.concatenate([ia, ib])
            counts = np.concatenate([ca, cb])
            if len(items):
                u, inv = np.unique(items, return_inverse=True)
                summed = np.zeros(len(u), np.int64)
                np.add.at(summed, inv, counts)
                it, ct = self._compress(u, summed)
            else:
                it = ct = np.zeros(self.k, np.int64)
            out[g, : self.k] = it
            out[g, self.k:] = ct
        return out

    def out_fields(self, name, in_field):
        from .. import dtypes as _dt
        return [Field(name, _dt.int64,
                      block_shape=Shape(Unknown, self.k), sql_rank=1),
                Field(f"{name}_counts", _dt.int64,
                      block_shape=Shape(Unknown, self.k), sql_rank=1)]

    def finalize(self, name, table):
        t = np.asarray(table, np.int64)
        return {name: t[:, : self.k].copy(),
                f"{name}_counts": t[:, self.k:].copy()}


# ---------------------------------------------------------------------------
# public constructors (the names users put in the fetches mapping)
# ---------------------------------------------------------------------------

def hll_sketch(bits: int = 10) -> HllSketch:
    """A HyperLogLog distinct-count combiner (``2**bits`` registers)."""
    return HllSketch(bits=bits)


def quantile_sketch(qs=0.5, alpha: float = 0.02,
                    min_value: float = 1e-6,
                    max_value: float = 1e6) -> QuantileSketch:
    """A mergeable relative-error quantile combiner (DDSketch grid)."""
    return QuantileSketch(qs=qs, alpha=alpha, min_value=min_value,
                          max_value=max_value)


def top_k_sketch(k: int = 8) -> TopKSketch:
    """A Misra–Gries top-k heavy-hitter combiner."""
    return TopKSketch(k=k)


# ergonomic aliases matching the combiner-name idiom
approx_distinct = hll_sketch
approx_quantile = quantile_sketch
approx_top_k = top_k_sketch


# (the mapping-shape checks live in ONE place — engine.ops._is_sketch /
# _monoid_mapping — and the three aggregation paths all route through
# them; this module only defines the combiners themselves)
