"""The six core operations: map/reduce/aggregate over TensorFrames.

Engine analogue of the reference's ``DebugRowOps`` + ``SchemaTransforms``
(``/root/reference/src/main/scala/org/tensorframes/impl/DebugRowOps.scala``),
with the same user-visible contracts:

- ``map_blocks`` / ``map_rows`` are **lazy**, append output columns **sorted
  by name** (``DebugRowOps.scala:344-355``), and reject fetch names that
  collide with existing columns;
- ``map_blocks(trim=True)`` returns only the fetch columns and may change the
  number of rows;
- ``reduce_blocks`` requires, for each fetch ``z``, an input ``z_input`` of
  rank one higher (``core.py:234-237``); ``reduce_rows`` requires inputs
  ``z_1``/``z_2`` of the fetch's own shape (``core.py:109-111``); both are
  **eager** and reduce per-partition first, then combine partials — the
  reference's Spark tree-reduce becomes a single stacked block-reduce (the
  combine order is unspecified by contract);
- ``aggregate`` groups by key columns and reduces each group with the
  buffered-compaction contract of the reference's UDAF
  (``DebugRowOps.scala:587-681``).

Validation errors mirror ``Operations.scala:7-15``'s exception taxonomy.
"""

from __future__ import annotations

import inspect
import threading
from typing import (Callable, Dict, List, Mapping, NamedTuple, Optional,
                    Sequence, Tuple, Union)

from collections import OrderedDict

import jax
import numpy as np

from .. import dtypes as _dt
from .. import memory as _memory
from ..computation import Computation, TensorSpec
from ..frame import Block, GroupedFrame, Row, TensorFrame
from ..marshal import Column
from ..observability.events import traced_query
from ..schema import Field, Schema
from ..shape import Shape, Unknown
from ..utils.logging import get_logger
from ..utils.tracing import span
from .compaction import CompactionBuffer, DEFAULT_BUFFER_SIZE
from .executor import (BlockExecutor, default_executor,
                       default_padding_executor)
from . import pipeline as _pipeline

_log = get_logger("engine.ops")

__all__ = [
    "map_blocks", "map_rows", "reduce_blocks", "reduce_rows", "aggregate",
    "InputNotFoundError", "InvalidTypeError", "InvalidShapeError",
]


class InputNotFoundError(ValueError):
    """A computation input has no matching DataFrame column
    (``Operations.scala:7-9`` InputNotFoundException analogue)."""


class InvalidTypeError(ValueError):
    """Column/input dtype mismatch — no implicit casting is performed
    (``Operations.scala:13-15`` InvalidTypeException analogue)."""


class InvalidShapeError(ValueError):
    """Column/input shape incompatibility
    (``Operations.scala:10-12`` InvalidDimensionException analogue)."""


Fetches = Union[Computation, Callable]


# ---------------------------------------------------------------------------
# Computation adaptation: callables -> Computation bound to the frame schema
# ---------------------------------------------------------------------------

def _callable_input_names(fn: Callable) -> List[str]:
    sig = inspect.signature(fn)
    names = []
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD,
                      p.KEYWORD_ONLY):
            names.append(p.name)
        else:
            raise ValueError(
                f"Cannot derive computation inputs from *args/**kwargs "
                f"parameter {p.name!r}; pass a Computation instead")
    return names


def _dsl_to_computation(fetches, schema: Schema, block_level: bool):
    """Hook for DSL nodes (duck-typed); implemented in tensorframes_tpu.dsl."""
    from ..dsl import lower as _dsl_lower  # local import; cycle-free
    return _dsl_lower.nodes_to_computation(fetches, schema, block_level)


def _is_dsl(fetches) -> bool:
    if isinstance(fetches, (list, tuple)) and fetches:
        fetches = fetches[0]
    return hasattr(fetches, "_tft_dsl_node")


def _field_spec(field: Field, block_level: bool, context: str) -> Shape:
    if not field.dtype.tensor:
        raise InvalidTypeError(
            f"Column {field.name!r} has non-tensor type {field.dtype.name} "
            f"and cannot feed a computation ({context}); it can only pass "
            f"through or serve as a group_by key")
    if field.block_shape is None:
        raise InvalidShapeError(
            f"Column {field.name!r} has no tensor shape information; run "
            f"analyze() on the frame first ({context})")
    return field.block_shape if block_level else field.block_shape.tail


def _map_computation(fetches: Fetches, schema: Schema,
                     block_level: bool) -> Computation:
    if isinstance(fetches, Computation):
        return fetches
    if _is_dsl(fetches):
        return _dsl_to_computation(fetches, schema, block_level)
    if callable(fetches):
        names = _callable_input_names(fetches)
        specs = []
        for n in names:
            field = schema.get(n)
            if field is None:
                raise InputNotFoundError(
                    f"Computation input {n!r} found no matching column; "
                    f"columns: {schema.names}")
            specs.append(TensorSpec(
                n, field.dtype, _field_spec(field, block_level, "map")))
        return Computation.trace(fetches, specs)
    raise TypeError(f"Unsupported fetches object: {type(fetches)}")


def _reduce_computation(fetches: Fetches, schema: Schema,
                        suffixes: Sequence[str],
                        block_level: bool) -> Computation:
    """Build/check a reduce computation whose inputs are derived from fetch
    names + naming-contract suffixes ('_input' or '_1'/'_2')."""
    if isinstance(fetches, Computation):
        return fetches
    if _is_dsl(fetches):
        from ..dsl import lower as _dsl_lower
        return _dsl_lower.nodes_to_reduce_computation(
            fetches, schema, suffixes, block_level)
    if callable(fetches):
        names = _callable_input_names(fetches)
        specs = []
        for n in names:
            base = _strip_suffix(n, suffixes)
            if base is None:
                raise InputNotFoundError(
                    f"Reduce input {n!r} does not follow the naming "
                    f"contract (expected one of "
                    f"{[f'<col>{s}' for s in suffixes]})")
            field = schema.get(base)
            if field is None:
                raise InputNotFoundError(
                    f"Reduce input {n!r}: no column named {base!r}; "
                    f"columns: {schema.names}")
            shape = _field_spec(field, block_level, "reduce")
            specs.append(TensorSpec(n, field.dtype, shape))
        return Computation.trace(fetches, specs)
    raise TypeError(f"Unsupported fetches object: {type(fetches)}")


def _strip_suffix(name: str, suffixes: Sequence[str]) -> Optional[str]:
    for s in suffixes:
        if name.endswith(s) and len(name) > len(s):
            return name[: -len(s)]
    return None


# ---------------------------------------------------------------------------
# Schema validation (SchemaTransforms analogue)
# ---------------------------------------------------------------------------

def _validate_map(comp: Computation, schema: Schema, block_level: bool,
                  trim: bool) -> Schema:
    for spec in comp.inputs:
        field = schema.get(spec.name)
        if field is None:
            raise InputNotFoundError(
                f"Computation input {spec.name!r} found no matching column; "
                f"columns: {schema.names}")
        if field.dtype is not spec.dtype:
            raise InvalidTypeError(
                f"Column {spec.name!r} has type {field.dtype} but the "
                f"computation expects {spec.dtype}; no implicit casting is "
                f"performed")
        declared = _field_spec(field, block_level, "map")
        if not declared.is_more_precise_than(spec.shape) and \
                not spec.shape.is_more_precise_than(declared):
            raise InvalidShapeError(
                f"Column {spec.name!r} shape {declared} is incompatible "
                f"with computation input shape {spec.shape}")
    out_fields = []
    for spec in comp.outputs:  # already sorted by name
        if not trim and spec.name in schema:
            raise ValueError(
                f"Fetch name {spec.name!r} collides with an existing "
                f"column; fetch names must differ from all input columns")
        shape = spec.shape
        if block_level:
            if shape.ndim == 0:
                raise InvalidShapeError(
                    f"Fetch {spec.name!r} is a scalar; block-level outputs "
                    f"must have a leading row dimension")
            shape = shape.with_lead(Unknown)
        else:
            shape = shape.prepend(Unknown)
        out_fields.append(Field(spec.name, spec.dtype, block_shape=shape,
                                sql_rank=max(0, shape.ndim - 1)))
    if trim:
        return Schema(out_fields)
    return schema.append(out_fields)


def _validate_reduce(comp: Computation, schema: Schema,
                     suffixes: Sequence[str], rank_delta: int) -> None:
    """Check the reduce naming contract (reduceBlocksSchema /
    reduceRowsSchema analogue, ``DebugRowOps.scala:76-258``)."""
    fetch_names = set(comp.output_names)
    consumed = set()
    for spec in comp.inputs:
        base = _strip_suffix(spec.name, suffixes)
        if base is None or base not in fetch_names:
            raise InputNotFoundError(
                f"Reduce input {spec.name!r} does not correspond to any "
                f"fetch; fetches: {sorted(fetch_names)} with suffixes "
                f"{list(suffixes)}")
        field = schema.get(base)
        if field is None:
            raise InputNotFoundError(
                f"Reduce fetch {base!r} has no matching column; columns: "
                f"{schema.names}")
        if field.dtype is not spec.dtype:
            raise InvalidTypeError(
                f"Column {base!r} has type {field.dtype} but reduce input "
                f"{spec.name!r} expects {spec.dtype}")
        out_spec = comp.output(base)
        if out_spec.dtype is not spec.dtype:
            raise InvalidTypeError(
                f"Fetch {base!r} dtype {out_spec.dtype} differs from its "
                f"input {spec.name!r} dtype {spec.dtype}")
        if spec.shape.ndim != out_spec.shape.ndim + rank_delta:
            raise InvalidShapeError(
                f"Reduce input {spec.name!r} has rank {spec.shape.ndim}; "
                f"expected fetch rank + {rank_delta} = "
                f"{out_spec.shape.ndim + rank_delta}")
        consumed.add(base)
    for f in comp.output_names:
        missing = [f + s for s in suffixes if not any(
            i.name == f + s for i in comp.inputs)]
        if missing:
            raise InputNotFoundError(
                f"Fetch {f!r} is missing required reduce input(s) "
                f"{missing}")
    unused = [n for n in schema.names if n not in consumed]
    if unused:
        # the reference tolerates ride-along columns a reduction does not
        # consume (BasicOperationsSuite.scala:178-187: a string `key2`
        # rides along silently and reduce_sum over `x` returns Row(4.1)) —
        # match that contract, but with a warning instead of silence: an
        # unconsumed column in a reduce has repeatedly been a user bug in
        # the reference's own demos (geom_mean.py). The columns simply do
        # not appear in the result row.
        _log.warning(
            "Columns %s are not consumed by the reduction and will be "
            "ignored (select() the fetch-backing columns to silence this)",
            unused)


# ---------------------------------------------------------------------------
# pipelined streaming shared by the lazy block ops
# ---------------------------------------------------------------------------

def _stream_thunk(df: TensorFrame, ex, run_block, submit_block,
                  drain_block, tag: Optional[str] = None):
    """The lazy forcing every streaming op shares: blocks through the
    bounded in-flight window, drained FIFO (``docs/pipeline.md``).
    ``tag`` is the stream's stable identity for preemption checkpoints
    (op + computation input/output names + the input frame's plan
    string — identical across a park and its resume, distinct between
    ops); ``None`` (the safe default for any future call site that
    forgets one) makes the stream preemptible WITHOUT checkpointing."""
    return lambda: _pipeline.run_pipelined(
        df.blocks(), run_block, submit_block, drain_block,
        depth=_pipeline.stream_depth(ex), tag=tag)


def _stream_tag(op: str, comp, plan: str) -> str:
    """The checkpoint identity of one op stream: the op, the
    computation's input/output names, and the output frame's plan
    string. Two DIFFERENT sibling streams in one query must never
    share a tag + block count (a resumed checkpoint restores only into
    its own stream — ``engine/preempt.py``); computations whose
    in/out names coincide but whose bodies differ are not
    distinguished here, which is covered by the deterministic forcing
    order of a thunk re-run plus the discard-on-first-mismatch
    semantics of the checkpoint."""
    return (f"{op}[{','.join(comp.input_names)}->"
            f"{','.join(comp.output_names)}]{plan}")


def _drain_with(finish):
    """A drain half that passes finished Blocks through (empty/ragged
    blocks complete at submit) and finishes pendings with ``finish(b,
    host_out)``."""
    def drain_block(pending, b: Block) -> Block:
        if isinstance(pending, Block):
            return pending
        return finish(b, pending.drain())
    return drain_block


# ---------------------------------------------------------------------------
# map_blocks
# ---------------------------------------------------------------------------

def empty_schema_block(schema: Schema) -> Block:
    """A 0-row block of ``schema``, Unknown cell dims floored at 0 — the
    empty-partition construction (reference DebugRowOps.scala:374-385).
    The SINGLE definition: ``map_blocks``' empty guard and the plan
    executor's empty-chain replay must agree bit-for-bit."""
    cols: Dict[str, Column] = {}
    for f in schema:
        cell = f.cell_shape
        dims = tuple(0 if d == Unknown else d
                     for d in (cell.dims if cell else ()))
        cols[f.name] = np.empty((0,) + dims, f.dtype.np_storage)
    return Block(cols, 0)


def empty_fetch_columns(b: Block, outputs) -> Block:
    """A 0-row block: ``b``'s columns plus empty fetch columns built
    from row-level output specs — ``map_rows``' empty guard, shared
    with the plan executor's empty-chain replay."""
    cols = dict(b.columns)
    for s in outputs:
        dims = tuple(0 if d == Unknown else d for d in s.shape.dims)
        cols[s.name] = np.empty((0,) + dims, s.dtype.np_storage)
    return Block(cols, 0)


def map_blocks(fetches: Fetches, df: TensorFrame, trim: bool = False,
               executor: Optional[BlockExecutor] = None) -> TensorFrame:
    """Transform a frame block-by-block, appending (or, with ``trim``,
    replacing with) the computation's outputs. Lazy."""
    ex = executor or default_executor()
    # the canonical computation is cached per fetches object (weakly):
    # repeated chains over the same fetches share one comp — and with
    # it every downstream jit/program cache AND the plan-fingerprint
    # result cache's op identity (docs/adaptive.md)
    comp = cached_map_computation(fetches, df.schema, block_level=True)
    out_schema = _validate_map(comp, df.schema, block_level=True, trim=trim)
    in_names = comp.input_names
    fetch_names = comp.output_names
    _log.debug("map_blocks: inputs=%s fetches=%s trim=%s",
               in_names, fetch_names, trim)

    def empty_block() -> Block:
        return empty_schema_block(out_schema)

    def finish_block(b: Block, out: Dict[str, np.ndarray]) -> Block:
        lead = {out[f].shape[0] for f in fetch_names}
        if len(lead) > 1:
            raise InvalidShapeError(
                f"Fetches disagree on output row count: "
                f"{ {f: out[f].shape[0] for f in fetch_names} }")
        n_out = lead.pop()
        if not trim and n_out != b.num_rows:
            raise InvalidShapeError(
                f"map_blocks output has {n_out} rows for a {b.num_rows}-row "
                f"block; use trim=True for row-count-changing computations")
        if trim:
            return Block({f: out[f] for f in fetch_names}, n_out)
        cols = dict(b.columns)
        cols.update({f: out[f] for f in fetch_names})
        return Block(cols, b.num_rows)

    def run_block(b: Block) -> Block:
        if b.num_rows == 0:
            return empty_block()
        with span("map_blocks.block"):
            arrays = {n: b.dense(n) for n in in_names}
            # trim may legally change the row count; padding would corrupt
            # it, and non-row-local computations must see the true block.
            out = ex.run(comp, arrays, pad_ok=not trim)
        return finish_block(b, out)

    def submit_block(b: Block):
        if b.num_rows == 0:
            return empty_block()  # finished: flows through the window
        arrays = {n: b.dense(n) for n in in_names}
        return _pipeline.submit(ex, comp, arrays, pad_ok=not trim)

    rows_h, bytes_h = _memory.propagate_hints(df, out_schema)
    plan_s = f"map_blocks({df._plan})"
    out = TensorFrame(out_schema,
                      _stream_thunk(df, ex, run_block, submit_block,
                                    _drain_with(finish_block),
                                    tag=_stream_tag("map_blocks", comp,
                                                    plan_s)),
                      df.num_partitions,
                      plan=plan_s,
                      rows_hint=None if trim else rows_h,
                      bytes_hint=None if trim else bytes_h)
    if executor is None:
        # record the logical-plan node (docs/plan.md); an explicit
        # executor= pins the per-op path, so no node is attached
        from ..plan.nodes import MapBlocksNode, attach, node_for
        attach(out, MapBlocksNode(node_for(df), out_schema, comp, trim))
    return out


# ---------------------------------------------------------------------------
# map_rows
# ---------------------------------------------------------------------------

def map_rows(fetches: Fetches, df: TensorFrame,
             executor: Optional[BlockExecutor] = None) -> TensorFrame:
    """Transform a frame row-by-row, appending output columns. Lazy.

    Dense blocks take a vectorized path (``jax.vmap`` over the row dim — one
    compile per block signature instead of the reference's one
    ``Session.Run`` per row, ``DebugRowOps.scala:810-841``); ragged columns
    fall back to genuine per-row execution, which is what makes
    variable-length cells work.
    """
    # rows are independent by construction here, so the bucketed-padding
    # executor is safe: streams of odd-sized blocks (and ragged group
    # sizes) share O(log) compile signatures instead of one per size
    ex = executor or default_padding_executor()
    # cached per fetches object, like map_blocks/filter_rows: the
    # canonical comp is what the result-cache fingerprint interns
    comp = cached_map_computation(fetches, df.schema, block_level=False)
    out_schema = _validate_map(comp, df.schema, block_level=False, trim=False)
    in_names = comp.input_names
    fetch_names = comp.output_names

    # the vmapped twin is cached ON the computation: a fresh Computation
    # per call would defeat every per-Computation jit cache downstream —
    # repeated map_rows over the same comp (the streaming layer maps one
    # comp across every batch) must re-dispatch one compiled program, not
    # re-trace per call. Benign race: two threads building it construct
    # equal twins and the setdefault-style getattr keeps one winner.
    vcomp = getattr(comp, "_tft_vmapped", None)
    if vcomp is None:
        vcomp = Computation(
            lambda d: jax.vmap(comp.fn)(d),
            [TensorSpec(s.name, s.dtype, s.shape.prepend(Unknown))
             for s in comp.inputs],
            [TensorSpec(s.name, s.dtype, s.shape.prepend(Unknown))
             for s in comp.outputs])
        with _comp_cache_lock:
            prior = getattr(comp, "_tft_vmapped", None)
            if prior is None:
                comp._tft_vmapped = vcomp
            else:
                vcomp = prior

    def attach_outputs(b: Block, out: Dict[str, np.ndarray]) -> Block:
        cols = dict(b.columns)
        cols.update({f: out[f] for f in fetch_names})
        return Block(cols, b.num_rows)

    def run_block(b: Block) -> Block:
        if b.num_rows == 0:
            return empty_fetch_columns(b, comp.outputs)
        dense = all(not b.is_ragged(n) for n in in_names)
        if dense:
            with span("map_rows.block_dense"):
                arrays = {n: b.dense(n) for n in in_names}
                out = ex.run(vcomp, arrays)
            return attach_outputs(b, out)
        # ragged: group rows by cell-shape signature and run ONE vmapped
        # dispatch per distinct signature (instead of the reference's one
        # Session.Run per row, DebugRowOps.scala:810-841). Each group's
        # stacked block is packed in a single threaded native copy.
        from .. import native as _native
        cells = {n: [np.asarray(b.columns[n][i]) for i in range(b.num_rows)]
                 for n in in_names}
        groups: Dict[Tuple, List[int]] = {}
        for i in range(b.num_rows):
            sig = tuple(cells[n][i].shape for n in in_names)
            groups.setdefault(sig, []).append(i)
        per_row: Dict[str, List[Optional[np.ndarray]]] = {
            f: [None] * b.num_rows for f in fetch_names}
        for idxs in groups.values():
            arrays = {}
            for n in in_names:
                grp = [cells[n][i] for i in idxs]
                values, _ = _native.pack_ragged(grp, dtype=grp[0].dtype)
                arrays[n] = values.reshape((len(idxs),) + grp[0].shape)
            # rows are independent under vmap, so row-dim padding is as
            # safe here as on the dense path: group sizes bucket to O(log)
            # compile signatures instead of one per distinct count
            out = ex.run(vcomp, arrays)
            for f in fetch_names:
                for j, i in enumerate(idxs):
                    per_row[f][i] = out[f][j]
        cols = dict(b.columns)
        for f in fetch_names:
            arrays = per_row[f]
            shapes = {a.shape for a in arrays}
            cols[f] = (np.stack(arrays) if len(shapes) == 1
                       else arrays)
        return Block(cols, b.num_rows)

    def submit_block(b: Block):
        # empty and ragged blocks run serially at submit (ragged blocks
        # are many grouped dispatches, not one async unit) and flow
        # through the window as finished Blocks; dense blocks pipeline.
        if b.num_rows == 0 or any(b.is_ragged(n) for n in in_names):
            return run_block(b)
        arrays = {n: b.dense(n) for n in in_names}
        return _pipeline.submit(ex, vcomp, arrays)

    rows_h, bytes_h = _memory.propagate_hints(df, out_schema)
    plan_s = f"map_rows({df._plan})"
    out = TensorFrame(out_schema,
                      _stream_thunk(df, ex, run_block, submit_block,
                                    _drain_with(attach_outputs),
                                    tag=_stream_tag("map_rows", comp,
                                                    plan_s)),
                      df.num_partitions,
                      plan=plan_s,
                      rows_hint=rows_h, bytes_hint=bytes_h)
    if executor is None:
        from ..plan.nodes import MapRowsNode, attach, node_for
        attach(out, MapRowsNode(node_for(df), out_schema, comp, vcomp))
    return out


# ---------------------------------------------------------------------------
# filter_rows
# ---------------------------------------------------------------------------

def cached_map_computation(fetches, schema: Schema,
                           block_level: bool) -> Computation:
    """`_map_computation` with reuse keyed weakly by the fetches object —
    the map-side twin of :func:`cached_reduce_computation` (a fresh
    Computation per call would defeat every per-Computation jit/program
    cache downstream). Thread-safe: concurrent queries (the serving
    layer's workers) racing the same fetches converge on ONE canonical
    Computation — the per-fetches dict is only read/written under
    ``_comp_cache_lock`` and the insert is a ``setdefault``, so the loser
    of a trace race adopts the winner's object (tracing itself runs
    outside the lock)."""
    sig = ("map", block_level,
           tuple((f.name, f.dtype.name,
                  tuple(f.block_shape.dims) if f.block_shape is not None
                  else None)
                 for f in schema))
    try:
        with _comp_cache_lock:
            per = _fetches_comp_cache.setdefault(fetches, {})
            comp = per.get(sig)
    except TypeError:
        per = None
        comp = None
    if comp is not None:
        return comp
    comp = _map_computation(fetches, schema, block_level=block_level)
    if per is not None:
        with _comp_cache_lock:
            comp = per.setdefault(sig, comp)
    return comp


def _filter_computation(predicate: Fetches, schema: Schema) -> Computation:
    """Build/validate a filter predicate: one rank-1 boolean/integer fetch
    over block-level columns (nonzero keeps the row). Shared by the host
    op and the mesh ``dfilter``."""
    comp = cached_map_computation(predicate, schema, block_level=True)
    if len(comp.outputs) != 1:
        raise InvalidShapeError(
            f"filter predicate must produce exactly one fetch, got "
            f"{comp.output_names}")
    out_spec = comp.outputs[0]
    if len(out_spec.shape.dims) != 1:
        raise InvalidShapeError(
            f"filter predicate fetch {out_spec.name!r} must be a rank-1 "
            f"row mask, got shape {out_spec.shape}")
    if out_spec.dtype.np_storage.kind not in ("b", "i"):
        raise InvalidTypeError(
            f"filter predicate fetch {out_spec.name!r} must be boolean or "
            f"integer (nonzero keeps the row), got {out_spec.dtype.name}")
    return comp


def filter_rows(predicate: Fetches, df: TensorFrame,
                executor: Optional[BlockExecutor] = None) -> TensorFrame:
    """Keep the rows where ``predicate`` holds. Lazy.

    The reference had no filter of its own — users reached for Spark's
    relational ``df.filter`` around the six tensor ops; a frame library
    standing alone needs one. ``predicate`` follows the map-computation
    conventions (named args select columns, DSL nodes work too) and must
    produce exactly ONE boolean/integer vector of block length; nonzero
    keeps the row. The schema is unchanged; every column (including
    non-tensor pass-through columns like strings) is masked.
    """
    ex = executor or default_executor()
    comp = _filter_computation(predicate, df.schema)
    in_names = comp.input_names
    pname = comp.output_names[0]

    def apply_mask(b: Block, out: Dict[str, np.ndarray]) -> Block:
        mask = np.asarray(out[pname]).astype(bool)
        if mask.shape != (b.num_rows,):
            raise InvalidShapeError(
                f"filter predicate produced shape {mask.shape} for a "
                f"{b.num_rows}-row block")
        keep = int(mask.sum())
        # feedback selectivity (ROADMAP 2a): the per-op path observes
        # too, so chains that never fuse still sharpen their estimates
        from ..plan.nodes import record_selectivity
        record_selectivity(comp, b.num_rows, keep)
        if keep == b.num_rows:
            return b
        cols: Dict[str, Column] = {}
        for n, c in b.columns.items():
            if isinstance(c, np.ndarray):
                cols[n] = c[mask]
            else:  # ragged / list-backed columns mask by index
                cols[n] = [c[i] for i in np.flatnonzero(mask)]
        return Block(cols, keep)

    def run_block(b: Block) -> Block:
        if b.num_rows == 0:
            return b
        with span("filter_rows.block"):
            arrays = {n: b.dense(n) for n in in_names}
            # masks are row-aligned, so bucketed padding stays legal
            out = ex.run(comp, arrays, pad_ok=True)
        return apply_mask(b, out)

    def submit_block(b: Block):
        if b.num_rows == 0:
            return b
        arrays = {n: b.dense(n) for n in in_names}
        return _pipeline.submit(ex, comp, arrays, pad_ok=True)

    # the hint is an UPPER bound: a filter keeps at most its input
    rows_h, bytes_h = _memory.propagate_hints(df, df.schema)
    plan_s = f"filter_rows({df._plan})"
    out = TensorFrame(df.schema,
                      _stream_thunk(df, ex, run_block, submit_block,
                                    _drain_with(apply_mask),
                                    tag=_stream_tag("filter_rows", comp,
                                                    plan_s)),
                      df.num_partitions,
                      plan=plan_s,
                      rows_hint=rows_h, bytes_hint=bytes_h)
    if executor is None:
        from ..plan.nodes import FilterNode, attach, node_for
        attach(out, FilterNode(node_for(df), df.schema, comp))
    return out


# ---------------------------------------------------------------------------
# reduce_blocks / reduce_rows
# ---------------------------------------------------------------------------

@traced_query("reduce_blocks")
def reduce_blocks(fetches: Fetches, df: TensorFrame,
                  executor: Optional[BlockExecutor] = None) -> Dict[str, np.ndarray]:
    """Reduce the whole frame to one row. Eager.

    Per-partition block-reduce, then one combine over the stacked partials —
    the reference's Spark tree-reduce (``DebugRowOps.scala:511-512``)
    collapses to a single second-level reduce since the combine order is
    contractually unspecified.
    """
    ex = executor or default_executor()
    comp = _reduce_computation(fetches, df.schema, ("_input",),
                               block_level=True)
    _validate_reduce(comp, df.schema, ("_input",), rank_delta=1)
    fetch_names = comp.output_names

    def block_arrays(b: Block) -> Dict[str, np.ndarray]:
        return {f + "_input": b.dense(f) for f in fetch_names}

    # empty-partition guard (reference :477-479); per-partition partials
    # stream through the pipelined window like the map ops
    nonempty = [b for b in df.blocks() if b.num_rows > 0]
    with span("reduce_blocks.partials"):
        partials: List[Dict[str, np.ndarray]] = _pipeline.run_pipelined(
            nonempty,
            lambda b: ex.run(comp, block_arrays(b), pad_ok=False),
            lambda b: _pipeline.submit(ex, comp, block_arrays(b),
                                       pad_ok=False),
            lambda p, b: p.drain(),
            depth=_pipeline.stream_depth(ex),
            tag=_stream_tag("reduce_blocks", comp, f"({df._plan})"))
    if not partials:
        raise ValueError("reduce_blocks on an empty frame")
    if len(partials) == 1:
        return partials[0]
    with span("reduce_blocks.combine"):
        stacked = {f + "_input": np.stack([p[f] for p in partials])
                   for f in fetch_names}
        return ex.run(comp, stacked, pad_ok=False)


@traced_query("reduce_rows")
def reduce_rows(fetches: Fetches, df: TensorFrame,
                executor: Optional[BlockExecutor] = None) -> Dict[str, np.ndarray]:
    """Pairwise-reduce the whole frame to one row. Eager.

    Contract: for fetch ``z``, inputs ``z_1``/``z_2`` with z's shape/dtype;
    combine order unspecified (reference ``core.py:96-97``). Dense partitions
    fold in a single compiled ``lax.scan`` (the per-partition sequential fold
    of ``performReducePairwise``, ``DebugRowOps.scala:895-932``, without a
    session call per row); partials then fold pairwise across partitions.
    """
    ex = executor or default_executor()
    comp = _reduce_computation(fetches, df.schema, ("_1", "_2"),
                               block_level=False)
    _validate_reduce(comp, df.schema, ("_1", "_2"), rank_delta=0)
    fetch_names = comp.output_names

    def scan_comp() -> Computation:
        def fold(d: Mapping[str, np.ndarray]):
            init = {f: d[f][0] for f in fetch_names}
            xs = {f: d[f][1:] for f in fetch_names}

            def step(carry, x):
                feeds = {f + "_1": carry[f] for f in fetch_names}
                feeds.update({f + "_2": x[f] for f in fetch_names})
                out = comp.fn(feeds)
                return {f: out[f] for f in fetch_names}, ()

            carry, _ = jax.lax.scan(step, init, xs)
            return carry

        return Computation(
            fold,
            [TensorSpec(f, comp.output(f).dtype,
                        comp.output(f).shape.prepend(Unknown))
             for f in fetch_names],
            list(comp.outputs))

    folder = scan_comp()
    partials: List[Dict[str, np.ndarray]] = []
    for b in df.blocks():
        if b.num_rows == 0:
            continue
        dense = all(not b.is_ragged(f) for f in fetch_names)
        if dense:
            partials.append(ex.run(folder, {f: b.dense(f)
                                            for f in fetch_names},
                            pad_ok=False))
        else:
            acc = {f: np.asarray(b.columns[f][0]) for f in fetch_names}
            for i in range(1, b.num_rows):
                feeds = {f + "_1": acc[f] for f in fetch_names}
                feeds.update({f + "_2": np.asarray(b.columns[f][i])
                              for f in fetch_names})
                acc = ex.run(comp, feeds, pad_ok=False)
            partials.append(acc)
    if not partials:
        raise ValueError("reduce_rows on an empty frame")
    acc = partials[0]
    for p in partials[1:]:
        feeds = {f + "_1": acc[f] for f in fetch_names}
        feeds.update({f + "_2": p[f] for f in fetch_names})
        acc = ex.run(comp, feeds, pad_ok=False)
    return acc


# ---------------------------------------------------------------------------
# aggregate
# ---------------------------------------------------------------------------

class KeyFactorization(NamedTuple):
    """Dense-id view of (possibly multiple) scalar key columns: the
    shuffle's key→partition mapping of the reference (Catalyst groupBy)
    reduced to a host factorization — per-row VALUES never come through."""

    ids: np.ndarray            # [n] group index per input row
    uniques: List[np.ndarray]  # per key column: each group's key value
    num_groups: int
    order: np.ndarray          # [n] lexsort permutation (sorted-by-key)
    seg_starts: np.ndarray     # [num_groups] group start offsets in `order`


def _factorize_keys(key_arrays: Sequence[np.ndarray]) -> KeyFactorization:
    n = len(key_arrays[0])
    order = np.lexsort(tuple(reversed(tuple(key_arrays))))
    sorted_keys = [a[order] for a in key_arrays]
    changed = np.zeros(n, dtype=bool)
    changed[0] = True
    for a in sorted_keys:
        changed[1:] |= a[1:] != a[:-1]
    gidx_sorted = np.cumsum(changed) - 1
    ids = np.empty(n, np.int64)
    ids[order] = gidx_sorted
    uniques = [a[changed] for a in sorted_keys]
    return KeyFactorization(ids, uniques, int(gidx_sorted[-1]) + 1,
                            order, np.flatnonzero(changed))


def _blockwise_key_factorization(blocks, keys):
    """Global key→dense-id factorization WITHOUT concatenating the frame.

    The reference streamed partitions through the UDAF shuffle and never
    held the whole dataset in one buffer; ``Block.concat`` of the frame
    made host aggregation peak at ~3× the column bytes (round-3 weak #5).
    Instead: factorize each block locally (lexsort over that block only),
    merge the SMALL per-block unique-key tables into the global table,
    and remap each block's local ids. Peak extra memory is one block's
    sort copy plus the per-row id arrays (int32 where they fit).

    Returns ``(ids_blocks, uniques, num_groups)`` — one dense-id array
    per block (aligned with the block's rows), the global unique key
    columns (lexicographically sorted, the output key order), and the
    group count. Empty blocks get empty id arrays.
    """
    # per block keep ONLY (uniques, int32 local ids): a retained
    # KeyFactorization would pin its int64 ids AND order arrays (2x 8
    # bytes/row across all blocks — the very footprint this path removes)
    per_block = []
    for b in blocks:
        if b.num_rows == 0:
            per_block.append(None)
            continue
        f = _factorize_keys([b.dense(k) for k in keys])
        local_dt = np.int32 if f.num_groups < 2 ** 31 else np.int64
        per_block.append((f.uniques, f.ids.astype(local_dt)))
        del f
    nonempty = [p for p in per_block if p is not None]
    if not nonempty:
        return [np.empty(0, np.int64) for _ in blocks], \
            [np.empty(0) for _ in keys], 0
    if len(nonempty) == 1:
        uniques, ids = nonempty[0]
        return [ids if p is not None else np.empty(0, ids.dtype)
                for p in per_block], list(uniques), len(uniques[0])
    cat = [np.concatenate([u[i] for u, _ in nonempty])
           for i in range(len(keys))]
    gf = _factorize_keys(cat)  # tables only: small
    id_dt = np.int32 if gf.num_groups < 2 ** 31 else np.int64
    ids_blocks = []
    off = 0
    for i, p in enumerate(per_block):
        if p is None:
            ids_blocks.append(np.empty(0, id_dt))
            continue
        uniques_b, local_ids = p
        g = len(uniques_b[0])
        local_to_global = gf.ids[off:off + g].astype(id_dt)
        ids_blocks.append(local_to_global[local_ids])
        per_block[i] = None  # release the local ids as we go
        off += g
    return ids_blocks, list(gf.uniques), gf.num_groups


def _fact_from_global_ids(ids: np.ndarray) -> KeyFactorization:
    """A KeyFactorization over PRE-ASSIGNED global group ids (one block's
    rows): segments are the groups present in the block, ``uniques[0]``
    their GLOBAL ids, while ``.ids`` are re-densified LOCAL ids (0..G_b-1)
    — consumers scatter into [G_b]-sized tables."""
    order = np.argsort(ids, kind="stable")
    sorted_ids = ids[order]
    changed = np.zeros(len(ids), dtype=bool)
    changed[0] = True
    changed[1:] = sorted_ids[1:] != sorted_ids[:-1]
    starts = np.flatnonzero(changed)
    dense_sorted = np.cumsum(changed) - 1
    dense = np.empty(len(ids), dense_sorted.dtype)
    dense[order] = dense_sorted
    return KeyFactorization(dense, [sorted_ids[starts]], len(starts),
                            order, starts)


def _is_sketch(c) -> bool:
    from ..relational.sketch import SketchCombiner
    return isinstance(c, SketchCombiner)


def _monoid_mapping(fetches) -> bool:
    """True for the ``{column: combiner}`` aggregate form — combiner
    names (sum/min/max/prod) or :class:`~..relational.sketch
    .SketchCombiner` instances (approx_distinct / approx_quantile /
    approx_top_k), freely mixed."""
    return (isinstance(fetches, Mapping) and bool(fetches)
            and all(isinstance(v, str) or _is_sketch(v)
                    for v in fetches.values()))


def _validate_monoid_fetches(col_combiners: Mapping[str, str],
                             value_names: Sequence[str],
                             drop_hint: str,
                             schema: Optional[Schema] = None) -> None:
    """Shared checks for the {column: combiner} aggregate form (host
    and mesh paths raise identical exceptions). Combiners are scalar
    names or sketch combiners; ``schema`` (when given) lets sketches
    validate their input column."""
    from ..parallel.collectives import COMBINERS as _known
    unknown = sorted(set(col_combiners) - set(value_names))
    if unknown:
        raise InputNotFoundError(
            f"Aggregate fetches {unknown} match no value column; value "
            f"columns: {list(value_names)}")
    unused = [n for n in value_names if n not in col_combiners]
    if unused:
        # same ride-along tolerance as _validate_reduce (the reference's
        # reduce contract, BasicOperationsSuite.scala:178-187): columns no
        # fetch consumes drop out of the result, with a warning
        _log.warning(
            "Columns %s are not consumed by the aggregation and will be "
            "ignored (drop them %s to silence this)", unused, drop_hint)
    for name, cname in col_combiners.items():
        if _is_sketch(cname):
            if schema is not None:
                cname.validate_input(schema[name])
            continue
        if cname not in _known:
            raise ValueError(
                f"Unknown combiner {cname!r} for {name!r}; known: "
                f"{sorted(_known)} (or a relational sketch combiner — "
                f"approx_distinct/approx_quantile/approx_top_k)")


# Segment-reduce implementations for the monoid combiner names (the same
# names COMBINERS serves for dreduce_blocks). "sum" routes through the
# one-hot-matmul Pallas kernel on TPU (ops/segment_reduce.py); the others
# through XLA's segment primitives.
def _segment_reduce(cname: str, values, ids, num_segments: int):
    import jax.numpy as jnp

    from ..ops.segment_reduce import segment_sum as _segsum
    if cname == "sum":
        return _segsum(values, ids, num_segments)
    fn = {"min": jax.ops.segment_min, "max": jax.ops.segment_max,
          "prod": jax.ops.segment_prod}[cname]
    return fn(jnp.asarray(values), jnp.asarray(ids),
              num_segments=num_segments)


def _monoid_aggregate(col_combiners: Mapping[str, str],
                      grouped: GroupedFrame) -> TensorFrame:
    """Keyed aggregation for the associative monoids: key→dense-id
    factorization on the host, then ONE segment-reduce launch per fetch
    column — O(1) device dispatches regardless of the number of groups,
    where the generic compaction path pays O(groups).

    Sketch combiners (``relational.sketch``) ride the same structure:
    per-block partial STATE tables (group ids shared with the scalar
    columns; HLL registers / quantile bucket counts fold through the
    same segment kernels) combined across blocks with the sketch's own
    monoid, finalized into estimate columns at the end.
    """
    df = grouped.frame
    keys = grouped.keys
    value_names = [n for n in df.schema.names if n not in keys]
    _validate_monoid_fetches(col_combiners, value_names,
                             "with select() first", schema=df.schema)

    blocks = df.blocks()
    for b in blocks:
        for k in keys:
            if b.num_rows and (b.is_ragged(k) or b.dense(k).ndim != 1):
                raise InvalidTypeError(
                    f"Key column {k!r} must be scalar-typed")
    fetch_names = sorted(col_combiners)
    scalar_names = [f for f in fetch_names
                    if not _is_sketch(col_combiners[f])]
    sketch_names = [f for f in fetch_names
                    if _is_sketch(col_combiners[f])]
    out_fields = [df.schema[k] for k in keys]
    for f in fetch_names:
        if f in sketch_names:
            out_fields.extend(
                col_combiners[f].out_fields(f, df.schema[f]))
        else:
            out_fields.append(Field(
                f, df.schema[f].dtype,
                block_shape=_field_spec(df.schema[f], True, "aggregate")
                .with_lead(Unknown),
                sql_rank=df.schema[f].sql_rank))
    n = sum(b.num_rows for b in blocks)
    if n == 0:
        return TensorFrame.from_blocks(
            [Block({f.name: np.empty(
                (0,) + tuple(d for d in (f.cell_shape.dims
                                         if f.cell_shape else ())
                             if d != Unknown),
                f.dtype.np_storage) for f in out_fields}, 0)],
            Schema(out_fields))

    # blockwise: per-block segment-reduce partials combined with the
    # monoid — the frame is never concatenated (bounded host memory)
    ids_blocks, uniques, num_groups = _blockwise_key_factorization(
        blocks, keys)
    combine_np = {"sum": np.add, "prod": np.multiply,
                  "min": np.minimum, "max": np.maximum}
    cols: Dict[str, np.ndarray] = {k: u for k, u in zip(keys, uniques)}
    mem_mgr = _memory.active()
    with span("aggregate.sketch_fold"):
        for f in sketch_names:
            sk = col_combiners[f]
            table = None
            for b, ids in zip(blocks, ids_blocks):
                if b.num_rows == 0:
                    continue
                vals = np.asarray(b.columns[f])
                mem_tok = (mem_mgr.reserve(
                    2 * int(vals.nbytes) + int(ids.nbytes),
                    op="aggregate.sketch_fold")
                    if mem_mgr is not None else 0)
                try:
                    part = sk.block_partial(vals, ids, num_groups)
                finally:
                    if mem_tok:
                        mem_mgr.release(mem_tok)
                table = part if table is None \
                    else sk.combine_np(table, part)
            from ..utils.tracing import counters as _counters
            _counters.inc("relational.sketch_folds")
            cols.update(sk.finalize(f, table))
    with span("aggregate.segment_reduce"):
        for f in scalar_names:
            field = df.schema[f]
            dd = _dt.device_dtype(field.dtype)
            out = None
            for b, ids in zip(blocks, ids_blocks):
                if b.num_rows == 0:
                    continue
                vals = b.dense(f)
                if vals.dtype != dd:
                    from .. import native as _native
                    vals = _native.convert(vals, dd)
                # per-block dispatch admitted against the device budget
                # (the partial materializes to host immediately below,
                # so only one block's reduce is device-resident at once)
                mem_tok = (mem_mgr.reserve(
                    2 * int(vals.nbytes) + int(ids.nbytes),
                    op="aggregate.segment_reduce")
                    if mem_mgr is not None else 0)
                try:
                    part = np.asarray(_segment_reduce(
                        col_combiners[f], vals, ids, num_groups))
                finally:
                    if mem_tok:
                        mem_mgr.release(mem_tok)
                # groups absent from a block hold the combiner's neutral
                # element (segment_* identity), so the pairwise combine
                # is exact
                out = part if out is None \
                    else combine_np[col_combiners[f]](out, part)
            if out.dtype != field.dtype.np_storage \
                    and field.dtype is not _dt.bfloat16:
                out = out.astype(field.dtype.np_storage)
            cols[f] = out
    return TensorFrame.from_blocks([Block(cols, num_groups)],
                                   Schema(out_fields))


import weakref

# Computation objects rebuilt per call would defeat per-Computation jit
# caches (every aggregate with callable fetches would re-trace its device
# program); this weak cache reuses one Computation per (fetches, schema).
# All access is under _comp_cache_lock: the cache is shared by every
# forcing thread once the serving layer multiplexes queries, and a
# lock-free setdefault would hand two racing threads two different
# Computation objects — silently doubling every downstream jit cache.
_fetches_comp_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()
_comp_cache_lock = threading.Lock()
# per-Computation host-fold program cache (an OrderedDict used as an
# LRU): move_to_end/popitem racing across threads corrupts the order
# book, so every touch is under this lock (jit compilation is not)
_hostfold_lock = threading.Lock()


def cached_reduce_computation(fetches, value_schema, suffixes,
                              block_level: bool):
    """`_reduce_computation` with reuse keyed weakly by the fetches object
    (callables); unhashable/unweakrefable fetches build fresh.
    Thread-safe like :func:`cached_map_computation`: racing threads
    converge on one canonical Computation."""
    sig = (tuple(suffixes), block_level,
           tuple((f.name, f.dtype.name,
                  tuple(f.block_shape.dims) if f.block_shape is not None
                  else None)
                 for f in value_schema))
    try:
        with _comp_cache_lock:
            per = _fetches_comp_cache.setdefault(fetches, {})
            comp = per.get(sig)
    except TypeError:
        per = None
        comp = None
    if comp is not None:
        return comp
    comp = _reduce_computation(fetches, value_schema, suffixes,
                               block_level=block_level)
    if per is not None:
        with _comp_cache_lock:
            comp = per.setdefault(sig, comp)
    return comp


def _aggregate_segmented_fold(comp, fetch_names, fetch_blocks, fact,
                              schema) -> Dict[str, np.ndarray]:
    """All-groups fold in one compiled program (rows pre-sorted by key).

    Per group: the fold of the user computation over its contiguous rows
    via a segmented ``associative_scan`` (pairwise two-row blocks), the
    segment tail scattered into the ``[G, ...]`` output, then one final
    application over each group's single-row block — identical semantics
    to ``CompactionBuffer`` under the algebraic-regrouping contract, at
    O(log rows) combiner depth instead of O(groups) dispatches.
    """
    import jax
    import jax.numpy as jnp

    names = sorted(fetch_names)
    G = len(fact.seg_starts)
    n = len(fact.ids)
    ids_sorted = np.asarray(fact.ids)[np.asarray(fact.order)].astype(
        np.int32)
    dev_blocks = []
    for f in names:
        a = fetch_blocks[f]
        dd = _dt.device_dtype(schema[f].dtype)
        if a.dtype != dd:
            from .. import native as _native
            a = _native.convert(a, dd)
        dev_blocks.append(a)

    key = (G, n,
           tuple((f, a.shape, str(a.dtype))
                 for f, a in zip(names, dev_blocks)))
    with _hostfold_lock:
        cache = getattr(comp, "_tft_hostfold_cache", None)
        if cache is None:
            cache = comp._tft_hostfold_cache = OrderedDict()
        fn = cache.get(key)
        if fn is not None:
            cache.move_to_end(key)
    if fn is None:
        def pair(av, bv):
            out = comp.fn({f + "_input": jnp.stack([av[f], bv[f]])
                           for f in names})
            return {f: out[f] for f in names}

        def single(av):
            out = comp.fn({f + "_input": av[f][None] for f in names})
            return {f: out[f] for f in names}

        pair_v = jax.vmap(pair)
        single_v = jax.vmap(single)

        def program(sid, *vals):
            svals = dict(zip(names, vals))

            def op(a, b):
                a_id, a_v = a
                b_id, b_v = b
                same = a_id == b_id
                comb = pair_v(a_v, b_v)
                out_v = {}
                for f in names:
                    m = same.reshape((-1,) + (1,) * (comb[f].ndim - 1))
                    out_v[f] = jnp.where(m, comb[f], b_v[f])
                return (b_id, out_v)

            _, scanned = jax.lax.associative_scan(op, (sid, svals),
                                                  axis=0)
            tail = jnp.concatenate(
                [sid[1:] != sid[:-1], jnp.ones((1,), bool)])
            target = jnp.where(tail, sid, G)
            table = {}
            for f in names:
                z = jnp.zeros((G,) + scanned[f].shape[1:],
                              scanned[f].dtype)
                table[f] = z.at[target].set(scanned[f], mode="drop")
            return single_v(table)

        fn = jax.jit(program)
        with _hostfold_lock:
            # a racing thread may have built the same program; keep the
            # first so every caller dispatches one shared executable
            fn = cache.setdefault(key, fn)
            cache.move_to_end(key)
            while len(cache) > 64:
                cache.popitem(last=False)

    mem_mgr = _memory.active()
    mem_tok = (mem_mgr.reserve(
        2 * sum(int(a.nbytes) for a in dev_blocks) + int(ids_sorted.nbytes),
        op="aggregate.segmented_fold") if mem_mgr is not None else 0)
    try:
        with span("aggregate.segmented_fold"):
            final = fn(ids_sorted, *dev_blocks)
    finally:
        if mem_tok:
            mem_mgr.release(mem_tok)
    cols: Dict[str, np.ndarray] = {}
    for f in names:
        v = np.asarray(final[f])
        fld = schema[f]
        if v.dtype != fld.dtype.np_storage and fld.dtype is not _dt.bfloat16:
            v = v.astype(fld.dtype.np_storage)
        cols[f] = v
    return cols


@traced_query("aggregate")
def aggregate(fetches: Fetches, grouped: GroupedFrame,
              buffer_size: int = DEFAULT_BUFFER_SIZE,
              executor: Optional[BlockExecutor] = None) -> TensorFrame:
    """Algebraic keyed aggregation: for each distinct key, reduce the
    group's rows with the fetch computation (reduce_blocks contract).

    Two paths:

    - ``fetches`` is a mapping ``{column: combiner-name}`` (sum/min/max/
      prod): host key factorization + ONE segment-reduce device launch per
      column (the Pallas one-hot-matmul kernel for float sums on TPU) —
      O(1) dispatches for any number of groups;
    - ``fetches`` is a computation: host-side sort-by-key (the Catalyst
      groupBy shuffle of the reference, ``DebugRowOps.scala:533-578``),
      then each group reduces through a :class:`CompactionBuffer` honoring
      the UDAF buffered-compaction contract (buffer_size=10 by default,
      ``DebugRowOps.scala:559``).
    """
    if _monoid_mapping(fetches):
        return _monoid_aggregate(fetches, grouped)
    ex = executor or default_executor()
    # the single-program fold runs comp.fn under in-process jax.jit, so it
    # only replaces the per-group dispatch loop when that IS the effective
    # executor; an explicit executor= or a TFT_EXECUTOR=pjrt process
    # default keeps the CompactionBuffer path through that executor
    use_segmented_fold = type(ex) is BlockExecutor and not ex.pad_rows
    df = grouped.frame
    keys = grouped.keys
    value_schema = df.schema.select(
        [n for n in df.schema.names if n not in keys])
    comp = cached_reduce_computation(fetches, value_schema, ("_input",),
                                     block_level=True)
    _validate_reduce(comp, value_schema, ("_input",), rank_delta=1)
    fetch_names = comp.output_names

    def reduce_fn(block: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
        return ex.run(comp, {f + "_input": block[f] for f in fetch_names},
                      pad_ok=False)

    blocks = df.blocks()
    for b in blocks:
        for k in keys:
            if b.num_rows and (b.is_ragged(k) or b.dense(k).ndim != 1):
                raise InvalidTypeError(
                    f"Key column {k!r} must be scalar-typed")

    n = sum(b.num_rows for b in blocks)
    out_fields = [df.schema[k] for k in keys] + [
        Field(s.name, s.dtype,
              block_shape=s.shape.prepend(Unknown),
              sql_rank=s.shape.ndim)
        for s in comp.outputs]
    if n == 0:
        return TensorFrame.from_blocks(
            [Block({f.name: np.empty((0,), f.dtype.np_storage)
                    for f in out_fields}, 0)], Schema(out_fields))

    # blockwise "shuffle": the frame is never concatenated. Each block is
    # sorted by GLOBAL group id and reduced to one partial row per group
    # present in it; the per-block partials then combine through one more
    # pass of the same machinery (legal under the algebraic-regrouping
    # contract, ``core.py:96-97`` — the reference's UDAF merge() does
    # exactly this with executor-side partial buffers,
    # ``DebugRowOps.scala:617-662``). Peak host memory is one block's
    # sorted copy + the id arrays, not 3x the frame.
    ids_blocks, uniques, num_groups = _blockwise_key_factorization(
        blocks, keys)
    from .. import native as _native

    use_fold = (use_segmented_fold
                and getattr(comp, "_native_dynamic", None) is None)

    def block_partials(fetch_b, fact_b):
        """One partial row per group present, in segment order."""
        if use_fold:
            # ONE compiled device program for the block's groups — a
            # segmented associative_scan whose operator is the user
            # computation on two-row blocks. A non-default executor
            # (explicit, or TFT_EXECUTOR=pjrt) keeps the
            # CompactionBuffer path so the computation runs through that
            # executor; deserialized computations (exported.call) have
            # no vmap batching rule and also keep it.
            return _aggregate_segmented_fold(comp, fetch_names, fetch_b,
                                             fact_b, df.schema)
        # CompactionBuffer path: ingest each segment in power-of-two
        # chunks (capped), so the whole aggregation touches O(log)
        # distinct compile signatures and O(rows/cap + log rows)
        # dispatches per group instead of the reference's O(rows/10);
        # the partials buffer still compacts every `buffer_size` rows
        # (the UDAF contract).
        seg_starts = fact_b.seg_starts
        seg_ends = np.append(seg_starts[1:], len(fact_b.ids))
        chunk_cap = 1 << 16
        out_rows: Dict[str, List[np.ndarray]] = {f: [] for f in
                                                 fetch_names}
        for a, bnd in zip(seg_starts, seg_ends):
            buf = CompactionBuffer(fetch_names, reduce_fn, buffer_size)
            c, rem = a, bnd - a
            while rem >= chunk_cap:
                buf.update_block({f: fetch_b[f][c:c + chunk_cap]
                                  for f in fetch_names}, chunk_cap)
                c += chunk_cap
                rem -= chunk_cap
            p = chunk_cap >> 1
            while rem:
                if rem >= p:
                    buf.update_block({f: fetch_b[f][c:c + p]
                                      for f in fetch_names}, p)
                    c += p
                    rem -= p
                p >>= 1
            result = buf.evaluate()
            for f in fetch_names:
                out_rows[f].append(result[f])
        return {f: np.stack(out_rows[f]) for f in fetch_names}

    partial_gids: List[np.ndarray] = []
    partial_rows: Dict[str, List[np.ndarray]] = {f: [] for f in
                                                 fetch_names}
    for b, ids in zip(blocks, ids_blocks):
        if b.num_rows == 0:
            continue
        fact_b = _fact_from_global_ids(ids)
        fetch_b = {f: _native.gather_rows(b.dense(f), fact_b.order)
                   for f in fetch_names}
        cols_b = block_partials(fetch_b, fact_b)
        partial_gids.append(fact_b.uniques[0])
        for f in fetch_names:
            partial_rows[f].append(cols_b[f])

    if len(partial_gids) == 1:
        cols = {f: partial_rows[f][0] for f in fetch_names}
    else:
        ids2 = np.concatenate(partial_gids)
        fact2 = _fact_from_global_ids(ids2)
        fetch2 = {f: np.concatenate(partial_rows[f])[fact2.order]
                  for f in fetch_names}
        cols = block_partials(fetch2, fact2)

    for k, u in zip(keys, uniques):
        cols[k] = u
    return TensorFrame.from_blocks([Block(cols, num_groups)],
                                   Schema(out_fields))
