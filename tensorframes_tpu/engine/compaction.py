"""Buffered compaction for keyed aggregation.

The contract of the reference's ``TensorFlowUDAF``
(``DebugRowOps.scala:587-681``): an aggregation buffer collects incoming rows
and, whenever it reaches ``buffer_size`` (reference hardcodes 10,
``DebugRowOps.scala:559``), compacts them through one block-reduce down to a
single partial row; ``merge`` concatenates two buffers and compacts;
``evaluate`` compacts whatever remains to exactly one row. This bounds the
memory per group while amortizing the per-call overhead of the reduction
program over blocks of rows.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional

import numpy as np

__all__ = ["CompactionBuffer", "DEFAULT_BUFFER_SIZE"]

DEFAULT_BUFFER_SIZE = 10


class CompactionBuffer:
    """Accumulates per-column cell arrays; compacts via a block-reduce fn.

    ``reduce_fn`` maps {col: stacked block [k, *cell]} -> {col: cell} — one
    partial row from a block of k rows.
    """

    def __init__(self, columns: List[str],
                 reduce_fn: Callable[[Dict[str, np.ndarray]], Dict[str, np.ndarray]],
                 buffer_size: int = DEFAULT_BUFFER_SIZE):
        if buffer_size < 2:
            raise ValueError("buffer_size must be >= 2")
        self.columns = list(columns)
        self.reduce_fn = reduce_fn
        self.buffer_size = buffer_size
        self._rows: List[Dict[str, np.ndarray]] = []

    def __len__(self):
        return len(self._rows)

    def update(self, row: Dict[str, np.ndarray]) -> None:
        self._rows.append({c: np.asarray(row[c]) for c in self.columns})
        if len(self._rows) >= self.buffer_size:
            self.compact()

    def update_block(self, block: Dict[str, np.ndarray], num_rows: int) -> None:
        """Bulk ingest: reduce a whole block at once, then buffer the partial.

        The TPU-friendly entry point — one program launch per block instead
        of per row."""
        if num_rows == 0:
            return
        partial = self.reduce_fn({c: np.asarray(block[c])
                                  for c in self.columns})
        self._rows.append({c: np.asarray(partial[c]) for c in self.columns})
        if len(self._rows) >= self.buffer_size:
            self.compact()

    def merge(self, other: "CompactionBuffer") -> None:
        self._rows.extend(other._rows)
        if len(self._rows) >= self.buffer_size:
            self.compact()

    def compact(self) -> None:
        if len(self._rows) <= 1:
            return
        block = {c: np.stack([r[c] for r in self._rows])
                 for c in self.columns}
        partial = self.reduce_fn(block)
        self._rows = [{c: np.asarray(partial[c]) for c in self.columns}]

    def evaluate(self) -> Dict[str, np.ndarray]:
        if not self._rows:
            raise ValueError("Nothing to evaluate: buffer is empty")
        self.compact()
        return dict(self._rows[0])
