"""Block executor: jit-compile-cached execution of computations on blocks.

Replaces the reference's per-partition C++ session path
(``DebugRowOps.scala:755-794``: convert -> readGraph -> new Session ->
``tfLock.synchronized { session.Run }`` -> convertBack). The XLA model has no
session and needs no lock: a computation is compiled once per distinct input
signature (shape/dtype tuple) and the compiled executable is re-dispatched
for every block with that signature. The compile cache is the engine's answer
to the reference's "unknown leading dimension" problem (SURVEY.md §7 hard
part #1): exact-shape compiles by default, with an optional bucketed-padding
mode that pads the row dim to the next power of two so streams of odd-sized
blocks share executables (safe only for row-local computations, hence opt-in;
reductions and trim never pad).
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, Mapping, Optional, Tuple

import jax
import numpy as np

from .. import dtypes as _dt
from .. import memory as _memory
from .. import native as _native
from ..computation import Computation
from ..observability import flight as _flight
from ..observability.events import add_event as _obs_event
from ..observability.events import current_trace as _obs_current_trace
from ..resilience import (default_policy, env_bool, faults, is_oom,
                          is_permanent)
from ..utils.logging import get_logger
from ..utils.tracing import (counters, enabled as _tracing_enabled,
                             histograms, span)

__all__ = ["BlockExecutor", "PaddingExecutor", "PendingBlock",
           "default_executor", "default_padding_executor",
           "set_computation_interner", "to_storage_dtype"]

_log = get_logger("engine.executor")


# Shared cross-query compile cache hook (the serving layer's interner):
# when installed, every run/submit first maps its Computation to a
# process-canonical equivalent, so two tenants tracing the same `x + 3`
# land on ONE weak-keyed jit cache entry instead of recompiling per
# Computation object. One slot, installed by serve.QueryScheduler;
# None (the default) is zero-cost.
_comp_interner = None


def set_computation_interner(fn):
    """Install (or clear with ``None``) the computation interner; returns
    the previous hook so callers can restore it."""
    global _comp_interner
    prev = _comp_interner
    _comp_interner = fn
    return prev


def current_computation_interner():
    """The installed interner (None when off) — lets an uninstalling
    owner check it still holds the slot before restoring."""
    return _comp_interner


def _intern(comp: Computation) -> Computation:
    f = _comp_interner
    if f is None:
        return comp
    try:
        out = f(comp)
        return out if out is not None else comp
    except Exception as e:  # interning is an optimization, never a gate
        _log.debug("computation interner failed (%s); running the "
                   "un-interned computation", e)
        return comp


def _oom_split_enabled() -> bool:
    return env_bool("TFT_OOM_SPLIT", True)


_backend_cpu: Optional[bool] = None


def _backend_is_cpu() -> bool:
    global _backend_cpu
    if _backend_cpu is None:
        try:
            _backend_cpu = jax.default_backend() == "cpu"
        except Exception:  # backend probe failed; assume host-only
            _backend_cpu = True
    return _backend_cpu


def _split_rows(comp: Computation, arrays: Mapping, n_rows: int):
    """Halve the row dimension: two input mappings whose row-dimensioned
    inputs are the top / bottom halves (non-row inputs ride whole)."""
    half = n_rows // 2
    first, second = {}, {}
    for spec in comp.inputs:
        a = arrays[spec.name]
        if spec.shape.ndim > 0 and spec.shape.head == -1:
            first[spec.name] = a[:half]
            second[spec.name] = a[half:]
        else:
            first[spec.name] = a
            second[spec.name] = a
    return first, second


def _concat_outputs(comp: Computation, a: Mapping, b: Mapping):
    """Stitch two half-block results back together; every output must be
    row-dimensioned (the row-local contract the split path requires)."""
    out = {}
    for spec in comp.outputs:
        if not (spec.shape.ndim > 0 and spec.shape.head == -1):
            raise ValueError(
                f"output {spec.name!r} has no row dimension; the OOM "
                f"split path only serves row-local computations")
        out[spec.name] = np.concatenate([a[spec.name], b[spec.name]])
    return out


def _oom_split_run(executor, comp: Computation, arrays: Mapping,
                   n_rows: Optional[int], cause: BaseException):
    """Re-dispatch an OOM'd row-local block as two halves (recursively:
    a half that still OOMs halves again through the same path).

    The caller established row-locality before calling; each half runs
    at its EXACT shape (``pad_ok=False``) — re-padding a half back up to
    the minimum bucket would dispatch the identical program and OOM
    identically, making the recovery futile for small blocks.

    Returns the stitched outputs, or re-raises ``cause`` when splitting
    is impossible (no rows / single row / non-row outputs / disabled).
    """
    if (not _oom_split_enabled() or not n_rows or n_rows < 2
            or any(not (s.shape.ndim > 0 and s.shape.head == -1)
                   for s in comp.outputs)):
        raise cause
    counters.inc("oom_split.dispatches")
    # OOM forensics: tag the split with the HBM watermark observed at
    # the moment it fired (backends without memory_stats contribute
    # nothing; gated on an active trace so the untraced path never
    # calls memory_stats)
    hbm: Dict = {}
    if _obs_current_trace() is not None:
        try:
            from ..observability import device as _obs_device
            wm = _obs_device.watermark()
            if wm is not None:
                hbm = {"hbm_live_bytes": wm["live_bytes"],
                       "hbm_peak_bytes": wm["peak_bytes"]}
        except Exception as e:
            # best-effort forensics, but never silently: a regression in
            # the sampler must not make watermark tags vanish unnoticed
            _log.debug("OOM watermark sample failed: %s", e)
    _obs_event("oom_split", rows=n_rows, error=type(cause).__name__,
               **hbm)
    _flight.record("engine.oom_split", rows=n_rows,
                   error=type(cause).__name__, **hbm)
    _log.warning(
        "block dispatch hit an OOM-shaped failure (%s); re-dispatching "
        "as two %d/%d-row halves", cause, n_rows // 2,
        n_rows - n_rows // 2)
    first, second = _split_rows(comp, arrays, n_rows)
    with span("executor.oom_split"):
        out_a = _run_half(executor, comp, first, n_rows // 2)
        out_b = _run_half(executor, comp, second, n_rows - n_rows // 2)
    return _concat_outputs(comp, out_a, out_b)


def _run_half(executor, comp: Computation, arrays: Mapping, n_rows: int):
    """One half of a split: exact-shape dispatch, recursing into a
    further split when the half itself still OOMs."""
    try:
        return executor.run(comp, arrays, pad_ok=False)
    except Exception as e:
        if is_oom(e):
            return _oom_split_run(executor, comp, arrays, n_rows, e)
        raise


def _dispatch_estimate(dev_arrays: Mapping, pad_to, n_rows) -> int:
    """Admission estimate of one dispatch's device footprint: inputs
    (scaled to the padded row count when bucketing) plus outputs
    assumed input-sized — 2x the staged input bytes."""
    total = 0
    for a in dev_arrays.values():
        total += int(a.nbytes)
    if pad_to and n_rows:
        total = int(total * (pad_to / n_rows))
    return 2 * total


def _splittable(comp: Computation, row_local: bool, n_rows) -> bool:
    """Whether the proactive pre-dispatch split is legal: the same
    row-locality contract as the reactive OOM split (every output
    row-dimensioned, >= 2 rows to halve)."""
    return bool(
        row_local and n_rows and n_rows >= 2
        and all(s.shape.ndim > 0 and s.shape.head == -1
                for s in comp.outputs))


def _proactive_split_run(executor, comp: Computation, arrays: Mapping,
                         n_rows: int, est: int):
    """Split a block BEFORE dispatch when its admission estimate alone
    exceeds the whole device budget (ROADMAP item 5's "blind split"
    fix: the reactive ``oom_split`` waited for the allocator to fail
    first). Counted separately (``memory.proactive_splits``); each half
    re-enters :meth:`BlockExecutor.run` and splits again if still over.
    """
    counters.inc("memory.proactive_splits")
    _obs_event("proactive_split", rows=n_rows, est_bytes=est)
    mgr = _memory.active()
    _flight.record("memory.proactive_split", rows=n_rows, bytes=est,
                   limit=mgr.limit if mgr is not None else None)
    _log.info(
        "block of %d rows (~%d B estimated) exceeds the device budget; "
        "splitting before dispatch", n_rows, est)
    first, second = _split_rows(comp, arrays, n_rows)
    with span("executor.proactive_split"):
        out_a = executor.run(comp, first, pad_ok=True)
        out_b = executor.run(comp, second, pad_ok=True)
    return _concat_outputs(comp, out_a, out_b)


def _next_bucket(n: int, minimum: int = 8) -> int:
    b = minimum
    while b < n:
        b *= 2
    return b


def _timed_first_dispatch(fn, dev_arrays):
    """First dispatch of a freshly-jitted signature: jax traces and
    XLA-compiles synchronously inside this call (only execution is
    async), so its duration IS the compile time. Feeds the always-on
    ``compile_seconds`` histogram and, when a query trace listens, a
    ``compile`` event."""
    t0 = time.perf_counter()
    out = fn(dev_arrays)
    dt = time.perf_counter() - t0
    histograms.observe("compile_seconds", dt, engine="jax")
    _obs_event("compile", name="jax", dur=dt, engine="jax")
    return out


def to_storage_dtype(a: np.ndarray, dtype) -> np.ndarray:
    """Cast one host output array to its column storage dtype (bfloat16
    keeps its device view) — the single rule ``_convert_back`` and the
    plan executor's final-column conversion share."""
    storage = dtype.np_storage
    if a.dtype != storage and dtype is not _dt.bfloat16:
        return _native.convert(a, storage)
    return a


def _row_count(comp: Computation, arrays: Mapping) -> Optional[int]:
    """Leading row count of the first row-dimensioned input, if any."""
    for spec in comp.inputs:
        if spec.shape.ndim > 0 and spec.shape.head == -1:
            return np.asarray(arrays[spec.name]).shape[0]
    return None


def _pad_inputs(comp: Computation, arrays: Mapping, pad_to: int,
                n_rows: int) -> Dict[str, np.ndarray]:
    """Pad row-dimensioned inputs to ``pad_to`` rows (edge fill; pooled
    staging buffers so bucketed sizes reuse allocations)."""
    padded = {}
    for spec in comp.inputs:
        a = np.asarray(arrays[spec.name])
        if spec.shape.ndim > 0 and spec.shape.head == -1:
            dst = _native.empty_aligned((pad_to,) + a.shape[1:], a.dtype)
            dst[:n_rows] = a
            dst[n_rows:] = a[n_rows - 1:n_rows]  # edge fill
            a = dst
        padded[spec.name] = a
    return padded


def _slice_outputs(comp: Computation, out: Mapping, pad_to: int,
                   n_rows: int) -> Dict[str, np.ndarray]:
    """Drop pad rows from row-dimensioned outputs."""
    result = {}
    for spec in comp.outputs:
        a = out[spec.name]
        if spec.shape.ndim > 0 and spec.shape.head == -1 \
                and a.shape[:1] == (pad_to,):
            a = a[:n_rows]
        result[spec.name] = a
    return result


class PendingBlock:
    """One in-flight block: dispatched asynchronously, barrier deferred.

    The drain half of the :meth:`BlockExecutor.submit` /
    :meth:`drain` split. ``drain()`` waits for readiness and converts
    outputs back to host storage dtypes. Resilience composition: the
    async fast path carries NO retry loop — any failure (recorded at
    submit, or surfacing here at the output barrier, where JAX's async
    dispatch materializes execution errors) re-runs the originating
    block **synchronously** through :meth:`BlockExecutor.run`, i.e.
    through the existing retry / OOM-split / pad-fallback machinery.
    Each such recovery increments ``pipeline.sync_fallbacks``.
    """

    __slots__ = ("_executor", "_comp", "_arrays", "_pad_ok", "_out",
                 "_pad_to", "_n_rows", "_error", "_host", "_mem_mgr",
                 "_mem_bytes", "_keep_device", "__weakref__")

    def __init__(self, executor, comp, arrays, pad_ok, out=None,
                 pad_to=None, n_rows=None, error=None,
                 keep_device=False):
        self._executor = executor
        self._comp = comp
        self._arrays = arrays
        self._pad_ok = pad_ok
        self._out = out
        self._pad_to = pad_to
        self._n_rows = n_rows
        self._error = error
        # keep_device drains return raw (sliced) device outputs — the
        # plan executor's pipelined resident edges (docs/plan.md); an
        # early ledger spill (mem_spill) still hands back host arrays,
        # which every consumer accepts
        self._keep_device = keep_device
        # memory-manager integration: while in the FIFO window this
        # block is a registered spill candidate — its device output can
        # be drained to pinned host early under pressure
        self._host: Optional[Dict[str, np.ndarray]] = None
        self._mem_mgr = None
        self._mem_bytes = 0

    # -- memory-ledger entry protocol (docs/memory.md) ---------------------
    def mem_name(self) -> str:
        return f"pending-block-{id(self):x}"

    def mem_is_spilled(self) -> bool:
        return self._out is None

    def mem_device_bytes(self) -> int:
        return self._mem_bytes if self._out is not None else 0

    def mem_host_bytes(self) -> int:
        return 0  # spilled pendings ARE their drain result; never fault

    def mem_fault(self) -> int:
        return 0

    def mem_spill(self) -> int:
        """Early-drain the device output to host (called under the
        ledger lock, so it cannot race :meth:`drain` — drain unregisters
        first). A conversion failure records the error for the normal
        drain-side recovery."""
        if self._out is None or self._error is not None:
            return 0
        try:
            self._host = self._executor._convert_back(
                self._comp, self._out, self._pad_to, self._n_rows)
        except Exception as e:
            self._error = e
        self._out = None
        freed = self._mem_bytes
        self._mem_bytes = 0
        return freed

    def drain(self) -> Dict[str, np.ndarray]:
        m = self._mem_mgr
        if m is not None:
            # unregister FIRST (under the ledger lock): after this no
            # concurrent spill can touch our device output
            self._mem_mgr = None
            m.drop(self)
        if self._host is not None:
            return self._host
        if self._error is None:
            try:
                faults.check("drain")
                if self._keep_device:
                    out = self._out
                    result = {}
                    for spec in self._comp.outputs:
                        a = out[spec.name]
                        if self._pad_to is not None \
                                and spec.shape.ndim > 0 \
                                and spec.shape.head == -1 \
                                and a.shape[:1] == (self._pad_to,):
                            a = a[:self._n_rows]
                        result[spec.name] = a
                    jax.block_until_ready(result)
                    return result
                return self._executor._convert_back(
                    self._comp, self._out, self._pad_to, self._n_rows)
            except Exception as e:
                self._error = e
        if self._pad_to is None and is_permanent(self._error):
            # a deterministic failure with no padded attempt to fall back
            # from re-runs identically: raise it here (serial semantics,
            # attributed to this block by the FIFO drain) instead of
            # paying a duplicate execution and a bogus "recovery" count.
            # Padded-path errors always re-run: the sync path's
            # exact-shape fallback can still recover them.
            raise self._error
        counters.inc("pipeline.sync_fallbacks")
        _obs_event("sync_fallback", error=type(self._error).__name__,
                   padded=self._pad_to is not None)
        _flight.record("pipeline.sync_fallback",
                       error=type(self._error).__name__,
                       padded=self._pad_to is not None)
        _log.warning(
            "async fast path failed for a block (%s); re-running it "
            "synchronously through the resilient path", self._error)
        self._out = None  # drop the failed device outputs before re-running
        return self._executor.run(self._comp, self._arrays,
                                  pad_ok=self._pad_ok,
                                  keep_device=self._keep_device)


class BlockExecutor:
    """Executes :class:`Computation`s on columnar blocks with a compile cache.

    ``pad_rows``: when True, blocks are padded along the leading (row)
    dimension to power-of-two buckets before execution and outputs sliced
    back — one compile serves many block sizes. Only valid for computations
    whose per-row outputs do not depend on other rows.

    ``donate``: padded dispatches donate their input buffers to XLA
    (``jax.jit(..., donate_argnums=0)``) so the staging buckets'
    device allocations are reused for outputs instead of doubling HBM
    peak. Safe because every row-dimensioned input on that path is a
    freshly-built staging buffer the engine owns (``_pad_inputs``), never
    a caller array. ``TFT_DONATE=0`` disables.
    """

    def __init__(self, pad_rows: bool = False, donate: bool = True):
        self.pad_rows = pad_rows
        self._donate = donate
        # Keyed by the live Computation object (weakly): entries die with the
        # computation, so neither unbounded growth nor stale reuse after
        # CPython id() recycling is possible.
        self._cache: "weakref.WeakKeyDictionary[Computation, Dict[Tuple, object]]" = \
            weakref.WeakKeyDictionary()
        self._lock = threading.Lock()
        self.compile_count = 0  # observability: distinct signatures compiled

    # -- compile cache -----------------------------------------------------
    @staticmethod
    def _sig(comp: Computation, dev_arrays: Mapping) -> Tuple:
        """Compile-cache signature of one input mapping.

        The sorted input-name order is computed once per Computation and
        cached on it — the per-block ``sorted()`` over every (name, shape,
        dtype) tuple was measurable on streams of small blocks."""
        names = getattr(comp, "_tft_sig_names", None)
        if names is None:
            names = comp._tft_sig_names = tuple(
                sorted(s.name for s in comp.inputs))
        return tuple((n, dev_arrays[n].shape, str(dev_arrays[n].dtype))
                     for n in names)

    def _compiled(self, comp: Computation, sig: Tuple,
                  donate: bool = False):
        """Returns ``(fn, fresh)`` — ``fresh`` is True when THIS call
        created the jitted wrapper (a compile-cache miss): the caller
        times the first dispatch and attributes it as compile time
        (jax compiles lazily at first call, so the wrapper's creation
        itself costs nothing)."""
        # Double-checked locking: the lock-free fast path is safe under
        # the GIL (a dict read racing a dict write sees either the old or
        # the new table, never a torn one); EVERY mutation of the
        # weak-keyed outer map and the per-computation signature dicts
        # happens under self._lock, so two threads racing the same new
        # signature compile once and both get that executable
        # (tests/test_resilience.py::TestConcurrentDispatch).
        if donate:
            sig = ("donate",) + sig
        fresh = False
        per_comp = self._cache.get(comp)
        fn = None if per_comp is None else per_comp.get(sig)
        if fn is None:
            with self._lock:
                per_comp = self._cache.setdefault(comp, {})
                fn = per_comp.get(sig)
                if fn is None:
                    fn = jax.jit(comp.fn, donate_argnums=0) if donate \
                        else jax.jit(comp.fn)
                    per_comp[sig] = fn
                    fresh = True
                    self.compile_count += 1
                    counters.inc("compile_cache.misses")
                    _obs_event("compile_cache", hit=False)
                    _log.debug("compile #%d for signature %s",
                               self.compile_count, sig)
                elif _tracing_enabled():  # raced another thread to it
                    counters.inc("compile_cache.hits")
                    _obs_event("compile_cache", hit=True)
        elif _tracing_enabled():
            # hit bookkeeping only under tracing: hits are a per-dispatch
            # perf stat, and the counter's global mutex must not serialize
            # the lock-free fast path above when observability is off
            # (misses are rare and already inside the compile lock, so
            # they stay always-on)
            counters.inc("compile_cache.hits")
            _obs_event("compile_cache", hit=True)
        return fn, fresh

    def _donate_padded(self) -> bool:
        # donation only ever applies to the padded staging path, whose
        # row-dimensioned inputs the engine freshly allocates per dispatch
        # (and whose non-row inputs are host numpy, copied at device_put —
        # a donated copy, never the caller's buffer). Default: on where
        # device memory is the scarce resource (TPU/GPU), off on CPU —
        # there it buys nothing and a donating executable is an extra
        # compile-cache entry next to the plain one. TFT_DONATE overrides
        # either way.
        return self._donate and env_bool("TFT_DONATE",
                                         not _backend_is_cpu())

    # -- execution ---------------------------------------------------------
    def _dispatch(self, comp: Computation, dev_arrays: Mapping,
                  donate: bool = False):
        """Compile (cached) + dispatch one signature, with transient
        failures retried under the process policy. Fault sites:
        ``compile``, ``dispatch``, ``oom``."""
        sig = self._sig(comp, dev_arrays)

        def attempt():
            faults.check("compile")
            fn, fresh = self._compiled(comp, sig, donate=donate)
            faults.check("dispatch")
            faults.check("oom")
            with span("executor.dispatch"):
                if fresh:
                    out = _timed_first_dispatch(fn, dev_arrays)
                else:
                    out = fn(dev_arrays)
                # JAX dispatch is async: an execution failure would
                # otherwise surface at convert_back's np.asarray, OUTSIDE
                # this retry and the OOM-split handlers (it also keeps
                # device time attributed to this span)
                jax.block_until_ready(out)
            return out

        return default_policy().call(attempt, op="executor.dispatch")

    def _convert_inputs(self, comp: Computation, arrays: Mapping):
        """Host marshalling half: inputs cast to device dtypes; returns
        ``(dev_arrays, n_rows)`` with ``n_rows`` the leading row count of
        the first row-dimensioned input (None when there is none).

        Already-device-resident inputs (jax arrays in the device dtype —
        the logical plan's stage chaining, ``docs/plan.md``) pass through
        untouched: no D2H pull, no host cast, no re-upload."""
        dev_arrays = {}
        n_rows = None
        with span("executor.convert"):
            for spec in comp.inputs:
                a = arrays[spec.name]
                dd = _dt.device_dtype(spec.dtype)
                if isinstance(a, jax.Array) and a.dtype == dd:
                    dev_arrays[spec.name] = a
                else:
                    a = np.asarray(a)
                    if a.dtype != dd:
                        a = _native.convert(a, dd)  # threaded when built
                    dev_arrays[spec.name] = a
                if spec.shape.ndim > 0 and spec.shape.head == -1:
                    n_rows = a.shape[0] if n_rows is None else n_rows
        return dev_arrays, n_rows

    def _plan_pad(self, n_rows, pad_ok: bool):
        """Bucketed-padding plan: ``(row_local, pad_to)``.

        pad_rows+pad_ok is the executor's row-locality contract — the
        same property that makes padding safe makes halving safe."""
        row_local = bool(self.pad_rows and pad_ok and n_rows)
        pad_to = None
        if row_local:  # 0-row blocks never pad
            pad_to = _next_bucket(n_rows)
            if pad_to == n_rows:
                pad_to = None
        return row_local, pad_to

    def _convert_back(self, comp: Computation, out, pad_to,
                      n_rows) -> Dict[str, np.ndarray]:
        """D2H half: readiness wait (``np.asarray`` blocks on the async
        dispatch), pad-row slicing, storage-dtype casts."""
        result: Dict[str, np.ndarray] = {}
        with span("executor.convert_back"):
            host_out = {s.name: np.asarray(out[s.name])
                        for s in comp.outputs}
            if pad_to is not None:
                host_out = _slice_outputs(comp, host_out, pad_to, n_rows)
            for spec in comp.outputs:
                result[spec.name] = to_storage_dtype(
                    host_out[spec.name], spec.dtype)
        return result

    def run(self, comp: Computation,
            arrays: Mapping[str, np.ndarray],
            pad_ok: bool = True,
            keep_device: bool = False) -> Dict[str, np.ndarray]:
        """Run a computation on host arrays; returns host arrays.

        ``keep_device=True`` returns the raw device outputs instead of
        converting back to host storage dtypes — the logical plan's
        stage chaining feeds them straight into the next stage's inputs
        (``docs/plan.md``). Recovery paths (OOM split, proactive split)
        still return host arrays; callers must accept either.

        Inputs are cast to their device dtypes (double -> f32 on TPU) and
        outputs cast back to the computation's declared storage dtypes.

        Failure handling (``docs/resilience.md``): transient dispatch
        errors retry with backoff; a failing bucketed (padded) compile
        falls back to the exact shape; an OOM-shaped error on a row-local
        dispatch re-runs the block as two halves.

        Memory admission (``docs/memory.md``): under an active device
        budget the dispatch's estimated footprint is reserved first —
        spilling cold resident buffers, then waiting (bounded) for
        in-flight work; a row-local block whose estimate alone exceeds
        the whole budget splits BEFORE dispatch
        (``memory.proactive_splits``). With no budget configured this is
        one global read.
        """
        comp = _intern(comp)
        dev_arrays, n_rows = self._convert_inputs(comp, arrays)
        row_local, pad_to = self._plan_pad(n_rows, pad_ok)
        mgr = _memory.active()
        mem_tok = 0
        if mgr is not None:
            est = _dispatch_estimate(dev_arrays, pad_to, n_rows)
            if mgr.would_overflow(est) and _splittable(comp, row_local,
                                                       n_rows):
                return _proactive_split_run(self, comp, arrays, n_rows,
                                            est)
            mem_tok = mgr.reserve(est, op="executor.run")
        try:
            out = None
            if pad_to is not None:
                try:
                    faults.check("pad_compile")
                    padded = _pad_inputs(comp, dev_arrays, pad_to, n_rows)
                    out = self._dispatch(comp, padded,
                                         donate=self._donate_padded())
                except Exception as e:
                    if is_oom(e):
                        return _oom_split_run(self, comp, arrays, n_rows,
                                              e)
                    counters.inc("pad_fallback.compiles")
                    _obs_event("pad_fallback", pad_to=pad_to, rows=n_rows,
                               error=type(e).__name__)
                    _log.warning(
                        "bucketed %d-row compile/dispatch failed (%s); "
                        "falling back to the exact %d-row shape",
                        pad_to, e, n_rows)
                    pad_to = None
            if out is None:
                try:
                    out = self._dispatch(comp, dev_arrays)
                except Exception as e:
                    if is_oom(e) and row_local:
                        return _oom_split_run(self, comp, arrays, n_rows,
                                              e)
                    raise

            if keep_device:
                result = {}
                for spec in comp.outputs:
                    a = out[spec.name]
                    if pad_to is not None and spec.shape.ndim > 0 \
                            and spec.shape.head == -1 \
                            and a.shape[:1] == (pad_to,):
                        a = a[:n_rows]  # slices stay device-resident
                    result[spec.name] = a
                return result
            return self._convert_back(comp, out, pad_to, n_rows)
        finally:
            if mem_tok:
                mgr.release(mem_tok)

    def submit(self, comp: Computation,
               arrays: Mapping[str, np.ndarray],
               pad_ok: bool = True,
               keep_device: bool = False) -> PendingBlock:
        """Async fast-path half of :meth:`run`: convert + pad + dispatch
        with NO readiness barrier and NO retry loop. Never raises — any
        failure (including injected compile/dispatch/oom/pad_compile
        faults) is recorded on the returned :class:`PendingBlock`, whose
        ``drain()`` re-runs the block synchronously through :meth:`run`
        and therefore through the full resilience machinery.
        """
        comp = _intern(comp)
        pad_to = None
        mem = None  # (manager, token, est) while a reservation is held
        try:
            dev_arrays, n_rows = self._convert_inputs(comp, arrays)
            _, pad_to = self._plan_pad(n_rows, pad_ok)
            mgr = _memory.active()
            if mgr is not None:
                est = _dispatch_estimate(dev_arrays, pad_to, n_rows)
                tok = mgr.try_reserve(est, op="executor.submit")
                if tok is None:
                    # pressure: the async fast path must NEVER block (a
                    # stream waiting here while holding its own window
                    # would deadlock the budget) — run synchronously
                    # through the admitted path, which may wait, spill,
                    # or proactively split
                    counters.inc("memory.sync_dispatches")
                    _obs_event("mem_sync_dispatch", rows=n_rows,
                               est_bytes=est)
                    from .pipeline import ReadyResult
                    return ReadyResult(self.run(comp, arrays,
                                                pad_ok=pad_ok,
                                                keep_device=keep_device))
                mem = (mgr, tok, est)
            donate = False
            if pad_to is not None:
                faults.check("pad_compile")
                dev_arrays = _pad_inputs(comp, dev_arrays, pad_to, n_rows)
                donate = self._donate_padded()
            faults.check("compile")
            fn, fresh = self._compiled(comp, self._sig(comp, dev_arrays),
                                       donate=donate)
            faults.check("dispatch")
            faults.check("oom")
            with span("executor.dispatch_async"):
                # a fresh signature compiles synchronously inside this
                # call even on the async path — worth attributing
                out = (_timed_first_dispatch(fn, dev_arrays) if fresh
                       else fn(dev_arrays))
            pending = PendingBlock(self, comp, arrays, pad_ok, out=out,
                                   pad_to=pad_to, n_rows=n_rows,
                                   keep_device=keep_device)
            if mem is not None:
                # the reservation becomes a resident ledger entry: while
                # this block sits in the FIFO window its device output is
                # a spill candidate (early host drain under pressure)
                mgr, tok, est = mem
                pending._mem_mgr = mgr
                pending._mem_bytes = est
                mgr.convert_reservation(tok, pending)
                mem = None
            return pending
        except Exception as e:
            if mem is not None:
                mem[0].release(mem[1])
            # pad_to rides along so drain() knows whether the sync
            # re-run's exact-shape fallback could still recover this
            return PendingBlock(self, comp, arrays, pad_ok, error=e,
                                pad_to=pad_to, keep_device=keep_device)

    def clear(self):
        with self._lock:
            self._cache.clear()


class PaddingExecutor:
    """Bucketed-padding wrapper around ANY exact-shape executor.

    Pads the leading (row) dimension of row-dimensioned inputs to
    power-of-two buckets before delegating, and slices outputs back — so
    streams of odd-sized blocks share the inner executor's compiled
    programs (the same compile-signature bound ``BlockExecutor(pad_rows=
    True)`` provides, but composable with e.g. the native PJRT executor).
    Only valid for row-local computations, like every padding path.
    """

    def __init__(self, inner):
        self.inner = inner
        self.pad_rows = True

    @property
    def compile_count(self) -> int:
        return self.inner.compile_count

    def run(self, comp: Computation, arrays: Mapping[str, np.ndarray],
            pad_ok: bool = True) -> Dict[str, np.ndarray]:
        n_rows = _row_count(comp, arrays)
        pad_to = _next_bucket(n_rows) if (pad_ok and n_rows) else None
        if pad_to is None or pad_to == n_rows:  # incl. 0-row blocks
            try:
                return self.inner.run(comp, arrays, pad_ok=False)
            except Exception as e:
                if is_oom(e) and pad_ok:  # pad_ok == row-local here
                    return _oom_split_run(self, comp, arrays, n_rows, e)
                raise
        try:
            faults.check("pad_compile")
            padded = _pad_inputs(comp, arrays, pad_to, n_rows)
            out = self.inner.run(comp, padded, pad_ok=False)
        except Exception as e:
            if is_oom(e):
                return _oom_split_run(self, comp, arrays, n_rows, e)
            # a failing bucketed compile must not take the job down when
            # the exact shape (the no-padding semantics) can still run
            counters.inc("pad_fallback.compiles")
            _obs_event("pad_fallback", pad_to=pad_to, rows=n_rows,
                       error=type(e).__name__)
            _log.warning(
                "bucketed %d-row compile failed (%s); falling back to "
                "the exact %d-row shape", pad_to, e, n_rows)
            try:
                return self.inner.run(comp, arrays, pad_ok=False)
            except Exception as e2:
                # the exact-shape fallback can OOM too; this path is as
                # row-local as the one above, so the split still applies
                if is_oom(e2):
                    return _oom_split_run(self, comp, arrays, n_rows, e2)
                raise
        return _slice_outputs(comp, out, pad_to, n_rows)

    def clear(self):
        self.inner.clear()


_default: Optional[BlockExecutor] = None
_default_padding: Optional[BlockExecutor] = None
_default_lock = threading.Lock()


def default_executor() -> BlockExecutor:
    """Exact-shape executor: block-level computations may be cross-row
    (e.g. ``z = x - mean(x)``), so padding would corrupt them.

    ``TFT_EXECUTOR=pjrt`` routes the process default through the native
    C++ PJRT core (``native_pjrt.PjrtBlockExecutor``) with the jax
    in-process path as fallback if the native library is unavailable.
    """
    global _default
    if _default is None:
        with _default_lock:
            if _default is None:
                import os
                if os.environ.get("TFT_EXECUTOR") == "pjrt":
                    try:
                        from ..native_pjrt import PjrtBlockExecutor
                        _default = PjrtBlockExecutor()
                    except Exception as e:  # fall back to the jax path
                        _log.warning(
                            "TFT_EXECUTOR=pjrt requested but the native "
                            "core is unavailable (%s); using the jax "
                            "executor", e)
                        _default = BlockExecutor()
                else:
                    _default = BlockExecutor()
    return _default


def default_padding_executor() -> BlockExecutor:
    """Bucketed-padding executor for row-local computations (``map_rows``:
    rows are independent under vmap, so padding the row dim to power-of-two
    buckets is safe and bounds compile signatures to O(log max_rows) for
    streams of odd-sized blocks — SURVEY.md §7 hard part #1).

    Under ``TFT_EXECUTOR=pjrt`` the buckets wrap the native PJRT executor
    (:class:`PaddingExecutor` composition), so map_rows runs through the
    C++ core too."""
    global _default_padding
    if _default_padding is None:
        inner = default_executor()  # resolves TFT_EXECUTOR + fallback once
        with _default_lock:
            if _default_padding is None:
                if type(inner) is BlockExecutor:
                    _default_padding = BlockExecutor(pad_rows=True)
                else:
                    # native core default: share its ONE client (a second
                    # PJRT client per process can be refused on TPU hosts)
                    _default_padding = PaddingExecutor(inner)
    return _default_padding
