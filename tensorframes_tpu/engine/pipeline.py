"""Pipelined block execution: overlap marshalling, H2D, compute, and D2H.

The engine's hot path used to be fully serial: every map/filter/reduce
materialized its result with ``[run_block(b) for b in df.blocks()]``, and
each dispatch hard-barriered (``jax.block_until_ready``) before converting
outputs back to host — so host marshalling, H2D transfer, device compute,
and D2H readback never overlapped across blocks. This module is the
streaming replacement: a bounded window of **in-flight blocks** where block
*k+1*'s convert/pad/device_put runs while block *k* computes on device and
block *k−1* drains back to host (the inter-step overlap of "Extending
TensorFlow's Semantics with Pipelined Execution", PAPERS.md).

The executor side is split in two halves (``BlockExecutor.submit`` /
``PendingBlock.drain``): *submit* converts inputs, plans padding, and
dispatches asynchronously — no barrier; *drain* waits for readiness and
converts outputs back. :func:`run_pipelined` keeps at most
``TFT_PIPELINE_DEPTH`` (default 3) blocks in flight and drains strictly
FIFO, so **output ordering is preserved** and the lazy-thunk contract of
the ops is unchanged.

Resilience composition (the load-bearing part): the async fast path has no
retry loop of its own. Any error — at submit (compile/dispatch) or
surfacing at drain (async execution failures materialize at the output
barrier) — is attributed to its originating block, and that block is
re-run **synchronously** through ``executor.run``, i.e. through the
existing retry / OOM-split / pad-fallback machinery
(``docs/resilience.md``). Counted in ``pipeline.sync_fallbacks``.

``TFT_PIPELINE_DEPTH=1`` (or a single-block frame) restores the serial
path exactly: the ops' unchanged per-block function runs in a plain loop,
bit-identical to the pre-pipeline engine.

Preemption composition (``docs/serving.md``): when the serving layer
activates a :class:`~.preempt.PreemptionScope` around a forcing, the
stream polls it between submits — a cancel raises a classified
``QueryCancelled`` at the boundary; a preempt drains the in-flight
window, parks the drained prefix as a ``QueryCheckpoint``
(``memory/checkpoint.py``), and raises ``QueryPreempted`` for the
scheduler to re-queue. On resume the parked outputs restore and only
the remaining blocks re-dispatch (``pipeline.resumed_blocks``). With no
scope active the cost is one contextvar read per stream.

Multi-query composition: when the serving layer installs a
:class:`SlotPool` (``docs/serving.md``), every pipelined stream leases
one pool slot per in-flight block, bounding TOTAL cross-query block
concurrency instead of per-stream depth only; waits are counted in
``pipeline.slot_waits`` and recorded as ``slot_wait`` trace events. With
no pool installed (the default, anything outside a serving scheduler)
the leasing path is a single ``None`` check.

Observability: ``pipeline.submitted`` / ``pipeline.drained`` /
``pipeline.sync_fallbacks`` are always-on counters
(``utils.tracing.counters``); window occupancy is sampled into the
``pipeline.occupancy`` gauge and submit/drain run inside
``pipeline.submit`` / ``pipeline.drain`` spans when tracing is enabled.
With an active :class:`~..observability.QueryTrace` each block also
records typed ``block_submit``/``block_compute``/``block_drain`` events
on its in-flight slot's track plus per-submit occupancy samples — the
chrome-trace export (``docs/observability.md``) renders one track per
slot so depth tuning becomes visual.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, List, Optional, Sequence, TypeVar

from ..observability import baseline as _baseline
from ..observability import device as _obs_device
from ..observability import events as _obs
from ..resilience import check_deadline, env_int
from ..resilience import invariants as _invariants
from ..utils.logging import get_logger
from ..utils.tracing import counters, gauge, span
from . import preempt as _preempt

__all__ = ["DEFAULT_DEPTH", "pipeline_depth", "stream_depth", "submit",
           "run_pipelined", "ReadyResult", "PipelinedExecutor",
           "SlotPool", "install_slot_pool", "current_slot_pool",
           "last_occupancy"]

_log = get_logger("engine.pipeline")

DEFAULT_DEPTH = 3

B = TypeVar("B")
R = TypeVar("R")


class SlotPool:
    """A process-wide budget of in-flight pipeline blocks, leased by
    concurrent query streams.

    Without a pool, N queries racing into the engine each open their own
    ``TFT_PIPELINE_DEPTH`` window — total in-flight memory scales with
    whoever shows up. The serving layer installs one pool sized to the
    machine (``serve.QueryScheduler``: workers x depth by default,
    ``TFT_SERVE_SLOTS`` overrides) and every pipelined stream leases a
    slot per in-flight block from it, so cross-query block concurrency is
    bounded globally, not per caller.

    Deadlock-free by construction: a stream that cannot lease drains its
    OWN oldest in-flight block first (freeing a slot it holds), and
    blocks only when it holds none — at which point every held slot
    belongs to a stream that is computing and will drain. Waiting streams
    honor the ambient resilience deadline.
    """

    __slots__ = ("slots", "_sem", "_leased", "_lock")

    def __init__(self, slots: int):
        if slots < 1:
            raise ValueError(f"SlotPool needs >= 1 slot, got {slots}")
        self.slots = int(slots)
        self._sem = threading.Semaphore(self.slots)
        # explicit lease count alongside the semaphore: the invariant
        # auditors (resilience/invariants.py) need to READ the balance
        # — a Semaphore's internal value is not inspectable — so every
        # acquire/release keeps this mirror
        self._leased = 0
        self._lock = threading.Lock()

    def try_acquire(self, timeout: float = 0.0) -> bool:
        if timeout <= 0:
            got = self._sem.acquire(blocking=False)
        else:
            got = self._sem.acquire(timeout=timeout)
        if got:
            with self._lock:
                self._leased += 1
        return got

    def release(self) -> None:
        with self._lock:
            self._leased -= 1
        self._sem.release()

    def leased(self) -> int:
        """Currently-outstanding leases (negative = a release without
        an acquire; the auditors flag both directions)."""
        with self._lock:
            return self._leased


_slot_pool: Optional[SlotPool] = None

# mean in-flight window occupancy of the most recently COMPLETED
# stream in this process (best-effort: concurrent streams overwrite
# each other; None before any stream and after a serial/depth-1 run).
# The adaptive planner's stream-feedback records read it right after
# their own forcing's stream completes (docs/adaptive.md), where the
# most-recent stream IS that forcing's on the uncontended path.
_last_occupancy: Optional[float] = None


def last_occupancy() -> Optional[float]:
    return _last_occupancy


def install_slot_pool(pool: Optional[SlotPool]) -> Optional[SlotPool]:
    """Install (or clear with ``None``) the process slot pool; returns
    the previous pool so callers can restore it. Streams snapshot the
    pool at entry, so a swap mid-stream never mismatches a lease."""
    global _slot_pool
    prev = _slot_pool
    _slot_pool = pool
    return prev


def current_slot_pool() -> Optional[SlotPool]:
    return _slot_pool


def pipeline_depth(explicit: Optional[int] = None) -> int:
    """The in-flight block window: ``explicit`` if given, else
    ``TFT_PIPELINE_DEPTH`` (default 3), floored at 1 (depth 1 = serial).

    Re-read per stream forcing — the knob is cheap and tests/benchmarks
    flip it between runs.
    """
    d = explicit if explicit is not None \
        else env_int("TFT_PIPELINE_DEPTH", DEFAULT_DEPTH)
    return max(1, d)


class ReadyResult:
    """A pre-computed pending: drains to a value already in hand.

    The generic fallback for executors without a ``submit`` half (e.g.
    :class:`~..engine.executor.PaddingExecutor` wrapping a native core):
    the block runs eagerly — through the executor's full resilient path —
    at submit time, so the stream stays correct (no overlap, same
    semantics).
    """

    __slots__ = ("_value",)

    def __init__(self, value):
        self._value = value

    def drain(self):
        return self._value


def stream_depth(executor) -> Optional[int]:
    """The depth an executor pins for op-internal streams: a
    :class:`PipelinedExecutor` carries its own, anything else defers to
    ``TFT_PIPELINE_DEPTH`` (None)."""
    if isinstance(executor, PipelinedExecutor):
        return executor.depth
    return None


def submit(executor, comp, arrays, pad_ok: bool = True):
    """Submit one block on ``executor``: its async ``submit`` half when it
    has one, else the eager :class:`ReadyResult` fallback. Returns an
    object with a ``drain()`` method."""
    sub = getattr(executor, "submit", None)
    if sub is not None:
        return sub(comp, arrays, pad_ok=pad_ok)
    return ReadyResult(executor.run(comp, arrays, pad_ok=pad_ok))


def run_pipelined(blocks: Sequence[B],
                  serial_fn: Callable[[B], R],
                  submit_fn: Callable[[B], object],
                  drain_fn: Callable[[object, B], R],
                  depth: Optional[int] = None,
                  tag: Optional[str] = None) -> List[R]:
    """Run a block stream through a bounded in-flight window, in order.

    ``serial_fn(b)`` is the unchanged serial per-block function — used
    verbatim when the effective depth is 1 or the stream has at most one
    block, so ``TFT_PIPELINE_DEPTH=1`` IS the pre-pipeline engine.
    ``submit_fn(b)`` starts a block (returns a pending with ``drain()``,
    or any finished value the paired ``drain_fn`` recognizes);
    ``drain_fn(pending, b)`` completes it. Drains are strictly FIFO:
    results come back in block order. ``tag`` names the logical stream
    for preemption checkpoints (``engine/preempt.py``): a checkpoint
    parked here only ever restores into a stream with the SAME tag and
    block count — a resume whose execution path changed (fused plan
    fell back per-op, say) discards and re-runs instead. Untagged
    streams (``None``) are still preemptible but never checkpoint:
    with no stable identity, a full re-run is the only safe resume.
    """
    global _last_occupancy
    blocks = list(blocks)
    d = pipeline_depth(depth)
    trace = _obs.current_trace()
    scope = _preempt.current_scope()
    start = 0
    restored: Optional[List[R]] = None
    if scope is not None:
        if tag is not None:
            # disambiguate same-tag sibling streams within one run
            # attempt: the Nth same-tag stream parked only ever
            # restores into the Nth same-tag stream of the resume
            tag = f"{tag}#{scope.stream_ordinal(tag)}"
        # resume: a parked checkpoint restores the drained prefix and
        # the stream re-dispatches only the remaining blocks
        restored = _preempt.resume_stream(scope, len(blocks), tag)
        if restored:
            start = len(restored)
            # the restored prefix's filter counts were noted in the
            # PRIOR attempt's row ledger: this attempt's can no longer
            # balance, so it is voided rather than faked
            _invariants.taint_rows(
                f"resumed {start} restored block(s) of stream {tag!r}")
    if d <= 1 or len(blocks) - start <= 1:
        _last_occupancy = None  # a serial run has no window to measure
        if trace is None and scope is None:
            return [serial_fn(b) for b in blocks]
        out0: List[R] = list(restored or ())
        for i in range(start, len(blocks)):
            b = blocks[i]
            if scope is not None and _preempt.boundary(scope, i > start):
                _preempt.park(scope, out0, len(blocks), tag)  # raises
            if trace is None:
                out0.append(serial_fn(b))
                continue
            rows, nbytes = _obs.block_meta(b)
            t0 = trace.clock()
            r = serial_fn(b)
            rows_out, _ = _obs.block_meta(r)
            trace.add("block_run", name=f"block {i}", ts=t0,
                      dur=trace.clock() - t0, track=1, block=i,
                      rows=rows, bytes=nbytes, rows_out=rows_out)
            out0.append(r)
        return out0

    out: List[R] = list(restored or ())
    # window entries: (pending, block, index, submit_end_ts, leased)
    window: "deque" = deque()
    pool = _slot_pool  # snapshot: a mid-stream swap must not mismatch
    occ_sum = 0
    occ_n = 0

    def drain_one() -> None:
        pending, b, i, t_sub, leased = window.popleft()
        slot = i % d + 1
        try:
            t0 = 0.0
            if trace is not None:
                t0 = trace.clock()
                # the block's in-flight residency: submit end -> drain
                # start
                trace.add("block_compute", name=f"compute b{i}", ts=t_sub,
                          dur=max(t0 - t_sub, 0.0), track=slot, block=i)
            with span("pipeline.drain"):
                result = drain_fn(pending, b)
            out.append(result)
            counters.inc("pipeline.drained")
            if trace is not None:
                rows_out, _ = _obs.block_meta(result)
                trace.add("block_drain", name=f"drain b{i}", ts=t0,
                          dur=trace.clock() - t0, track=slot, block=i,
                          rows_out=rows_out)
                # HBM watermark around the drain (latched no-op on
                # backends without memory_stats, e.g. CPU)
                _obs_device.sample(trace, "block_drain")
        finally:
            if leased:
                pool.release()

    def lease_slot() -> bool:
        """One slot from the pool, draining our own window to make room
        when the pool is exhausted (never deadlocks: a stream holding no
        slots only waits on streams that are computing)."""
        if pool is None:
            return False
        if pool.try_acquire():
            return True
        counters.inc("pipeline.slot_waits")
        t0 = trace.clock() if trace is not None else 0.0
        # measured always-on (contended path only): the sentinel's cost
        # vector attributes this wait in seconds, not just a count
        w0 = time.perf_counter()
        while not pool.try_acquire(timeout=0.05):
            check_deadline("pipeline.slot")
            if window:
                drain_one()
        _baseline.note_wait(time.perf_counter() - w0)
        if trace is not None:
            trace.add("slot_wait", ts=t0, dur=trace.clock() - t0)
        return True

    try:
        for i in range(start, len(blocks)):
            b = blocks[i]
            if scope is not None and _preempt.boundary(scope, i > start):
                # preempt: finish what is in flight (never kill a
                # dispatched block), park the drained prefix, raise
                while window:
                    drain_one()
                _preempt.park(scope, out, len(blocks), tag)  # raises
            leased = lease_slot()
            # everything between the lease and the window.append is
            # guarded: a failure anywhere here (submit, or even a trace
            # hook) would otherwise strand the lease outside the window
            try:
                t0 = 0.0
                rows = nbytes = None
                if trace is not None:
                    rows, nbytes = _obs.block_meta(b)
                    t0 = trace.clock()
                with span("pipeline.submit"):
                    pending = submit_fn(b)
                t1 = trace.clock() if trace is not None else 0.0
            except BaseException:
                if leased:  # never made it into the window
                    pool.release()
                raise
            window.append((pending, b, i, t1, leased))
            counters.inc("pipeline.submitted")
            occ_sum += len(window)
            occ_n += 1
            gauge("pipeline.occupancy", len(window))
            if trace is not None:
                trace.add("block_submit", name=f"submit b{i}", ts=t0,
                          dur=t1 - t0, track=i % d + 1, block=i, rows=rows,
                          bytes=nbytes)
                trace.add("occupancy", value=len(window))
            if len(window) >= d:
                drain_one()
        while window:
            drain_one()
    finally:
        # an error unwinding mid-stream must not leak the undrained
        # entries' leases (their async work finishes on its own)
        while window:
            entry = window.popleft()
            if entry[4]:
                pool.release()
        if occ_n:
            _last_occupancy = occ_sum / occ_n
    return out


class PipelinedExecutor:
    """A block-stream runner bound to an inner executor and a depth.

    Thin orchestration handle over :func:`run_pipelined` /
    :func:`submit` for callers outside ``engine.ops`` that want the same
    windowed execution over their own block streams::

        pex = PipelinedExecutor(default_executor(), depth=4)
        results = pex.map(block_arrays, comp)          # ordered host dicts

    ``run`` delegates to the inner executor unchanged, so a
    ``PipelinedExecutor`` is accepted anywhere an ``executor=`` argument
    is (the six ops pipeline their own streams internally; handing them a
    ``PipelinedExecutor`` additionally pins the depth without consulting
    ``TFT_PIPELINE_DEPTH``).
    """

    def __init__(self, inner, depth: Optional[int] = None):
        self.inner = inner
        self._depth = depth

    @property
    def depth(self) -> int:
        return pipeline_depth(self._depth)

    @property
    def pad_rows(self) -> bool:
        return getattr(self.inner, "pad_rows", False)

    @property
    def compile_count(self) -> int:
        return self.inner.compile_count

    def run(self, comp, arrays, pad_ok: bool = True):
        return self.inner.run(comp, arrays, pad_ok=pad_ok)

    def submit(self, comp, arrays, pad_ok: bool = True):
        return submit(self.inner, comp, arrays, pad_ok=pad_ok)

    def map(self, block_arrays: Sequence, comp,
            pad_ok: bool = True) -> List:
        """Run ``comp`` over a sequence of input mappings, pipelined,
        results in input order."""
        return run_pipelined(
            block_arrays,
            lambda a: self.inner.run(comp, a, pad_ok=pad_ok),
            lambda a: self.submit(comp, a, pad_ok=pad_ok),
            lambda p, a: p.drain(),
            depth=self.depth)

    def clear(self):
        self.inner.clear()
