"""Execution engine: compile-cached block execution + the six core ops.

The analogue of the reference's ``DebugRowOps`` execution layer
(``/root/reference/src/main/scala/org/tensorframes/impl/DebugRowOps.scala``),
re-designed for XLA: instead of a C++ TF ``Session`` per partition guarded by
a global lock, each distinct (computation, block-shape) pair is jit-compiled
once and cached; partitions then execute as data-parallel XLA launches with
no interpreter in the loop.
"""

from .executor import BlockExecutor, PendingBlock, default_executor
from .ops import (
    map_blocks, map_rows, reduce_blocks, reduce_rows, aggregate,
    InputNotFoundError, InvalidTypeError, InvalidShapeError,
)
from .compaction import CompactionBuffer
from .pipeline import PipelinedExecutor, pipeline_depth, run_pipelined

__all__ = [
    "BlockExecutor", "PendingBlock", "default_executor",
    "PipelinedExecutor", "pipeline_depth", "run_pipelined",
    "map_blocks", "map_rows", "reduce_blocks", "reduce_rows", "aggregate",
    "CompactionBuffer",
    "InputNotFoundError", "InvalidTypeError", "InvalidShapeError",
]
