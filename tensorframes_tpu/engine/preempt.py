"""Preemption tokens: cooperative query interruption at block boundaries.

The serving layer (``docs/serving.md``) needs two things the engine
could not do: **cancel** a running query, and **preempt** a low-priority
whale so a higher-priority tenant's query runs now instead of after it.
Both are cooperative — the pipeline's submit/drain split gives every
query natural yield points at block boundaries, and killing a dispatch
mid-flight is neither possible nor desirable (XLA owns it). This module
is the token that crosses the scheduler/engine boundary:

- the scheduler activates a :class:`PreemptionScope` (a contextvar)
  around a query's forcing and flips ``request_cancel`` /
  ``request_preempt`` from any thread;
- :func:`~.pipeline.run_pipelined` polls the scope between submits
  (:func:`boundary`): a cancel raises a classified
  :class:`~..resilience.QueryCancelled`; a preempt first **drains the
  in-flight window** (blocks are never killed mid-dispatch), then parks
  the completed outputs as a
  :class:`~..memory.checkpoint.QueryCheckpoint` (:func:`park`) and
  raises :class:`~..resilience.QueryPreempted` for the scheduler to
  re-queue;
- on resume the scheduler re-activates the scope with the checkpoint
  and the stream restores the parked outputs (:func:`resume_stream`),
  re-dispatching only the remaining blocks — bit-identical to an
  uninterrupted run.

The deterministic ``preempt`` fault site (``TFT_FAULTS=preempt:N``,
``docs/resilience.md``) drives this path without a concurrent
preemptor: :func:`boundary` converts the injected fault into a preempt
request, exactly like ``device:1`` drives elastic recovery.

Zero-cost when idle: with no scope active, the engine pays one
contextvar read per stream (not per block).
"""

from __future__ import annotations

import contextlib
import contextvars
import threading
from typing import Iterator, List, Optional, Sequence

from ..observability import events as _obs
from ..observability import flight as _flight
from ..resilience import QueryCancelled, QueryPreempted
from ..resilience import faults as _faults
from ..utils.logging import get_logger
from ..utils.tracing import counters

__all__ = ["PreemptionScope", "current_scope", "activate", "boundary",
           "park", "resume_stream"]

_log = get_logger("engine.preempt")

_scope: "contextvars.ContextVar[Optional[PreemptionScope]]" = \
    contextvars.ContextVar("tft_preempt_scope", default=None)


class PreemptionScope:
    """One query's preemption token + checkpoint carrier.

    Request flags are sticky until consumed: ``request_preempt`` is
    cleared when the stream parks (or when the query completes first —
    a preempt racing natural completion is a no-op); ``request_cancel``
    is never cleared (a cancelled query must not resume).
    """

    __slots__ = ("query_id", "checkpoint", "reason", "worker_fault",
                 "_cancel", "_preempt", "_lock", "_tag_counts")

    def __init__(self, query_id: str, checkpoint=None):
        self.query_id = query_id
        self.checkpoint = checkpoint  # QueryCheckpoint or None
        self.reason = ""
        # set by boundary() when the `worker` fault site fires: the park
        # doubles as a worker crash — the scheduler's requeue path tells
        # the serving fabric so it can kill this worker (docs/serving.md)
        self.worker_fault = False
        self._cancel = False
        self._preempt = False
        self._lock = threading.Lock()
        # per-run-attempt ordinal of each stream tag: the scheduler
        # builds a FRESH scope per attempt, so counts restart at 0 on
        # resume — which is exactly what makes the ordinal a usable
        # identity (see stream_ordinal)
        self._tag_counts: dict = {}

    def stream_ordinal(self, tag: str) -> int:
        """The 0-based index of this stream among same-tag streams of
        THIS run attempt. Tags are structural (op + comp in/out names
        + input plan) and can collide between near-identical sibling
        streams; the ordinal disambiguates them: a checkpoint parked as
        the Nth same-tag stream only restores into the Nth same-tag
        stream of the resumed run. A thunk that rebuilds its whole
        chain per call (losing upstream frame caches) shifts ordinals
        on resume — the mismatch then DISCARDS the checkpoint (cold
        re-run) instead of restoring a sibling's outputs (wrong
        data)."""
        n = self._tag_counts.get(tag, 0)
        self._tag_counts[tag] = n + 1
        return n

    # -- requests (any thread) --------------------------------------------
    def request_cancel(self, reason: str = "") -> None:
        with self._lock:
            self._cancel = True
            if reason:
                self.reason = reason

    def request_preempt(self, reason: str = "") -> None:
        with self._lock:
            if not self._cancel:
                self._preempt = True
                if reason:
                    self.reason = reason

    @property
    def cancel_requested(self) -> bool:
        return self._cancel

    @property
    def preempt_requested(self) -> bool:
        return self._preempt

    def _take_preempt(self) -> None:
        with self._lock:
            self._preempt = False

    def ensure_checkpoint(self):
        if self.checkpoint is None:
            from ..memory.checkpoint import QueryCheckpoint
            self.checkpoint = QueryCheckpoint(self.query_id)
        return self.checkpoint

    def __repr__(self):
        flags = []
        if self._cancel:
            flags.append("cancel")
        if self._preempt:
            flags.append("preempt")
        return (f"PreemptionScope({self.query_id!r}, "
                f"requested={'+'.join(flags) or 'none'})")


def current_scope() -> Optional[PreemptionScope]:
    return _scope.get()


@contextlib.contextmanager
def activate(scope: PreemptionScope) -> Iterator[PreemptionScope]:
    """Make ``scope`` the ambient preemption token for this thread's
    forcing (nested activations are a bug — one scope per query)."""
    token = _scope.set(scope)
    try:
        yield scope
    finally:
        _scope.reset(token)


def boundary(scope: PreemptionScope, progressed: bool = True) -> bool:
    """One block-boundary poll. Raises
    :class:`~..resilience.QueryCancelled` on a pending cancel; returns
    True when the caller should park and raise (preempt pending).

    ``progressed`` is False at the degenerate boundary before any block
    of this run has started: real requests are honored there (yielding
    with an empty prefix is correct), but the injected ``preempt``
    fault site only fires after strict progress — so every
    ``TFT_FAULTS=preempt:N``-driven preemption parks at a strictly
    later cursor than the last, and the drive always converges."""
    if scope.cancel_requested:
        cp = scope.checkpoint
        if cp is not None:
            cp.free()  # a cancelled query never resumes
        counters.inc("pipeline.cancelled_streams")
        # emitted HERE (the victim's thread) so the event lands in the
        # cancelled query's own trace, not the canceller's
        _obs.add_event("cancel", name=scope.query_id,
                       reason=scope.reason or "requested")
        _flight.record("preempt.cancel", query=scope.query_id,
                       reason=scope.reason or "requested")
        raise QueryCancelled(
            f"query {scope.query_id} cancelled at a block boundary"
            + (f" ({scope.reason})" if scope.reason else ""))
    if progressed and _faults.may_fire("preempt"):
        try:
            _faults.check("preempt")
        except _faults.InjectedFault as e:
            scope.request_preempt(f"injected fault: {e}")
    if progressed and _faults.may_fire("worker"):
        # the `worker` site kills the PROCESS, not just the query: park
        # like a preempt (checkpoint persists to the durable tier), and
        # flag the scope so the scheduler's requeue path reports the
        # crash to the serving fabric (docs/resilience.md)
        try:
            _faults.check("worker")
        except _faults.InjectedFault as e:
            scope.worker_fault = True
            scope.request_preempt(f"worker fault: {e}")
    return scope.preempt_requested


def park(scope: PreemptionScope, outputs: Sequence, total: int,
         tag: Optional[str] = None):
    """Park ``outputs`` (the drained prefix of a ``total``-block stream)
    on the scope's checkpoint and raise
    :class:`~..resilience.QueryPreempted`. The caller has already
    drained its in-flight window. ``tag`` identifies the logical
    stream so a resume down a DIFFERENT execution path (e.g. a fused
    plan that fell back per-op between runs) can never restore the
    wrong stream's outputs. A tagless stream (``None`` — e.g. an
    ad-hoc ``PipelinedExecutor.map``) has no stable identity to resume
    into, so it yields WITHOUT checkpointing: two anonymous streams of
    equal length must never restore each other's outputs, and a full
    re-run is always correct."""
    scope._take_preempt()
    # this run attempt ends here: same-tag ordinals restart on the next
    # attempt (the scheduler builds a fresh scope anyway; direct engine
    # users reuse theirs across the park and its resume)
    scope._tag_counts.clear()
    if tag is None:
        counters.inc("pipeline.preempted_streams")
        _obs.add_event("preempt_park", name=scope.query_id, blocks=0,
                       total=int(total), bytes=0,
                       reason=scope.reason or "requested")
        _flight.record("preempt.park", query=scope.query_id, blocks=0,
                       total=int(total), bytes=0, anonymous=True,
                       reason=scope.reason or "requested")
        _log.info("query %s preempted at an anonymous stream boundary "
                  "%d/%d (%s); no checkpoint — resume re-runs it",
                  scope.query_id, len(outputs), total,
                  scope.reason or "requested")
        raise QueryPreempted(
            f"query {scope.query_id} preempted (anonymous stream, "
            f"no checkpoint)"
            + (f" ({scope.reason})" if scope.reason else ""))
    moved = scope.ensure_checkpoint().park_stream(outputs, total, tag)
    counters.inc("pipeline.preempted_streams")
    _obs.add_event("preempt_park", name=scope.query_id,
                   blocks=len(outputs), total=int(total), bytes=moved,
                   reason=scope.reason or "requested")
    _flight.record("preempt.park", query=scope.query_id,
                   blocks=len(outputs), total=int(total), bytes=moved,
                   reason=scope.reason or "requested")
    _log.info("query %s preempted at block boundary %d/%d (%s); %d B "
              "moved off-device", scope.query_id, len(outputs), total,
              scope.reason or "requested", moved)
    raise QueryPreempted(
        f"query {scope.query_id} preempted at block boundary "
        f"{len(outputs)}/{total}"
        + (f" ({scope.reason})" if scope.reason else ""))


def resume_stream(scope: PreemptionScope, total: int,
                  tag: Optional[str] = None) -> Optional[List]:
    """Restore a parked stream's outputs (the resume half); ``None``
    when nothing is parked, the parked stream does not match, or the
    stream is anonymous (``tag=None`` never parks, so it never
    restores)."""
    if tag is None:
        return None
    cp = scope.checkpoint
    if cp is None or cp.empty:
        return None
    restored = cp.resume_stream(total, tag)
    if restored:
        counters.inc("pipeline.resumed_blocks", len(restored))
        _obs.add_event("resume", name=scope.query_id,
                       blocks=len(restored), total=int(total))
        _flight.record("preempt.resume", query=scope.query_id,
                       blocks=len(restored), total=int(total))
        _log.info("query %s resumed: %d/%d block(s) restored from its "
                  "checkpoint; re-dispatching the rest",
                  scope.query_id, len(restored), total)
    return restored
