"""Tensor shape model with unknown dimensions.

TPU-native re-design of the reference's shape layer
(``/root/reference/src/main/scala/org/tensorframes/Shape.scala:13-106``): a
shape is a tuple of dims where ``Unknown`` (-1) marks a dimension whose size is
not statically known (typically the leading "rows in this block" dimension).

Unlike the reference — whose shapes travel inside TF ``TensorShapeProto``s —
these shapes are plain Python data that (a) annotate DataFrame column metadata,
(b) parameterize JAX avals when computations are compiled, and (c) drive the
padding/bucketing policy that reconciles dynamic block sizes with XLA's static
shape requirement.
"""

from __future__ import annotations

import math
from typing import Iterable, Optional, Sequence, Tuple

Unknown: int = -1

__all__ = [
    "Unknown",
    "Shape",
    "HighDimException",
]


class HighDimException(Exception):
    """Raised when a tensor of unsupported rank is encountered.

    Mirrors the reference's ``HighDimException`` (``Shape.scala:105-106``).
    """

    def __init__(self, shape: "Shape"):
        super().__init__(f"Shape {shape} is too high-dimensional for this operation")
        self.shape = shape


class Shape:
    """An immutable tensor shape; dims may be ``Unknown`` (-1).

    ``Shape.empty`` is the scalar shape (rank 0).
    """

    __slots__ = ("_dims",)

    empty: "Shape"  # set below

    def __init__(self, *dims: int):
        if len(dims) == 1 and isinstance(dims[0], (tuple, list, Shape)):
            dims = tuple(dims[0])
        d = []
        for x in dims:
            xi = int(x)
            if xi < 0:
                xi = Unknown
            d.append(xi)
        self._dims = tuple(d)

    # -- basic accessors ---------------------------------------------------
    @property
    def dims(self) -> Tuple[int, ...]:
        return self._dims

    @property
    def ndim(self) -> int:
        return len(self._dims)

    def __len__(self) -> int:
        return len(self._dims)

    def __iter__(self):
        return iter(self._dims)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return Shape(self._dims[i])
        return self._dims[i]

    def __eq__(self, other) -> bool:
        if isinstance(other, Shape):
            return self._dims == other._dims
        if isinstance(other, (tuple, list)):
            return self._dims == tuple(other)
        return NotImplemented

    def __hash__(self) -> int:
        # Must match tuple hashing: __eq__ admits tuple/list interop, so a
        # dict keyed by Shape must also hit on the equal tuple and vice versa.
        return hash(self._dims)

    def __repr__(self) -> str:
        inner = ",".join("?" if d == Unknown else str(d) for d in self._dims)
        return f"[{inner}]"

    # -- predicates --------------------------------------------------------
    @property
    def is_scalar(self) -> bool:
        return len(self._dims) == 0

    @property
    def has_unknown(self) -> bool:
        return Unknown in self._dims

    @property
    def num_elements(self) -> Optional[int]:
        """Element count, or None if any dim is unknown."""
        if self.has_unknown:
            return None
        return math.prod(self._dims) if self._dims else 1

    # -- derivations -------------------------------------------------------
    def prepend(self, dim: int) -> "Shape":
        """New shape with one leading dimension added (block-of-rows shape)."""
        return Shape((int(dim) if dim >= 0 else Unknown,) + self._dims)

    @property
    def tail(self) -> "Shape":
        """Drop the leading dimension (block shape -> cell shape)."""
        if not self._dims:
            raise ValueError("cannot take tail of a scalar shape")
        return Shape(self._dims[1:])

    @property
    def head(self) -> int:
        if not self._dims:
            raise ValueError("scalar shape has no head dimension")
        return self._dims[0]

    def with_lead(self, dim: int) -> "Shape":
        """Replace the leading dimension."""
        if not self._dims:
            raise ValueError("scalar shape has no lead dimension")
        return Shape((int(dim) if dim >= 0 else Unknown,) + self._dims[1:])

    # -- compatibility lattice --------------------------------------------
    def is_more_precise_than(self, other: "Shape") -> bool:
        """True if self refines ``other``: same rank and every dim of self is
        either equal to other's or other's is Unknown.

        The precision check from the reference (``Shape.scala:39-44``):
        a concrete shape is more precise than one with unknowns.
        """
        if len(self._dims) != len(other._dims):
            return False
        for mine, theirs in zip(self._dims, other._dims):
            if theirs != Unknown and mine != theirs:
                return False
        return True

    def check_more_precise_than(self, other: "Shape", context: str = "") -> None:
        if not self.is_more_precise_than(other):
            msg = f"Shape {self} is not at least as precise as {other}"
            if context:
                msg += f" ({context})"
            raise ValueError(msg)

    def merge(self, other: "Shape") -> Optional["Shape"]:
        """Least-upper-bound of two shapes: dims that disagree become Unknown.

        Returns None when ranks differ (no common shape). This is the per-column
        merge used by the deep ``analyze`` scan
        (reference: ``ExperimentalOperations.scala:118-156``).
        """
        if len(self._dims) != len(other._dims):
            return None
        merged = tuple(
            a if a == b else Unknown for a, b in zip(self._dims, other._dims)
        )
        return Shape(merged)

    def broadcast_with(self, other: "Shape") -> "Shape":
        """Numpy-style broadcast of two shapes; Unknown dims broadcast to
        Unknown unless the other side is 1.

        DSL shape inference for binary elementwise ops (the analogue of the
        reference's ``broadcastShape``, ``dsl/DslImpl.scala:115-132``).
        """
        a, b = self._dims, other._dims
        if len(a) < len(b):
            a = (1,) * (len(b) - len(a)) + a
        elif len(b) < len(a):
            b = (1,) * (len(a) - len(b)) + b
        out = []
        for x, y in zip(a, b):
            if x == 1:
                out.append(y)
            elif y == 1:
                out.append(x)
            elif x == Unknown or y == Unknown:
                # Unknown against anything stays Unknown: the unknown side may
                # still turn out to be 1 and broadcast the other way.
                out.append(Unknown)
            elif x == y:
                out.append(x)
            else:
                raise ValueError(f"Cannot broadcast shapes {self} and {other}")
        return Shape(tuple(out))

    # -- concrete-shape helpers -------------------------------------------
    def assert_concrete(self, context: str = "") -> Tuple[int, ...]:
        if self.has_unknown:
            raise ValueError(
                f"Shape {self} has unknown dimensions{': ' + context if context else ''}"
            )
        return self._dims

    def matches_concrete(self, concrete: Sequence[int]) -> bool:
        """Does a concrete runtime shape conform to this (possibly unknown)
        declared shape?"""
        if len(concrete) != len(self._dims):
            return False
        for mine, got in zip(self._dims, concrete):
            if mine != Unknown and mine != int(got):
                return False
        return True

    # -- constructors ------------------------------------------------------
    @staticmethod
    def of(dims: Iterable[int]) -> "Shape":
        return Shape(tuple(dims))

    @staticmethod
    def scalar() -> "Shape":
        return Shape()


Shape.empty = Shape()
