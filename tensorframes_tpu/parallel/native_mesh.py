"""Mesh execution through the native C++ PJRT core (GSPMD-partitioned).

The reference's defining property is that *every* execution bottoms out in
C++ — each partition's work runs in a libtensorflow session
(``TensorFlowOps.scala:55-64``, ``DebugRowOps.scala:776-788``). The
single-host six ops already do (``native_pjrt.PjrtBlockExecutor``); this
module extends the property to the DISTRIBUTED half of the framework: the
same logical programs ``dmap_blocks`` / ``dreduce_blocks`` build are

- lowered once on the driver (jax used for tracing only, GSPMD flavor:
  ``mhlo.sharding``-annotated global shapes),
- compiled in the native core as ONE SPMD-partitioned executable
  (``tfr_pjrt_compile_spmd`` — XLA's SPMD partitioner derives the
  per-device program and inserts the ICI collectives), and
- executed across all mesh devices in ONE native call with per-device
  shard buffers (``tfr_pjrt_execute_replicated``).

Routing: ``TFT_EXECUTOR=pjrt`` (the same switch that routes the host
engine through the native core) enables this path for single-process
meshes, covering row-aligned ``dmap_blocks``, the collective
``dreduce_blocks``, the full ``dsort`` columnsort pipeline (local sorts
AND all_to_all/ppermute exchanges in one executable), ``dfilter``, and
both ``daggregate`` paths — the monoid segment-reduce (with the XLA
scatter-add ``segment_sum`` flavor: the Pallas flavor lowers to Mosaic
custom calls outside the native backends' vocabulary) and the generic
sorted-scan fold — so every mesh op now reaches the C++ core. Anything
the native route cannot express (trim/global outputs, bfloat16 columns,
multi-host frames) falls back to the in-process jax dispatch with
identical semantics. The device-resident benchmark loops
keep using the jax path — data staying in jax Arrays is the point there;
the native mesh path demonstrates (and tests, cpu:4 parity vs jax) that
the C ABI can host the sharded programs themselves.
"""

from __future__ import annotations

import os
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

import numpy as np

from ..observability import events as _obs
from ..utils.logging import get_logger
from ..utils.tracing import histograms as _histograms
from ..utils.tracing import span

_log = get_logger("native_mesh")


def _record_compile(dt: float) -> None:
    """Compile-time attribution for a native SPMD compile: always feeds
    the ``compile_seconds`` histogram (compiles are rare), and attaches a
    ``compile`` event to the active query trace when one listens."""
    _histograms.observe("compile_seconds", dt, engine="native_mesh")
    _obs.add_event("compile", name="native_mesh", dur=dt,
                   engine="native_mesh")


def _trace_native_dispatch(trace, op: str, args_per_dev) -> float:
    """Per-device ``shard`` events (actual marshalled bytes per device)
    before a native replicated execute; returns the dispatch start
    timestamp. Caller records the matching ``mesh_dispatch`` after."""
    for p, dev_args in enumerate(args_per_dev):
        nb = sum(int(getattr(a, "nbytes", 0) or 0) for a in dev_args)
        trace.add("shard", name=f"{op} shard {p}", device=p, bytes=nb,
                  native=True, track=_obs.DEVICE_TRACK_BASE + p)
    return trace.clock()

__all__ = ["executor_for", "NativeMeshExecutor"]

_executors: Dict[str, "NativeMeshExecutor"] = {}
_executors_lock = threading.Lock()
_unavailable_logged = False


def executor_for(mesh) -> Optional["NativeMeshExecutor"]:
    """The process-wide native mesh executor able to span ``mesh``, or
    ``None`` when native mesh routing is off or unavailable.

    Enabled by ``TFT_EXECUTOR=pjrt`` (single-process only: a multi-host
    mesh's shards live in other processes, which the in-process native
    client cannot address — and cross-process native CPU collectives are
    not buildable from this environment's libtensorflow wheel, whose
    headers ship only ``in_process_collectives``; no Gloo/MPI backend.
    Multi-process meshes therefore execute via jax's distributed
    runtime, by construction, not omission). The native client needs at least as many
    devices as the mesh: ``TFT_PJRT_MESH_BACKEND`` overrides the spec;
    by default a ``cpu`` backend is widened to ``cpu:<n_devices>`` and a
    plugin backend is used as-is (its device count is the grant's).
    """
    global _unavailable_logged
    if os.environ.get("TFT_EXECUTOR") != "pjrt":
        return None
    import jax

    if jax.process_count() > 1:
        return None
    n = mesh.num_devices
    spec = os.environ.get("TFT_PJRT_MESH_BACKEND")
    if spec is None:
        base = os.environ.get("TFT_PJRT_BACKEND", "cpu")
        spec = f"cpu:{n}" if base == "cpu" or base.startswith("cpu:") \
            else base
    with _executors_lock:
        if spec in _executors:  # including the failed-once None sentinel
            ex = _executors[spec]
        else:
            try:
                ex = NativeMeshExecutor(spec)
            except Exception as e:
                if not _unavailable_logged:
                    _log.warning(
                        "TFT_EXECUTOR=pjrt mesh routing unavailable (%s); "
                        "mesh ops use the in-process jax path", e)
                    _unavailable_logged = True
                ex = None
            _executors[spec] = ex
    if ex is None or ex.client.device_count < n:
        return None
    return ex


def _shardy_off():
    """Context: lower with GSPMD sharding annotations (``mhlo.sharding``)
    instead of the shardy dialect — the native core's StableHLO→HLO
    conversion + SPMD partitioner consume the GSPMD form."""
    import contextlib
    import jax

    @contextlib.contextmanager
    def ctx():
        old = jax.config.jax_use_shardy_partitioner
        jax.config.update("jax_use_shardy_partitioner", False)
        try:
            yield
        finally:
            jax.config.update("jax_use_shardy_partitioner", old)

    return ctx()


_NOT_ROUTABLE = object()  # cached verdict: this program can't go native


class NativeMeshExecutor:
    """GSPMD mesh programs compiled + executed by the C++ PJRT core."""

    CACHE_CAP = 32        # dreduce programs (executor-wide)
    COMP_CACHE_CAP = 8    # dmap signatures per live Computation

    def __init__(self, backend: str):
        from ..native_pjrt import PjrtCoreClient

        self.client = PjrtCoreClient(backend)
        self._cache: "OrderedDict[tuple, object]" = OrderedDict()
        self._lock = threading.Lock()
        self.compile_count = 0
        self.dispatch_count = 0

    def _cache_put(self, cache: OrderedDict, key, entry, cap: int):
        """Insert under self._lock with LRU eviction. Evicted executables
        are NOT closed here: another thread may have read the entry and be
        mid-execute outside the lock; dropping the cache reference lets
        the executable's own ``__del__`` free the native handle once the
        last reference (including that thread's) is gone."""
        cache[key] = entry
        cache.move_to_end(key)
        while len(cache) > cap:
            cache.popitem(last=False)

    # -- shard marshalling -------------------------------------------------
    @staticmethod
    def _supported(np_dtype) -> bool:
        from ..native_pjrt import _CODES

        return np.dtype(np_dtype) in _CODES

    @staticmethod
    def _split(host: np.ndarray, sharding, dev_order) -> List[np.ndarray]:
        imap = sharding.devices_indices_map(host.shape)
        return [np.ascontiguousarray(host[imap[d]]) for d in dev_order]

    @staticmethod
    def _assemble(shards: List[np.ndarray], sharding, shape, dtype,
                  dev_order) -> np.ndarray:
        if getattr(sharding, "is_fully_replicated", False):
            return shards[0]  # every device holds the whole array
        out = np.empty(shape, dtype)
        imap = sharding.devices_indices_map(shape)
        for piece, d in zip(shards, dev_order):
            out[imap[d]] = piece
        return out

    # -- dmap --------------------------------------------------------------
    def dmap(self, comp, dist) -> Optional[Dict[str, np.ndarray]]:
        """Run a row-aligned map natively; global padded outputs as numpy.

        Returns ``None`` when this program cannot take the native route
        (non-row-aligned outputs, unsupported dtypes) — the caller falls
        back to the jax dispatch.
        """
        import jax

        mesh = dist.mesh
        n_total = mesh.num_devices
        in_names = list(comp.input_names)
        out_names = [s.name for s in comp.outputs]
        host_in = {n: np.asarray(dist.columns[n]) for n in in_names}
        key = ("dmap", mesh.mesh, n_total,
               tuple((n, host_in[n].shape, str(host_in[n].dtype))
                     for n in in_names))
        # cached ON the computation (the _tft_jitted pattern): entries die
        # with it, so id() recycling can never alias two programs. The
        # entry stores the output specs with the executable, so cache hits
        # skip retracing (no per-call jax.eval_shape); a NOT_ROUTABLE
        # verdict is cached too, so un-routable programs fall back to jax
        # without re-tracing every dispatch.
        with self._lock:
            per_comp = getattr(comp, "_tft_native_mesh_cache", None)
            if per_comp is None:
                per_comp = comp._tft_native_mesh_cache = OrderedDict()
            entry = per_comp.get(key)
            if entry is not None:
                per_comp.move_to_end(key)
        if entry is _NOT_ROUTABLE:
            return None
        in_shardings = [mesh.row_sharding(host_in[n].ndim)
                        for n in in_names]
        if entry is None:
            def flat_fn(*args):
                out = comp.fn(dict(zip(in_names, args)))
                return tuple(out[n] for n in out_names)

            avals = [jax.ShapeDtypeStruct(
                host_in[n].shape, host_in[n].dtype, sharding=s)
                for n, s in zip(in_names, in_shardings)]
            routable = all(self._supported(a.dtype)
                           for a in host_in.values())
            out_avals = out_shardings = None
            if routable:
                out_avals = jax.eval_shape(flat_fn, *avals)
                padded = dist.padded_rows
                routable = all(
                    o.shape and o.shape[0] == padded
                    and self._supported(o.dtype) for o in out_avals)
            if not routable:
                with self._lock:
                    self._cache_put(per_comp, key, _NOT_ROUTABLE,
                                    self.COMP_CACHE_CAP)
                return None
            out_shardings = [mesh.row_sharding(len(o.shape))
                             for o in out_avals]
            with self._lock:
                entry = per_comp.get(key)
                if entry is None or entry is _NOT_ROUTABLE:
                    t_c = time.perf_counter()
                    with _shardy_off():
                        text = jax.jit(
                            flat_fn, in_shardings=in_shardings,
                            out_shardings=tuple(out_shardings),
                        ).lower(*avals).as_text().encode()
                    exe = self.client.compile_spmd(text, n_total)
                    _record_compile(time.perf_counter() - t_c)
                    entry = (exe, out_avals, out_shardings)
                    self._cache_put(per_comp, key, entry,
                                    self.COMP_CACHE_CAP)
                    self.compile_count += 1
        exe, out_avals, out_shardings = entry
        dev_order = list(mesh.mesh.devices.flat)
        per_arg = [self._split(host_in[n], s, dev_order)
                   for n, s in zip(in_names, in_shardings)]
        args_per_dev = [[shards[p] for shards in per_arg]
                        for p in range(n_total)]
        trace = _obs.current_trace()
        t0 = (_trace_native_dispatch(trace, "dmap_blocks", args_per_dev)
              if trace is not None else 0.0)
        with span("native_mesh.dmap_dispatch"):
            outs = exe.execute(args_per_dev)
        if trace is not None:
            trace.add("mesh_dispatch", name="dmap_blocks", ts=t0,
                      dur=max(trace.clock() - t0, 0.0), native=True)
        self.dispatch_count += 1
        result = {}
        for i, (nm, oav, osh) in enumerate(
                zip(out_names, out_avals, out_shardings)):
            result[nm] = self._assemble(
                [outs[p][i] for p in range(n_total)], osh, oav.shape,
                oav.dtype, dev_order)
        return result

    # -- generic sharded program -------------------------------------------
    def _entry_for(self, cache_key, build_fn, host_args, in_shardings,
                   out_shardings, mesh, owner=None, out_check=None):
        """Compile-or-reuse the GSPMD program (shared by the one-shot and
        resident-loop dispatch paths); ``None`` when not routable."""
        import jax

        n_total = mesh.num_devices
        with self._lock:
            if owner is not None:
                cache = getattr(owner, "_tft_native_mesh_cache", None)
                if cache is None:
                    cache = owner._tft_native_mesh_cache = OrderedDict()
                cap = self.COMP_CACHE_CAP
            else:
                cache = self._cache
                cap = self.CACHE_CAP
            entry = cache.get(cache_key)
            if entry is not None:
                cache.move_to_end(cache_key)
        if entry is _NOT_ROUTABLE:
            return None
        if entry is None:
            fn = build_fn()
            avals = [jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=s)
                     for a, s in zip(host_args, in_shardings)]
            routable = all(self._supported(a.dtype) for a in host_args)
            out_avals = out_sh = None
            if routable:
                out_avals = jax.eval_shape(fn, *avals)
                if not isinstance(out_avals, (list, tuple)):
                    out_avals = (out_avals,)
                routable = all(self._supported(o.dtype)
                               for o in out_avals)
                if routable and out_check is not None:
                    routable = bool(out_check(out_avals))
                if routable:
                    out_sh = (out_shardings(out_avals)
                              if callable(out_shardings)
                              else out_shardings)
            if not routable:
                with self._lock:
                    self._cache_put(cache, cache_key, _NOT_ROUTABLE, cap)
                return None
            with self._lock:
                entry = cache.get(cache_key)
                if entry is None or entry is _NOT_ROUTABLE:
                    try:
                        t_c = time.perf_counter()
                        with _shardy_off():
                            # out_shardings FORCED: ops that post-process
                            # a shard_map result (e.g. dsort's global
                            # slice) would otherwise let GSPMD pick
                            # replicated outputs, and the per-device
                            # buffers would not be the shards the
                            # assembler expects
                            text = jax.jit(
                                fn, out_shardings=tuple(out_sh),
                            ).lower(*avals).as_text().encode()
                        exe = self.client.compile_spmd(text, n_total)
                        _record_compile(time.perf_counter() - t_c)
                    except Exception:
                        # latch: don't re-trace/re-lower on every call
                        # just to fail again
                        self._cache_put(cache, cache_key, _NOT_ROUTABLE,
                                        cap)
                        raise
                    entry = (exe, out_avals, out_sh)
                    self._cache_put(cache, cache_key, entry, cap)
                    self.compile_count += 1
        return entry

    def run_sharded(self, cache_key, build_fn, host_args, in_shardings,
                    out_shardings, mesh, owner=None, out_check=None):
        """Compile-or-reuse ONE GSPMD program and execute it natively.

        ``build_fn() -> traceable fn`` over positional args matching
        ``host_args``/``in_shardings``; ``out_shardings`` is a list (or a
        callable of the out avals returning one). ``out_check(out_avals)
        -> bool`` vetoes routing from the abstract output shapes (e.g.
        dmap's row-alignment requirement). Results come back as GLOBAL
        numpy arrays assembled from the per-device shards. Returns
        ``None`` when not routable — the verdict (including a FAILED
        compile: a backend without a lowering for some collective must
        not pay a full re-trace per call before the jax fallback) is
        cached. ``owner`` (e.g. a live Computation) keys the cache on the
        owning object instead of the executor-wide LRU, dying with it.
        """
        n_total = mesh.num_devices
        host_args = [np.asarray(a) for a in host_args]
        entry = self._entry_for(cache_key, build_fn, host_args,
                                in_shardings, out_shardings, mesh,
                                owner=owner, out_check=out_check)
        if entry is None:
            return None
        exe, out_avals, out_sh = entry
        dev_order = list(mesh.mesh.devices.flat)
        per_arg = [self._split(a, s, dev_order)
                   for a, s in zip(host_args, in_shardings)]
        args_per_dev = [[shards[p] for shards in per_arg]
                        for p in range(n_total)]
        trace = _obs.current_trace()
        op = str(cache_key[0]) if isinstance(cache_key, tuple) \
            and cache_key else "run_sharded"
        t0 = (_trace_native_dispatch(trace, op, args_per_dev)
              if trace is not None else 0.0)
        with span("native_mesh.sharded_dispatch"):
            outs = exe.execute(args_per_dev)
        result = [self._assemble([outs[p][i] for p in range(n_total)],
                                 sh, oav.shape, oav.dtype, dev_order)
                  for i, (oav, sh) in enumerate(zip(out_avals, out_sh))]
        if trace is not None:
            trace.add("mesh_dispatch", name=op, ts=t0,
                      dur=max(trace.clock() - t0, 0.0), native=True)
        self.dispatch_count += 1  # after assembly: failures don't count
        return result

    def run_sharded_loop(self, cache_key, build_fn, host_args,
                         in_shardings, out_shardings, mesh, iters: int,
                         owner=None):
        """Iterate ONE GSPMD program with DEVICE-RESIDENT loop state.

        The shards upload once, each dispatch's output buffers feed the
        next dispatch directly (``PjrtDeviceBuffer`` handles — HBM on a
        TPU host, no per-call host marshalling), and only the final
        iteration's results come back as global numpy arrays. Requires
        the program's outputs to match its inputs positionally
        (shape + dtype) — the fixed-point/loop-state shape every
        iterative workload (k-means, logreg) has. Returns ``None`` when
        the program is not natively routable.
        """
        if iters < 1:
            raise ValueError(f"iters must be >= 1, got {iters}")
        n_total = mesh.num_devices
        host_args = [np.asarray(a) for a in host_args]
        entry = self._entry_for(cache_key, build_fn, host_args,
                                in_shardings, out_shardings, mesh,
                                owner=owner)
        if entry is None:
            return None
        exe, out_avals, out_sh = entry
        # shardings must match too: each replica's output buffer feeds
        # the same input slot, so a rows-sharded input produced as a
        # columns-sharded output would silently permute the loop state
        mismatch = [
            i for i, (a, o, ish, osh)
            in enumerate(zip(host_args, out_avals, in_shardings, out_sh))
            if a.shape != o.shape or a.dtype != o.dtype or ish != osh]
        if len(host_args) != len(out_avals) or mismatch:
            raise ValueError(
                "run_sharded_loop needs outputs matching inputs "
                f"positionally (shape, dtype AND sharding); mismatched "
                f"positions: {mismatch}")
        dev_order = list(mesh.mesh.devices.flat)
        per_arg = [self._split(a, s, dev_order)
                   for a, s in zip(host_args, in_shardings)]
        args = [[shards[p] for shards in per_arg] for p in range(n_total)]
        with span("native_mesh.resident_loop"):
            for _ in range(iters - 1):
                args = exe.execute(args, keep_outputs=True)
                self.dispatch_count += 1
            outs = exe.execute(args, keep_outputs=False)
            self.dispatch_count += 1
        return [self._assemble([outs[p][i] for p in range(n_total)],
                               sh, oav.shape, oav.dtype, dev_order)
                for i, (oav, sh) in enumerate(zip(out_avals, out_sh))]

    # -- collective reduce -------------------------------------------------
    def dreduce_collective(self, shard_fn, in_specs, names, dist,
                           nv_host: np.ndarray, cache_key
                           ) -> Optional[List[np.ndarray]]:
        """Run the collective-reduce shard program natively.

        ``shard_fn``/``in_specs`` are the SAME per-shard function and
        specs the jax path wraps in ``shard_map`` — one source of truth
        for masking/combiner semantics. ``cache_key`` is the caller's
        stable program key (the ``_collective_cache`` key: mesh + columns
        + combiners + shapes). Outputs are replicated (one numpy array
        per reduced column).
        """
        from ..utils.compat import shard_map
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = dist.mesh
        in_shardings = [NamedSharding(mesh.mesh, s) for s in in_specs]
        host_args = [nv_host.astype(np.int32)] + [dist.columns[n]
                                                 for n in names]

        def build():
            return shard_map(shard_fn, mesh=mesh.mesh,
                             in_specs=tuple(in_specs),
                             out_specs=tuple(P() for _ in names))

        out_shardings = [NamedSharding(mesh.mesh, P()) for _ in names]
        return self.run_sharded(("dreduce", cache_key), build, host_args,
                                in_shardings, out_shardings, mesh)
