"""Collective combiners for mesh reductions.

The reference's cross-partition combine was always a user graph evaluated
pairwise over Spark's reduce tree (``DebugRowOps.scala:511-512, 721-739``).
On a mesh, the combine becomes an XLA collective when it is one of the
known associative monoids — ``psum``-family over ICI — and each combiner
carries its neutral element so row-padding to equal shard sizes is safe.
Arbitrary user combines fall back to gather-then-local-reduce (see
``distributed.py``), mirroring the reference's "order unspecified" contract.
"""

from __future__ import annotations

from typing import Callable, Dict, NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

__all__ = ["Combiner", "COMBINERS"]


class Combiner(NamedTuple):
    """An associative reduction: local block-reduce, mesh collective, and
    the padding-neutral element.

    ``ici`` names the XLA collective primitive the cross-shard combine
    lowers to — the observability layer records it as a typed
    ``collective`` event on the active query trace, so a trace says not
    just *that* a mesh reduce ran but *which* ICI traffic it implied.
    """

    name: str
    local: Callable  # (block, axis) -> partial
    collective: Callable  # (partial, axis_name) -> combined
    neutral: Callable  # (dtype) -> scalar
    ici: str = "psum"  # the collective primitive (trace attribution)


def _neutral_min(dt):
    dtn = np.dtype(dt)
    # ml_dtypes floats (bfloat16) register with kind 'V': they are
    # floating for neutral-element purposes, but issubdtype says no
    if np.issubdtype(dtn, np.floating) or dtn.kind == "V":
        return np.array(np.inf, dt)
    return np.array(np.iinfo(dtn).max, dt)


def _neutral_max(dt):
    dtn = np.dtype(dt)
    if np.issubdtype(dtn, np.floating) or dtn.kind == "V":
        return np.array(-np.inf, dt)
    return np.array(np.iinfo(dtn).min, dt)


COMBINERS: Dict[str, Combiner] = {
    "sum": Combiner(
        "sum",
        lambda b, axis=0: jnp.sum(b, axis=axis),
        lambda x, axis_name: jax.lax.psum(x, axis_name),
        lambda dt: np.array(0, dt),
        ici="psum"),
    "min": Combiner(
        "min",
        lambda b, axis=0: jnp.min(b, axis=axis),
        lambda x, axis_name: jax.lax.pmin(x, axis_name),
        _neutral_min,
        ici="pmin"),
    "max": Combiner(
        "max",
        lambda b, axis=0: jnp.max(b, axis=axis),
        lambda x, axis_name: jax.lax.pmax(x, axis_name),
        _neutral_max,
        ici="pmax"),
    "prod": Combiner(
        "prod",
        lambda b, axis=0: jnp.prod(b, axis=axis),
        lambda x, axis_name: jax.lax.all_gather(x, axis_name).prod(axis=0),
        lambda dt: np.array(1, dt),
        ici="all_gather"),
}
