"""Distribution layer: device meshes, sharded frames, ICI collectives.

This replaces the reference's entire Spark distribution model (SURVEY.md
§2.3): partition-parallel ``mapPartitions`` becomes batch sharding over a
``jax.sharding.Mesh`` data axis; the Spark broadcast of the serialized graph
becomes XLA program replication; the reduce tree / shuffle becomes
``psum``-family collectives over ICI (with DCN mesh axes for multi-host).
Long-context sequence parallelism (ring attention over ``ppermute``) is a
first-class citizen of the same mesh.
"""

from .mesh import DeviceMesh, local_mesh
from .distributed import (
    DistributedFrame, daggregate, dfilter, distribute, dmap_blocks,
    dreduce_blocks, dsort)
from .collectives import COMBINERS
from .elastic import admit_devices, grow_mesh, probe_device
from .exchange import dexchange, shuffle_daggregate, shuffle_enabled
from .ring import ring_attention, ring_allreduce
from .cluster import cluster_mesh, distribute_local, initialize

__all__ = [
    "DeviceMesh", "local_mesh",
    "DistributedFrame", "daggregate", "dfilter", "distribute",
    "dmap_blocks", "dreduce_blocks", "dsort",
    "COMBINERS",
    "admit_devices", "grow_mesh", "probe_device",
    "dexchange", "shuffle_daggregate", "shuffle_enabled",
    "ring_attention", "ring_allreduce",
    "cluster_mesh", "distribute_local", "initialize",
]
