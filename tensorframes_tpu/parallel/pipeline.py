"""Pipeline parallelism: GPipe-style stage execution over a mesh axis.

The reference has nothing like this (Spark partitions are embarrassingly
parallel); it exists because the multi-chip design makes pipeline a
first-class mesh axis. The implementation is the canonical TPU pattern
(the scaling-book recipe): stage parameters are stacked on a leading
``[P, ...]`` dim sharded over the ``pipe`` axis, and ``shard_map`` runs the
schedule — a ``lax.scan`` over ``M + P - 1`` ticks in which every device
applies its stage to the activation it holds and ``lax.ppermute`` rotates
activations one hop down the ICI ring. Microbatch ``m`` is picked up by
stage 0 at tick ``m`` and emitted by stage ``P-1`` at tick ``m + P - 1``;
in between, all stages work on different microbatches in flight (the
steady-state of the GPipe schedule — the ``P-1`` warmup/cooldown ticks are
the bubble). The whole schedule is one compiled program, differentiable
end-to-end (``ppermute`` transposes to the reverse rotation, so backprop
pipelines in the opposite direction automatically).
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
from ..utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from .mesh import DeviceMesh
from .ring import _varying

__all__ = ["pipeline_apply"]


def pipeline_apply(stage_fn: Callable, stacked_params, x: jax.Array,
                   mesh: DeviceMesh, pipe_axis: str = "pipe",
                   num_microbatches: int = None,
                   data_axis: str = None) -> jax.Array:
    """Run ``x`` through ``P`` pipeline stages over ``pipe_axis``.

    - ``stage_fn(params_for_one_stage, act) -> act`` — one stage's compute;
      activations must keep one shape throughout (the usual transformer
      block contract).
    - ``stacked_params``: pytree whose leaves have leading dim ``P``
      (stage-major). The caller shards them over ``pipe_axis``; inside the
      shard each device sees leading dim 1 — its own stage.
    - ``x``: [B, ...] batch; split into ``num_microbatches`` (default P)
      equal microbatches along dim 0.
    - ``data_axis``: when given, the per-microbatch row dim stays sharded
      over it through the pipeline (dp x pp composition); otherwise rows
      are replicated across the data axis inside the schedule.

    Returns the full batch output.
    """
    pipe_size = mesh.mesh.shape[pipe_axis]
    M = num_microbatches or pipe_size
    B = x.shape[0]
    if B % M:
        raise ValueError(f"Batch {B} not divisible into {M} microbatches")
    mb = B // M
    xs = x.reshape((M, mb) + x.shape[1:])

    row_spec = P(None, data_axis, *([None] * (x.ndim - 1)))
    in_specs = (
        jax.tree_util.tree_map(lambda _: P(pipe_axis), stacked_params,
                               is_leaf=lambda l: l is None),
        row_spec,  # stage 0 consumes microbatches; rows stay data-sharded
    )
    # Each device returns ITS outs buffer under a leading pipe-sharded dim;
    # only the last stage's slice holds real data and the caller reads just
    # that — no collective inside the schedule (a psum here would move the
    # full zero buffer of every non-final stage across the ring every call).
    out_specs = P(pipe_axis, None, data_axis, *([None] * (x.ndim - 1)))

    def shard_fn(params, xs_rep):
        p = jax.lax.axis_index(pipe_axis)
        params1 = jax.tree_util.tree_map(lambda a: a[0], params)
        ticks = M + pipe_size - 1
        perm = [(i, (i + 1) % pipe_size) for i in range(pipe_size)]

        def tick(carry, t):
            buf, outs = carry
            # stage 0 picks up microbatch t (clamped; masked when t >= M)
            fresh = xs_rep[jnp.minimum(t, M - 1)]
            inp = jnp.where(p == 0, fresh, buf)
            act = stage_fn(params1, inp)
            # last stage emits microbatch t - (P-1) when it is valid
            # (where, not lax.cond: branches must agree on shard_map's
            # varying-axis types, and an unconditional masked update does)
            m_idx = t - (pipe_size - 1)
            valid = jnp.logical_and(p == pipe_size - 1, m_idx >= 0)
            updated = jax.lax.dynamic_update_index_in_dim(
                outs, act, jnp.clip(m_idx, 0, M - 1), 0)
            outs = jnp.where(valid, updated, outs)
            nxt = jax.lax.ppermute(act, pipe_axis, perm)
            return (nxt, outs), None

        # the carries become device-varying inside the loop (they depend on
        # axis_index); their initial values must be typed varying too
        buf0 = _varying(jnp.zeros_like(xs_rep[0]), pipe_axis, data_axis)
        outs0 = _varying(jnp.zeros_like(xs_rep), pipe_axis, data_axis)
        (_, outs), _ = jax.lax.scan(tick, (buf0, outs0),
                                    jnp.arange(ticks))
        # outs is populated only on the last stage (zeros elsewhere);
        # return it under a leading size-1 dim that the out_spec shards
        # over the pipe axis — the caller slices stage P-1's entry.
        return outs[None]

    fn = shard_map(shard_fn, mesh=mesh.mesh,
                   in_specs=in_specs, out_specs=out_specs)
    out = fn(stacked_params, xs)[pipe_size - 1]
    return out.reshape((B,) + out.shape[2:])
