"""Device mesh construction and sharding helpers.

The TPU-native replacement for the reference's "cluster": where Spark gave
the reference a set of executor JVMs, a :class:`DeviceMesh` names the TPU
chips of a slice (and, multi-host, of a pod) as mesh axes. The default
1-axis ``data`` mesh reproduces the reference's pure data parallelism
(``rdd.mapPartitions``); extra axes (``model``, ``seq``) host tensor and
sequence parallelism the reference never had but the design must not
preclude (SURVEY.md §5).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

__all__ = ["DeviceMesh", "local_mesh"]


class DeviceMesh:
    """A named mesh over JAX devices with sharding convenience methods."""

    def __init__(self, mesh: Mesh, data_axis: str = "data"):
        self.mesh = mesh
        self.data_axis = data_axis
        if data_axis not in mesh.axis_names:
            raise ValueError(
                f"Mesh has axes {mesh.axis_names}; no {data_axis!r} axis")

    @property
    def axis_names(self) -> Tuple[str, ...]:
        return tuple(self.mesh.axis_names)

    @property
    def num_data_shards(self) -> int:
        return self.mesh.shape[self.data_axis]

    @property
    def num_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    def row_sharding(self, ndim: int) -> NamedSharding:
        """Shard the leading (row) dim over the data axis, replicate rest."""
        spec = PartitionSpec(self.data_axis, *([None] * (ndim - 1)))
        return NamedSharding(self.mesh, spec)

    def replicated(self) -> NamedSharding:
        return NamedSharding(self.mesh, PartitionSpec())

    def __repr__(self):
        shape = dict(self.mesh.shape)
        return f"DeviceMesh({shape}, data_axis={self.data_axis!r})"


def local_mesh(num_devices: Optional[int] = None,
               axis_names: Sequence[str] = ("data",),
               shape: Optional[Sequence[int]] = None) -> DeviceMesh:
    """Build a mesh over the locally visible devices.

    One real chip gives a 1-device mesh (the degenerate case every op still
    runs through); 8 virtual CPU devices (tests) or a v5e-8 slice give the
    8-way data mesh of the BASELINE configs.
    """
    devices = jax.devices()
    if num_devices is not None:
        if num_devices < 1:
            raise ValueError(
                f"num_devices must be >= 1, got {num_devices}")
        if num_devices > len(devices):
            raise ValueError(
                f"local_mesh(num_devices={num_devices}) asked for more "
                f"devices than the {len(devices)} visible; lower "
                f"num_devices (or add devices, e.g. "
                f"--xla_force_host_platform_device_count on CPU)")
        devices = devices[:num_devices]
    n = len(devices)
    if shape is None:
        shape = (n,) + (1,) * (len(axis_names) - 1)
    if int(np.prod(shape)) != n:
        # validate against what the CALLER asked for: naming only the
        # visible device count when num_devices was given is misleading
        if num_devices is not None:
            raise ValueError(
                f"Mesh shape {shape} covers {int(np.prod(shape))} "
                f"device(s) but num_devices={num_devices} was requested "
                f"— make the shape's product equal num_devices")
        raise ValueError(
            f"Mesh shape {shape} covers {int(np.prod(shape))} device(s) "
            f"but {n} are visible")
    arr = np.array(devices).reshape(shape)
    return DeviceMesh(Mesh(arr, tuple(axis_names)),
                      data_axis=axis_names[0])
