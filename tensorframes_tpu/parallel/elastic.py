"""Elastic meshes: device-loss tolerance and skew-adaptive repartitioning.

The reference survived executor loss for free — Spark's lineage re-ran
the lost partitions on the survivors. The TPU-native port had nothing:
one dead chip in the mesh killed every query on it, even though the
resilience layer already classified the error and the mesh observability
already measured per-device stragglers without acting on either signal.
This module closes both loops at the one place every mesh op passes
through — the dispatch boundary of ``dmap_blocks`` / ``dfilter`` /
``dsort`` / ``dreduce_blocks`` / ``daggregate``:

- **Device-loss tolerance** (:func:`elastic_call`): a failure classified
  ``device_lost`` (:func:`~..resilience.is_device_lost` — real
  ``DEVICE_LOST`` statuses, or the deterministic ``device`` fault site)
  rebuilds a shrunken :class:`~.mesh.DeviceMesh` over the surviving
  devices, re-shards the frame (host round-trip; the rows that lived on
  the lost device are the ones that genuinely have to move, counted in
  ``mesh.reshard_rows``), and re-runs the op — the query completes with
  correct results instead of raising. DrJAX's sharded-MapReduce framing
  (PAPERS.md) is the reference point: the op is a mesh-shape-polymorphic
  program, so re-expressing it over S-1 devices is a re-shard plus a
  re-dispatch, not a rewrite. Only data-only meshes (every non-data axis
  of size 1) can shrink rectangularly; anything else re-raises.

  NOTE on lineage: the re-shard reads the frame's device-resident
  blocks back through the host. Under fault injection (and host-backed
  CPU meshes) every shard is still readable; on real hardware the lost
  device's shard may not be, in which case the re-shard itself raises
  and the caller must rebuild from its host-side source — re-computing
  lost shards from true lineage is the documented follow-on.

- **Skew-adaptive repartitioning** (:func:`note_dispatch` →
  ``_maybe_rebalance``): the mesh observability layer's per-device
  readiness timings (recorded while tracing is on) feed a per-mesh
  tracker; when the straggler ratio (max/median device time) stays above
  ``TFT_SKEW_WARN`` for ``TFT_SKEW_REBALANCE_AFTER`` consecutive
  dispatches, the next op on that mesh re-partitions the frame's rows
  proportionally to observed per-device throughput (slow devices get
  fewer valid rows; the padded layout stays equal-shard, per-shard
  validity carries the imbalance). Before/after balance is recorded on
  the frame (rendered by ``DistributedFrame.explain()``) and as a
  ``rebalance`` trace event.

- **Elastic growth** (:func:`admit_devices` — the inverse of shrink):
  recovered or newly arrived devices rejoin a mesh after passing a
  probe + warm-up dispatch (:func:`probe_device`, bounded by
  ``TFT_ADMIT_PROBE_TIMEOUT_S``); resident frames re-shard onto the
  grown mesh order-preservingly (bit-identical for row-local ops), and
  an old→grown upgrade registry migrates every OTHER frame still on
  the old mesh at its next dispatch boundary — which is how stream
  pumps and the serve scheduler pick up a grown mesh at the next
  batch/query boundary without restarting. Skew penalties recorded
  against the returning layout are cleared; a shrink→grow→shrink churn
  loop converges with zero lost or duplicated rows.

- **Hot-key salting** (:func:`plan_key_salt` / :func:`fold_salted`):
  ``daggregate``'s monoid host-key path splits any key holding more than
  ``TFT_HOT_KEY_FRACTION`` of the rows across ``num_data_shards`` salt
  slots and folds the per-salt partials back on the host — bounding the
  largest segment a single scatter lane ever sees.

Counters (always on): ``mesh.devices_lost``, ``mesh.shrinks``,
``mesh.reshard_rows``, ``mesh.rebalances``, ``mesh.salted_keys``,
``mesh.grows``, ``mesh.devices_admitted``,
``mesh.admit_probe_failures``, ``mesh.grow_migrations`` — also
exported as ``tft_mesh_*`` series on the metrics endpoint. Trace events
(when a query trace is active): ``mesh_shrink`` (one per lost device,
carrying its id), ``rebalance``, ``key_salt``, ``mesh_grow``,
``mesh_grow_pickup``, ``admit_probe_failed``.

Zero-cost-when-healthy: with no fault armed and no skew pending,
:func:`elastic_call` adds one env read, one fault-site check, and one
dict probe per op (bench-enforced <2%, ``bench.py``
``elastic_degraded_mesh``). ``TFT_ELASTIC=0`` disables recovery (a
device loss raises, the pre-elastic behavior); :func:`bypass` strips the
layer entirely for benchmark baselines.
"""

from __future__ import annotations

import contextlib
import re
import statistics
import threading
import time
import weakref
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh

from ..observability import events as _obs
from ..observability import flight as _flight
from ..observability import metrics as _metrics
from ..resilience import faults as _faults
from ..resilience.classify import is_device_lost
from ..resilience.policy import env_bool, env_float, env_int
from ..utils.logging import get_logger
from ..utils.tracing import counters, gauge
from .mesh import DeviceMesh

__all__ = ["elastic_call", "enabled", "bypass", "lost_device_ids",
           "shrink_mesh", "reshard", "note_dispatch", "salt_fraction",
           "plan_key_salt", "fold_salted",
           "probe_device", "grow_mesh", "admit_devices"]

_log = get_logger("parallel.elastic")

_bypassed = False


def enabled() -> bool:
    """Device-loss recovery armed? (``TFT_ELASTIC``, default on.)"""
    return not _bypassed and env_bool("TFT_ELASTIC", True)


@contextlib.contextmanager
def bypass():
    """Strip the elastic layer entirely (no fault-site check, no skew
    tracker, no recovery) — the benchmark baseline for measuring what
    the enabled-but-idle layer costs on a healthy mesh."""
    global _bypassed
    was = _bypassed
    _bypassed = True
    try:
        yield
    finally:
        _bypassed = was


# ---------------------------------------------------------------------------
# the dispatch boundary
# ---------------------------------------------------------------------------

def elastic_call(op: str, dist, run: Callable):
    """Run ``run(dist)`` with skew-adaptive repartitioning and
    device-loss recovery.

    ``run`` must be re-runnable against a replacement frame: it receives
    the (possibly re-sharded) :class:`~.distributed.DistributedFrame`
    and performs the whole op, including its own transient-retry policy.
    On a ``device_lost`` failure the mesh shrinks by the lost device(s)
    and ``run`` re-runs on the re-sharded frame; up to S-1 successive
    losses are survivable, a loss on a 1-shard mesh re-raises.
    """
    if _bypassed:
        return run(dist)
    dist = _maybe_grow(op, dist)
    dist = _maybe_rebalance(op, dist)
    rebalance = getattr(dist, "_rebalance", None)
    result = None
    last: Optional[BaseException] = None
    for _ in range(max(dist.mesh.num_data_shards, 1)):
        try:
            _faults.check("device")
            result = run(dist)
            break
        except Exception as e:  # noqa: BLE001 - reclassified below
            if not is_device_lost(e) or not enabled():
                raise
            if dist.mesh.num_data_shards <= 1:
                _log.error(
                    "%s: device lost on a single-shard mesh — nothing "
                    "to shrink to; re-raising", op)
                raise
            last = e
            dist = _recover(e, dist, op)
            # recovery re-sharded with an even prefix layout: any
            # rebalanced per-shard placement from this call is gone,
            # and reporting it on the result would be a lie
            rebalance = None
    else:
        raise last if last is not None else RuntimeError(
            f"{op}: elastic recovery exhausted")  # pragma: no cover
    if rebalance is not None and hasattr(result, "mesh") \
            and hasattr(result, "schema"):
        # surface the rebalance on the frame the CALLER holds (the op's
        # output derives from the rebalanced input): explain() renders it
        result._rebalance = rebalance
    return result


def lost_device_ids(exc: BaseException, mesh: DeviceMesh) -> List[int]:
    """Which flat device indices of ``mesh`` died, best-effort.

    The error message is the primary evidence (``device <i>`` — PJRT
    status texts and the injected ``device`` fault both name the index);
    failing that, each device is probed with a tiny transfer and the
    unresponsive ones are reported. When neither identifies a device
    (e.g. an anonymous ``DEVICE_LOST`` on a healthy-looking host-backed
    mesh), device 0 is dropped — documented, deterministic, and safe:
    dropping a healthy device only shrinks capacity.
    """
    n = mesh.num_devices
    ids = sorted({int(m) for m in
                  re.findall(r"device[\s_#]*(\d+)", str(exc),
                             re.IGNORECASE)
                  if 0 <= int(m) < n})
    if ids and len(ids) < n:
        return ids
    lost = []
    for i, d in enumerate(mesh.mesh.devices.flat):
        try:
            jax.block_until_ready(jax.device_put(np.zeros(1, np.int8), d))
        except Exception as probe_err:  # noqa: BLE001 - probing for death
            _log.warning("device %d failed its liveness probe: %s",
                         i, probe_err)
            lost.append(i)
    if lost and len(lost) < n:
        return lost
    _log.warning("could not identify the lost device from %r; dropping "
                 "device 0 (set TFT_FAULT_DEVICE / name the device in "
                 "the error to steer this)", str(exc)[:200])
    return [0]


def _data_mesh(mesh: DeviceMesh, devices: Sequence,
               action: str) -> DeviceMesh:
    """A new mesh with ``mesh``'s axis layout over ``devices`` on the
    DATA axis, wherever it sits — every other axis must be size 1 (the
    shared data-only guard of shrink and grow)."""
    if mesh.num_devices != mesh.num_data_shards:
        raise ValueError(
            f"elastic {action} needs a data-only mesh (non-data axes "
            f"all size 1); {mesh!r} has {mesh.num_devices} devices "
            f"over {mesh.num_data_shards} data shards")
    data_pos = mesh.axis_names.index(mesh.data_axis)
    shape = tuple(len(devices) if i == data_pos else 1
                  for i in range(len(mesh.axis_names)))
    arr = np.array(list(devices)).reshape(shape)
    return DeviceMesh(Mesh(arr, mesh.axis_names), data_axis=mesh.data_axis)


def shrink_mesh(mesh: DeviceMesh, lost: Sequence[int]) -> DeviceMesh:
    """A new data mesh over ``mesh``'s devices minus ``lost`` (flat
    indices). Only data-only meshes (every non-data axis of size 1) can
    shrink rectangularly; others raise."""
    gone = set(lost)
    survivors = [d for i, d in enumerate(mesh.mesh.devices.flat)
                 if i not in gone]
    if not survivors:
        raise ValueError(f"all {mesh.num_devices} devices of {mesh!r} "
                         f"reported lost; nothing to shrink to")
    return _data_mesh(mesh, survivors, "shrink")


def reshard(dist, mesh: DeviceMesh,
            shard_rows: Optional[np.ndarray] = None):
    """Rebuild ``dist``'s columns over ``mesh`` through the host.

    ``shard_rows`` (len ``mesh.num_data_shards``) places each shard's
    valid-row count explicitly (the skew-rebalance layout; per-shard
    validity carries the imbalance); ``None`` lays the valid rows out as
    an even prefix (the ``distribute()`` layout). Global row order is
    preserved either way, so row-local results collect bit-identically.
    """
    from .distributed import DistributedFrame  # import cycle: lazy

    S = mesh.num_data_shards
    n = dist.num_rows
    mask = dist.valid_row_mask()
    if shard_rows is None:
        padded = ((n + S - 1) // S) * S if n else S
        shard_valid_out = None
        offsets = None
    else:
        shard_rows = np.asarray(shard_rows, np.int64)
        if shard_rows.shape != (S,) or int(shard_rows.sum()) != n:
            raise ValueError(
                f"shard_rows {shard_rows} does not distribute {n} rows "
                f"over {S} shards")
        rows_per = max(1, int(shard_rows.max()))
        padded = rows_per * S
        shard_valid_out = shard_rows
        offsets = np.concatenate([[0], np.cumsum(shard_rows)[:-1]])

    def place(valid: np.ndarray, fill) -> np.ndarray:
        out = np.full((padded,) + valid.shape[1:], fill, valid.dtype)
        if shard_rows is None:
            out[:n] = valid
        else:
            for i in range(S):
                k = int(shard_rows[i])
                out[i * rows_per: i * rows_per + k] = \
                    valid[offsets[i]: offsets[i] + k]
        return out

    cols: Dict[str, object] = {}
    for f in dist.schema:
        a = dist.host_read_padded(f.name)
        a = a[mask] if dist.shard_valid is not None else a[:n]
        if not f.dtype.tensor:
            cols[f.name] = place(a, None)
            continue
        out = place(a, 0)
        cols[f.name] = jax.device_put(out, mesh.row_sharding(out.ndim))
    return DistributedFrame(mesh, dist.schema, cols, n,
                            shard_valid=shard_valid_out)


def _recover(exc: BaseException, dist, op: str):
    """Shrink + re-shard after a classified device loss; returns the
    replacement frame (same rows, smaller mesh)."""
    mesh = dist.mesh
    lost = lost_device_ids(exc, mesh)
    new_mesh = shrink_mesh(mesh, lost)  # raises for non-data meshes
    lost_ids = {int(getattr(mesh.mesh.devices.flat[i], "id", i))
                for i in lost}
    # a grow upgrade that would re-admit the just-lost device(s) must
    # die with them, or the next op would migrate straight back onto a
    # dead chip and loop shrink->grow->shrink against it
    _forget_upgrades_containing(lost_ids)
    # …and the ids join the lost pool: admit_devices' default candidate
    # set, so recovery-driven growth targets genuinely lost chips first
    _lost_pool.update(lost_ids)
    # 1-axis data mesh: flat device index == data shard index, so the
    # lost shards' valid rows are exactly the data that must round-trip
    per_shard = dist.per_shard_valid()
    moved = int(sum(per_shard[i] for i in lost
                    if i < mesh.num_data_shards))
    new_dist = reshard(dist, new_mesh)
    counters.inc("mesh.devices_lost", len(lost))
    counters.inc("mesh.shrinks")
    counters.inc("mesh.reshard_rows", moved)
    gauge("mesh.active_devices", new_mesh.num_devices)
    for d in lost:
        _obs.add_event("mesh_shrink", name=op, device=int(d),
                       devices_before=mesh.num_devices,
                       devices_after=new_mesh.num_devices,
                       reshard_rows=moved)
        _flight.record("mesh.shrink", op=op, device=int(d),
                       devices_before=mesh.num_devices,
                       devices_after=new_mesh.num_devices,
                       reshard_rows=moved)
    # a device loss is one of the flight recorder's auto-dump triggers
    # (docs/observability.md): the ring right now holds the decisions
    # that led here
    _flight.maybe_dump("device_lost")
    _log.warning(
        "%s: device loss (%s); lost device(s) %s — mesh shrunk "
        "%d -> %d shards, %d row(s) re-sharded through the host; "
        "re-running the op on the surviving devices",
        op, type(exc).__name__, lost, mesh.num_data_shards,
        new_mesh.num_data_shards, moved)
    return new_dist


# ---------------------------------------------------------------------------
# elastic mesh GROWTH (the inverse of shrink: re-admit recovered devices)
# ---------------------------------------------------------------------------

# old DeviceMesh INSTANCE (by id, held weakly) -> the grown DeviceMesh
# every frame still living on that mesh object should migrate to.
# Checked at the elastic_call dispatch boundary (_maybe_grow), which is
# exactly how stream pumps and the serve scheduler pick up a grown mesh
# at their next batch/query boundary without holding a mesh reference
# themselves. Keyed by object identity, NOT by device set: a fresh mesh
# a user later builds over the same devices (deliberately excluding the
# admitted ones) must never be captured by an old upgrade.
_upgrade_lock = threading.Lock()
_upgrades: Dict[int, Tuple["weakref.ref", DeviceMesh]] = {}
# flat ids of devices dropped by elastic shrinks and not yet
# re-admitted: the default candidate set of admit_devices (the
# recovered-chip case), so growth never grabs another live mesh's
# healthy devices while genuinely lost ones exist
_lost_pool: set = set()


def lost_pool() -> List[int]:
    """Flat ids of devices dropped by elastic shrinks and not yet
    re-admitted (``tft.health()``'s mesh section reads this): non-empty
    means meshes are running shrunken and ``admit_devices`` has
    recovery candidates waiting."""
    return sorted(_lost_pool)


def _forget_upgrades_containing(device_ids: set) -> None:
    """Drop grow upgrades whose TARGET mesh includes any of these
    (just-lost) devices."""
    if not _upgrades:
        return
    with _upgrade_lock:
        for k, (ref, m) in list(_upgrades.items()):
            if ref() is None or device_ids & set(_mesh_key(m)):
                _upgrades.pop(k, None)


def _start_probe(dev):
    """Launch one device probe (tiny transfer + warm-up compiled
    dispatch) on a daemon thread; returns ``(thread, result_dict)``.
    A probe wedged inside an unkillable ``device_put`` leaks its
    daemon thread — the price of never wedging admission itself."""
    result: Dict[str, object] = {}

    def _probe():
        try:
            x = jax.device_put(np.arange(4, dtype=np.int32), dev)
            jax.block_until_ready(x)
            # warm-up dispatch: compile + execute on the candidate
            y = jax.jit(lambda a: a + 1)(x)
            jax.block_until_ready(y)
            result["ok"] = bool(int(np.asarray(y)[0]) == 1)
        except Exception as e:  # noqa: BLE001 - probing for health
            result["err"] = e

    th = threading.Thread(target=_probe, daemon=True,
                          name="tft-admit-probe")
    th.start()
    return th, result


def _probe_verdict(dev, th, result, timeout_s: float) -> bool:
    """Judge a launched probe AFTER its join: alive = hung, error =
    unhealthy, else the computed check."""
    if th.is_alive():
        _log.warning("admit probe of %r timed out after %.1fs; not "
                     "admitting it", dev, timeout_s)
        return False
    err = result.get("err")
    if err is not None:
        _log.warning("admit probe of %r failed (%s: %s); not admitting "
                     "it", dev, type(err).__name__, err)
        return False
    return bool(result.get("ok"))


def probe_device(dev, timeout_s: Optional[float] = None) -> bool:
    """The trust gate before re-admission: a tiny transfer AND a
    warm-up compiled dispatch must complete within
    ``TFT_ADMIT_PROBE_TIMEOUT_S`` (default 5s). A device that can hold
    bytes but not compute — a half-recovered chip — must not rejoin;
    neither may one that hangs (the probe runs on a daemon thread so a
    wedged transfer cannot wedge admission — a hung probe's thread
    leaks until the process exits, which is the documented cost)."""
    if timeout_s is None:
        timeout_s = env_float("TFT_ADMIT_PROBE_TIMEOUT_S", 5.0)
    th, result = _start_probe(dev)
    th.join(timeout=max(float(timeout_s), 0.0))
    return _probe_verdict(dev, th, result, timeout_s)


def grow_mesh(mesh: DeviceMesh, devices: Sequence) -> DeviceMesh:
    """The inverse of :func:`shrink_mesh`: a new data mesh over
    ``mesh``'s devices plus ``devices`` (appended on the data axis;
    already-member devices are ignored). Only data-only meshes grow
    rectangularly; others raise."""
    current = list(mesh.mesh.devices.flat)
    fresh = [d for d in devices if d not in current]
    if not fresh:
        return mesh
    return _data_mesh(mesh, current + fresh, "grow")


def admit_devices(target, devices: Optional[Sequence] = None,
                  probe: bool = True):
    """Re-admit recovered (or newly arrived) devices into a mesh.

    ``target`` is a :class:`~.distributed.DistributedFrame` (returns the
    frame re-sharded over the grown mesh — order-preserving, so
    row-local results stay bit-identical) or a :class:`~.mesh.DeviceMesh`
    (returns the grown mesh). ``devices`` defaults to the devices this
    process LOST to elastic shrinks and has not re-admitted (the
    recovered-chip case); with none recorded, it widens to every
    visible non-member (with an advisory log — in a multi-mesh process
    pass ``devices=`` explicitly so another mesh's devices are not
    absorbed). Each candidate must pass :func:`probe_device` (transfer
    + warm-up dispatch) before it is trusted; failures are skipped and
    counted (``mesh.admit_probe_failures``), never fatal.

    Side effects beyond the returned value:

    - the old→grown mapping is registered so every OTHER frame still on
      the old mesh migrates at its next op (``elastic_call``) — stream
      pumps and the serve scheduler pick the grown mesh up at their next
      batch/query boundary with no restart;
    - persistent-skew penalties recorded against the returning layout
      are cleared (a device that was a straggler before it died gets a
      fresh start);
    - ``mesh.grows`` / ``mesh.devices_admitted`` count it, a
      ``mesh_grow`` event lands in the active query trace, and
      ``mesh.active_devices`` updates.

    No candidates (or none passing the probe) returns ``target``
    unchanged.
    """
    dist = None
    mesh = target
    if not isinstance(target, DeviceMesh):
        dist, mesh = target, target.mesh
    current = list(mesh.mesh.devices.flat)
    if devices is None:
        devices = [d for d in jax.devices() if d not in current]
        # prefer devices this process actually LOST (the recovered-chip
        # case): when any exist, never grab another live mesh's healthy
        # devices by default — pass devices= explicitly to widen
        recovered = [d for d in devices
                     if int(getattr(d, "id", -1)) in _lost_pool]
        if recovered:
            devices = recovered
        elif devices:
            _log.info(
                "admit_devices: no recorded lost devices; defaulting "
                "to every visible non-member (%d candidate(s)) — in a "
                "multi-mesh process pass devices= explicitly so "
                "another mesh's devices are not absorbed",
                len(devices))
    else:
        devices = [d for d in devices if d not in current]
    if probe and devices:
        # probes are independent: launch them all, judge them against
        # ONE shared deadline — N half-recovered candidates cost one
        # timeout, not N stacked ones
        timeout_s = env_float("TFT_ADMIT_PROBE_TIMEOUT_S", 5.0)
        launched = [(d, *_start_probe(d)) for d in devices]
        give_up = time.monotonic() + max(float(timeout_s), 0.0)
        admitted = []
        for d, th, result in launched:
            th.join(timeout=max(0.0, give_up - time.monotonic()))
            if _probe_verdict(d, th, result, timeout_s):
                admitted.append(d)
            else:
                counters.inc("mesh.admit_probe_failures")
                _obs.add_event("admit_probe_failed",
                               device=int(getattr(d, "id", -1)))
    else:
        admitted = list(devices)
    if not admitted:
        if devices:
            _log.warning("admit_devices: none of the %d candidate "
                         "device(s) passed the probe; mesh unchanged",
                         len(devices))
        return target
    new_mesh = grow_mesh(mesh, admitted)
    with _tracker_lock:
        # un-do persistent-skew penalties for the returning layout: a
        # streak recorded before the device left must not trigger a
        # rebalance against data it no longer describes
        _tracker.pop(_mesh_key(mesh), None)
        _tracker.pop(_mesh_key(new_mesh), None)
    with _upgrade_lock:
        # compress chains: anything already upgrading TO this mesh
        # OBJECT now points at the grown one; prune dead refs while
        # here
        for k, (ref, m) in list(_upgrades.items()):
            if ref() is None:
                _upgrades.pop(k, None)
            elif m is mesh:
                _upgrades[k] = (ref, new_mesh)
        _upgrades[id(mesh)] = (weakref.ref(mesh), new_mesh)
    _lost_pool.difference_update(
        int(getattr(d, "id", -1)) for d in admitted)
    counters.inc("mesh.grows")
    counters.inc("mesh.devices_admitted", len(admitted))
    gauge("mesh.active_devices", new_mesh.num_devices)
    _obs.add_event("mesh_grow",
                   devices=[int(getattr(d, "id", -1)) for d in admitted],
                   devices_before=mesh.num_devices,
                   devices_after=new_mesh.num_devices)
    _flight.record("mesh.grow",
                   devices=[int(getattr(d, "id", -1)) for d in admitted],
                   devices_before=mesh.num_devices,
                   devices_after=new_mesh.num_devices)
    _log.info("mesh grown %d -> %d device(s): admitted %s (probe + "
              "warm-up passed); frames on the old mesh migrate at "
              "their next dispatch", mesh.num_devices,
              new_mesh.num_devices,
              [int(getattr(d, "id", -1)) for d in admitted])
    if dist is None:
        return new_mesh
    return reshard(dist, new_mesh)


def _maybe_grow(op: str, dist):
    """Migrate a frame whose mesh OBJECT has a registered grow upgrade
    onto the grown mesh (order-preserving reshard) before the op
    dispatches. Identity-keyed: only frames sharing the upgraded mesh
    instance migrate — a user-built fresh mesh over the same devices is
    never captured. The healthy-path cost is one dict truthiness
    check."""
    if not _upgrades:
        return dist
    with _upgrade_lock:
        ent = _upgrades.get(id(dist.mesh))
        new_mesh = ent[1] if ent is not None \
            and ent[0]() is dist.mesh else None
    if new_mesh is None:
        return dist
    try:
        out = reshard(dist, new_mesh)
    except Exception as e:  # noqa: BLE001 - growth is opportunistic
        _log.warning(
            "%s: could not migrate the frame onto the grown mesh (%s: "
            "%s); running on %r", op, type(e).__name__, e, dist.mesh)
        return dist
    counters.inc("mesh.grow_migrations")
    _obs.add_event("mesh_grow_pickup", name=op,
                   devices_after=new_mesh.num_devices)
    _log.info("%s: frame migrated onto the grown %d-device mesh at its "
              "dispatch boundary", op, new_mesh.num_devices)
    return out


# ---------------------------------------------------------------------------
# skew-adaptive repartitioning
# ---------------------------------------------------------------------------

_tracker_lock = threading.Lock()
# mesh identity (flat device-id tuple) -> {"hits": consecutive
# above-threshold dispatches, "times": last per-device durations}
_tracker: Dict[tuple, dict] = {}


def _mesh_key(mesh: DeviceMesh) -> tuple:
    return tuple(int(getattr(d, "id", i))
                 for i, d in enumerate(mesh.mesh.devices.flat))


def _rebalance_after() -> int:
    """Consecutive skewed dispatches before acting (0 disables)."""
    return env_int("TFT_SKEW_REBALANCE_AFTER", 3)


def note_dispatch(mesh: DeviceMesh, op: str,
                  times: Sequence[float]) -> None:
    """Feed one traced dispatch's per-device readiness durations to the
    skew tracker (called from the d-ops' trace instrumentation — per-
    device timings only exist while tracing is on, exactly like the
    skew report they power).

    ``times`` are the CUMULATIVE ordered-wait readiness durations the
    trace records (duration until device i AND every earlier one were
    ready). Detection uses their max/median ratio — exactly the skew
    report's straggler signal, with the same inherent blind spot (a
    shard-0 straggler inflates every cumulative time equally and is
    invisible; only late-shard stragglers cross the threshold). The
    REBALANCE weights, however, must not be: ``1/cumulative`` is
    monotone toward shard 0 by construction, so per-device cost is
    estimated from the marginal increments (the extra wait each shard
    added beyond its predecessor), floored at 10% of the largest
    increment — a shard that added no wait is "fast", but never more
    than 10x faster than the straggler.
    """
    n = _rebalance_after()
    if n <= 0 or len(times) < 2:
        return
    med = statistics.median(times)
    ratio = (max(times) / med) if med > 0 else 0.0
    from ..observability.report import _skew_threshold
    key = _mesh_key(mesh)
    with _tracker_lock:
        if ratio > _skew_threshold():
            incs = [float(times[0])] + [
                max(float(t) - float(p), 0.0)
                for p, t in zip(times, times[1:])]
            floor = 0.1 * max(incs)
            st = _tracker.setdefault(key, {"hits": 0, "times": None})
            st["hits"] += 1
            st["times"] = [max(i, floor) for i in incs]
            st["ratio"] = ratio
        else:  # a balanced dispatch resets the streak; dropping the
            # entry keeps the tracker EMPTY on healthy meshes, which is
            # what keeps _maybe_rebalance's fast path one dict probe
            _tracker.pop(key, None)


def _maybe_rebalance(op: str, dist):
    """Re-partition ``dist`` proportionally to observed per-device
    throughput once the tracker says the skew is persistent."""
    if not _tracker:
        # fast path (bench-enforced): no skew recorded on ANY mesh —
        # one dict truthiness check, no lock, no env read, no mesh key
        return dist
    n = _rebalance_after()
    if n <= 0:
        return dist
    key = _mesh_key(dist.mesh)
    with _tracker_lock:
        st = _tracker.get(key)
        if st is None or st["hits"] < n or st["times"] is None:
            return dist
        times = st["times"]
        ratio = st.get("ratio", 0.0)
        _tracker.pop(key)  # act once per streak
    S = dist.mesh.num_data_shards
    if len(times) != S or dist.num_rows < S:
        return dist
    try:
        before = dist.per_shard_valid()
    except ValueError:
        return dist  # non-tiling global-result frames keep their layout
    # rows proportional to throughput (1/time), exact total via largest
    # remainders
    speed = np.array([1.0 / max(t, 1e-9) for t in times])
    want = speed / speed.sum() * dist.num_rows
    after = np.floor(want).astype(np.int64)
    rem = dist.num_rows - int(after.sum())
    if rem > 0:
        order = np.argsort(-(want - after))
        after[order[:rem]] += 1
    if np.array_equal(before, after):
        return dist
    new_dist = reshard(dist, dist.mesh, shard_rows=after)
    counters.inc("mesh.rebalances")
    _obs.add_event("rebalance", name=op, ratio=round(ratio, 3),
                   before=[int(v) for v in before],
                   after=[int(v) for v in after])
    from ..observability.report import _skew_threshold
    _flight.record("mesh.rebalance", op=op, ratio=round(ratio, 3),
                   threshold=_skew_threshold(), streak=n,
                   before=[int(v) for v in before],
                   after=[int(v) for v in after])
    new_dist._rebalance = {"op": op, "ratio": ratio,
                           "before": [int(v) for v in before],
                           "after": [int(v) for v in after]}
    _log.info(
        "%s: straggler ratio %.2f persisted %d dispatch(es); rows "
        "re-partitioned by observed throughput %s -> %s", op, ratio, n,
        [int(v) for v in before], [int(v) for v in after])
    return new_dist


# ---------------------------------------------------------------------------
# hot-key salting (daggregate's monoid host-key path)
# ---------------------------------------------------------------------------

def salt_fraction() -> Optional[float]:
    """The hot-key frequency threshold, or None when salting is off
    (``TFT_SALT_HOT_KEYS``, default on; ``TFT_HOT_KEY_FRACTION``,
    default 0.5 — a key is hot above HALF the rows)."""
    if not env_bool("TFT_SALT_HOT_KEYS", True):
        return None
    frac = env_float("TFT_HOT_KEY_FRACTION", 0.5)
    if frac is None or not 0.0 < frac < 1.0:
        return None
    return frac


def plan_key_salt(dist, ids_dev, num_groups: int, n_shards: int
                  ) -> Optional[Tuple[object, int, Tuple[np.ndarray, int]]]:
    """Salt hot groups across ``n_shards`` slots.

    Returns ``(salted_ids_dev, effective_groups, (hot, K))`` — or None
    when no group crosses the threshold (or the frame is too small for
    salting to matter). Row ``r`` of a hot group lands in salt slot
    ``r % K``; slot 0 keeps the original group id, slots 1..K-1 map to
    appended table rows that :func:`fold_salted` folds back, so cold
    groups and output order are untouched.
    """
    frac = salt_fraction()
    if frac is None or n_shards <= 1 or num_groups <= 0:
        return None
    n = dist.num_rows
    if n < 4 * n_shards:
        return None
    ids_host = np.asarray(ids_dev)
    valid = ids_host >= 0
    counts = np.bincount(ids_host[valid], minlength=num_groups)
    hot = np.flatnonzero(counts > frac * n)
    if hot.size == 0:
        return None
    K = n_shards
    G = num_groups
    hot_rank = np.full(G, -1, np.int64)
    hot_rank[hot] = np.arange(hot.size)
    j = np.arange(ids_host.shape[0]) % K  # even spread within each shard
    salted = ids_host.astype(np.int64, copy=True)
    m = valid & (hot_rank[np.clip(ids_host, 0, G - 1)] >= 0) & (j > 0)
    salted[m] = G + hot_rank[ids_host[m]] * (K - 1) + (j[m] - 1)
    salted = salted.astype(np.int32)
    eff = G + int(hot.size) * (K - 1)
    ids2 = jax.make_array_from_callback(
        (salted.shape[0],), dist.mesh.row_sharding(1),
        lambda idx: salted[idx])
    counters.inc("mesh.salted_keys", int(hot.size))
    _obs.add_event("key_salt", name="daggregate", count=int(hot.size),
                   salt=K, groups=[int(g) for g in hot[:16]])
    _flight.record("mesh.salt", count=int(hot.size), fraction=frac,
                   slots=K, rows=n, groups=[int(g) for g in hot[:16]])
    _log.info("daggregate: %d hot key group(s) (> %.0f%% of %d rows) "
              "salted across %d slots", hot.size, frac * 100, n, K)
    # 4th element: each hot group's observed row fraction — the
    # hot-key OBSERVATION surfaced by frame.hot_keys()/explain()
    # (consumers index [0..2]; the append is compatible)
    return ids2, eff, (hot, K), counts[hot] / max(n, 1)


_SALT_FOLD = {"sum": np.add, "min": np.minimum, "max": np.maximum,
              "prod": np.multiply}


def fold_salted(table, salt_map: Tuple[np.ndarray, int],
                cname: str) -> np.ndarray:
    """Fold a ``[effective_groups, ...]`` salted partial table back to
    ``[num_groups, ...]`` with the combiner's numpy twin."""
    hot, K = salt_map
    t = np.asarray(table)
    G = t.shape[0] - hot.size * (K - 1)
    base = t[:G].copy()
    if hot.size:
        extras = t[G:].reshape((hot.size, K - 1) + t.shape[1:])
        stack = np.concatenate([base[hot][:, None], extras], axis=1)
        base[hot] = _SALT_FOLD[cname].reduce(stack, axis=1)
    return base


# ---------------------------------------------------------------------------
# metrics
# ---------------------------------------------------------------------------

_MESH_FAMILIES = (
    ("mesh.devices_lost", "tft_mesh_devices_lost_total",
     "Mesh devices lost and recovered from (elastic shrink)."),
    ("mesh.shrinks", "tft_mesh_shrinks_total",
     "Mesh shrink events (one per loss incident, any device count)."),
    ("mesh.reshard_rows", "tft_mesh_reshard_rows_total",
     "Rows re-sharded through the host by elastic recovery."),
    ("mesh.rebalances", "tft_mesh_rebalances_total",
     "Skew-adaptive repartitions applied."),
    ("mesh.salted_keys", "tft_mesh_salted_keys_total",
     "Hot key groups salted across shards by daggregate."),
    ("mesh.grows", "tft_mesh_grows_total",
     "Mesh grow events (recovered/new devices re-admitted after probe "
     "+ warm-up — the inverse of shrink)."),
    ("mesh.devices_admitted", "tft_mesh_devices_admitted_total",
     "Devices re-admitted into meshes by elastic growth."),
    ("mesh.admit_probe_failures", "tft_mesh_admit_probe_failures_total",
     "Candidate devices that failed the admission probe (transfer + "
     "warm-up dispatch) and were NOT admitted."),
    ("mesh.grow_migrations", "tft_mesh_grow_migrations_total",
     "Frames migrated onto a grown mesh at their next dispatch "
     "boundary."),
    ("mesh.dispatches", "tft_mesh_dispatches_total",
     "Compiled mesh-op program dispatches (a fused distributed plan "
     "counts ONE for its whole chain — docs/plan.md)."),
    ("mesh.interstage_host_bytes", "tft_mesh_interstage_host_bytes_total",
     "Bytes crossing device->host BETWEEN chained mesh ops (dfilter "
     "survivor counts / keep masks); zero on fused chains."),
    ("dplan.fused_forcings", "tft_dplan_fused_forcings_total",
     "Lazy distributed chains forced as one fused GSPMD program."),
    ("dplan.fallbacks", "tft_dplan_fallbacks_total",
     "Fused mesh programs that fell back to the per-op replay."),
    ("mesh.exchange_dispatches", "tft_mesh_exchange_dispatches_total",
     "Hash-repartition exchanges dispatched (parallel/exchange.py)."),
    ("mesh.exchange_rows", "tft_mesh_exchange_rows_total",
     "Rows routed through the shuffle exchange."),
    ("mesh.exchange_bytes", "tft_mesh_exchange_bytes_total",
     "Device bytes admitted for exchange send+receive buffers."),
    ("mesh.exchange_skew_events", "tft_mesh_exchange_skew_events_total",
     "Exchanges whose partition-size imbalance crossed TFT_SKEW_WARN "
     "(flight-recorded as mesh.exchange_skew)."),
    ("mesh.shuffle_daggregates", "tft_mesh_shuffle_daggregates_total",
     "Shuffle-partitioned aggregations run."),
    ("mesh.shuffle_agg_routes", "tft_mesh_shuffle_agg_routes_total",
     "daggregate calls auto-routed to the shuffle path by the "
     "TFT_SHUFFLE_AGG_GROUPS threshold."),
)


def _render_metrics() -> List[str]:
    snap = counters.snapshot()
    lines: List[str] = []
    for key, fam, help_text in _MESH_FAMILIES:
        lines.append(f"# HELP {fam} {help_text}")
        lines.append(f"# TYPE {fam} counter")
        lines.append(f"{fam} {snap.get(key, 0)}")
    return lines


_metrics.register_metrics_provider("mesh", _render_metrics)
