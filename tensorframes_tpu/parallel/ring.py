"""Ring collectives: sequence-parallel (ring) attention and ring allreduce.

Long-context support is first-class in this framework even though the
reference predates attention entirely (SURVEY.md §5 notes only that the
mesh/collective layer must not preclude it). Both primitives run inside
``shard_map`` over a mesh axis and move data with ``jax.lax.ppermute`` —
neighbor hops that ride the ICI ring, never materializing the full sequence
(or the full gradient) on one chip.

``ring_attention`` shards the sequence dimension of q/k/v across the axis
and rotates k/v blocks around the ring, maintaining flash-attention-style
online softmax statistics (running max ``m``, normalizer ``l``, accumulator
``o``), so each chip holds only S/n of the sequence at any time. Supports
causal masking via global position indices.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from ..utils import compat as _compat
from ..utils.compat import shard_map
from jax.sharding import PartitionSpec as P

from .mesh import DeviceMesh
from ..observability.events import current_trace as _current_trace

__all__ = ["ring_attention", "ring_allreduce"]


def _traced_ring_dispatch(kind: str, fn, args, axis: str, devices: int,
                          hops: int):
    """Dispatch a ring program, recording a ``collective`` event on the
    active query trace (host-timed through readiness — tracing ON pays a
    barrier; the untraced path keeps jax's async dispatch untouched).
    Inputs that are tracers (the caller is itself under jit) skip the
    timing: there is no host-visible dispatch to measure there.
    """
    trace = _current_trace()
    if trace is None:
        return fn(*args)
    tracer_t = getattr(jax.core, "Tracer", ())
    if tracer_t and any(isinstance(a, tracer_t) for a in args):
        return fn(*args)
    t0 = trace.clock()
    out = fn(*args)
    jax.block_until_ready(out)
    trace.add("collective", name=kind, ts=t0,
              dur=max(trace.clock() - t0, 0.0), axis=axis,
              devices=devices, hops=hops)
    return out


def _varying(a, *axes: Optional[str]):
    """Type a fresh constant as device-varying over ``axes`` so it can seed
    a loop carry that becomes varying (shard_map's varying-manual-axes
    checker rejects unvarying→varying carries; the cast is free). ``None``
    axes and axes ``a`` already varies over are skipped (pcast rejects
    both). A carry must be cast over EVERY axis its updates vary on — e.g.
    ring attention's (m, l, o) vary over the batch/head axes too as soon
    as they combine with the sharded q block."""
    if not hasattr(jax.lax, "pcast"):
        return a
    have = _compat.vma_of(a)
    need = tuple(ax for ax in axes if ax is not None and ax not in have)
    if not need:
        return a
    return jax.lax.pcast(a, need, to="varying")


def _local_attn_update(q, k, v, m, l, o, scale, mask):
    """One flash-attention block update with blockwise softmax rescaling.

    q: [B, Sq, H, D]; k/v: [B, Sk, H, D]; m/l: [B, H, Sq]; o like q.
    ``mask``: [Sq, Sk] boolean or None.
    """
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k) * scale
    if mask is not None:
        scores = jnp.where(mask[None, None, :, :], scores, -jnp.inf)
    m_new = jnp.maximum(m, scores.max(axis=-1))
    # guard fully-masked rows: keep m finite so exp() stays 0, not NaN
    m_safe = jnp.where(jnp.isneginf(m_new), 0.0, m_new)
    p = jnp.exp(scores - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask[None, None, :, :], p, 0.0)
    alpha = jnp.where(jnp.isneginf(m), 0.0, jnp.exp(m - m_safe))
    l_new = alpha * l + p.sum(axis=-1)
    o_new = o * alpha.transpose(0, 2, 1)[..., None] + \
        jnp.einsum("bhqk,bkhd->bqhd", p, v)
    return m_new, l_new, o_new


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   mesh: DeviceMesh, seq_axis: Optional[str] = None,
                   causal: bool = False,
                   batch_axis: Optional[str] = None,
                   head_axis: Optional[str] = None) -> jax.Array:
    """Exact attention over a sequence sharded across a mesh axis.

    q/k/v: [batch, seq, heads, head_dim], seq row-sharded over ``seq_axis``
    (defaults to the mesh's data axis). Returns the attention output with
    the same sharding. Each ring step computes one local q-block/k-block
    interaction and ppermutes k/v one hop; softmax is exact via online
    (m, l, o) accumulation. Peak per-chip memory is O(S/n), enabling
    sequences n times longer than single-chip attention.

    ``batch_axis``/``head_axis`` additionally shard the batch and head dims
    (data/tensor parallelism composed with the sequence ring): attention is
    independent across batch and heads, so those axes never communicate —
    only k/v hop the ring over ``seq_axis``.
    """
    axis = seq_axis or mesh.data_axis
    n = mesh.mesh.shape[axis]
    # python float (weak type) so f32/bf16 inputs are not promoted
    scale = float(1.0 / np.sqrt(q.shape[-1]))
    perm = [(i, (i + 1) % n) for i in range(n)]

    def shard_fn(q_blk, k_blk, v_blk):
        B, S, H, D = q_blk.shape
        my = jax.lax.axis_index(axis)
        q_pos = my * S + jnp.arange(S)

        # carries combine with the sharded q block, so they vary over the
        # batch/head axes too when those are set — cast over all of them
        m0 = _varying(jnp.full((B, H, S), -jnp.inf, q_blk.dtype),
                      axis, batch_axis, head_axis)
        l0 = _varying(jnp.zeros((B, H, S), q_blk.dtype),
                      axis, batch_axis, head_axis)
        o0 = _varying(jnp.zeros_like(q_blk), axis, batch_axis, head_axis)

        def step(i, carry):
            k_cur, v_cur, m, l, o = carry
            # the k/v block now resident arrived from `i` hops upstream
            src = (my - i) % n
            k_pos = src * S + jnp.arange(S)
            mask = (q_pos[:, None] >= k_pos[None, :]) if causal else None
            m, l, o = _local_attn_update(q_blk, k_cur, v_cur, m, l, o,
                                         scale, mask)
            k_nxt = jax.lax.ppermute(k_cur, axis, perm)
            v_nxt = jax.lax.ppermute(v_cur, axis, perm)
            return (k_nxt, v_nxt, m, l, o)

        k_f, v_f, m, l, o = jax.lax.fori_loop(
            0, n, step, (k_blk, v_blk, m0, l0, o0))
        l_safe = jnp.where(l == 0.0, 1.0, l)
        return o / l_safe.transpose(0, 2, 1)[..., None]

    spec = P(batch_axis, axis, head_axis, None)
    fn = shard_map(shard_fn, mesh=mesh.mesh,
                   in_specs=(spec, spec, spec), out_specs=spec)
    # n ring steps, each ppermuting k AND v one hop
    return _traced_ring_dispatch("ring_attention", fn, (q, k, v), axis,
                                 n, hops=2 * n)


def ring_allreduce(x: jax.Array, mesh: DeviceMesh,
                   axis: Optional[str] = None) -> jax.Array:
    """Bandwidth-optimal allreduce built from ppermute hops.

    ``x`` has shape [n, ...] with the leading dim sharded over the axis —
    one local value per device. Returns the same shape where every slice is
    the full sum. The classic schedule: reduce-scatter then all-gather,
    2(n-1) neighbor hops each moving 1/n of the payload. XLA's ``psum`` is
    normally what you want; this exists as the explicit-ICI-schedule
    primitive and benchmark baseline.
    """
    ax = axis or mesh.data_axis
    n = mesh.mesh.shape[ax]
    if n == 1:
        return x
    if x.shape[0] != n:
        raise ValueError(
            f"ring_allreduce expects leading dim == axis size {n}, got "
            f"{x.shape[0]}")
    fwd = [(i, (i + 1) % n) for i in range(n)]

    def shard_fn(blk):
        # blk: [1, ...] — this device's local value
        me = jax.lax.axis_index(ax)
        flat = blk.reshape(-1)
        pad = (-flat.shape[0]) % n
        if pad:
            flat = jnp.pad(flat, (0, pad))
        chunks = flat.reshape(n, -1)

        # reduce-scatter: at step s, send the partially-reduced chunk
        # (me - s) and fold the arriving chunk (me - s - 1) into our local
        # copy; after n-1 steps this device owns fully-reduced chunk me+1.
        buf = jnp.take(chunks, me % n, axis=0)
        for s in range(n - 1):
            buf = jax.lax.ppermute(buf, ax, fwd)
            buf = buf + jnp.take(chunks, (me - s - 1) % n, axis=0)
        owned = (me + 1) % n

        # all-gather: rotate each fully-reduced chunk around the ring
        out = _varying(jnp.zeros_like(chunks), ax)
        cur, idx = buf, owned
        out = out.at[idx].set(cur)
        for _ in range(n - 1):
            cur = jax.lax.ppermute(cur, ax, fwd)
            # node i-1 owned chunk i, so each arrival is one index lower
            idx = (idx - 1) % n
            out = out.at[idx].set(cur)
        full = out.reshape(-1)
        if pad:
            full = full[:-pad]
        return full.reshape(blk.shape)

    fn = shard_map(shard_fn, mesh=mesh.mesh,
                   in_specs=P(ax), out_specs=P(ax))
    # reduce-scatter + all-gather: 2(n-1) neighbor hops
    return _traced_ring_dispatch("ring_allreduce", fn, (x,), ax, n,
                                 hops=2 * (n - 1))
